/*
 * C predict ABI for the TPU-native framework (parity surface of the
 * reference's include/mxnet/c_predict_api.h, re-declared for
 * libmxtpu_predict.so — see src/predict_api.cc for the implementation
 * notes). Link: -lmxtpu_predict. All functions return 0 on success and -1
 * on failure; MXGetLastError() describes the failure.
 */
#ifndef MXTPU_C_PREDICT_API_H_
#define MXTPU_C_PREDICT_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* PredictorHandle;
typedef uint32_t mx_uint;

/* Last error message of the calling thread. */
const char* MXGetLastError(void);

/*
 * Build a predictor from a symbol JSON and a .params blob.
 * dev_type/dev_id are accepted for source compatibility; device placement
 * follows the framework's default context (the TPU when present).
 * input_shape_indptr has num_input_nodes+1 entries delimiting each input's
 * dims inside input_shape_data (e.g. one NCHW input: indptr {0,4}).
 */
int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char** input_keys,
                 const mx_uint* input_shape_indptr,
                 const mx_uint* input_shape_data, PredictorHandle* out);

/* As MXPredCreate, keeping only the named outputs. */
int MXPredCreatePartialOut(const char* symbol_json_str,
                           const void* param_bytes, int param_size,
                           int dev_type, int dev_id, mx_uint num_input_nodes,
                           const char** input_keys,
                           const mx_uint* input_shape_indptr,
                           const mx_uint* input_shape_data,
                           mx_uint num_output_nodes,
                           const char** output_keys, PredictorHandle* out);

/* Stage a float32 input (size = element count, must match the bound shape). */
int MXPredSetInput(PredictorHandle handle, const char* key,
                   const float* data, mx_uint size);

/* Run the staged inputs through the compiled graph. */
int MXPredForward(PredictorHandle handle);

/*
 * Shape of output `index`. The returned pointer is valid until the next
 * call on this handle (the reference's transient-buffer contract).
 */
int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint** shape_data, mx_uint* shape_ndim);

/* Copy output `index` into data (size = element count, checked). */
int MXPredGetOutput(PredictorHandle handle, mx_uint index, float* data,
                    mx_uint size);

/* Re-bind with new input shapes (recompiles once; XLA caches per shape). */
int MXPredReshape(PredictorHandle handle, mx_uint num_input_nodes,
                  const char** input_keys, const mx_uint* input_shape_indptr,
                  const mx_uint* input_shape_data, PredictorHandle* out);

int MXPredFree(PredictorHandle handle);

#ifdef __cplusplus
}
#endif
#endif  /* MXTPU_C_PREDICT_API_H_ */
