/*
 * Training-side C ABI (minimal imperative slice).
 *
 * Reference surface: include/mxnet/c_api.h (115 functions). This is the
 * ~20-function subset that makes end-to-end training reachable from C or a
 * foreign-language binding: NDArray CRUD + synchronous host copies,
 * imperative op invocation by registered name (the reference's
 * MXImperativeInvoke, src/c_api/c_api_ndarray.cc:322, keyed by
 * AtomicSymbolCreator; here ops are addressed by their registry name),
 * executor bind/forward/backward over a symbol JSON, and KVStore
 * init/push/pull. The compute path is XLA behind the mxnet_tpu package; this
 * ABI embeds CPython exactly like c_predict_api (src/predict_api.cc) and is
 * GIL-correct from any thread.
 *
 * Conventions: every function returns 0 on success, -1 on failure with the
 * message available from MXGetLastError() (thread-local). Pointer outputs
 * returned by List/GetShape calls point at handle-owned storage valid until
 * the next call on the same handle.
 */
#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef uint32_t mx_uint;
typedef void* NDArrayHandle;
typedef void* ExecutorHandle;
typedef void* KVStoreHandle;

const char* MXGetLastError(void);

/* ---- NDArray ---------------------------------------------------------- */
/* Create a zero-initialized float32 NDArray on the default context.
 * (dev_type/dev_id accepted for reference-signature compatibility; device
 * placement is the embedding process's MXNET_DEFAULT_CONTEXT.) */
int MXNDArrayCreate(const mx_uint* shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, NDArrayHandle* out);
int MXNDArrayFree(NDArrayHandle handle);
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const float* data,
                             size_t size);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, float* data, size_t size);
int MXNDArrayGetShape(NDArrayHandle handle, mx_uint* out_dim,
                      const mx_uint** out_pdata);
int MXNDArrayWaitAll(void);

/* ---- Imperative invoke ------------------------------------------------ */
/* Invoke a registered op by name. If *num_outputs is 0 on entry the op
 * allocates its outputs and *outputs points at handle storage owned by the
 * library (valid until the next invoke on this thread; the caller owns the
 * returned handles and must MXNDArrayFree them). If *num_outputs > 0,
 * *outputs supplies write-target arrays (in-place update, the optimizer-op
 * idiom). Attribute values are strings, parsed exactly like symbol JSON. */
int MXImperativeInvokeByName(const char* op_name, int num_inputs,
                             NDArrayHandle* inputs, int* num_outputs,
                             NDArrayHandle** outputs, int num_params,
                             const char** param_keys,
                             const char** param_vals);

/* ---- Executor (bind by symbol JSON) ----------------------------------- */
/* simple_bind: infer every shape from the named input shapes (CSR layout as
 * in MXPredCreate), allocate args/grads (grad_req=write), return a training
 * executor. */
int MXTrainExecutorCreate(const char* symbol_json, mx_uint num_inputs,
                          const char** input_keys,
                          const mx_uint* input_shape_indptr,
                          const mx_uint* input_shape_data,
                          ExecutorHandle* out);
int MXExecutorForward(ExecutorHandle handle, int is_train);
/* head_grads may be NULL (loss-style outputs supply their own). */
int MXExecutorBackward(ExecutorHandle handle, mx_uint num_head,
                       NDArrayHandle* head_grads);
int MXExecutorNumOutputs(ExecutorHandle handle, int* out);
int MXExecutorGetOutput(ExecutorHandle handle, mx_uint index,
                        NDArrayHandle* out);
/* Names valid until the handle is freed. */
int MXExecutorListArguments(ExecutorHandle handle, mx_uint* out_size,
                            const char*** out_names);
int MXExecutorGetArg(ExecutorHandle handle, const char* name,
                     NDArrayHandle* out);
/* *out is NULL (rc 0) for inputs with no gradient (data/labels). */
int MXExecutorGetGrad(ExecutorHandle handle, const char* name,
                      NDArrayHandle* out);
int MXExecutorFree(ExecutorHandle handle);

/* ---- KVStore ---------------------------------------------------------- */
int MXKVStoreCreate(const char* type, KVStoreHandle* out);
int MXKVStoreInit(KVStoreHandle handle, mx_uint num, const int* keys,
                  NDArrayHandle* vals);
int MXKVStorePush(KVStoreHandle handle, mx_uint num, const int* keys,
                  NDArrayHandle* vals, int priority);
int MXKVStorePull(KVStoreHandle handle, mx_uint num, const int* keys,
                  NDArrayHandle* outs, int priority);
int MXKVStoreFree(KVStoreHandle handle);

#ifdef __cplusplus
}  /* extern "C" */
#endif

#endif  /* MXTPU_C_API_H_ */
