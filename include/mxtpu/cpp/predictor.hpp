/*
 * Header-only C++ wrapper over the C predict ABI (the cpp-package analogue
 * for the deployment surface; reference: cpp-package/include/mxnet-cpp).
 * RAII handles, std::vector IO, exceptions from MXGetLastError.
 *
 *   mxtpu::Predictor pred(json, params, {{"data", {1, 3, 224, 224}}});
 *   pred.SetInput("data", batch);
 *   pred.Forward();
 *   std::vector<float> probs = pred.GetOutput(0);
 */
#ifndef MXTPU_CPP_PREDICTOR_HPP_
#define MXTPU_CPP_PREDICTOR_HPP_

#include <map>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "../c_predict_api.h"

namespace mxtpu {

class Error : public std::runtime_error {
 public:
  using std::runtime_error::runtime_error;
};

class Predictor {
 public:
  using Shapes = std::map<std::string, std::vector<mx_uint>>;

  Predictor(const std::string& symbol_json, const std::string& param_bytes,
            const Shapes& input_shapes,
            const std::vector<std::string>& output_keys = {}) {
    std::vector<const char*> keys;
    std::vector<mx_uint> indptr{0};
    std::vector<mx_uint> dims;
    for (const auto& kv : input_shapes) {
      keys.push_back(kv.first.c_str());
      dims.insert(dims.end(), kv.second.begin(), kv.second.end());
      indptr.push_back(static_cast<mx_uint>(dims.size()));
    }
    int rc;
    if (output_keys.empty()) {
      rc = MXPredCreate(symbol_json.c_str(), param_bytes.data(),
                        static_cast<int>(param_bytes.size()), 1, 0,
                        static_cast<mx_uint>(keys.size()), keys.data(),
                        indptr.data(), dims.data(), &handle_);
    } else {
      std::vector<const char*> outs;
      for (const auto& o : output_keys) outs.push_back(o.c_str());
      rc = MXPredCreatePartialOut(
          symbol_json.c_str(), param_bytes.data(),
          static_cast<int>(param_bytes.size()), 1, 0,
          static_cast<mx_uint>(keys.size()), keys.data(), indptr.data(),
          dims.data(), static_cast<mx_uint>(outs.size()), outs.data(),
          &handle_);
    }
    if (rc != 0) throw Error(MXGetLastError());
  }

  Predictor(const Predictor&) = delete;
  Predictor& operator=(const Predictor&) = delete;
  Predictor(Predictor&& other) noexcept : handle_(other.handle_) {
    other.handle_ = nullptr;
  }
  Predictor& operator=(Predictor&& other) noexcept {
    std::swap(handle_, other.handle_);
    return *this;
  }

  ~Predictor() {
    if (handle_) MXPredFree(handle_);
  }

  void SetInput(const std::string& key, const std::vector<float>& data) {
    if (MXPredSetInput(handle_, key.c_str(), data.data(),
                       static_cast<mx_uint>(data.size())) != 0)
      throw Error(MXGetLastError());
  }

  void Forward() {
    if (MXPredForward(handle_) != 0) throw Error(MXGetLastError());
  }

  std::vector<mx_uint> GetOutputShape(mx_uint index) {
    mx_uint* data;
    mx_uint ndim;
    if (MXPredGetOutputShape(handle_, index, &data, &ndim) != 0)
      throw Error(MXGetLastError());
    return std::vector<mx_uint>(data, data + ndim);
  }

  std::vector<float> GetOutput(mx_uint index) {
    auto shape = GetOutputShape(index);
    mx_uint total = 1;
    for (auto d : shape) total *= d;
    std::vector<float> out(total);
    if (MXPredGetOutput(handle_, index, out.data(), total) != 0)
      throw Error(MXGetLastError());
    return out;
  }

  /* New independently-owned predictor bound to new input shapes. */
  Predictor Reshape(const Shapes& input_shapes) {
    std::vector<const char*> keys;
    std::vector<mx_uint> indptr{0};
    std::vector<mx_uint> dims;
    for (const auto& kv : input_shapes) {
      keys.push_back(kv.first.c_str());
      dims.insert(dims.end(), kv.second.begin(), kv.second.end());
      indptr.push_back(static_cast<mx_uint>(dims.size()));
    }
    PredictorHandle h;
    if (MXPredReshape(handle_, static_cast<mx_uint>(keys.size()), keys.data(),
                      indptr.data(), dims.data(), &h) != 0)
      throw Error(MXGetLastError());
    return Predictor(h);
  }

 private:
  explicit Predictor(PredictorHandle h) : handle_(h) {}
  PredictorHandle handle_ = nullptr;
};

}  // namespace mxtpu

#endif  /* MXTPU_CPP_PREDICTOR_HPP_ */
