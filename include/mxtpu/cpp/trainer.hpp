/*
 * Header-only C++ wrapper over the training C ABI (the cpp-package
 * analogue for the training surface; reference: cpp-package/include/
 * mxnet-cpp Executor/NDArray/Optimizer). RAII handles, std::vector IO,
 * exceptions from MXGetLastError.
 *
 *   mxtpu::Trainer tr(json, {{"data", {8, 1, 28, 28}},
 *                            {"softmax_label", {8}}});
 *   tr.SetArg("conv1_weight", weights);
 *   tr.Forward(true);
 *   std::vector<float> probs = tr.GetOutput(0);
 *   tr.Backward();
 *   tr.SGDUpdate(0.01f);            // in-place sgd_update on every param
 */
#ifndef MXTPU_CPP_TRAINER_HPP_
#define MXTPU_CPP_TRAINER_HPP_

#include <cstdio>
#include <map>
#include <stdexcept>
#include <string>
#include <vector>

#include "../c_api.h"

namespace mxtpu {

class TrainError : public std::runtime_error {
 public:
  using std::runtime_error::runtime_error;
};

namespace detail {
inline void check(int rc, const char* what) {
  if (rc != 0)
    throw TrainError(std::string(what) + ": " + MXGetLastError());
}

// RAII over one NDArrayHandle
class NDHandle {
 public:
  NDHandle() = default;
  explicit NDHandle(NDArrayHandle h) : h_(h) {}
  NDHandle(NDHandle&& o) noexcept : h_(o.h_) { o.h_ = nullptr; }
  NDHandle& operator=(NDHandle&& o) noexcept {
    if (this != &o) { reset(); h_ = o.h_; o.h_ = nullptr; }
    return *this;
  }
  NDHandle(const NDHandle&) = delete;
  NDHandle& operator=(const NDHandle&) = delete;
  ~NDHandle() { reset(); }
  void reset() { if (h_) { MXNDArrayFree(h_); h_ = nullptr; } }
  NDArrayHandle get() const { return h_; }
  explicit operator bool() const { return h_ != nullptr; }

  size_t Size() const {
    mx_uint nd; const mx_uint* shp;
    check(MXNDArrayGetShape(h_, &nd, &shp), "MXNDArrayGetShape");
    size_t n = 1;
    for (mx_uint i = 0; i < nd; ++i) n *= shp[i];
    return n;
  }
  std::vector<float> ToVector() const {
    std::vector<float> out(Size());
    check(MXNDArraySyncCopyToCPU(h_, out.data(), out.size()),
          "MXNDArraySyncCopyToCPU");
    return out;
  }
  void FromVector(const std::vector<float>& v) const {
    check(MXNDArraySyncCopyFromCPU(h_, v.data(), v.size()),
          "MXNDArraySyncCopyFromCPU");
  }

 private:
  NDArrayHandle h_ = nullptr;
};
}  // namespace detail

class Trainer {
 public:
  using Shapes = std::map<std::string, std::vector<mx_uint>>;

  // simple_bind over symbol JSON; ``input_shapes`` names the data/label
  // inputs (they get no gradient; everything else is a trainable param).
  Trainer(const std::string& symbol_json, const Shapes& input_shapes) {
    std::vector<const char*> keys;
    std::vector<mx_uint> indptr{0};
    std::vector<mx_uint> dims;
    for (const auto& kv : input_shapes) {
      keys.push_back(kv.first.c_str());
      dims.insert(dims.end(), kv.second.begin(), kv.second.end());
      indptr.push_back(static_cast<mx_uint>(dims.size()));
    }
    detail::check(
        MXTrainExecutorCreate(symbol_json.c_str(),
                              static_cast<mx_uint>(keys.size()), keys.data(),
                              indptr.data(), dims.data(), &handle_),
        "MXTrainExecutorCreate");
  }
  ~Trainer() { if (handle_) MXExecutorFree(handle_); }
  Trainer(const Trainer&) = delete;
  Trainer& operator=(const Trainer&) = delete;

  std::vector<std::string> ArgNames() const {
    mx_uint n; const char** names;
    detail::check(MXExecutorListArguments(handle_, &n, &names),
                  "MXExecutorListArguments");
    return std::vector<std::string>(names, names + n);
  }

  std::vector<float> GetArg(const std::string& name) const {
    return arg_(name).ToVector();
  }
  void SetArg(const std::string& name, const std::vector<float>& v) const {
    arg_(name).FromVector(v);
  }
  size_t ArgSize(const std::string& name) const { return arg_(name).Size(); }
  // false when the argument is a data/label input (no gradient)
  bool HasGrad(const std::string& name) const {
    NDArrayHandle g = nullptr;
    detail::check(MXExecutorGetGrad(handle_, name.c_str(), &g),
                  "MXExecutorGetGrad");
    detail::NDHandle owned(g);
    return static_cast<bool>(owned);
  }
  std::vector<float> GetGrad(const std::string& name) const {
    NDArrayHandle g = nullptr;
    detail::check(MXExecutorGetGrad(handle_, name.c_str(), &g),
                  "MXExecutorGetGrad");
    if (!g) throw TrainError(name + " has no gradient");
    return detail::NDHandle(g).ToVector();
  }

  void Forward(bool is_train) const {
    detail::check(MXExecutorForward(handle_, is_train ? 1 : 0),
                  "MXExecutorForward");
  }
  void Backward() const {
    detail::check(MXExecutorBackward(handle_, 0, nullptr),
                  "MXExecutorBackward");
  }
  int NumOutputs() const {
    int n = 0;
    detail::check(MXExecutorNumOutputs(handle_, &n), "MXExecutorNumOutputs");
    return n;
  }
  std::vector<float> GetOutput(mx_uint index) const {
    NDArrayHandle h = nullptr;
    detail::check(MXExecutorGetOutput(handle_, index, &h),
                  "MXExecutorGetOutput");
    return detail::NDHandle(h).ToVector();
  }
  std::vector<mx_uint> GetOutputShape(mx_uint index) const {
    NDArrayHandle h = nullptr;
    detail::check(MXExecutorGetOutput(handle_, index, &h),
                  "MXExecutorGetOutput");
    detail::NDHandle owned(h);
    mx_uint nd; const mx_uint* shp;
    detail::check(MXNDArrayGetShape(owned.get(), &nd, &shp),
                  "MXNDArrayGetShape");
    return std::vector<mx_uint>(shp, shp + nd);
  }

  // one in-place sgd_update over every parameter with a gradient
  // (MXImperativeInvokeByName, the reference's optimizer-op idiom)
  void SGDUpdate(float lr) const {
    char lr_str[32];
    std::snprintf(lr_str, sizeof(lr_str), "%g", lr);
    const char* keys[] = {"lr"};
    const char* vals[] = {lr_str};
    for (const auto& name : ArgNames()) {
      NDArrayHandle g = nullptr;
      detail::check(MXExecutorGetGrad(handle_, name.c_str(), &g),
                    "MXExecutorGetGrad");
      if (!g) continue;
      detail::NDHandle grad(g);
      detail::NDHandle weight;
      {
        NDArrayHandle w = nullptr;
        detail::check(MXExecutorGetArg(handle_, name.c_str(), &w),
                      "MXExecutorGetArg");
        weight = detail::NDHandle(w);
      }
      NDArrayHandle ins[2] = {weight.get(), grad.get()};
      NDArrayHandle out = weight.get();
      NDArrayHandle* outs = &out;
      int n_out = 1;
      detail::check(MXImperativeInvokeByName("sgd_update", 2, ins, &n_out,
                                             &outs, 1, keys, vals),
                    "MXImperativeInvokeByName(sgd_update)");
    }
  }

 private:
  detail::NDHandle arg_(const std::string& name) const {
    NDArrayHandle h = nullptr;
    detail::check(MXExecutorGetArg(handle_, name.c_str(), &h),
                  "MXExecutorGetArg");
    if (!h) throw TrainError("unknown argument " + name);
    return detail::NDHandle(h);
  }

  ExecutorHandle handle_ = nullptr;
};

}  // namespace mxtpu

#endif  // MXTPU_CPP_TRAINER_HPP_
