"""Package setup for mxnet_tpu (reference: python/setup.py).

The native IO runtime (src/io_native.cc) is JIT-compiled on first use and
cached under build/ (see mxnet_tpu/io_native.py), so no build step is needed
at install time; an sdist/wheel ships the C++ source alongside the package.
"""
from setuptools import find_packages, setup

setup(
    name="mxnet_tpu",
    version="0.1.0",
    description="TPU-native deep learning framework with pre-Gluon MXNet capabilities",
    packages=find_packages(include=["mxnet_tpu", "mxnet_tpu.*"]),
    python_requires=">=3.9",
    install_requires=["numpy", "jax"],
)
