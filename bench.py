"""Benchmark: the three BASELINE.md scoreboard metrics in ONE JSON line.

- ``resnet50_train_throughput`` (img/s, + MFU): synthetic fwd+bwd+SGD,
  counterpart of the reference's ``train_imagenet.py --benchmark 1``
  (example/image-classification/README.md:255-261). Baseline: 109 img/s on
  1x K80, batch 32 (README.md:149-156).
- ``lstm_tokens_per_s``: bucketed-LSTM training step at the PTB config
  (example/rnn/lstm_bucketing.py defaults: 2x200 LSTM, embed 200, batch 32,
  bucket 60).
- ``allreduce_gbps``: collective bus bandwidth via tools/bandwidth/measure
  (the reference's tools/bandwidth/measure.py KVStore metric). With one
  local chip this runs on the 8-process virtual CPU mesh (fabric field says
  so); on a pod slice the same path measures ICI.

Timing note: ``jax.block_until_ready`` is a no-op over the axon tunnel, so
every measurement syncs by fetching a scalar to host.

Probe policy (round-5 fix, tightened this round): the backend probe runs in
a FRESH subprocess per attempt with a hard per-attempt timeout, retrying
with exponential backoff. A hung *process* never heals (hence the fresh
subprocess each time), but a flapping *tunnel* does — round 4's
single-attempt-on-timeout policy forfeited the scoreboard to one transient
hang. Retries are bounded by MXNET_BENCH_PROBE_ATTEMPTS (default 4) and the
window, and a CLEAN backend-absence error ends the probe immediately — the
r05 degraded CPU runs burned 4x180 s of timeouts for a backend that was
conclusively absent. On fallback the output carries ``degraded: true`` PLUS
``onchip_artifact``, a machine-readable pointer to the latest committed
on-chip measurement so the round's real number is never lost. Knobs:
MXNET_BENCH_PROBE_TIMEOUT_S (legacy alias MXTPU_BENCH_PROBE_TIMEOUT),
MXNET_BENCH_PROBE_ATTEMPTS, MXTPU_BENCH_PROBE_WINDOW,
MXTPU_BENCH_PROBE_CODE (probe snippet, tests).

The ``fusion_patterns`` leg (docs/PERF.md §13) A/Bs the generic pattern
fusion engine off-vs-on (warm measure-and-cache verdicts) on a transformer
training step and asserts the warm arm re-tunes and retraces ZERO times.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...extras}.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

BASELINE_IMG_S = 109.0  # reference README.md:149-156, resnet-50, 1x K80, b32

# ResNet-50 @224: ~4.09 GFLOP forward per image (2*MACs); training ≈ 3x fwd
_TRAIN_FLOPS_PER_IMG = 3 * 4.09e9


def _mfu_fields(flops_per_step, step_s, dev):
    """Analytic-FLOPs MFU for one bench leg (docs/PERF.md §4/§15): the
    model's training FLOPs per step (2×MACs fwd, ×3 for fwd+bwd+update)
    over wall step time, as a fraction of the device's bf16 peak. Off-TPU
    the peak is unknown, so ``mfu`` is None — but the achieved FLOP/s rate
    still lands in the report, keeping the campaign's round-over-round
    trajectory trackable on any fabric."""
    from mxnet_tpu.device_info import bf16_peak_flops

    out = {"model_flops_per_step": int(flops_per_step),
           "model_tflops_per_s": round(flops_per_step / step_s / 1e12, 5)}
    peak = (bf16_peak_flops(dev.device_kind)
            if dev.platform not in ("cpu",) else None)
    out["mfu"] = (round(flops_per_step / step_s / peak, 4)
                  if peak else None)
    return out


def _transformer_train_flops(batch, seq, d, heads, layers, ffn, vocab):
    """Per-step analytic training FLOPs of the decoder-only zoo
    transformer: per token, the per-layer matmuls (qkv, proj, ffn up/down)
    plus the attention score/apply contractions (counted dense — the
    block-causal lowering computes ~half, which MFU deliberately does not
    credit), plus the vocab head; ×3 for training."""
    per_tok = layers * (2 * d * 3 * d       # qkv projection
                        + 2 * d * d         # output projection
                        + 2 * (d * ffn + ffn * d)   # ffn up + down
                        + 4 * seq * d)      # scores (2TD) + apply (2TD)
    per_tok += 2 * d * vocab                # lm head
    return 3 * batch * seq * per_tok


def _lstm_train_flops(batch, seq, hidden, embed, layers, vocab):
    """PTB-config LSTM: per token, the 4-gate matmuls per layer (input dim
    = embed for layer 0, hidden above) plus the vocab head; ×3 train."""
    per_tok = 2 * 4 * hidden * (hidden + embed)
    per_tok += (layers - 1) * 2 * 4 * hidden * (2 * hidden)
    per_tok += 2 * hidden * vocab
    return 3 * batch * seq * per_tok


def _recommender_train_flops(batch, embed_dim=64, dense_dim=16,
                             bottom=(128,), top=(512, 256)):
    """DLRM-style two-tower click model (models/recommender.py defaults):
    bottom MLP + top MLP matmuls per sample (embedding lookups move bytes,
    not FLOPs); ×3 train."""
    dims = (dense_dim,) + tuple(bottom) + (embed_dim,)
    mac = sum(a * b for a, b in zip(dims, dims[1:]))
    tdims = (3 * embed_dim + 1,) + tuple(top) + (1,)
    mac += sum(a * b for a, b in zip(tdims, tdims[1:]))
    return 3 * batch * 2 * mac


# stderr markers that mean the backend is DEFINITIVELY absent (jax raised
# cleanly, no tunnel involved): retrying cannot heal these, so the probe
# stops at the first one instead of burning the whole retry budget —
# the r05 degraded CPU runs paid 4×180 s of timeouts for exactly this
_PROBE_CONCLUSIVE = ("Unable to initialize backend",
                     "No visible TPU", "no TPU devices",
                     "NOT_FOUND", "failed to initialize")


def _probe_backend(window=None, timeout=None):
    """Check that the ambient JAX platform can actually initialize.

    Each attempt is a fresh subprocess with a hard per-attempt timeout (a
    hung process must cost one attempt, not the driver's whole budget);
    attempts retry with exponential backoff until either the ``window``
    expires or the attempt cap is hit — a flapping *tunnel* heals under
    retries (see module docstring), but a CLEAN backend-absence error
    (``_PROBE_CONCLUSIVE``) ends the probe immediately.

    Knobs: ``MXNET_BENCH_PROBE_TIMEOUT_S`` seconds per attempt (default
    180; legacy alias ``MXTPU_BENCH_PROBE_TIMEOUT``),
    ``MXNET_BENCH_PROBE_ATTEMPTS`` max attempts (default 4), and the
    legacy ``MXTPU_BENCH_PROBE_WINDOW`` overall wall budget (default
    720 s) — whichever limit trips first ends the probe."""
    window = float(os.environ.get("MXTPU_BENCH_PROBE_WINDOW", window or 720))
    timeout = float(os.environ.get(
        "MXNET_BENCH_PROBE_TIMEOUT_S",
        os.environ.get("MXTPU_BENCH_PROBE_TIMEOUT", timeout or 180)))
    try:
        max_attempts = max(1, int(os.environ.get(
            "MXNET_BENCH_PROBE_ATTEMPTS", "4")))
    except ValueError:
        max_attempts = 4
    code = (os.environ.get("MXTPU_BENCH_PROBE_CODE")
            or "import jax; d = jax.devices(); print(d[0].platform)")
    deadline = time.monotonic() + window
    backoff, attempt = 5.0, 0
    while True:
        attempt += 1
        conclusive = False
        try:
            out = subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                timeout=timeout, text=True,
            )
            if out.returncode == 0 and out.stdout.strip():
                if attempt > 1:
                    sys.stderr.write(
                        "bench: backend probe recovered on attempt %d\n" % attempt)
                return True
            err = out.stderr.strip()[-500:]
            conclusive = any(m in out.stderr for m in _PROBE_CONCLUSIVE)
        except subprocess.TimeoutExpired:
            err = "timed out after %gs" % timeout
        sys.stderr.write("bench: backend probe attempt %d failed: %s\n"
                         % (attempt, err))
        if conclusive:
            sys.stderr.write(
                "bench: backend absence is conclusive; not retrying\n")
            return False
        if attempt >= max_attempts:
            sys.stderr.write(
                "bench: probe attempt cap (%d) reached\n" % max_attempts)
            return False
        if time.monotonic() + backoff > deadline:
            return False
        time.sleep(backoff)
        backoff = min(backoff * 2, 120.0)


def _onchip_artifact():
    """Locate the latest committed on-chip measurement so a degraded (CPU
    fallback) bench line still points the scoreboard at the round's real TPU
    numbers. Prefers PERF_MEASURED_r*.json (builder's on-chip artifact), else
    the newest non-degraded TPU BENCH_r*.json."""
    import glob

    root = os.path.dirname(os.path.abspath(__file__))
    for pat, pick in (("PERF_MEASURED_r*.json", "perf_measured"),
                      ("BENCH_r*.json", "bench")):
        for path in sorted(glob.glob(os.path.join(root, pat)), reverse=True):
            try:
                with open(path) as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue
            if pick == "perf_measured":
                rows = rec.get("resnet50_train") or []
                if rows:
                    best = max(rows, key=lambda r: r.get("img_s", 0))
                    return {"file": os.path.basename(path),
                            "device": rec.get("device"),
                            "img_s": best.get("img_s"),
                            "mfu": best.get("mfu")}
            else:
                # driver wrapper schema: {"n", "cmd", "rc", "tail", "parsed"}
                rec = rec.get("parsed") or rec
                if (rec.get("platform") not in (None, "cpu")
                        and not rec.get("degraded") and rec.get("value")):
                    return {"file": os.path.basename(path),
                            "device": rec.get("device"),
                            "img_s": rec.get("value"),
                            "mfu": rec.get("mfu")}
    return None


def _sync(x):
    """True device barrier: fetch a scalar (see module docstring)."""
    import jax.numpy as jnp

    return np.asarray(jnp.sum(x[0].astype(jnp.float32))
                      if isinstance(x, (tuple, list)) else
                      jnp.sum(x.astype(jnp.float32)))


def _make_trainer(net, dev, batch_shapes, compute_dtype, parallel,
                  data_names=None):
    mesh = parallel.make_mesh((1,), axis_names=("data",), devices=[dev])
    trainer = parallel.SPMDTrainer(
        net, mesh, optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
        compute_dtype=compute_dtype,
        data_names=data_names or tuple(n for n in batch_shapes
                                       if "label" not in n),
        label_names=tuple(n for n in batch_shapes if "label" in n))
    data_shapes = {n: s for n, s in batch_shapes.items() if "label" not in n}
    label_shapes = {n: s for n, s in batch_shapes.items() if "label" in n}
    trainer.init_params(data_shapes, label_shapes, seed=0)
    return trainer


def _place(trainer, name, arr):
    import jax

    return jax.device_put(arr, trainer.rules.named(
        trainer.rules.batch_spec(arr.shape)))


def _fused_report(batch, image, dtype):
    """Engage status of the fused conv+BN stack at the bench's shapes,
    forward AND backward, plus the analytic per-step HBM byte model
    (docs/PERF.md §6/§6b). Pure gate/policy queries — no device work — so
    the report always reflects exactly what the timed step could engage
    under the ambient MXNET_FUSED_CONV_BN[_BWD] env and committed WINS
    table."""
    import jax.numpy as jnp

    from mxnet_tpu import fusion
    from mxnet_tpu.ops.conv_bn_bytes import resnet50_sites, step_byte_model

    dt = jnp.dtype(dtype)
    rep = {"sites": 0, "fwd_engaged": 0, "bwd_engaged": 0, "bwd_modes": {}}
    for kernel, stride, K, N, H, count, res_count in resnet50_sites(
            image=image):
        x_shape = (batch, K, H, H)
        w_shape = (N, K) + kernel
        for res_flag, cnt in ((False, count - res_count),
                              (True, res_count)):
            if not cnt:
                continue
            rep["sites"] += cnt
            if not fusion.gate(kernel, stride, x_shape, w_shape, dt, True,
                               res=res_flag):
                continue
            rep["fwd_engaged"] += cnt
            mode = fusion.bwd_mode(kernel, stride, x_shape, w_shape, dt,
                                   True, res=res_flag)
            if mode != "xla":
                rep["bwd_engaged"] += cnt
            rep["bwd_modes"][mode] = rep["bwd_modes"].get(mode, 0) + cnt
    rep["byte_model_gb"] = step_byte_model(batch, image=image,
                                           itemsize=dt.itemsize)
    return rep


def _bench_resnet50(on_tpu, models, parallel, dev):
    image = 224 if on_tpu else 64
    candidates = [512, 256, 128, 64, 32] if on_tpu else [8]
    net = models.get_symbol("resnet-50", num_classes=1000,
                            image_shape="3,%d,%d" % (image, image))
    rs = np.random.RandomState(0)
    trainer = x = y = batch = None
    for batch in candidates:
        try:
            trainer = _make_trainer(
                net, dev, {"data": (batch, 3, image, image),
                           "softmax_label": (batch,)},
                "bfloat16" if on_tpu else None, parallel)
            # feed the batch in the compute dtype (saves the on-chip fp32
            # materialization + cast; measured ~1.6% step time, docs/PERF.md)
            import jax.numpy as jnp

            x_host = rs.rand(batch, 3, image, image).astype("float32")
            if on_tpu:
                x_host = x_host.astype(jnp.bfloat16)
            x = _place(trainer, "data", x_host)
            y = _place(trainer, "softmax_label",
                       rs.randint(0, 1000, (batch,)).astype("float32"))
            for _ in range(3):
                outs = trainer.step({"data": x}, {"softmax_label": y})
            _sync(outs)
            break
        except Exception:
            if batch == candidates[-1]:
                raise
            trainer = None
    n_steps = 10 if on_tpu else 3

    def timed(tr):
        from mxnet_tpu import telemetry

        mark = telemetry.enabled()  # off by default: zero touch on the clock
        t0 = time.perf_counter()
        for _ in range(n_steps):
            outs = tr.step({"data": x}, {"softmax_label": y})
            if mark:
                telemetry.mark_step()
        _sync(outs)
        return batch * n_steps / (time.perf_counter() - t0)

    img_s = timed(trainer)
    res = {"img_s": img_s, "batch": batch, "image": image,
           "step_ms": 1000 * batch / img_s,
           "flops_per_img": _TRAIN_FLOPS_PER_IMG * (image / 224.0) ** 2}
    res.update(_mfu_fields(res["flops_per_img"] * batch,
                           batch / img_s, dev))
    try:
        res["fused_conv_bn"] = _fused_report(
            batch, image, "bfloat16" if on_tpu else "float32")
    except Exception as exc:  # the report must never sink the number
        res["fused_conv_bn"] = {"error": "%s: %s"
                                % (type(exc).__name__, exc)}

    # A/B the fused conv+BN Pallas path (docs/PERF.md §6) on the chip. The
    # WINS table may predate this device (or be empty); forcing the path
    # here measures it regardless, and the HEADLINE number is whichever
    # lowering is faster — the same per-shape decision the gate makes, at
    # whole-step granularity. Failures fall back silently with a note.
    # Skipped when the caller pinned the env to 0 (fusion off) or 1 (the
    # baseline above already ran fused — nothing to compare).
    prev_env = os.environ.get("MXNET_FUSED_CONV_BN")
    if on_tpu and (prev_env or "auto") == "auto":
        trainer = None  # release baseline params/opt state before tr2
        try:
            os.environ["MXNET_FUSED_CONV_BN"] = "1"
            tr2 = _make_trainer(
                net, dev, {"data": (batch, 3, image, image),
                           "softmax_label": (batch,)},
                "bfloat16", parallel)
            for _ in range(3):
                outs = tr2.step({"data": x}, {"softmax_label": y})
            _sync(outs)
            fused = timed(tr2)
            res["fused_img_s"] = fused
            res["fused_faster"] = bool(fused > img_s)
            if fused > img_s:
                res["img_s"] = fused
                res["step_ms"] = 1000 * batch / fused
        except Exception as exc:
            res["fused_error"] = "%s: %s" % (type(exc).__name__, exc)
        finally:
            if prev_env is None:
                os.environ.pop("MXNET_FUSED_CONV_BN", None)
            else:
                os.environ["MXNET_FUSED_CONV_BN"] = prev_env
    return res


def _bench_lstm(on_tpu, models, parallel, dev):
    """PTB-shape bucketed-LSTM training step (BASELINE config 3)."""
    batch, seq = (32, 60) if on_tpu else (8, 12)
    vocab, hidden, embed, layers = 10000, 200, 200, 2
    net = models.get_symbol("lstm", num_classes=vocab, num_embed=embed,
                            num_hidden=hidden, num_layers=layers,
                            seq_len=seq, batch_size=batch)
    rs = np.random.RandomState(0)
    # initial states are DATA (the reference feeds init_states per batch,
    # example/rnn/lstm.py provide_data), not trainable params. NOTE: their
    # leading dim is num_layers, not batch — fine on this 1-device mesh,
    # but a multi-device data mesh must not batch_spec-shard them
    shapes = {"data": (batch, seq),
              "lstm_init_h": (layers, batch, hidden),
              "lstm_init_c": (layers, batch, hidden),
              "softmax_label": (batch, seq)}
    trainer = _make_trainer(net, dev, shapes,
                            "bfloat16" if on_tpu else None, parallel,
                            data_names=("data", "lstm_init_h", "lstm_init_c"))
    data = {"data": _place(trainer, "data",
                           rs.randint(1, vocab, (batch, seq)).astype("float32")),
            "lstm_init_h": _place(trainer, "lstm_init_h",
                                  np.zeros((layers, batch, hidden), "float32")),
            "lstm_init_c": _place(trainer, "lstm_init_c",
                                  np.zeros((layers, batch, hidden), "float32"))}
    y = _place(trainer, "softmax_label",
               rs.randint(1, vocab, (batch, seq)).astype("float32"))
    for _ in range(3):
        outs = trainer.step(data, {"softmax_label": y})
    _sync(outs)
    n_steps = 20 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(n_steps):
        outs = trainer.step(data, {"softmax_label": y})
    _sync(outs)
    dt = time.perf_counter() - t0
    res = {"tokens_per_s": batch * seq * n_steps / dt, "batch": batch,
           "seq_len": seq, "step_ms": 1000 * dt / n_steps}
    res.update(_mfu_fields(
        _lstm_train_flops(batch, seq, hidden, embed, layers, vocab),
        dt / n_steps, dev))
    return res


def _bench_allreduce():
    """KVStore allreduce bandwidth (the BASELINE.md metric): push+pull
    round-trip through the dist KVStore's bucketed collective path
    (docs/PERF.md §11), 8 worker processes under tools/launch.py
    (measure.py --kvstore). The payload rides 16 keys pushed per-key with
    priorities — the schedule a real training round emits — swept over
    MXNET_KVSTORE_BUCKET_MB values; the headline is the best point and the
    report carries the whole sweep plus the engine's overlap gauge. With
    only one local chip the workers run on CPU; on a multi-host slice the
    same command measures ICI/DCN."""
    root = os.path.dirname(os.path.abspath(__file__))
    import jax

    fabric = ("%s-8proc" % jax.devices()[0].platform
              if len(jax.devices()) > 1 else "cpu-8proc")
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "MXNET_DEFAULT_CONTEXT": "cpu",
                "MXNET_TELEMETRY": "counters"})
    out = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "launch.py"), "-n", "8",
         "--launcher", "local", sys.executable,
         os.path.join(root, "tools", "bandwidth", "measure.py"),
         "--kvstore", "--sizes", "64", "--keys", "16", "--iters", "5",
         "--bucket-mb-sweep", "4,16,25", "--json"],
        capture_output=True, text=True, timeout=600, env=env, cwd=root)
    recs = []
    dec = json.JSONDecoder()
    for l in out.stdout.splitlines():
        l = l.strip()
        # workers share one stdout: tolerate interleaved/concatenated lines
        while l.startswith("{"):
            try:
                rec, end = dec.raw_decode(l)
            except ValueError:
                break
            if "busbw_gbps" in rec:
                recs.append(rec)
            l = l[end:].lstrip()
    if not recs:
        raise RuntimeError(
            "kvstore bandwidth run produced no JSON (rc=%d): %s"
            % (out.returncode, (out.stderr or out.stdout).strip()[-400:]))
    rec = max(recs, key=lambda r: r["busbw_gbps"])
    res = {"gbps": rec["busbw_gbps"], "devices": rec["devices"],
           "fabric": fabric}
    if "bucket_mb" in rec:
        res["bucket_mb"] = rec["bucket_mb"]
    if rec.get("overlap_ratio") is not None:
        res["overlap_ratio"] = rec["overlap_ratio"]
    sweep = {str(r["bucket_mb"]): r["busbw_gbps"] for r in recs
             if "bucket_mb" in r}
    if sweep:
        res["bucket_sweep"] = sweep
    # second datapoint: the XLA device-mesh allreduce (shard_map psum over a
    # single-process mesh). On a real multi-chip slice this rides ICI; with
    # only one local device it runs on an 8-device virtual CPU mesh and is
    # labeled as such. Optional — its failure must not sink the kvstore
    # number above.
    try:
        env2 = dict(os.environ)
        if len(jax.devices()) > 1:
            mesh_fabric = "%s-%ddev" % (jax.devices()[0].platform,
                                        len(jax.devices()))
        else:
            mesh_fabric = "cpu-shmem-8dev"
            env2.update({"JAX_PLATFORMS": "cpu",
                         "MXNET_DEFAULT_CONTEXT": "cpu",
                         "XLA_FLAGS": (env2.get("XLA_FLAGS", "") +
                                       " --xla_force_host_platform_device_count=8")})
        out2 = subprocess.run(
            [sys.executable, os.path.join(root, "tools", "bandwidth",
                                          "measure.py"), "--sizes", "64",
             "--json"],
            capture_output=True, text=True, timeout=600, env=env2, cwd=root)
        for l in out2.stdout.splitlines():
            if l.startswith("{"):
                res["device_mesh_gbps"] = json.loads(l)["busbw_gbps"]
                res["device_mesh_fabric"] = mesh_fabric
        if "device_mesh_gbps" not in res:
            res["device_mesh_error"] = (
                "no JSON from measure.py (rc=%d): %s"
                % (out2.returncode, (out2.stderr or out2.stdout).strip()[-300:]))
    except Exception as exc:
        res["device_mesh_error"] = "%s: %s" % (type(exc).__name__, exc)
    return res


_FUSION_BENCH_WORKER = r"""
import json, os, sys, time
import numpy as np
sys.path.insert(0, sys.argv[1])
os.environ.setdefault("MXNET_TELEMETRY", "counters")
mode, dir_binary, dir_sched, steps = (sys.argv[2], sys.argv[3], sys.argv[4],
                                      int(sys.argv[5]))
os.environ["MXNET_FUSED_PATTERNS"] = "0"  # the off-arm bind comes first
import mxnet_tpu as mx
from mxnet_tpu import fusion_tune, telemetry

B, T = 2, 512
rs = np.random.RandomState(0)


def build():
    net = mx.models.get_symbol("transformer", vocab_size=1000, model_dim=128,
                               num_heads=4, num_layers=2, seq_len=T)
    exe = net.simple_bind(mx.context.current_context(), data=(B, T),
                          softmax_label=(B, T))
    for name, arr in exe.arg_dict.items():
        if name not in ("data", "softmax_label"):
            arr[:] = (rs.rand(*arr.shape) - 0.5).astype("float32") * 0.1
    exe.arg_dict["data"][:] = rs.randint(1, 1000, (B, T)).astype("float32")
    exe.arg_dict["softmax_label"][:] = \
        rs.randint(1, 1000, (B, T)).astype("float32")
    for _ in range(2):  # compile (+ tuning, on the engine arms) + warmup
        outs = exe.forward_backward()
    np.asarray(outs[0].asnumpy())
    return exe


if mode == "cold":
    # cold-tune arm: engine on, empty cache — the first trace measures
    # each pattern site and persists the verdicts. The parent sets
    # MXNET_FUSION_TUNE_SCHEDULES per arm (0 = PR 9 binary verdicts,
    # default = schedule search); dir_binary carries the arm's cache dir.
    os.environ["MXNET_FUSED_PATTERNS"] = "auto"
    os.environ["MXNET_FUSION_TUNE_DIR"] = dir_binary
    build()
    print(json.dumps({"fusion_bench": 1, "mode": mode,
                      "tunes": telemetry.counter("fusion.tune").value}),
          flush=True)
    raise SystemExit(0)

# A/B arm (warm caches): THREE executors in one process — engine off, the
# PR 9 binary-verdict engine (warm cache tuned with SCHEDULES=0), and the
# schedule-search engine (warm cache tuned with the schedule fan-out) —
# timed in interleaved blocks so host-speed drift hits every arm equally
# (the checkpoint leg's ABBA discipline)
exe_off = build()
os.environ["MXNET_FUSED_PATTERNS"] = "auto"
os.environ["MXNET_FUSION_TUNE_SCHEDULES"] = "0"
os.environ["MXNET_FUSION_TUNE_DIR"] = dir_binary
exe_bin = build()
fusion_tune.reset()  # drop the in-process memo: next bind reads dir_sched
del os.environ["MXNET_FUSION_TUNE_SCHEDULES"]
os.environ["MXNET_FUSION_TUNE_DIR"] = dir_sched
exe_sched = build()
tunes_warmup = telemetry.counter("fusion.tune").value
pre = dict(telemetry.counters())

BLOCK, ROUNDS = max(1, steps // 4), 4
times = {"off": [], "binary": [], "sched": []}
for _ in range(ROUNDS):
    for arm, exe in (("off", exe_off), ("binary", exe_bin),
                     ("sched", exe_sched)):
        t0 = time.perf_counter()
        for _ in range(BLOCK):
            outs = exe.forward_backward()
        np.asarray(outs[0].asnumpy())
        times[arm].append((time.perf_counter() - t0) / BLOCK)
post = dict(telemetry.counters())
med = {arm: sorted(v)[len(v) // 2] for arm, v in times.items()}
# the schedule-search cache's per-site winners, for the report
schedules = {}
try:
    payload = json.load(open(fusion_tune.cache_path()))
    for key, r in payload["entries"].items():
        if r.get("engage"):
            schedules[key.split("|", 1)[0]] = {
                "lowering": r.get("lowering"),
                "schedule": r.get("schedule"),
                "schedules_searched": r.get("schedules_searched")}
except Exception:
    pass
rec = {
    "fusion_bench": 1, "mode": mode,
    "step_ms_off": round(med["off"] * 1000, 3),
    "step_ms_binary": round(med["binary"] * 1000, 3),
    "step_ms_sched": round(med["sched"] * 1000, 3),
    "tunes_warmup": tunes_warmup,
    "tunes_post_warmup": post.get("fusion.tune", 0) - pre.get("fusion.tune", 0),
    "retraces_post_warmup":
        post.get("executor.retrace", 0) - pre.get("executor.retrace", 0),
    "tune_cache_hits": post.get("fusion.tune_cache_hit", 0),
    "schedules": schedules,
    "pattern_engaged": {
        k.split("fusion.pattern_engaged.", 1)[1]: v
        for k, v in post.items()
        if k.startswith("fusion.pattern_engaged.")},
}
print(json.dumps(rec), flush=True)
"""


def _bench_fusion_patterns(dev):
    """Pattern-engine A/B leg (docs/PERF.md §13/§15): the SAME transformer
    training step under three engines, in fresh subprocesses so trace
    caches and telemetry cannot bleed:

    - ``off``    — ``MXNET_FUSED_PATTERNS=0`` baseline.
    - ``binary`` — the PR 9 binary-verdict engine: warm cache tuned with
      ``MXNET_FUSION_TUNE_SCHEDULES=0`` (default candidate only).
    - ``sched``  — the schedule-search engine (this round's tentpole):
      warm cache whose winners carry measured block/chunk schedules.

    Two cold subprocess runs tune the two caches; the warm A/B process
    binds all three executors and times them in interleaved blocks. The
    gate asserts zero re-tunes and zero post-warmup retraces on the warm
    arms — the measure-and-cache contract — and the report carries the
    per-site winning schedules plus analytic-FLOPs MFU per arm so the MFU
    campaign's trajectory is tracked round over round."""
    import tempfile

    root = os.path.dirname(os.path.abspath(__file__))
    steps = int(os.environ.get("MXTPU_BENCH_FUSION_STEPS", "12"))
    out = {}
    env_base = dict(os.environ)
    # bound the cold arms' measurement cost (schedule search multiplies
    # the candidate count); both arms tune at the same iters, so the A/B
    # stays fair
    env_base.setdefault("MXNET_FUSION_TUNE_ITERS", "4")
    with tempfile.TemporaryDirectory(prefix="mxtpu_fusion_tune") as tdir:
        dir_binary = os.path.join(tdir, "binary")
        dir_sched = os.path.join(tdir, "sched")
        script = os.path.join(tdir, "worker.py")
        with open(script, "w") as f:
            f.write(_FUSION_BENCH_WORKER)
        for mode, arm_dir, schedules in (("cold", dir_binary, "0"),
                                         ("cold", dir_sched, None),
                                         ("ab", dir_binary, None)):
            env = dict(env_base)
            if schedules is not None:
                env["MXNET_FUSION_TUNE_SCHEDULES"] = schedules
            else:
                env.pop("MXNET_FUSION_TUNE_SCHEDULES", None)
            r = subprocess.run(
                [sys.executable, script, root, mode, arm_dir, dir_sched,
                 str(steps)],
                capture_output=True, text=True, timeout=1500, cwd=root,
                env=env)
            rec = None
            for l in r.stdout.splitlines():
                if l.startswith("{") and "fusion_bench" in l:
                    rec = json.loads(l)
            if rec is None:
                raise RuntimeError(
                    "fusion bench %s arm produced no JSON (rc=%d): %s"
                    % (mode, r.returncode,
                       (r.stderr or r.stdout).strip()[-400:]))
            rec.pop("fusion_bench", None)
            rec.pop("mode", None)
            out[mode + ("" if mode == "ab" else ":" + arm_dir)] = rec
    ab = out["ab"]
    res = {
        "model": "transformer_b2_seq512_d128",
        "step_ms_off": ab["step_ms_off"],
        "step_ms_binary": ab["step_ms_binary"],
        "step_ms_sched": ab["step_ms_sched"],
        "speedup": round(ab["step_ms_off"] / ab["step_ms_sched"], 4),
        "sched_vs_binary": round(
            ab["step_ms_binary"] / ab["step_ms_sched"], 4),
        "tunes_cold_binary": out["cold:" + dir_binary]["tunes"],
        "tunes_cold_sched": out["cold:" + dir_sched]["tunes"],
        "tunes_warm": ab["tunes_warmup"] + ab["tunes_post_warmup"],
        "tune_cache_hits_warm": ab["tune_cache_hits"],
        "retraces_post_warmup": ab["retraces_post_warmup"],
        "schedules": ab["schedules"],
        "pattern_engaged": ab["pattern_engaged"],
    }
    flops = _transformer_train_flops(2, 512, 128, 4, 2, 2048, 1000)
    for arm in ("off", "binary", "sched"):
        res["mfu_" + arm] = _mfu_fields(
            flops, ab["step_ms_" + arm] / 1000.0, dev)
    res["improved"] = bool(res["speedup"] > 1.0)
    # the campaign acceptance: the schedule-search engine is no worse than
    # the binary-verdict engine (1% timer-noise band)
    res["sched_ge_binary"] = bool(
        res["step_ms_sched"] <= res["step_ms_binary"] * 1.01)
    res["zero_retune_warm"] = bool(res["tunes_warm"] == 0)
    return res


_CKPT_BENCH_WORKER = r"""
import json, os, sys, threading, time
import numpy as np
sys.path.insert(0, sys.argv[1])
os.environ.setdefault("MXNET_KVSTORE_BUCKET_MB", "1")
os.environ["MXNET_KVSTORE_UPDATE"] = "sharded"
os.environ.setdefault("MXNET_TELEMETRY", "counters")
import mxnet_tpu as mx
from mxnet_tpu import telemetry

mx.kv.create("dist_tpu_sync")  # dist.init before any JAX computation
workdir = sys.argv[2]
# a realistically-sized step (~100 ms on the CI host): the leg measures the
# checkpoint overhead a real training run would see, not the degenerate
# ratio against a sub-10ms toy step where any fixed cost looks enormous
BATCH, BATCHES, EPOCHS, DIM = 64, 15, 3, 256


def _mlp():
    s = mx.sym.Variable("data")
    s = mx.sym.FullyConnected(s, num_hidden=1024, name="fc1")
    s = mx.sym.Activation(s, act_type="relu")
    s = mx.sym.FullyConnected(s, num_hidden=512, name="fc2")
    s = mx.sym.Activation(s, act_type="relu")
    s = mx.sym.FullyConnected(s, num_hidden=10, name="fc3")
    return mx.sym.SoftmaxOutput(s, name="softmax")


def _data():
    rs = np.random.RandomState(7)
    x = rs.rand(BATCHES * BATCH, DIM).astype("float32")
    y = rs.randint(0, 10, (BATCHES * BATCH,)).astype("float32")
    return mx.io.NDArrayIter(x, y, batch_size=BATCH)


# per-epoch checkpoint cadence: epoch 0 warms the compile caches, then a
# balanced ABBA/BAAB interleave of plain (0) and checkpointing (5) epochs —
# the host's speed drifts on a timescale comparable to one PHASE, so the
# mode must alternate faster than the drift, inside ONE fit
PERIOD = 5
SCHED = [0, 0, PERIOD, PERIOD, 0, PERIOD, 0, 0, PERIOD]


def run(ckpt_dir):
    stamps = []
    g = telemetry.gauge("checkpoint.inflight")

    def cb(param):
        v = g.value  # a save submitted last round may still be in flight
        if v:
            peak["inflight"] = max(peak["inflight"], v)
        ctl = param.locals["self"]  # the ElasticFit controller
        ctl.checkpoint_period = SCHED[min(param.epoch, len(SCHED) - 1)]
        if param.epoch >= 1:  # epoch 0 is the compile warmup
            stamps.append((param.epoch, time.time()))

    mod = mx.mod.Module(_mlp(), context=mx.cpu(), fused_step=False)
    mod.fit(_data(), num_epoch=len(SCHED), kvstore="dist_tpu_sync",
            optimizer="sgd",
            optimizer_params=(("learning_rate", 0.05), ("momentum", 0.9)),
            batch_end_callback=cb,
            elastic={"checkpoint_dir": ckpt_dir,
                     "checkpoint_period": 0, "resume": False})
    per_epoch = {}
    for (e0, t0), (e1, t1) in zip(stamps, stamps[1:]):
        if e0 == e1:
            per_epoch.setdefault(e0, []).append(t1 - t0)
    med = {}
    for e, steps in per_epoch.items():
        steps.sort()
        med[e] = steps[len(steps) // 2]
    plain = [med[e] for e in med if SCHED[e] == 0]
    ckpt = [med[e] for e in med if SCHED[e] != 0]
    return (sum(plain) / len(plain), sum(ckpt) / len(ckpt))


peak = {"inflight": 0.0}
stop = threading.Event()


def _sample():
    # gentle poll (5 ms): on a small host a hot sampler would perturb the
    # very step time this leg measures; the batch callback above reads the
    # gauge at every round boundary as the deterministic backstop
    g = telemetry.gauge("checkpoint.inflight")
    while not stop.is_set():
        v = g.value
        if v:
            peak["inflight"] = max(peak["inflight"], v)
        time.sleep(0.005)


threading.Thread(target=_sample, daemon=True).start()
plain, ckpt = run(os.path.join(workdir, "ckpt"))
stop.set()
rank = int(os.environ.get("MXNET_TPU_WORKER_ID", "0"))
if rank == 0:
    print(json.dumps({
        "ckpt_bench": 1,
        "step_ms_plain": round(plain * 1000, 3),
        "step_ms_ckpt": round(ckpt * 1000, 3),
        "regression": round(ckpt / plain - 1, 4),
        "peak_inflight": peak["inflight"],
        "saves": telemetry.counter("checkpoint.saves").value,
    }), flush=True)
"""


def _bench_checkpoint():
    """Async-checkpoint overhead leg (docs/FAULT_TOLERANCE.md): ONE
    2-process sharded-update fit whose epochs alternate checkpointing off
    and every-5th-round sharded async checkpoints in a balanced ABBA/BAAB
    interleave (epoch 0 = compile warmup; host-speed drift cancels because
    the mode alternates faster than the drift). Reports the mean of the
    per-epoch median step times per mode and their regression (acceptance:
    < 10%; the snapshot is device refs + a writer thread, so the
    device→host transfer and disk I/O overlap the next steps) and the peak
    ``checkpoint.inflight`` gauge (must be > 0: the write really was in
    flight while training ran)."""
    import tempfile

    root = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "MXNET_DEFAULT_CONTEXT": "cpu"})
    with tempfile.TemporaryDirectory(prefix="mxtpu_ckpt_bench") as workdir:
        script = os.path.join(workdir, "worker.py")
        with open(script, "w") as f:
            f.write(_CKPT_BENCH_WORKER)
        out = subprocess.run(
            [sys.executable, os.path.join(root, "tools", "launch.py"),
             "-n", "2", "--launcher", "local", "--cpu-devices", "1",
             sys.executable, script, root, workdir],
            capture_output=True, text=True, timeout=600, env=env, cwd=root)
    rec = None
    for l in out.stdout.splitlines():
        if l.startswith("{") and "ckpt_bench" in l:
            rec = json.loads(l)
    if rec is None:
        raise RuntimeError("no JSON from checkpoint bench (rc=%d): %s"
                           % (out.returncode,
                              (out.stderr or out.stdout).strip()[-400:]))
    rec.pop("ckpt_bench", None)
    return rec


def _bench_serving():
    """Serving leg (docs/SERVING.md): QPS + p99 under a fixed open-loop
    load for lenet/mlp, continuous-batching-vs-batch-1 saturation speedup
    on mlp, the transformer KV-cache decode rate, the shared-prefix
    cache + speculative-decoding leg (zipf workload: hit rate, prefill
    FLOPs saved, accepted-draft rate, p50/p99 vs the prefix-off
    baseline), and the FLEET leg — a
    4-replica router run under the seeded chaos plan (kill-one + mid-run
    rollout) recording aggregate QPS / p99 / redispatches / restarts next
    to its single-replica closed-loop baseline (docs/SERVING.md §Fleet).
    Each leg runs tools/serve_bench.py in a fresh subprocess (its
    telemetry/counter deltas must not bleed into this process)."""
    root = os.path.dirname(os.path.abspath(__file__))
    legs = {
        "mlp": ["--model", "mlp", "--qps", "120", "--duration", "2",
                "--compare-batch1"],
        "lenet": ["--model", "lenet", "--qps", "40", "--duration", "2"],
        "transformer_decode": ["--model", "transformer-decode", "--qps",
                               "30", "--duration", "2", "--rows", "4",
                               "--megastep-k", "8"],
        "prefix_spec": ["--model", "transformer-decode", "--workload",
                        "zipf-prefix", "--qps", "20", "--duration", "2"],
        "fleet": ["--model", "mlp", "--fleet", "--fleet-replicas", "4",
                  "--qps", "80", "--duration", "3"],
    }
    out = {}
    for name, extra in legs.items():
        try:
            r = subprocess.run(
                [sys.executable, os.path.join(root, "tools",
                                              "serve_bench.py"),
                 "--json"] + extra,
                capture_output=True, text=True, timeout=420,
                cwd=root)
            rec = None
            for l in r.stdout.splitlines():
                if l.startswith("{"):
                    rec = json.loads(l)
            if rec is None:
                raise RuntimeError("no JSON (rc=%d): %s"
                                   % (r.returncode,
                                      (r.stderr or r.stdout).strip()[-300:]))
            keep = {k: rec.get(k) for k in
                    ("qps", "p50_ms", "p99_ms", "batch_occupancy",
                     "retraces_post_warmup", "batching_speedup",
                     "qps_single_replica_closed", "replicas",
                     "redispatches", "replica_restarts", "paged_kv",
                     "host_gap_ms", "host_gap_per_token", "host_argmax",
                     "megastep", "workload", "prefix", "spec")
                    if rec.get(k) is not None}
            if name == "fleet":
                keep["resolved"] = rec.get("resolved")
                keep["rollout_applied"] = bool(
                    (rec.get("rollout") or {}).get("applied"))
            out[name] = keep
        except Exception as exc:
            out[name] = {"error": "%s: %s" % (type(exc).__name__, exc)}
    return out


def _bench_recommender(on_tpu, models, parallel, dev):
    """Recommender leg (docs/SPARSE.md): the embedding-dominated workload
    the row-sparse subsystem opens. Three numbers:

    - ``samples_per_s`` — single-device DLRM-style train step (embedding
      lookups + MLP) through the SPMD trainer;
    - ``embedding_bytes_moved`` / ``sparse_vs_dense_wire_ratio`` — from the
      2-process sparse-vs-dense smoke (tests/nightly/dist_sparse_kvstore):
      the wire bytes the sparse KVStore round actually moved for the
      tables vs the dense-push control, weight-parity enforced inside;
    - ``autoplan`` — the 8-device plan under a budget that makes
      replicated tables infeasible: the mesh and how many tables the
      per-param search sharded over the model axis.
    """
    batch = 512 if on_tpu else 64
    net = models.get_symbol("recommender")
    rs = np.random.RandomState(0)
    shapes = {"user": (batch,), "item": (batch,), "dense": (batch, 16),
              "label": (batch,)}
    trainer = _make_trainer(net, dev, shapes,
                            "bfloat16" if on_tpu else None, parallel,
                            data_names=("user", "item", "dense"))
    data = {"user": _place(trainer, "user",
                           rs.randint(0, 65536, (batch,)).astype("float32")),
            "item": _place(trainer, "item",
                           rs.randint(0, 32768, (batch,)).astype("float32")),
            "dense": _place(trainer, "dense",
                            rs.rand(batch, 16).astype("float32"))}
    y = _place(trainer, "label",
               rs.randint(0, 2, (batch,)).astype("float32"))
    for _ in range(3):
        outs = trainer.step(data, {"label": y})
    _sync(outs)
    n_steps = 20 if on_tpu else 5
    t0 = time.perf_counter()
    for _ in range(n_steps):
        outs = trainer.step(data, {"label": y})
    _sync(outs)
    dt = time.perf_counter() - t0
    res = {"samples_per_s": round(batch * n_steps / dt, 1), "batch": batch,
           "step_ms": round(1000 * dt / n_steps, 2)}
    res.update(_mfu_fields(_recommender_train_flops(batch), dt / n_steps,
                           dev))

    # 2-proc sparse-vs-dense wire measurement (parity gated inside)
    root = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "MXNET_DEFAULT_CONTEXT": "cpu"})
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", "--cpu-devices", "1",
         sys.executable,
         os.path.join(root, "tests", "nightly", "dist_sparse_kvstore.py")],
        capture_output=True, text=True, timeout=420, env=env, cwd=root)
    rec = None
    for line in r.stdout.splitlines():
        if line.startswith("DIST_SPARSE {"):
            rec = json.loads(line[len("DIST_SPARSE "):])
    if rec is None:
        raise RuntimeError("2-proc sparse smoke produced no row (rc=%d): %s"
                           % (r.returncode,
                              (r.stderr or r.stdout).strip()[-300:]))
    res["embedding_bytes_moved"] = rec["embedding_bytes_moved"]
    res["sparse_vs_dense_wire_ratio"] = rec["sparse_vs_dense_wire_ratio"]
    res["wire_parity_max_abs_diff"] = rec["parity_max_abs_diff"]
    res["rows_pushed_2proc"] = rec["rows_pushed"]

    # the 8-device plan when replicated tables do not fit (the regime the
    # subsystem targets): the search must shard the tables, not pipeline
    from mxnet_tpu.parallel import autoplan

    plan = autoplan.plan_parallel(
        net, {"user": (64,), "item": (64,), "dense": (64, 16),
              "label": (64,)},
        types={"user": "int32", "item": "int32"}, devices=8,
        budget_gb=0.0625, label="recommender")
    res["autoplan"] = {
        "mesh": dict(plan.mesh), "feasible": plan.feasible,
        "sharded_tables": sum(
            1 for n in ("user_embed_weight", "item_embed_weight")
            if any(plan.param_specs.get(n, []))),
        "comm_vs_naive": round(
            plan.predicted["comm_bytes"] / max(1, plan.naive["comm_bytes"]),
            6),
    }
    return res


def _bench_input_pipeline(dev):
    """Double-buffered input pipeline A/B (docs/PERF.md §15): the SAME
    small-MLP ``Module.fit`` twice from identical initial weights — plain
    ``NDArrayIter`` (host slicing + transfer inline with the step) vs the
    iterator wrapped in ``io.DevicePrefetchIter`` (batch N+1 sliced,
    ``device_put`` and parked by the pump thread while step N runs).
    Records the ``io.input_bound_pct`` gauge per arm (the fraction of
    epoch wall time the fit loop spent waiting on input — it must drop
    with prefetch on) and asserts the final weights are BITWISE identical
    (device transfer preserves bits; no augment hook here)."""
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry

    def mlp():
        s = mx.sym.Variable("data")
        s = mx.sym.FullyConnected(s, num_hidden=256, name="ip_fc1")
        s = mx.sym.Activation(s, act_type="relu")
        s = mx.sym.FullyConnected(s, num_hidden=64, name="ip_fc2")
        s = mx.sym.Activation(s, act_type="relu")
        s = mx.sym.FullyConnected(s, num_hidden=10, name="ip_fc3")
        return mx.sym.SoftmaxOutput(s, name="softmax")

    rs = np.random.RandomState(11)
    batch, batches, dim = 128, 24, 128
    x = rs.rand(batches * batch, dim).astype("float32")
    y = rs.randint(0, 10, (batches * batch,)).astype("float32")
    init = {
        "ip_fc1_weight": mx.nd.array(rs.rand(256, dim).astype("f") * 0.05),
        "ip_fc1_bias": mx.nd.array(np.zeros(256, "f")),
        "ip_fc2_weight": mx.nd.array(rs.rand(64, 256).astype("f") * 0.05),
        "ip_fc2_bias": mx.nd.array(np.zeros(64, "f")),
        "ip_fc3_weight": mx.nd.array(rs.rand(10, 64).astype("f") * 0.05),
        "ip_fc3_bias": mx.nd.array(np.zeros(10, "f")),
    }

    saved = telemetry.current_override()
    telemetry.set_mode("counters")
    try:
        def run(prefetch):
            it = mx.io.NDArrayIter(x, y, batch_size=batch)
            if prefetch:
                it = mx.io.DevicePrefetchIter(it)
            stamps = []  # epoch-1 batch boundaries: epoch 0 is the
            # compile warmup, so the median inter-batch gap here is the
            # STEADY-STATE step time (the other legs' timing contract)

            def cb(param):
                if param.epoch >= 1:
                    stamps.append(time.perf_counter())

            t0 = time.perf_counter()
            mod = mx.mod.Module(mlp(), context=mx.context.current_context())
            mod.fit(it, num_epoch=2, kvstore="local",
                    arg_params=dict(init), initializer=None,
                    batch_end_callback=cb)
            wall = time.perf_counter() - t0
            args, _ = mod.get_params()
            pct = telemetry.gauge("io.input_bound_pct").value
            gaps = sorted(b - a for a, b in zip(stamps, stamps[1:]))
            step_s = gaps[len(gaps) // 2] if gaps else wall
            return pct, wall, step_s, {k: v.asnumpy()
                                       for k, v in args.items()}

        # warmup pass for BOTH arms: the two fits share this process's
        # JAX trace/compile caches, so without it the second arm would
        # inherit the first's compile warmth and the wall/step numbers
        # would measure run ORDER, not the pipeline (the fusion leg
        # avoids the same bias with fresh subprocesses)
        run(False)
        run(True)
        pct_off, wall_off, step_off, params_off = run(False)
        pct_on, wall_on, step_on, params_on = run(True)
    finally:
        telemetry.set_mode(saved)
    res = {
        "input_bound_pct_off": pct_off,
        "input_bound_pct_on": pct_on,
        "input_bound_dropped": bool(pct_on < pct_off),
        "fit_wall_s_off": round(wall_off, 3),
        "fit_wall_s_on": round(wall_on, 3),
        "step_ms_off": round(step_off * 1000, 3),
        "step_ms_on": round(step_on * 1000, 3),
        "bitwise_identical": bool(all(
            np.array_equal(params_off[k], params_on[k])
            for k in params_off)),
        "batch": batch, "batches_per_epoch": batches,
    }
    flops = 3 * batch * 2 * (dim * 256 + 256 * 64 + 64 * 10)
    res.update(_mfu_fields(flops, step_on, dev))
    return res


def _bench_autoplan():
    """Auto-parallel planner leg (docs/PARALLEL_PLANNER.md): the plan the
    cost model picks for the transformer at 8 abstract devices (predicted
    comm bytes, chosen vs naive all-dp), plus a REAL 2-process CPU fit
    (tests/nightly/autoplan_measure.py) comparing the predicted grad-sync
    bytes against the measured ``kvstore.bytes.*`` counters — the planner's
    claim to a scoreboard number is only as good as that ratio."""
    root = os.path.dirname(os.path.abspath(__file__))
    from mxnet_tpu import models
    from mxnet_tpu.parallel import autoplan

    plan = autoplan.plan_parallel(
        models.get_symbol("transformer"),
        {"data": (2, 64), "softmax_label": (2, 64)},
        types={"data": "int32"}, devices=8, label="transformer")
    rec = {
        "transformer_mesh": dict(plan.mesh),
        "transformer_pipeline_stages": plan.pipeline_stages,
        "predicted_comm_bytes": plan.predicted["comm_bytes"],
        "naive_comm_bytes": plan.naive["comm_bytes"],
        "comm_vs_naive": round(
            plan.predicted["comm_bytes"] / max(1, plan.naive["comm_bytes"]),
            4),
        "predicted_peak_bytes": plan.predicted["peak_bytes"],
        "sharded_params": sum(1 for v in plan.param_specs.values() if any(v)),
    }
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", "--cpu-devices", "1",
         sys.executable,
         os.path.join(root, "tests", "nightly", "autoplan_measure.py")],
        capture_output=True, text=True, timeout=420, env=env, cwd=root)
    measured = None
    for line in r.stdout.splitlines():
        if line.startswith("AUTOPLAN_MEASURE {"):
            measured = json.loads(line[len("AUTOPLAN_MEASURE "):])
    if measured is None:
        raise RuntimeError("2-proc measure produced no row (rc=%d): %s"
                           % (r.returncode,
                              (r.stderr or r.stdout).strip()[-300:]))
    rec["measured_2proc"] = measured
    rec["within_2x"] = bool(0.5 <= measured["ratio"] <= 2.0)
    return rec


def main():
    degraded = False
    # nothing to probe when the platform is already pinned to CPU
    if os.environ.get("JAX_PLATFORMS", "") != "cpu" and not _probe_backend():
        # ambient (axon/TPU) backend unusable — fall back to CPU so the
        # bench still records *a* number, LOUDLY marked degraded
        os.environ["JAX_PLATFORMS"] = "cpu"
        degraded = True

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from mxnet_tpu import models, parallel
    from mxnet_tpu.device_info import bf16_peak_flops

    dev = jax.devices()[0]
    on_tpu = dev.platform not in ("cpu",)

    rn = _bench_resnet50(on_tpu, models, parallel, dev)
    peak = bf16_peak_flops(dev.device_kind) if on_tpu else None
    mfu = (rn["img_s"] * rn["flops_per_img"] / peak) if peak else None

    try:
        lstm = _bench_lstm(on_tpu, models, parallel, dev)
    except Exception as exc:  # secondary metric must not sink the bench
        lstm = {"error": "%s: %s" % (type(exc).__name__, exc)}
    try:
        ar = _bench_allreduce()
    except Exception as exc:
        ar = {"error": "%s: %s" % (type(exc).__name__, exc)}
    try:
        serving = _bench_serving()
    except Exception as exc:  # the serving leg must not sink the bench
        serving = {"error": "%s: %s" % (type(exc).__name__, exc)}
    try:
        ckpt = _bench_checkpoint()
    except Exception as exc:  # nor may the checkpoint leg
        ckpt = {"error": "%s: %s" % (type(exc).__name__, exc)}
    try:
        fusion_patterns = _bench_fusion_patterns(dev)
    except Exception as exc:  # nor may the pattern-engine leg
        fusion_patterns = {"error": "%s: %s" % (type(exc).__name__, exc)}
    try:
        input_pipeline = _bench_input_pipeline(dev)
    except Exception as exc:  # nor may the input-pipeline leg
        input_pipeline = {"error": "%s: %s" % (type(exc).__name__, exc)}
    try:
        autoplan_leg = _bench_autoplan()
    except Exception as exc:  # nor may the planner leg
        autoplan_leg = {"error": "%s: %s" % (type(exc).__name__, exc)}
    try:
        recommender = _bench_recommender(on_tpu, models, parallel, dev)
    except Exception as exc:  # nor may the recommender leg
        recommender = {"error": "%s: %s" % (type(exc).__name__, exc)}

    result = {
        "metric": "resnet50_train_throughput",
        "value": round(rn["img_s"], 2),
        "unit": "img/s",
        "vs_baseline": round(rn["img_s"] / BASELINE_IMG_S, 3),
        "batch": rn["batch"],
        "image_size": rn["image"],
        "device": dev.device_kind,
        "platform": dev.platform,
        "step_ms": round(rn["step_ms"], 2),
    }
    fc = rn.get("fused_conv_bn") or {}
    result["fused_conv_bn"] = fc
    # the headline flag the scoreboard reads: did the BACKWARD fused path
    # have an engage route this run (docs/PERF.md §6b)
    result["fused_bwd_engaged"] = bool(fc.get("bwd_engaged"))
    # MXNET_TELEMETRY=counters|trace: the registry's view of the same run —
    # retraces, fused engage counts, kv bytes/step — next to the wall time
    # (docs/OBSERVABILITY.md). Off by default; the report must never sink
    # the measured number.
    try:
        from mxnet_tpu import telemetry

        if telemetry.enabled():
            result["telemetry"] = telemetry.summarize()
    except Exception as exc:
        result["telemetry_error"] = "%s: %s" % (type(exc).__name__, exc)
    if degraded:
        result["degraded"] = True  # TPU probe failed; this is a CPU number
        try:
            art = _onchip_artifact()
        except Exception:  # the pointer must never sink the measured number
            art = None
        if art:
            result["onchip_artifact"] = art  # the round's real TPU numbers
    if mfu is not None:
        result["mfu"] = round(mfu, 4)
    elif on_tpu:
        result["mfu"] = None
        result["mfu_note"] = "no bf16 peak known for %r" % dev.device_kind
    if "error" not in lstm:
        result["lstm_tokens_per_s"] = round(lstm["tokens_per_s"], 1)
        result["lstm_config"] = "b%d_seq%d_2x200" % (lstm["batch"], lstm["seq_len"])
    else:
        result["lstm_error"] = lstm["error"]
    if "error" not in ar:
        result["allreduce_gbps"] = round(ar["gbps"], 3)
        result["allreduce_fabric"] = ar["fabric"]
        if "bucket_mb" in ar:
            result["allreduce_bucket_mb"] = ar["bucket_mb"]
        if "bucket_sweep" in ar:
            result["allreduce_bucket_sweep"] = ar["bucket_sweep"]
        if "overlap_ratio" in ar:
            result["allreduce_overlap_ratio"] = ar["overlap_ratio"]
        if ar["fabric"].startswith("cpu"):
            # interpretive guard: this number is host shared-memory loopback
            # through 8 local processes — it measures the kvstore code path,
            # NOT an interconnect. ICI/DCN bandwidth requires a pod slice
            # (v5e ICI spec ~186 GB/s/link; see tools/bandwidth/measure.py).
            result["allreduce_note"] = (
                "host-loopback (no TPU fabric attached); measures the "
                "kvstore path, not interconnect bandwidth")
        if "device_mesh_gbps" in ar:
            result["allreduce_device_mesh_gbps"] = ar["device_mesh_gbps"]
            result["allreduce_device_mesh_fabric"] = ar.get(
                "device_mesh_fabric")
    else:
        result["allreduce_error"] = ar["error"]
    result["serving"] = serving
    result["recommender"] = recommender
    result["checkpoint"] = ckpt
    result["fusion_patterns"] = fusion_patterns
    result["input_pipeline"] = input_pipeline
    result["autoplan"] = autoplan_leg
    print(json.dumps(result))


if __name__ == "__main__":
    try:
        main()
    except BaseException as exc:  # always leave ONE JSON line for the driver
        import traceback

        traceback.print_exc()
        print(json.dumps({
            "metric": "resnet50_train_throughput",
            "value": None,
            "unit": "img/s",
            "vs_baseline": None,
            "error": "%s: %s" % (type(exc).__name__, exc),
        }))
        raise SystemExit(1)
