"""Benchmark: ResNet-50 synthetic-data training throughput (img/s) + MFU.

Counterpart of the reference's synthetic benchmark mode
(example/image-classification/train_imagenet.py --benchmark 1 and
benchmark_score.py): fwd + bwd + SGD update on random data, steady-state
steps/sec. Baseline: the reference's published ResNet-50 training speed of
109 img/s on 1× K80 at batch 32 (example/image-classification/README.md:149).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""
import json
import time

import numpy as np

BASELINE_IMG_S = 109.0  # reference README.md:149-156, resnet-50, 1x K80, b32

# bf16 peak FLOP/s by device kind (public spec sheets)
_PEAK = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# ResNet-50 @224: ~4.09 GFLOP forward per image (2*MACs); training ≈ 3× fwd
_TRAIN_FLOPS_PER_IMG = 3 * 4.09e9


def main():
    import jax

    from mxnet_tpu import models, parallel

    dev = jax.devices()[0]
    on_tpu = dev.platform not in ("cpu",)
    image = 224 if on_tpu else 64
    candidates = [256, 128, 64, 32] if on_tpu else [8]

    mesh = parallel.make_mesh((1,), axis_names=("data",), devices=[dev])
    net = models.get_symbol("resnet-50", num_classes=1000,
                            image_shape="3,%d,%d" % (image, image))

    trainer = x = y = None
    for batch in candidates:
        try:
            trainer = parallel.SPMDTrainer(
                net, mesh,
                optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
                compute_dtype="bfloat16" if on_tpu else None,
            )
            trainer.init_params({"data": (batch, 3, image, image)},
                                {"softmax_label": (batch,)}, seed=0)
            rs = np.random.RandomState(0)
            # pre-place the synthetic batch on device once — the benchmark
            # measures the training step, not host→device feed (the
            # reference's --benchmark 1 likewise reuses one synthetic batch)
            x = jax.device_put(
                rs.rand(batch, 3, image, image).astype("float32"),
                trainer.rules.named(trainer.rules.batch_spec((batch, 3, image, image))))
            y = jax.device_put(
                rs.randint(0, 1000, (batch,)).astype("float32"),
                trainer.rules.named(trainer.rules.batch_spec((batch,))))
            # warmup: compile + 2 steady steps
            for _ in range(3):
                outs = trainer.step({"data": x}, {"softmax_label": y})
            jax.block_until_ready(outs)
            jax.block_until_ready(trainer.params)
            break
        except Exception:  # OOM at this batch — try the next size down
            if batch == candidates[-1]:
                raise
            trainer = None
            continue

    n_steps = 10 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(n_steps):
        outs = trainer.step({"data": x}, {"softmax_label": y})
    jax.block_until_ready(outs)
    jax.block_until_ready(trainer.params)
    dt = time.perf_counter() - t0

    img_s = batch * n_steps / dt
    # scale the FLOPs model with the benched resolution (FLOPs ∝ area)
    flops_per_img = _TRAIN_FLOPS_PER_IMG * (image / 224.0) ** 2
    peak = _PEAK.get(dev.device_kind)
    mfu = (img_s * flops_per_img / peak) if peak else None

    result = {
        "metric": "resnet50_train_throughput",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "batch": batch,
        "image_size": image,
        "device": dev.device_kind,
        "steps_timed": n_steps,
        "step_ms": round(1000 * dt / n_steps, 2),
    }
    if mfu is not None:
        result["mfu"] = round(mfu, 4)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
