"""Benchmark: ResNet-50 synthetic-data training throughput (img/s) + MFU.

Counterpart of the reference's synthetic benchmark mode
(example/image-classification/train_imagenet.py --benchmark 1 and
benchmark_score.py): fwd + bwd + SGD update on random data, steady-state
steps/sec. Baseline: the reference's published ResNet-50 training speed of
109 img/s on 1× K80 at batch 32 (example/image-classification/README.md:149).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

BASELINE_IMG_S = 109.0  # reference README.md:149-156, resnet-50, 1x K80, b32

# bf16 peak FLOP/s by device kind (public spec sheets)
_PEAK = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "TPU v7": 2307e12,
}


def _peak_flops(device_kind):
    """bf16 peak for a device kind, tolerant of naming variants."""
    if device_kind in _PEAK:
        return _PEAK[device_kind]
    # longest-prefix fuzzy match ("TPU v5p slice" → "TPU v5p", …); never the
    # reverse direction — a truncated/generic kind must yield None, not a guess
    best = None
    for kind, peak in _PEAK.items():
        if device_kind.startswith(kind):
            if best is None or len(kind) > len(best[0]):
                best = (kind, peak)
    return best[1] if best else None


# ResNet-50 @224: ~4.09 GFLOP forward per image (2*MACs); training ≈ 3× fwd
_TRAIN_FLOPS_PER_IMG = 3 * 4.09e9


def _probe_backend(timeout=180):
    """Check (in a subprocess, with a hard timeout) that the ambient JAX
    platform can actually initialize. Round-2 failure mode: the preset
    ``JAX_PLATFORMS=axon`` backend either raised at init or hung forever —
    probing out-of-process means a hang costs ``timeout`` seconds instead of
    the driver's whole budget. Returns True if the ambient platform works."""
    code = "import jax; d = jax.devices(); print(d[0].platform)"
    for attempt in range(3):
        if attempt:
            time.sleep(5 * attempt)
        try:
            out = subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                timeout=timeout, text=True,
            )
            if out.returncode == 0 and out.stdout.strip():
                return True
            sys.stderr.write("bench: backend probe attempt %d failed: %s\n"
                             % (attempt, out.stderr.strip()[-500:]))
        except subprocess.TimeoutExpired:
            sys.stderr.write("bench: backend probe attempt %d timed out\n" % attempt)
            return False  # a hang won't heal by retrying in-process
    return False


def main():
    # nothing to probe when the platform is already pinned to CPU
    if os.environ.get("JAX_PLATFORMS", "") != "cpu" and not _probe_backend():
        # ambient (axon/TPU) backend unusable — fall back to CPU so the
        # bench still records *a* number plus an explicit platform note
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from mxnet_tpu import models, parallel

    dev = jax.devices()[0]
    on_tpu = dev.platform not in ("cpu",)
    image = 224 if on_tpu else 64
    candidates = [256, 128, 64, 32] if on_tpu else [8]

    mesh = parallel.make_mesh((1,), axis_names=("data",), devices=[dev])
    net = models.get_symbol("resnet-50", num_classes=1000,
                            image_shape="3,%d,%d" % (image, image))

    trainer = x = y = None
    for batch in candidates:
        try:
            trainer = parallel.SPMDTrainer(
                net, mesh,
                optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
                compute_dtype="bfloat16" if on_tpu else None,
            )
            trainer.init_params({"data": (batch, 3, image, image)},
                                {"softmax_label": (batch,)}, seed=0)
            rs = np.random.RandomState(0)
            # pre-place the synthetic batch on device once — the benchmark
            # measures the training step, not host→device feed (the
            # reference's --benchmark 1 likewise reuses one synthetic batch)
            x = jax.device_put(
                rs.rand(batch, 3, image, image).astype("float32"),
                trainer.rules.named(trainer.rules.batch_spec((batch, 3, image, image))))
            y = jax.device_put(
                rs.randint(0, 1000, (batch,)).astype("float32"),
                trainer.rules.named(trainer.rules.batch_spec((batch,))))
            # warmup: compile + 2 steady steps
            for _ in range(3):
                outs = trainer.step({"data": x}, {"softmax_label": y})
            jax.block_until_ready(outs)
            jax.block_until_ready(trainer.params)
            break
        except Exception:  # OOM at this batch — try the next size down
            if batch == candidates[-1]:
                raise
            trainer = None
            continue

    n_steps = 10 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(n_steps):
        outs = trainer.step({"data": x}, {"softmax_label": y})
    jax.block_until_ready(outs)
    jax.block_until_ready(trainer.params)
    dt = time.perf_counter() - t0

    img_s = batch * n_steps / dt
    # scale the FLOPs model with the benched resolution (FLOPs ∝ area)
    flops_per_img = _TRAIN_FLOPS_PER_IMG * (image / 224.0) ** 2
    peak = _peak_flops(dev.device_kind)
    mfu = (img_s * flops_per_img / peak) if peak else None

    result = {
        "metric": "resnet50_train_throughput",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "batch": batch,
        "image_size": image,
        "device": dev.device_kind,
        "platform": dev.platform,
        "steps_timed": n_steps,
        "step_ms": round(1000 * dt / n_steps, 2),
    }
    if mfu is not None:
        result["mfu"] = round(mfu, 4)
    elif on_tpu:
        # unknown device kind — record what we saw so the peak table can grow
        result["mfu"] = None
        result["mfu_note"] = "no bf16 peak known for device_kind=%r" % dev.device_kind
    print(json.dumps(result))


if __name__ == "__main__":
    try:
        main()
    except BaseException as exc:  # always leave ONE JSON line for the driver
        import traceback

        traceback.print_exc()
        print(json.dumps({
            "metric": "resnet50_train_throughput",
            "value": None,
            "unit": "img/s",
            "vs_baseline": None,
            "error": "%s: %s" % (type(exc).__name__, exc),
        }))
        raise SystemExit(1)
