// Shared embedded-interpreter lifecycle machinery for the C ABI shims
// (c_api.cc, predict_api.cc). Internal linkage on purpose: each .so gets
// its own copy and counter — external linkage would interpose between
// libmxtpu_c.so and libmxtpu_predict.so when a host loads both.
//
// The problem this solves (measured, not theoretical): a host that frees
// its last handle and promptly exits races the backend's in-flight
// asynchronous work (buffer-deallocation callbacks on jax's pool threads)
// against process teardown — an intermittent exit-time SIGSEGV (~15% of
// runs from a C++ host on an 8-device CPU backend). Two pieces close it:
//
//  * quiesce(): gc + a short settle sleep, run at handle-Free entry points
//    (rare, end-of-life calls) so async frees retire before the host can
//    reach exit().
//  * an exit guard: the FIRST exit handler _exit()s after flushing stdio,
//    skipping every static destructor (destructor order vs live pool
//    threads is the underlying hazard). Exit handlers run LIFO and jax
//    keeps dlopening lazily (imports, first compile), each dlopen
//    registering destructors ABOVE an earlier guard — so the guard is
//    re-armed whenever the loaded-DSO count changed, from the create/
//    forward/free entry points (not per-call hot paths).
//
// Documented tradeoff: once this library has been used, host atexit
// handlers registered BEFORE the library's latest guard re-arm are
// skipped at exit (the guard _exit()s first). Hosts that need their own
// atexit work should do it before exit() or register after their last
// mxtpu call — or, when their atexit cleanup is essential (flushing a
// database, releasing cluster locks), export MXTPU_EXIT_GUARD=0 to
// disable the guard entirely and accept the documented ~15% exit-time
// SIGSEGV risk instead (quiesce() at the Free entry points still runs
// and closes most of the window). The variable is read at every re-arm
// attempt, so setenv("MXTPU_EXIT_GUARD", "0", 1) before the first mxtpu
// call is equivalent. See docs/ENV_VARS.md.
#ifndef MXTPU_SRC_EMBED_RUNTIME_H_
#define MXTPU_SRC_EMBED_RUNTIME_H_

#include <Python.h>

#include <cstdio>
#include <cstdlib>
#include <ctime>
#include <link.h>
#include <mutex>
#include <unistd.h>

namespace mxtpu_embed {

inline std::mutex& guard_mu() {
  static std::mutex mu;
  return mu;
}

inline double monotonic_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

inline double& last_quiesce() {
  static double t = -1e9;
  return t;
}

// gc + settle sleep so the backend's async callbacks retire while the
// interpreter is fully alive. Safe from any thread (takes the GIL).
inline void quiesce() {
  if (!Py_IsInitialized()) return;
  PyGILState_STATE st = PyGILState_Ensure();
  PyRun_SimpleString(
      "import gc, time\n"
      "gc.collect()\n"
      "time.sleep(0.05)\n");
  PyGILState_Release(st);
  std::lock_guard<std::mutex> lk(guard_mu());
  last_quiesce() = monotonic_s();
}

inline int count_dsos() {
  int n = 0;
  dl_iterate_phdr([](struct dl_phdr_info*, size_t, void* p) {
    ++*static_cast<int*>(p);
    return 0;
  }, &n);
  return n;
}

// Re-arm the exit guard if new shared objects appeared since last time.
// MXTPU_EXIT_GUARD=0 opts out for hosts with essential atexit cleanup
// (see the header comment for the tradeoff).
inline void ensure_exit_guard() {
  const char* guard_env = std::getenv("MXTPU_EXIT_GUARD");
  if (guard_env && guard_env[0] == '0' && guard_env[1] == '\0') return;
  std::lock_guard<std::mutex> lk(guard_mu());
  static int last = -1;
  int n = count_dsos();
  if (n == last) return;
  last = n;
  on_exit([](int status, void*) {
    bool settled;
    {
      std::lock_guard<std::mutex> lk(guard_mu());
      settled = monotonic_s() - last_quiesce() < 2.0;
    }
    // if nothing quiesced recently (host exited without freeing handles),
    // settle now. This takes the GIL and can block behind a long-running
    // call on another thread — bounded by that call, same as any API entry.
    if (!settled) quiesce();
    fflush(stdout);
    fflush(stderr);
    _exit(status);
  }, nullptr);
}

}  // namespace mxtpu_embed

#endif  // MXTPU_SRC_EMBED_RUNTIME_H_
