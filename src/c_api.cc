// Training-side C ABI: the minimal imperative slice of the reference's
// include/mxnet/c_api.h (NDArray CRUD, MXImperativeInvoke
// [src/c_api/c_api_ndarray.cc:322], executor bind/forward/backward, KVStore
// init/push/pull) over the mxnet_tpu package. Same CPython-embedding layering
// as src/predict_api.cc: the interpreter takes the place of the reference's
// static graph-executor library; every entry point is GIL-correct.
//
// Build (see mxnet_tpu/c_api.py): g++ -std=c++17 -O2 -shared -fPIC
//   c_api.cc $(python3-config --includes) -o libmxtpu_c.so
//   $(python3-config --ldflags --embed)
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include "embed_runtime.h"

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

typedef uint32_t mx_uint;
typedef void* NDArrayHandle;
typedef void* ExecutorHandle;
typedef void* KVStoreHandle;

namespace {

std::mutex g_init_mu;
thread_local std::string g_last_error;
// storage for handle arrays returned by MXImperativeInvokeByName
thread_local std::vector<NDArrayHandle> g_invoke_outs;



void ensure_python() {
  std::lock_guard<std::mutex> lk(g_init_mu);
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    PyEval_SaveThread();
    mxtpu_embed::ensure_exit_guard();
  }
}



struct Gil {
  PyGILState_STATE st;
  Gil() { st = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(st); }
};

int fail(const std::string& msg) {
  g_last_error = msg;
  return -1;
}

int fail_from_python() {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  PyObject* s = value ? PyObject_Str(value) : nullptr;
  const char* msg = (s && PyUnicode_Check(s)) ? PyUnicode_AsUTF8(s) : nullptr;
  if (!msg) {
    PyErr_Clear();
    msg = "unknown python error";
  }
  g_last_error = msg;
  Py_XDECREF(s);
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return -1;
}

// the python-side glue lives in mxnet_tpu.c_api (bind_from_json / invoke)
PyObject* glue() {
  static PyObject* mod = nullptr;  // borrowed forever
  if (!mod) mod = PyImport_ImportModule("mxnet_tpu.c_api");
  return mod;
}

// An NDArrayHandle owns one reference to a mxnet_tpu NDArray plus a cached
// shape for MXNDArrayGetShape's borrowed-pointer contract.
struct ND {
  PyObject* arr = nullptr;
  std::vector<mx_uint> shape;
};

ND* wrap(PyObject* arr /* stolen */) {
  auto* h = new ND();
  h->arr = arr;
  return h;
}

int cache_shape(ND* h) {
  PyObject* shp = PyObject_GetAttrString(h->arr, "shape");
  if (!shp) return fail_from_python();
  h->shape.clear();
  for (Py_ssize_t i = 0; i < PyTuple_Size(shp); ++i)
    h->shape.push_back(static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(shp, i))));
  Py_DECREF(shp);
  return 0;
}

// float32 contiguous view of an NDArray's host copy -> memcpy into data
int copy_to_host(PyObject* arr, float* data, size_t size) {
  PyObject* np_arr = PyObject_CallMethod(arr, "asnumpy", nullptr);
  if (!np_arr) return fail_from_python();
  PyObject* np = PyImport_ImportModule("numpy");
  PyObject* flat = np ? PyObject_CallMethod(np, "ascontiguousarray", "Os",
                                            np_arr, "float32")
                      : nullptr;
  Py_DECREF(np_arr);
  Py_XDECREF(np);
  if (!flat) return fail_from_python();
  Py_buffer view;
  if (PyObject_GetBuffer(flat, &view, PyBUF_CONTIG_RO) != 0) {
    Py_DECREF(flat);
    return fail_from_python();
  }
  int rc = 0;
  if (static_cast<size_t>(view.len) != size * sizeof(float))
    rc = fail("MXNDArraySyncCopyToCPU: caller buffer size mismatch");
  else
    memcpy(data, view.buf, view.len);
  PyBuffer_Release(&view);
  Py_DECREF(flat);
  return rc;
}

struct Exec {
  PyObject* ex = nullptr;         // mxnet_tpu Executor
  PyObject* arg_names = nullptr;  // list[str], pinned for ListArguments
  std::vector<const char*> name_ptrs;
};

struct KV {
  PyObject* kv = nullptr;
};

PyObject* handles_to_list(int n, NDArrayHandle* hs) {
  PyObject* lst = PyList_New(n);
  for (int i = 0; i < n; ++i) {
    PyObject* a = static_cast<ND*>(hs[i])->arr;
    Py_INCREF(a);
    PyList_SET_ITEM(lst, i, a);
  }
  return lst;
}

}  // namespace

extern "C" {

const char* MXGetLastError() { return g_last_error.c_str(); }

/* ---- NDArray ---------------------------------------------------------- */

int MXNDArrayCreate(const mx_uint* shape, mx_uint ndim, int /*dev_type*/,
                    int /*dev_id*/, int /*delay_alloc*/, NDArrayHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* g = glue();
  if (!g) return fail_from_python();
  PyObject* shp = PyTuple_New(ndim);
  for (mx_uint i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(shp, i, PyLong_FromUnsignedLong(shape[i]));
  // "(O)" (not "O"): CallMethod treats a bare tuple as the full arg list
  PyObject* arr = PyObject_CallMethod(g, "zeros", "(O)", shp);
  Py_DECREF(shp);
  if (!arr) return fail_from_python();
  *out = wrap(arr);
  return 0;
}

int MXNDArrayFree(NDArrayHandle handle) {
  auto* h = static_cast<ND*>(handle);
  if (!h) return 0;
  {
    Gil gil;
    Py_XDECREF(h->arr);
  }
  delete h;
  return 0;
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const float* data,
                             size_t size) {
  auto* h = static_cast<ND*>(handle);
  if (!h) return fail("null handle");
  Gil gil;
  PyObject* mem = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<float*>(data)),
      static_cast<Py_ssize_t>(size * sizeof(float)), PyBUF_READ);
  if (!mem) return fail_from_python();
  PyObject* r = PyObject_CallMethod(glue(), "copy_from_host", "OO",
                                    h->arr, mem);
  Py_DECREF(mem);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, float* data, size_t size) {
  auto* h = static_cast<ND*>(handle);
  if (!h) return fail("null handle");
  Gil gil;
  return copy_to_host(h->arr, data, size);
}

int MXNDArrayGetShape(NDArrayHandle handle, mx_uint* out_dim,
                      const mx_uint** out_pdata) {
  auto* h = static_cast<ND*>(handle);
  if (!h) return fail("null handle");
  Gil gil;
  if (cache_shape(h) != 0) return -1;
  *out_dim = static_cast<mx_uint>(h->shape.size());
  *out_pdata = h->shape.data();
  return 0;
}

int MXNDArrayWaitAll() {
  ensure_python();
  Gil gil;
  PyObject* r = PyObject_CallMethod(glue(), "waitall", nullptr);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

/* ---- Imperative invoke ------------------------------------------------ */

int MXImperativeInvokeByName(const char* op_name, int num_inputs,
                             NDArrayHandle* inputs, int* num_outputs,
                             NDArrayHandle** outputs, int num_params,
                             const char** param_keys,
                             const char** param_vals) {
  ensure_python();
  Gil gil;
  PyObject* ins = handles_to_list(num_inputs, inputs);
  PyObject* outs = Py_None;
  Py_INCREF(Py_None);
  if (*num_outputs > 0) {
    Py_DECREF(outs);
    outs = handles_to_list(*num_outputs, *outputs);
  }
  PyObject* keys = PyList_New(num_params);
  PyObject* vals = PyList_New(num_params);
  for (int i = 0; i < num_params; ++i) {
    PyList_SET_ITEM(keys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SET_ITEM(vals, i, PyUnicode_FromString(param_vals[i]));
  }
  PyObject* res = PyObject_CallMethod(glue(), "invoke", "sOOOO", op_name,
                                      ins, keys, vals, outs);
  Py_DECREF(ins);
  Py_DECREF(keys);
  Py_DECREF(vals);
  Py_DECREF(outs);
  if (!res) return fail_from_python();
  if (*num_outputs > 0) {
    // in-place: the caller's arrays were written through out=
    Py_DECREF(res);
    return 0;
  }
  Py_ssize_t n = PyList_Size(res);
  g_invoke_outs.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* a = PyList_GET_ITEM(res, i);
    Py_INCREF(a);
    g_invoke_outs.push_back(wrap(a));
  }
  Py_DECREF(res);
  *num_outputs = static_cast<int>(n);
  *outputs = g_invoke_outs.data();
  return 0;
}

/* ---- Executor --------------------------------------------------------- */

int MXTrainExecutorCreate(const char* symbol_json, mx_uint num_inputs,
                          const char** input_keys,
                          const mx_uint* input_shape_indptr,
                          const mx_uint* input_shape_data,
                          ExecutorHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* g = glue();
  if (!g) return fail_from_python();
  PyObject* shapes = PyDict_New();
  for (mx_uint i = 0; i < num_inputs; ++i) {
    PyObject* tup = PyTuple_New(input_shape_indptr[i + 1] -
                                input_shape_indptr[i]);
    for (mx_uint j = input_shape_indptr[i], k = 0;
         j < input_shape_indptr[i + 1]; ++j, ++k)
      PyTuple_SET_ITEM(tup, k, PyLong_FromUnsignedLong(input_shape_data[j]));
    PyDict_SetItemString(shapes, input_keys[i], tup);
    Py_DECREF(tup);
  }
  PyObject* ex = PyObject_CallMethod(g, "bind_from_json", "sO", symbol_json,
                                     shapes);
  Py_DECREF(shapes);
  if (!ex) return fail_from_python();
  auto* h = new Exec();
  h->ex = ex;
  *out = h;
  mxtpu_embed::ensure_exit_guard();  // jax imports dlopened during bind
  return 0;
}

int MXExecutorForward(ExecutorHandle handle, int is_train) {
  auto* h = static_cast<Exec*>(handle);
  if (!h) return fail("null handle");
  {
    Gil gil;
    PyObject* r = PyObject_CallMethod(h->ex, "forward", "i", is_train);
    if (!r) return fail_from_python();
    Py_DECREF(r);
  }
  mxtpu_embed::ensure_exit_guard();  // first compile dlopens lazily
  return 0;
}

int MXExecutorBackward(ExecutorHandle handle, mx_uint num_head,
                       NDArrayHandle* head_grads) {
  auto* h = static_cast<Exec*>(handle);
  if (!h) return fail("null handle");
  Gil gil;
  PyObject* r;
  if (num_head == 0 || head_grads == nullptr) {
    r = PyObject_CallMethod(h->ex, "backward", nullptr);
  } else {
    PyObject* lst = handles_to_list(static_cast<int>(num_head), head_grads);
    r = PyObject_CallMethod(h->ex, "backward", "O", lst);
    Py_DECREF(lst);
  }
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXExecutorNumOutputs(ExecutorHandle handle, int* out) {
  auto* h = static_cast<Exec*>(handle);
  if (!h) return fail("null handle");
  Gil gil;
  PyObject* outs = PyObject_GetAttrString(h->ex, "outputs");
  if (!outs) return fail_from_python();
  *out = static_cast<int>(PySequence_Length(outs));
  Py_DECREF(outs);
  return 0;
}

int MXExecutorGetOutput(ExecutorHandle handle, mx_uint index,
                        NDArrayHandle* out) {
  auto* h = static_cast<Exec*>(handle);
  if (!h) return fail("null handle");
  Gil gil;
  PyObject* outs = PyObject_GetAttrString(h->ex, "outputs");
  if (!outs) return fail_from_python();
  PyObject* a = PySequence_GetItem(outs, index);  // new ref
  Py_DECREF(outs);
  if (!a) return fail_from_python();
  *out = wrap(a);
  return 0;
}

int MXExecutorListArguments(ExecutorHandle handle, mx_uint* out_size,
                            const char*** out_names) {
  auto* h = static_cast<Exec*>(handle);
  if (!h) return fail("null handle");
  Gil gil;
  if (!h->arg_names) {
    h->arg_names = PyObject_CallMethod(glue(), "arg_names", "O", h->ex);
    if (!h->arg_names) return fail_from_python();
    h->name_ptrs.clear();
    for (Py_ssize_t i = 0; i < PyList_Size(h->arg_names); ++i)
      h->name_ptrs.push_back(
          PyUnicode_AsUTF8(PyList_GET_ITEM(h->arg_names, i)));
  }
  *out_size = static_cast<mx_uint>(h->name_ptrs.size());
  *out_names = h->name_ptrs.data();
  return 0;
}

static int get_from_dict(Exec* h, const char* method, const char* name,
                         NDArrayHandle* out) {
  PyObject* a = PyObject_CallMethod(glue(), method, "Os", h->ex, name);
  if (!a) return fail_from_python();
  if (a == Py_None) {  // e.g. grad of a no-grad input
    Py_DECREF(a);
    *out = nullptr;
    return 0;
  }
  *out = wrap(a);
  return 0;
}

int MXExecutorGetArg(ExecutorHandle handle, const char* name,
                     NDArrayHandle* out) {
  auto* h = static_cast<Exec*>(handle);
  if (!h) return fail("null handle");
  Gil gil;
  return get_from_dict(h, "get_arg", name, out);
}

int MXExecutorGetGrad(ExecutorHandle handle, const char* name,
                      NDArrayHandle* out) {
  auto* h = static_cast<Exec*>(handle);
  if (!h) return fail("null handle");
  Gil gil;
  return get_from_dict(h, "get_grad", name, out);
}

int MXExecutorFree(ExecutorHandle handle) {
  auto* h = static_cast<Exec*>(handle);
  if (!h) return 0;
  {
    Gil gil;
    Py_XDECREF(h->ex);
    Py_XDECREF(h->arg_names);
  }
  delete h;
  mxtpu_embed::quiesce();
  mxtpu_embed::ensure_exit_guard();
  return 0;
}

/* ---- KVStore ---------------------------------------------------------- */

int MXKVStoreCreate(const char* type, KVStoreHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* g = glue();
  if (!g) return fail_from_python();
  PyObject* kv = PyObject_CallMethod(g, "kv_create", "s", type);
  if (!kv) return fail_from_python();
  auto* h = new KV();
  h->kv = kv;
  *out = h;
  mxtpu_embed::ensure_exit_guard();
  return 0;
}

static int kv_call(KVStoreHandle handle, const char* method, mx_uint num,
                   const int* keys, NDArrayHandle* vals) {
  auto* h = static_cast<KV*>(handle);
  if (!h) return fail("null handle");
  Gil gil;
  PyObject* pykeys = PyList_New(num);
  for (mx_uint i = 0; i < num; ++i)
    PyList_SET_ITEM(pykeys, i, PyLong_FromLong(keys[i]));
  PyObject* pyvals = handles_to_list(static_cast<int>(num), vals);
  PyObject* r = PyObject_CallMethod(glue(), method, "OOO", h->kv, pykeys,
                                    pyvals);
  Py_DECREF(pykeys);
  Py_DECREF(pyvals);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXKVStoreInit(KVStoreHandle handle, mx_uint num, const int* keys,
                  NDArrayHandle* vals) {
  return kv_call(handle, "kv_init", num, keys, vals);
}

int MXKVStorePush(KVStoreHandle handle, mx_uint num, const int* keys,
                  NDArrayHandle* vals, int /*priority*/) {
  return kv_call(handle, "kv_push", num, keys, vals);
}

int MXKVStorePull(KVStoreHandle handle, mx_uint num, const int* keys,
                  NDArrayHandle* outs, int /*priority*/) {
  return kv_call(handle, "kv_pull", num, keys, outs);
}

int MXKVStoreFree(KVStoreHandle handle) {
  auto* h = static_cast<KV*>(handle);
  if (!h) return 0;
  {
    Gil gil;
    Py_XDECREF(h->kv);
  }
  delete h;
  mxtpu_embed::quiesce();
  mxtpu_embed::ensure_exit_guard();
  return 0;
}

}  // extern "C"
