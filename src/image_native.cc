// Native image pipeline: threaded JPEG decode + augment + batch assembly.
//
// TPU-native counterpart of the reference's C++ image IO
// (src/io/iter_image_recordio_2.cc:559 ImageRecordIOParser2 and
// src/io/image_aug_default.cc DefaultImageAugmenter): a reader thread streams
// RecordIO image records through an optional shuffling reservoir; decode
// workers JPEG/PNG-decode (libjpeg/libpng directly — no hidden thread
// pools: OpenCV's internal parallel runtime deadlocks under concurrent
// caller threads in some environments), resize / crop / mirror / normalize,
// and emit CHW float samples; the caller drains batches through ctypes
// (mxnet_tpu/image_native.py). All of it runs off the Python GIL — the
// feeding rate the MFU target needs cannot come from PIL threads.
//
// Build: g++ -std=c++17 -O3 -shared -fPIC -pthread image_native.cc
//        -o libmxtpu_image.so -ljpeg -lpng
#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

#include <csetjmp>

#include <jpeglib.h>
#include <png.h>

namespace {

// ------------------------------------------------------------ decode/resize
// Minimal HWC-RGB image container; all augment math is hand-rolled single
// passes (thread-safe by construction, SIMD-friendly inner loops).
struct Image {
  int h = 0, w = 0;
  std::vector<uint8_t> px;  // h*w*3, RGB
  uint8_t* row(int y) { return px.data() + static_cast<size_t>(y) * w * 3; }
  const uint8_t* row(int y) const {
    return px.data() + static_cast<size_t>(y) * w * 3;
  }
};

struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jmp;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  longjmp(reinterpret_cast<JpegErr*>(cinfo->err)->jmp, 1);
}

bool decode_jpeg(const uint8_t* buf, size_t n, Image* out) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jmp)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, n);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;  // grayscale/CMYK upconvert for free
  jpeg_start_decompress(&cinfo);
  out->h = cinfo.output_height;
  out->w = cinfo.output_width;
  out->px.resize(3u * out->h * out->w);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* rowp = out->row(cinfo.output_scanline);
    jpeg_read_scanlines(&cinfo, &rowp, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

bool decode_png(const uint8_t* buf, size_t n, Image* out) {
  png_image img;
  memset(&img, 0, sizeof(img));
  img.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(&img, buf, n)) return false;
  img.format = PNG_FORMAT_RGB;
  out->h = img.height;
  out->w = img.width;
  out->px.resize(PNG_IMAGE_SIZE(img));
  if (!png_image_finish_read(&img, nullptr, out->px.data(), 0, nullptr)) {
    png_image_free(&img);
    return false;
  }
  return true;
}

bool decode_any(const uint8_t* buf, size_t n, Image* out) {
  if (n >= 2 && buf[0] == 0xFF && buf[1] == 0xD8) return decode_jpeg(buf, n, out);
  if (n >= 8 && buf[0] == 0x89 && buf[1] == 'P') return decode_png(buf, n, out);
  // unknown magic: try jpeg then png
  return decode_jpeg(buf, n, out) || decode_png(buf, n, out);
}

// bilinear resize, HWC RGB u8 (one pass; per-row x-weights precomputed)
void resize_bilinear(const Image& src, int nh, int nw, Image* dst) {
  dst->h = nh;
  dst->w = nw;
  dst->px.resize(3u * nh * nw);
  const double sy = nh > 1 ? double(src.h - 1) / (nh - 1) : 0.0;
  const double sx = nw > 1 ? double(src.w - 1) / (nw - 1) : 0.0;
  std::vector<int> x0s(nw);
  std::vector<float> fxs(nw);
  for (int x = 0; x < nw; ++x) {
    double v = x * sx;
    int x0 = static_cast<int>(v);
    if (x0 > src.w - 2) x0 = src.w - 2 < 0 ? 0 : src.w - 2;
    x0s[x] = x0;
    fxs[x] = static_cast<float>(v - x0);
  }
  for (int y = 0; y < nh; ++y) {
    double v = y * sy;
    int y0 = static_cast<int>(v);
    if (y0 > src.h - 2) y0 = src.h - 2 < 0 ? 0 : src.h - 2;
    float fy = static_cast<float>(v - y0);
    const uint8_t* r0 = src.row(y0);
    const uint8_t* r1 = src.row(src.h > 1 ? y0 + 1 : y0);
    uint8_t* dr = dst->row(y);
    for (int x = 0; x < nw; ++x) {
      const uint8_t* p00 = r0 + 3 * x0s[x];
      const uint8_t* p01 = p00 + (src.w > 1 ? 3 : 0);
      const uint8_t* p10 = r1 + 3 * x0s[x];
      const uint8_t* p11 = p10 + (src.w > 1 ? 3 : 0);
      float fx = fxs[x];
      for (int c = 0; c < 3; ++c) {
        float top = p00[c] + fx * (p01[c] - p00[c]);
        float bot = p10[c] + fx * (p11[c] - p10[c]);
        dr[3 * x + c] = static_cast<uint8_t>(top + fy * (bot - top) + 0.5f);
      }
    }
  }
}

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;
constexpr size_t kIRHeaderBytes = 24;  // <IfQQ: flag, label, id, id2

struct RawRecord {
  std::vector<char> bytes;
  uint64_t seq = 0;
};

// Per-sample augment record for bbox-aware consumers (ImageDetIter):
// {pre-crop W, pre-crop H, crop x0, crop y0, mirror, true label length}.
// Detection boxes are normalized to the ORIGINAL image; aspect-preserving
// resizes keep normalized coords, so the consumer only needs the crop
// geometry + mirror flag to transform them (the reference did the box math
// in C++, src/io/image_det_aug_default.cc — here pixels stay native and the
// 5-float box transform stays in Python).
constexpr int kAugFloats = 6;

struct Sample {
  std::vector<float> data;    // C*H*W
  std::vector<float> label;   // label_width
  float aug[kAugFloats] = {0, 0, 0, 0, 0, 0};
  bool ok = false;            // false = decode failed; consumer skips seq
};

struct Pipeline {
  // config
  std::string path;
  int workers = 4;
  int batch = 32;
  int out_h = 224, out_w = 224;
  int resize = 0;          // resize shorter side first (0 = off)
  bool rand_crop = false;
  bool rand_mirror = false;
  float mean[3] = {0, 0, 0};
  float stdv[3] = {1, 1, 1};
  int label_width = 1;
  uint64_t seed = 0;
  int shuffle_buf = 0;     // >0: reservoir size for pseudo-shuffle

  // state
  FILE* fp = nullptr;
  std::thread reader;
  std::vector<std::thread> decoders;
  std::mutex mu;
  std::condition_variable cv_put, cv_get, cv_out;
  std::deque<RawRecord> inq;          // reader -> decoders
  std::vector<RawRecord> reservoir;   // shuffle mode
  std::map<uint64_t, Sample> outq;    // seq -> sample (reorder buffer)
  uint64_t next_seq = 0;              // next seq the reader will assign
  uint64_t next_out = 0;              // next seq the consumer will emit
  size_t in_capacity = 256;
  size_t out_capacity = 0;            // set to 4 * batch
  bool reader_done = false;
  bool stopping = false;
  std::atomic<int> in_flight{0};      // popped from inq, not yet in outq
  std::atomic<long> decode_errors{0};
  std::atomic<int> file_error{0};     // corrupt framing mid-file
  std::atomic<int> wstate[64] = {};   // per-worker phase (hang triage)
  std::vector<uint64_t> offsets;      // record offsets from the .idx

  bool producers_exhausted_locked() const {
    return reader_done && inq.empty() && reservoir.empty() &&
           in_flight.load() == 0;
  }

  // ------------------------------------------------------------- reader
  bool read_record(RawRecord* out) {
    uint32_t header[2];
    size_t got = fread(header, sizeof(uint32_t), 2, fp);
    if (got == 0 && feof(fp)) return false;  // clean end of file
    if (got != 2 || header[0] != kMagic) {
      // mid-file corruption is NOT an EOF: flag it so the consumer can
      // raise instead of silently truncating every epoch
      file_error.store(1);
      return false;
    }
    uint64_t n = header[1] & kLenMask;
    out->bytes.resize(n);
    if (n && fread(out->bytes.data(), 1, n, fp) != n) {
      file_error.store(1);
      return false;
    }
    uint64_t pad = (4 - n % 4) % 4;
    if (pad) fseek(fp, static_cast<long>(pad), SEEK_CUR);
    return true;
  }

  void reader_loop() {
    // Sequence ids assign OUTPUT order at dispatch time, so the consumer
    // sees record order when unshuffled and the permutation/reservoir order
    // when shuffled, independent of decode completion order.
    std::mt19937_64 rng(seed ^ 0x9e3779b97f4a7c15ull);
    RawRecord rec;
    // full-permutation shuffle when the .idx gave us record offsets: visit
    // offsets in a fresh random order each epoch (the Python path's
    // semantics); without an idx the reservoir below approximates it
    std::vector<uint64_t> order;
    if (shuffle_buf > 0 && !offsets.empty()) {
      order = offsets;
      for (size_t i = order.size(); i > 1; --i)
        std::swap(order[i - 1], order[rng() % i]);
    }
    size_t oi = 0;
    while (true) {
      if (!order.empty()) {
        if (oi >= order.size()) break;
        fseek(fp, static_cast<long>(order[oi++]), SEEK_SET);
      }
      if (!read_record(&rec)) break;
      bool use_reservoir = shuffle_buf > 0 && order.empty();
      std::unique_lock<std::mutex> lk(mu);
      if (use_reservoir && reservoir.size() <
              static_cast<size_t>(shuffle_buf)) {
        reservoir.push_back(std::move(rec));
        cv_get.notify_all();  // consumer shares cv_get; notify_one could
                              // wake it instead of a decoder and be lost
        continue;
      }
      cv_put.wait(lk, [&] { return inq.size() < in_capacity || stopping; });
      if (stopping) break;
      if (use_reservoir) {
        // swap a random reservoir slot out to the decode queue
        size_t k = rng() % reservoir.size();
        reservoir[k].seq = next_seq++;
        inq.push_back(std::move(reservoir[k]));
        reservoir[k] = std::move(rec);
      } else {
        rec.seq = next_seq++;
        inq.push_back(std::move(rec));
      }
      cv_get.notify_all();
    }
    std::lock_guard<std::mutex> lk(mu);
    // drain the reservoir tail in (already random) order
    for (auto& r : reservoir) {
      r.seq = next_seq++;
      inq.push_back(std::move(r));
    }
    reservoir.clear();
    reader_done = true;
    cv_get.notify_all();
  }

  // ------------------------------------------------------------ decoders
  bool augment_one(const RawRecord& rec, std::mt19937_64* rng, Sample* out) {
    if (rec.bytes.size() <= kIRHeaderBytes) return false;
    uint32_t flag;
    float scalar_label;
    memcpy(&flag, rec.bytes.data(), 4);
    memcpy(&scalar_label, rec.bytes.data() + 4, 4);
    const char* payload = rec.bytes.data() + kIRHeaderBytes;
    size_t payload_n = rec.bytes.size() - kIRHeaderBytes;

    out->label.assign(static_cast<size_t>(label_width), 0.f);
    size_t label_len = 1;
    if (flag > 0) {
      size_t lab_bytes = static_cast<size_t>(flag) * 4;
      if (payload_n < lab_bytes) return false;
      size_t n = std::min<size_t>(label_width, flag);
      memcpy(out->label.data(), payload, n * 4);
      label_len = n;
      payload += lab_bytes;
      payload_n -= lab_bytes;
    } else {
      out->label[0] = scalar_label;
    }

    Image img;
    if (!decode_any(reinterpret_cast<const uint8_t*>(payload), payload_n,
                    &img) || img.h < 1 || img.w < 1)
      return false;

    // resize shorter side (ResizeAug), keeping aspect
    if (resize > 0 && std::min(img.h, img.w) != resize) {
      double sc = static_cast<double>(resize) /
                  static_cast<double>(std::min(img.h, img.w));
      Image tmp;
      resize_bilinear(img, std::max(1, int(img.h * sc + 0.5)),
                      std::max(1, int(img.w * sc + 0.5)), &tmp);
      img = std::move(tmp);
    }
    // guarantee crop feasibility (ForceResizeAug fallback)
    if (img.h < out_h || img.w < out_w) {
      Image tmp;
      resize_bilinear(img, std::max(img.h, out_h), std::max(img.w, out_w),
                      &tmp);
      img = std::move(tmp);
    }
    int max_y = img.h - out_h, max_x = img.w - out_w;
    int y0, x0;
    if (rand_crop) {
      y0 = max_y ? static_cast<int>((*rng)() % (max_y + 1)) : 0;
      x0 = max_x ? static_cast<int>((*rng)() % (max_x + 1)) : 0;
    } else {  // center crop
      y0 = max_y / 2;
      x0 = max_x / 2;
    }
    bool mirror = rand_mirror && ((*rng)() & 1);
    out->aug[0] = static_cast<float>(img.w);
    out->aug[1] = static_cast<float>(img.h);
    out->aug[2] = static_cast<float>(x0);
    out->aug[3] = static_cast<float>(y0);
    out->aug[4] = mirror ? 1.f : 0.f;
    out->aug[5] = static_cast<float>(label_len);

    // RGB HWC u8 crop -> CHW float with mean/std, one fused pass
    out->data.resize(3u * out_h * out_w);
    const size_t plane = static_cast<size_t>(out_h) * out_w;
    float inv[3] = {1.f / stdv[0], 1.f / stdv[1], 1.f / stdv[2]};
    for (int y = 0; y < out_h; ++y) {
      const uint8_t* srow = img.row(y0 + y) + 3 * x0;
      float* d0 = out->data.data() + static_cast<size_t>(y) * out_w;
      for (int x = 0; x < out_w; ++x) {
        int sx = mirror ? (out_w - 1 - x) : x;
        const uint8_t* px = srow + 3 * sx;
        d0[x] = (px[0] - mean[0]) * inv[0];
        d0[x + plane] = (px[1] - mean[1]) * inv[1];
        d0[x + 2 * plane] = (px[2] - mean[2]) * inv[2];
      }
    }
    return true;
  }

  void decode_loop(int wid) {
    std::mt19937_64 rng(seed + 0x1000 + wid);
    while (true) {
      RawRecord rec;
      {
        wstate[wid & 63] = 1;  // waiting for input
        std::unique_lock<std::mutex> lk(mu);
        cv_get.wait(lk, [&] {
          return !inq.empty() || reader_done || stopping;
        });
        if (stopping) return;
        if (inq.empty()) {
          if (reader_done && reservoir.empty()) return;
          continue;
        }
        rec = std::move(inq.front());
        inq.pop_front();
        in_flight.fetch_add(1);
        cv_put.notify_one();
      }
      Sample s;
      wstate[wid & 63] = 2;  // decoding
      s.ok = augment_one(rec, &rng, &s);
      if (!s.ok) decode_errors.fetch_add(1);
      {
        wstate[wid & 63] = 3;  // waiting for output window
        std::unique_lock<std::mutex> lk(mu);
        // admission is by sequence WINDOW, not buffer size: a size gate
        // deadlocks once the buffer fills with seqs ahead while the worker
        // holding next_out waits for space. seq < next_out + capacity
        // always admits the consumer's next sample and still bounds memory.
        // Failed samples (skip markers, empty) are admitted unconditionally.
        cv_out.wait(lk, [&] {
          return rec.seq < next_out + out_capacity || !s.ok || stopping;
        });
        if (stopping) { in_flight.fetch_sub(1); return; }
        outq.emplace(rec.seq, std::move(s));
        in_flight.fetch_sub(1);
        wstate[wid & 63] = 4;  // pushed
        cv_get.notify_all();  // consumer may be waiting on this seq
      }
    }
  }

  // ------------------------------------------------------------- lifecycle
  void start() {
    stopping = false;
    reader_done = false;
    in_flight = 0;
    out_capacity = static_cast<size_t>(4) * batch;
    reader = std::thread([this] { reader_loop(); });
    decoders.clear();
    for (int i = 0; i < workers; ++i)
      decoders.emplace_back([this, i] { decode_loop(i); });
  }

  void stop() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stopping = true;
      cv_put.notify_all();
      cv_get.notify_all();
      cv_out.notify_all();
    }
    if (reader.joinable()) reader.join();
    for (auto& t : decoders)
      if (t.joinable()) t.join();
    decoders.clear();
    std::lock_guard<std::mutex> lk(mu);
    inq.clear();
    reservoir.clear();
    outq.clear();
    next_seq = 0;
    next_out = 0;
  }
};

}  // namespace

extern "C" {

void* mximg_open(const char* rec_path, const char* idx_path, int num_workers,
                 int batch_size, int out_h, int out_w, int resize,
                 int rand_crop, int rand_mirror, float mean_r, float mean_g,
                 float mean_b, float std_r, float std_g, float std_b,
                 int label_width, int shuffle_buf, unsigned long long seed) {
  FILE* fp = fopen(rec_path, "rb");
  if (!fp) return nullptr;
  auto* p = new Pipeline();
  if (idx_path && idx_path[0]) {
    // "key\toffset" per line (MXIndexedRecordIO / tools/im2rec format);
    // offsets enable the per-epoch full-permutation shuffle
    FILE* fi = fopen(idx_path, "r");
    if (fi) {
      char line[256];
      while (fgets(line, sizeof(line), fi)) {
        unsigned long long key, off;
        if (sscanf(line, "%llu %llu", &key, &off) == 2)
          p->offsets.push_back(off);
      }
      fclose(fi);
    }
  }
  p->path = rec_path;
  p->fp = fp;
  p->workers = std::max(1, num_workers);
  p->batch = std::max(1, batch_size);
  p->out_h = out_h;
  p->out_w = out_w;
  p->resize = resize;
  p->rand_crop = rand_crop != 0;
  p->rand_mirror = rand_mirror != 0;
  p->mean[0] = mean_r; p->mean[1] = mean_g; p->mean[2] = mean_b;
  p->stdv[0] = std_r; p->stdv[1] = std_g; p->stdv[2] = std_b;
  p->label_width = std::max(1, label_width);
  p->shuffle_buf = shuffle_buf;
  p->seed = seed;
  p->start();
  return p;
}

// Fills up to batch_size samples IN RECORD ORDER; returns the count
// (0 = epoch exhausted). ``aug`` (optional, batch x 6 floats) receives each
// sample's augment record {W, H, x0, y0, mirror, label_len} for bbox-aware
// consumers.
static int next_batch_impl(void* handle, float* data, float* labels,
                           float* aug) {
  auto* p = static_cast<Pipeline*>(handle);
  const size_t img_f = 3u * p->out_h * p->out_w;
  int got = 0;
  while (got < p->batch) {
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv_get.wait(lk, [&] {
      return p->outq.count(p->next_out) > 0 ||
             (p->producers_exhausted_locked() && p->outq.empty()) ||
             p->stopping;
    });
    if (p->stopping) break;
    auto it = p->outq.find(p->next_out);
    if (it == p->outq.end()) break;  // exhausted
    Sample s = std::move(it->second);
    p->outq.erase(it);
    ++p->next_out;
    // notify_all: with several decoders parked on cv_out, waking an
    // arbitrary one can leave the decoder holding the new window slot
    // asleep while the woken one re-waits -> deadlock
    p->cv_out.notify_all();
    lk.unlock();
    if (!s.ok) continue;  // corrupt record: skip its slot
    memcpy(data + static_cast<size_t>(got) * img_f, s.data.data(),
           img_f * sizeof(float));
    memcpy(labels + static_cast<size_t>(got) * p->label_width,
           s.label.data(), p->label_width * sizeof(float));
    if (aug)
      memcpy(aug + static_cast<size_t>(got) * kAugFloats, s.aug,
             kAugFloats * sizeof(float));
    ++got;
  }
  return got;
}

int mximg_next_batch(void* handle, float* data, float* labels) {
  return next_batch_impl(handle, data, labels, nullptr);
}

int mximg_next_batch_aug(void* handle, float* data, float* labels,
                         float* aug) {
  return next_batch_impl(handle, data, labels, aug);
}

// Rewind for the next epoch (new reader/decoder generation, new sample order
// when shuffling: reseed with an epoch counter via `epoch`).
void mximg_reset(void* handle, int epoch) {
  auto* p = static_cast<Pipeline*>(handle);
  p->stop();
  fseek(p->fp, 0, SEEK_SET);
  p->seed = p->seed * 0x100000001b3ull + static_cast<uint64_t>(epoch) + 1;
  p->start();
}

// Diagnostic: dump internal state to stderr (used by hang triage).
void mximg_debug_state(void* handle) {
  auto* p = static_cast<Pipeline*>(handle);
  std::lock_guard<std::mutex> lk(p->mu);
  fprintf(stderr,
          "[mximg] inq=%zu reservoir=%zu outq=%zu next_seq=%llu next_out=%llu"
          " in_flight=%d reader_done=%d stopping=%d\n",
          p->inq.size(), p->reservoir.size(), p->outq.size(),
          (unsigned long long)p->next_seq, (unsigned long long)p->next_out,
          p->in_flight.load(), (int)p->reader_done, (int)p->stopping);
  for (int i = 0; i < p->workers && i < 64; ++i)
    fprintf(stderr, "[mximg] worker %d state=%d\n", i, p->wstate[i].load());
  if (!p->outq.empty())
    fprintf(stderr, "[mximg] outq first=%llu last=%llu\n",
            (unsigned long long)p->outq.begin()->first,
            (unsigned long long)p->outq.rbegin()->first);
}

long mximg_decode_errors(void* handle) {
  return static_cast<Pipeline*>(handle)->decode_errors.load();
}

int mximg_file_error(void* handle) {
  return static_cast<Pipeline*>(handle)->file_error.load();
}

void mximg_close(void* handle) {
  auto* p = static_cast<Pipeline*>(handle);
  p->stop();
  fclose(p->fp);
  delete p;
}

}  // extern "C"
