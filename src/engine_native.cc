// Native dependency engine: async host-side scheduler with var-based
// read/write dependency tracking.
//
// TPU-native counterpart of the reference's engine layer
// (include/mxnet/engine.h:37-229, src/engine/threaded_engine.{h,cc},
// threaded_engine_perdevice.cc). On TPU the *device* scheduling job — stream
// ordering, kernel overlap — belongs to XLA/PJRT async dispatch, so this
// engine schedules the HOST side of the runtime: data-pipeline stages,
// checkpoint writes, callback fans, anything expressed as "run fn when these
// vars' pending writes drain". The dependency discipline matches the
// reference: readers of a var run concurrently between writes, writers
// serialize in push order (threaded_engine.h ThreadedVar AppendRead/Write).
//
// Differences by design, not omission: no per-device worker pools (host work
// only — one pool; device pools are XLA's), no FnProperty/priority lanes
// (XLA orders device work by data dependency), vars are int64 handles not
// pointers (ctypes-friendly ABI).
//
// Scheduling model: each var keeps a FIFO of pending ops. An op is eligible
//   - as a reader of v: no running writer on v and nothing but readers ahead
//     of it in v's queue;
//   - as a writer of v: v fully idle and the op is at v's queue head.
// An op runs when eligible on ALL its vars; claiming removes it from every
// queue and marks it running, so per-var eligibility is monotone until claim
// (new pushes only append). Completion re-scans affected queues.
//
// Exported C ABI (ctypes, see mxnet_tpu/engine.py):
//   mxeng_create(num_workers) -> handle
//   mxeng_new_var(h) -> var id
//   mxeng_push(h, fn, arg, const_vars*, n_const, mut_vars*, n_mut)
//   mxeng_wait_for_var(h, var)
//   mxeng_wait_for_all(h)
//   mxeng_pending(h) -> number of unfinished ops
//   mxeng_destroy(h)

#include <condition_variable>
#include <cstdint>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

typedef void (*OpFn)(void*);

struct Op {
  OpFn fn;
  void* arg;
  std::vector<int64_t> const_vars;
  std::vector<int64_t> mut_vars;

  bool reads(int64_t v) const {
    for (int64_t c : const_vars)
      if (c == v) return true;
    return false;
  }
};

struct Var {
  std::deque<Op*> queue;   // pending ops, program order
  int running_readers = 0;
  bool writer_running = false;

  bool idle() const {
    return queue.empty() && running_readers == 0 && !writer_running;
  }
};

class Engine {
 public:
  explicit Engine(int num_workers) {
    if (num_workers < 1) num_workers = 1;
    for (int i = 0; i < num_workers; ++i)
      workers_.emplace_back([this] { WorkerLoop(); });
  }

  ~Engine() {
    WaitForAll();
    {
      std::unique_lock<std::mutex> lk(mu_);
      shutdown_ = true;
      ready_cv_.notify_all();
    }
    for (auto& t : workers_) t.join();
  }

  int64_t NewVar() {
    std::unique_lock<std::mutex> lk(mu_);
    int64_t id = next_var_++;
    vars_.emplace(id, std::make_unique<Var>());
    return id;
  }

  void Push(OpFn fn, void* arg, const int64_t* cvars, int nc,
            const int64_t* mvars, int nm) {
    auto* op = new Op{fn, arg, {}, {}};
    // dedup; a var both read and mutated counts as mutated only (the
    // reference's CheckDuplicate rejects overlap; we resolve it)
    op->mut_vars.reserve(nm);
    for (int i = 0; i < nm; ++i) {
      bool dup = false;
      for (int64_t seen : op->mut_vars)
        if (seen == mvars[i]) { dup = true; break; }
      if (!dup) op->mut_vars.push_back(mvars[i]);
    }
    op->const_vars.reserve(nc);
    for (int i = 0; i < nc; ++i) {
      bool dup = false;
      for (int64_t seen : op->mut_vars)
        if (seen == cvars[i]) { dup = true; break; }
      for (int64_t seen : op->const_vars)
        if (seen == cvars[i]) { dup = true; break; }
      if (!dup) op->const_vars.push_back(cvars[i]);
    }
    std::unique_lock<std::mutex> lk(mu_);
    ++pending_;
    for (int64_t v : op->const_vars) GetVar(v)->queue.push_back(op);
    for (int64_t v : op->mut_vars) GetVar(v)->queue.push_back(op);
    TryClaim(op);
  }

  void WaitForVar(int64_t var) {
    std::unique_lock<std::mutex> lk(mu_);
    Var* v = GetVar(var);
    done_cv_.wait(lk, [&] { return v->idle(); });
  }

  void WaitForAll() {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [&] { return pending_ == 0; });
  }

  int64_t Pending() {
    std::unique_lock<std::mutex> lk(mu_);
    return pending_;
  }

 private:
  Var* GetVar(int64_t id) {
    auto it = vars_.find(id);
    if (it == vars_.end())
      it = vars_.emplace(id, std::make_unique<Var>()).first;
    return it->second.get();
  }

  // mu_ held. Eligibility of `op` on one of its vars.
  bool Eligible(int64_t vid, Op* op) {
    Var* v = GetVar(vid);
    if (v->writer_running) return false;
    bool as_reader = op->reads(vid);
    if (!as_reader && v->running_readers > 0) return false;
    for (Op* q : v->queue) {
      if (q == op) return true;           // nothing blocking ahead
      if (!as_reader) return false;       // writers claim only from the head
      if (!q->reads(vid)) return false;   // a writer is queued ahead
    }
    return false;  // op not queued on this var (claimed elsewhere) — bug guard
  }

  // mu_ held. Claim + enqueue to ready if eligible everywhere.
  void TryClaim(Op* op) {
    for (int64_t vid : op->const_vars)
      if (!Eligible(vid, op)) return;
    for (int64_t vid : op->mut_vars)
      if (!Eligible(vid, op)) return;
    for (int64_t vid : op->const_vars) {
      Var* v = GetVar(vid);
      ++v->running_readers;
      Remove(v, op);
    }
    for (int64_t vid : op->mut_vars) {
      Var* v = GetVar(vid);
      v->writer_running = true;
      Remove(v, op);
    }
    ready_.push_back(op);
    ready_cv_.notify_one();
  }

  static void Remove(Var* v, Op* op) {
    for (auto it = v->queue.begin(); it != v->queue.end(); ++it)
      if (*it == op) {
        v->queue.erase(it);
        return;
      }
  }

  // mu_ held. After a var's state change, walk its queue: try the leading
  // run of readers (each may be blocked elsewhere — skipping is safe, queue
  // order between readers is free), stop at the first writer, trying it
  // only if it heads the queue.
  void RescanVar(int64_t vid) {
    Var* v = GetVar(vid);
    // snapshot: TryClaim mutates the queue while we walk
    std::vector<Op*> snapshot(v->queue.begin(), v->queue.end());
    for (Op* q : snapshot) {
      if (q->reads(vid)) {
        TryClaim(q);
      } else {
        TryClaim(q);
        break;  // ops behind a queued writer stay blocked on this var
      }
    }
  }

  void OnComplete(Op* op) {
    std::unique_lock<std::mutex> lk(mu_);
    for (int64_t vid : op->const_vars) --GetVar(vid)->running_readers;
    for (int64_t vid : op->mut_vars) GetVar(vid)->writer_running = false;
    for (int64_t vid : op->const_vars) RescanVar(vid);
    for (int64_t vid : op->mut_vars) RescanVar(vid);
    --pending_;
    delete op;
    done_cv_.notify_all();
  }

  void WorkerLoop() {
    for (;;) {
      Op* op;
      {
        std::unique_lock<std::mutex> lk(mu_);
        ready_cv_.wait(lk, [&] { return shutdown_ || !ready_.empty(); });
        if (shutdown_ && ready_.empty()) return;
        op = ready_.front();
        ready_.pop_front();
      }
      op->fn(op->arg);
      OnComplete(op);
    }
  }

  std::mutex mu_;
  std::condition_variable ready_cv_, done_cv_;
  std::deque<Op*> ready_;
  std::unordered_map<int64_t, std::unique_ptr<Var>> vars_;
  std::vector<std::thread> workers_;
  int64_t next_var_ = 1;
  int64_t pending_ = 0;
  bool shutdown_ = false;
};

}  // namespace

extern "C" {

void* mxeng_create(int num_workers) { return new Engine(num_workers); }

int64_t mxeng_new_var(void* h) { return static_cast<Engine*>(h)->NewVar(); }

void mxeng_push(void* h, void (*fn)(void*), void* arg, const int64_t* cvars,
                int nc, const int64_t* mvars, int nm) {
  static_cast<Engine*>(h)->Push(fn, arg, cvars, nc, mvars, nm);
}

void mxeng_wait_for_var(void* h, int64_t var) {
  static_cast<Engine*>(h)->WaitForVar(var);
}

void mxeng_wait_for_all(void* h) { static_cast<Engine*>(h)->WaitForAll(); }

int64_t mxeng_pending(void* h) { return static_cast<Engine*>(h)->Pending(); }

void mxeng_destroy(void* h) { delete static_cast<Engine*>(h); }

}  // extern "C"
