// Native IO runtime: RecordIO reader, MNIST idx parser, threaded prefetcher.
//
// TPU-native counterpart of the reference's C++ IO stack (src/io/: RecordIO
// framing via dmlc-core, iter_mnist.cc:241 MNISTIter, iter_prefetcher.h:28
// PrefetcherIter). The device side needs none of this — PJRT owns transfers —
// but the host side still wants the file parsing and read-ahead off the
// Python thread, which is exactly what this library does: a producer thread
// fills a bounded queue of records while Python consumes them through ctypes.
//
// Build: g++ -std=c++17 -O2 -shared -fPIC -pthread io_native.cc -o libmxtpu_io.so
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

struct Record {
  char* data;
  uint64_t size;
};

// ---------------------------------------------------------------- RecordIO
struct RecordIOReader {
  FILE* fp;
};

bool read_one_record(FILE* fp, Record* out) {
  uint32_t header[2];
  if (fread(header, sizeof(uint32_t), 2, fp) != 2) return false;
  if (header[0] != kMagic) return false;
  uint64_t n = header[1] & kLenMask;
  char* buf = static_cast<char*>(malloc(n ? n : 1));
  if (n && fread(buf, 1, n, fp) != n) {
    free(buf);
    return false;
  }
  uint64_t pad = (4 - n % 4) % 4;
  if (pad) fseek(fp, static_cast<long>(pad), SEEK_CUR);
  out->data = buf;
  out->size = n;
  return true;
}

// --------------------------------------------------------------- Prefetcher
struct Prefetcher {
  FILE* fp = nullptr;
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  std::deque<Record> queue;
  size_t capacity = 16;
  bool eof = false;
  bool stop = false;

  void run() {
    Record rec;
    while (true) {
      if (!read_one_record(fp, &rec)) break;
      std::unique_lock<std::mutex> lk(mu);
      cv_put.wait(lk, [&] { return queue.size() < capacity || stop; });
      if (stop) {
        free(rec.data);
        break;
      }
      queue.push_back(rec);
      cv_get.notify_one();
    }
    std::lock_guard<std::mutex> lk(mu);
    eof = true;
    cv_get.notify_all();
  }
};

}  // namespace

extern "C" {

// ---- plain sequential reader ----
void* mxio_recordio_open(const char* path) {
  FILE* fp = fopen(path, "rb");
  if (!fp) return nullptr;
  auto* r = new RecordIOReader{fp};
  return r;
}

int mxio_recordio_next(void* handle, char** data, uint64_t* size) {
  auto* r = static_cast<RecordIOReader*>(handle);
  Record rec;
  if (!read_one_record(r->fp, &rec)) return 0;
  *data = rec.data;
  *size = rec.size;
  return 1;
}

void mxio_recordio_close(void* handle) {
  auto* r = static_cast<RecordIOReader*>(handle);
  fclose(r->fp);
  delete r;
}

// ---- threaded prefetcher ----
void* mxio_prefetch_open(const char* path, int capacity) {
  FILE* fp = fopen(path, "rb");
  if (!fp) return nullptr;
  auto* p = new Prefetcher();
  p->fp = fp;
  if (capacity > 0) p->capacity = static_cast<size_t>(capacity);
  p->worker = std::thread([p] { p->run(); });
  return p;
}

int mxio_prefetch_next(void* handle, char** data, uint64_t* size) {
  auto* p = static_cast<Prefetcher*>(handle);
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv_get.wait(lk, [&] { return !p->queue.empty() || p->eof; });
  if (p->queue.empty()) return 0;
  Record rec = p->queue.front();
  p->queue.pop_front();
  p->cv_put.notify_one();
  *data = rec.data;
  *size = rec.size;
  return 1;
}

void mxio_prefetch_close(void* handle) {
  auto* p = static_cast<Prefetcher*>(handle);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
    p->cv_put.notify_all();
  }
  if (p->worker.joinable()) p->worker.join();
  for (auto& rec : p->queue) free(rec.data);
  fclose(p->fp);
  delete p;
}

void mxio_free(void* ptr) { free(ptr); }

// ---- MNIST idx format (iter_mnist.cc ReadInt/LoadImg layout) ----
// Returns 1 on success; fills dims[0..ndim) and a malloc'd byte buffer.
int mxio_idx_read(const char* path, unsigned char** out, uint64_t* size,
                  int* ndim, int64_t* dims) {
  FILE* fp = fopen(path, "rb");
  if (!fp) return 0;
  unsigned char magic[4];
  if (fread(magic, 1, 4, fp) != 4 || magic[0] != 0 || magic[1] != 0) {
    fclose(fp);
    return 0;
  }
  int n = magic[3];
  if (n > 4) {
    fclose(fp);
    return 0;
  }
  uint64_t total = 1;
  for (int i = 0; i < n; ++i) {
    unsigned char b[4];
    if (fread(b, 1, 4, fp) != 4) {
      fclose(fp);
      return 0;
    }
    dims[i] = (int64_t(b[0]) << 24) | (int64_t(b[1]) << 16) |
              (int64_t(b[2]) << 8) | int64_t(b[3]);
    total *= static_cast<uint64_t>(dims[i]);
  }
  unsigned char* buf = static_cast<unsigned char*>(malloc(total ? total : 1));
  if (total && fread(buf, 1, total, fp) != total) {
    free(buf);
    fclose(fp);
    return 0;
  }
  fclose(fp);
  *out = buf;
  *size = total;
  *ndim = n;
  return 1;
}

}  // extern "C"
