// C predict ABI: the reference's c_predict_api.h surface over the TPU-native
// predictor (reference: include/mxnet/c_predict_api.h:1-210,
// src/c_api/c_predict_api.cc).
//
// Design: the compute path is XLA behind mxnet_tpu.predictor.Predictor; this
// shim embeds CPython and exposes the stable C symbols an application (or
// another language binding) links against — the same layering the reference
// used, with the interpreter taking the place of the static graph executor
// library. Every entry point is GIL-correct and usable from any thread.
//
// Build (see mxnet_tpu/predict_api.py): g++ -std=c++17 -O2 -shared -fPIC
//   predict_api.cc $(python3-config --includes) -o libmxtpu_predict.so
//   $(python3-config --ldflags --embed)
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include "embed_runtime.h"

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

typedef void* PredictorHandle;
typedef uint32_t mx_uint;

namespace {

std::mutex g_init_mu;
thread_local std::string g_last_error;

struct Pred {
  PyObject* predictor = nullptr;   // mxnet_tpu.predictor.Predictor
  PyObject* staged = nullptr;      // dict of inputs set via MXPredSetInput
  // creation arguments, retained so MXPredReshape can build an INDEPENDENT
  // predictor (a shared one would mutate under the old handle)
  PyObject* symbol_json = nullptr;
  PyObject* param_bytes = nullptr;
  PyObject* output_names = nullptr;
  // one cached fetch: GetOutputShape-then-GetOutput is the canonical call
  // sequence and must not copy device->host twice
  long cached_index = -1;
  std::vector<mx_uint> out_shape;
  std::vector<float> out_data;
};

PyObject* np_module() {
  static PyObject* np = nullptr;  // borrowed forever (interned)
  if (!np) np = PyImport_ImportModule("numpy");
  return np;
}

// Fetch output `index` into the handle's cache (caller holds the GIL).
int fetch_output(Pred* p, mx_uint index) {
  if (p->cached_index == static_cast<long>(index)) return 0;
  PyObject* out = PyObject_CallMethod(p->predictor, "get_output", "I", index);
  if (!out) return -1;
  PyObject* np = np_module();
  PyObject* flat = np ? PyObject_CallMethod(
      np, "ascontiguousarray", "Os", out, "float32") : nullptr;
  PyObject* shp = PyObject_GetAttrString(out, "shape");
  Py_DECREF(out);
  if (!flat || !shp) {
    Py_XDECREF(flat);
    Py_XDECREF(shp);
    return -1;
  }
  p->out_shape.clear();
  for (Py_ssize_t i = 0; i < PyTuple_Size(shp); ++i)
    p->out_shape.push_back(static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(shp, i))));
  Py_DECREF(shp);
  Py_buffer view;
  if (PyObject_GetBuffer(flat, &view, PyBUF_CONTIG_RO) != 0) {
    Py_DECREF(flat);
    return -1;
  }
  p->out_data.resize(static_cast<size_t>(view.len) / sizeof(float));
  memcpy(p->out_data.data(), view.buf, view.len);
  PyBuffer_Release(&view);
  Py_DECREF(flat);
  p->cached_index = static_cast<long>(index);
  return 0;
}



void ensure_python() {
  std::lock_guard<std::mutex> lk(g_init_mu);
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);  // the interpreter lives for the process lifetime
    PyEval_SaveThread();  // release the GIL so PyGILState_Ensure works
    mxtpu_embed::ensure_exit_guard();
  }
}



struct Gil {
  PyGILState_STATE st;
  Gil() { st = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(st); }
};

int fail_from_python() {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  PyObject* s = value ? PyObject_Str(value) : nullptr;
  const char* msg = (s && PyUnicode_Check(s)) ? PyUnicode_AsUTF8(s) : nullptr;
  if (!msg) {
    PyErr_Clear();  // PyUnicode_AsUTF8 may fail on unencodable text
    msg = "unknown python error";
  }
  g_last_error = msg;
  Py_XDECREF(s);
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return -1;
}

int fail(const std::string& msg) {
  g_last_error = msg;
  return -1;
}

// float32 C-order ndarray copy of `data` with the given shape
PyObject* make_array(const float* data, const std::vector<Py_ssize_t>& shape) {
  PyObject* np = np_module();
  if (!np) return nullptr;
  Py_ssize_t n = 1;
  for (auto d : shape) n *= d;
  PyObject* mem = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<float*>(data)),
      n * static_cast<Py_ssize_t>(sizeof(float)), PyBUF_READ);
  if (!mem) return nullptr;
  PyObject* flat = PyObject_CallMethod(np, "frombuffer", "Os", mem, "float32");
  Py_DECREF(mem);
  if (!flat) return nullptr;
  PyObject* shp = PyTuple_New(shape.size());
  for (size_t i = 0; i < shape.size(); ++i)
    PyTuple_SET_ITEM(shp, i, PyLong_FromSsize_t(shape[i]));
  PyObject* shaped = PyObject_CallMethod(flat, "reshape", "O", shp);
  Py_DECREF(flat);
  Py_DECREF(shp);
  if (!shaped) return nullptr;
  PyObject* owned = PyObject_CallMethod(shaped, "copy", nullptr);  // own memory
  Py_DECREF(shaped);
  return owned;
}

// Build a Predictor instance from (json, params, shapes-dict, outputs).
PyObject* new_predictor(PyObject* json, PyObject* params, PyObject* shapes,
                        PyObject* output_names) {
  PyObject* mod = PyImport_ImportModule("mxnet_tpu.predictor");
  if (!mod) return nullptr;
  PyObject* cls = PyObject_GetAttrString(mod, "Predictor");
  Py_DECREF(mod);
  if (!cls) return nullptr;
  PyObject* kwargs = PyDict_New();
  PyDict_SetItemString(kwargs, "output_names", output_names);
  PyObject* args = Py_BuildValue("(OOO)", json, params, shapes);
  PyObject* predictor = PyObject_Call(cls, args, kwargs);
  Py_DECREF(args);
  Py_DECREF(kwargs);
  Py_DECREF(cls);
  return predictor;
}

int create_impl(const char* symbol_json_str, const void* param_bytes,
                int param_size, mx_uint num_input_nodes,
                const char** input_keys, const mx_uint* input_shape_indptr,
                const mx_uint* input_shape_data, mx_uint num_output_nodes,
                const char** output_keys, PredictorHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* shapes = PyDict_New();
  for (mx_uint i = 0; i < num_input_nodes; ++i) {
    PyObject* tup = PyTuple_New(input_shape_indptr[i + 1] -
                                input_shape_indptr[i]);
    for (mx_uint j = input_shape_indptr[i], k = 0;
         j < input_shape_indptr[i + 1]; ++j, ++k)
      PyTuple_SET_ITEM(tup, k, PyLong_FromUnsignedLong(input_shape_data[j]));
    PyDict_SetItemString(shapes, input_keys[i], tup);
    Py_DECREF(tup);
  }
  PyObject* params = PyBytes_FromStringAndSize(
      static_cast<const char*>(param_bytes), param_size);
  PyObject* outputs = Py_None;
  Py_INCREF(Py_None);
  if (num_output_nodes > 0) {
    Py_DECREF(outputs);
    outputs = PyList_New(num_output_nodes);
    for (mx_uint i = 0; i < num_output_nodes; ++i)
      PyList_SET_ITEM(outputs, i, PyUnicode_FromString(output_keys[i]));
  }
  PyObject* json = PyUnicode_FromString(symbol_json_str);
  PyObject* predictor = new_predictor(json, params, shapes, outputs);
  Py_DECREF(shapes);
  if (!predictor) {
    Py_DECREF(json);
    Py_DECREF(params);
    Py_DECREF(outputs);
    return fail_from_python();
  }

  auto* p = new Pred();
  p->predictor = predictor;
  p->staged = PyDict_New();
  p->symbol_json = json;        // retained for MXPredReshape
  p->param_bytes = params;
  p->output_names = outputs;
  *out = p;
  mxtpu_embed::ensure_exit_guard();  // jax imports dlopened during create
  return 0;
}

}  // namespace

extern "C" {

const char* MXGetLastError() { return g_last_error.c_str(); }

int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int /*dev_type*/, int /*dev_id*/,
                 mx_uint num_input_nodes, const char** input_keys,
                 const mx_uint* input_shape_indptr,
                 const mx_uint* input_shape_data, PredictorHandle* out) {
  return create_impl(symbol_json_str, param_bytes, param_size,
                     num_input_nodes, input_keys, input_shape_indptr,
                     input_shape_data, 0, nullptr, out);
}

int MXPredCreatePartialOut(const char* symbol_json_str,
                           const void* param_bytes, int param_size,
                           int /*dev_type*/, int /*dev_id*/,
                           mx_uint num_input_nodes, const char** input_keys,
                           const mx_uint* input_shape_indptr,
                           const mx_uint* input_shape_data,
                           mx_uint num_output_nodes,
                           const char** output_keys, PredictorHandle* out) {
  return create_impl(symbol_json_str, param_bytes, param_size,
                     num_input_nodes, input_keys, input_shape_indptr,
                     input_shape_data, num_output_nodes, output_keys, out);
}

int MXPredSetInput(PredictorHandle handle, const char* key,
                   const float* data, mx_uint size) {
  auto* p = static_cast<Pred*>(handle);
  if (!p) return fail("null handle");
  Gil gil;
  // shape comes from the predictor's bound input spec; the flat size must
  // match it (the reference's contract: shape fixed at create time)
  PyObject* shapes = PyObject_GetAttrString(p->predictor, "input_shapes");
  if (!shapes) return fail_from_python();
  PyObject* shp = PyDict_GetItemString(shapes, key);  // borrowed
  if (!shp) {
    Py_DECREF(shapes);
    return fail(std::string("unknown input key: ") + key);
  }
  std::vector<Py_ssize_t> dims;
  Py_ssize_t want = 1;
  for (Py_ssize_t i = 0; i < PySequence_Length(shp); ++i) {
    PyObject* d = PySequence_GetItem(shp, i);
    dims.push_back(PyLong_AsSsize_t(d));
    want *= dims.back();
    Py_DECREF(d);
  }
  Py_DECREF(shapes);
  if (want != static_cast<Py_ssize_t>(size))
    return fail("MXPredSetInput: size mismatch for '" + std::string(key) +
                "'");
  PyObject* arr = make_array(data, dims);
  if (!arr) return fail_from_python();
  PyDict_SetItemString(p->staged, key, arr);
  Py_DECREF(arr);
  return 0;
}

int MXPredForward(PredictorHandle handle) {
  auto* p = static_cast<Pred*>(handle);
  if (!p) return fail("null handle");
  Gil gil;
  PyObject* fwd = PyObject_GetAttrString(p->predictor, "forward");
  if (!fwd) return fail_from_python();
  PyObject* empty = PyTuple_New(0);
  PyObject* r = PyObject_Call(fwd, empty, p->staged);
  Py_DECREF(empty);
  Py_DECREF(fwd);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  p->cached_index = -1;  // new forward invalidates the output cache
  mxtpu_embed::ensure_exit_guard();  // first compile dlopens lazily
  return 0;
}

int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint** shape_data, mx_uint* shape_ndim) {
  auto* p = static_cast<Pred*>(handle);
  if (!p) return fail("null handle");
  Gil gil;
  if (fetch_output(p, index) != 0) return fail_from_python();
  *shape_data = p->out_shape.data();
  *shape_ndim = static_cast<mx_uint>(p->out_shape.size());
  return 0;
}

int MXPredGetOutput(PredictorHandle handle, mx_uint index, float* data,
                    mx_uint size) {
  auto* p = static_cast<Pred*>(handle);
  if (!p) return fail("null handle");
  Gil gil;
  if (fetch_output(p, index) != 0) return fail_from_python();
  if (p->out_data.size() != size)
    return fail("MXPredGetOutput: caller buffer size mismatch");
  memcpy(data, p->out_data.data(), size * sizeof(float));
  return 0;
}

int MXPredReshape(PredictorHandle handle, mx_uint num_input_nodes,
                  const char** input_keys, const mx_uint* input_shape_indptr,
                  const mx_uint* input_shape_data, PredictorHandle* out) {
  auto* p = static_cast<Pred*>(handle);
  if (!p) return fail("null handle");
  Gil gil;
  PyObject* shapes = PyDict_New();
  for (mx_uint i = 0; i < num_input_nodes; ++i) {
    PyObject* tup = PyTuple_New(input_shape_indptr[i + 1] -
                                input_shape_indptr[i]);
    for (mx_uint j = input_shape_indptr[i], k = 0;
         j < input_shape_indptr[i + 1]; ++j, ++k)
      PyTuple_SET_ITEM(tup, k, PyLong_FromUnsignedLong(input_shape_data[j]));
    PyDict_SetItemString(shapes, input_keys[i], tup);
    Py_DECREF(tup);
  }
  // a fully INDEPENDENT predictor for the new shapes: sharing the old
  // Python object would mutate the old handle's executor underneath it
  PyObject* predictor = new_predictor(p->symbol_json, p->param_bytes,
                                      shapes, p->output_names);
  Py_DECREF(shapes);
  if (!predictor) return fail_from_python();
  auto* q = new Pred();
  q->predictor = predictor;
  q->staged = PyDict_New();
  q->symbol_json = p->symbol_json;
  Py_INCREF(q->symbol_json);
  q->param_bytes = p->param_bytes;
  Py_INCREF(q->param_bytes);
  q->output_names = p->output_names;
  Py_INCREF(q->output_names);
  *out = q;
  return 0;
}

int MXPredFree(PredictorHandle handle) {
  auto* p = static_cast<Pred*>(handle);
  if (!p) return 0;
  {
    Gil gil;
    Py_XDECREF(p->predictor);
    Py_XDECREF(p->staged);
    Py_XDECREF(p->symbol_json);
    Py_XDECREF(p->param_bytes);
    Py_XDECREF(p->output_names);
  }
  delete p;
  mxtpu_embed::quiesce();
  mxtpu_embed::ensure_exit_guard();
  return 0;
}

}  // extern "C"
