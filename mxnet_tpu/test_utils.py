"""Testing utilities (reference: python/mxnet/test_utils.py, 905 LoC).

The reference's core oracles, reproduced for the TPU build:
  * ``check_numeric_gradient`` — central finite differences vs the executor's
    fused-XLA backward (reference test_utils.py check_numeric_gradient).
  * ``check_symbolic_forward`` / ``check_symbolic_backward`` — outputs/grads
    vs expected numpy arrays.
  * ``check_consistency`` — same graph at different dtypes (the reference
    compared cpu-vs-gpu; with one XLA backend the meaningful axis is
    fp32-vs-bf16, the TPU fast path).
"""
from __future__ import annotations

import numpy as np

from .context import cpu, current_context
from .ndarray import array, zeros

__all__ = [
    "default_context",
    "same",
    "reldiff",
    "assert_almost_equal",
    "rand_ndarray",
    "random_arrays",
    "numeric_grad",
    "check_numeric_gradient",
    "check_symbolic_forward",
    "check_symbolic_backward",
    "check_consistency",
]

_rng = np.random.RandomState(1234)


def default_context():
    return current_context()


def same(a, b):
    return np.array_equal(a, b)


def reldiff(a, b):
    diff = np.sum(np.abs(a - b))
    norm = np.sum(np.abs(a)) + np.sum(np.abs(b))
    if diff == 0:
        return 0
    return diff / norm


def assert_almost_equal(a, b, threshold=None, rtol=1e-5, atol=1e-20, names=("a", "b")):
    if threshold is not None:
        rd = reldiff(np.asarray(a), np.asarray(b))
        if rd > threshold:
            raise AssertionError("reldiff %g > %g between %s and %s" % (rd, threshold, *names))
        return
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol)


def rand_ndarray(shape, dtype=np.float32, scale=1.0):
    return array(_rng.uniform(-scale, scale, shape).astype(dtype))


def random_arrays(*shapes):
    arrays = [_rng.randn(*s).astype(np.float32) for s in shapes]
    return arrays[0] if len(arrays) == 1 else arrays


def _as_location(sym, location):
    names = sym.list_arguments()
    if isinstance(location, dict):
        return {k: (v if isinstance(v, np.ndarray) else np.asarray(v)) for k, v in location.items()}
    return {n: (v if isinstance(v, np.ndarray) else np.asarray(v)) for n, v in zip(names, location)}


def _bind(sym, location, aux_states=None, grad_req="write", ctx=None):
    from . import executor

    ctx = ctx or current_context()
    args = {k: array(v) for k, v in location.items()}
    grads = {k: zeros(v.shape, dtype=np.asarray(v).dtype) for k, v in location.items()
             if grad_req != "null" and np.issubdtype(np.asarray(v).dtype, np.floating)}
    auxs = {k: array(v) for k, v in (aux_states or {}).items()}
    return executor.bind(sym, ctx, args, args_grad=grads or None,
                         grad_req=grad_req if grads else "null", aux_states=auxs)


def numeric_grad(executor, location, aux_states=None, eps=1e-4, use_forward_train=True):
    """Central finite differences over the executor's forward (reference:
    test_utils.py numeric_grad)."""
    approx_grads = {}
    for name, arr in location.items():
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        grad = np.zeros_like(arr, dtype=np.float64)
        flat = arr.reshape(-1)
        gflat = grad.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            executor.arg_dict[name][:] = arr
            fp = sum(o.asnumpy().astype(np.float64).sum()
                     for o in executor.forward(is_train=use_forward_train))
            flat[i] = orig - eps
            executor.arg_dict[name][:] = arr
            fm = sum(o.asnumpy().astype(np.float64).sum()
                     for o in executor.forward(is_train=use_forward_train))
            flat[i] = orig
            executor.arg_dict[name][:] = arr
            gflat[i] = (fp - fm) / (2 * eps)
        approx_grads[name] = grad.astype(arr.dtype)
    return approx_grads


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           check_eps=1e-2, grad_nodes=None, ctx=None):
    """Verify the executor's backward against finite differences
    (reference: test_utils.py check_numeric_gradient). The implicit head
    gradient is ones (total-sum objective)."""
    location = _as_location(sym, location)
    aux_states = {k: np.asarray(v) for k, v in (aux_states or {}).items()}
    exe = _bind(sym, location, aux_states, ctx=ctx)
    exe.forward(is_train=True)
    ones = [array(np.ones(o.shape, dtype="float32")) for o in exe.outputs]
    exe.backward(ones)
    symbolic = {k: (g.asnumpy() if g is not None else None)
                for k, g in exe.grad_dict.items()}

    fd_exe = _bind(sym, location, aux_states, grad_req="null", ctx=ctx)
    approx = numeric_grad(fd_exe, location, aux_states, eps=numeric_eps)

    names = grad_nodes if grad_nodes is not None else list(approx.keys())
    for name in names:
        if name not in approx or symbolic.get(name) is None:
            continue
        rd = reldiff(approx[name], symbolic[name])
        if rd > check_eps:
            raise AssertionError(
                "numeric gradient check failed for %r: reldiff %g > %g\nnumeric:\n%s\nsymbolic:\n%s"
                % (name, rd, check_eps, approx[name], symbolic[name]))


def check_symbolic_forward(sym, location, expected, check_eps=1e-4,
                           aux_states=None, ctx=None, is_train=False):
    """(reference: test_utils.py check_symbolic_forward)"""
    location = _as_location(sym, location)
    exe = _bind(sym, location, {k: np.asarray(v) for k, v in (aux_states or {}).items()},
                grad_req="null", ctx=ctx)
    outputs = [o.asnumpy() for o in exe.forward(is_train=is_train)]
    if isinstance(expected, dict):
        expected = [expected[n] for n in sym.list_outputs()]
    for out, exp in zip(outputs, expected):
        if reldiff(out, np.asarray(exp)) > check_eps:
            raise AssertionError("forward check failed: reldiff %g > %g"
                                 % (reldiff(out, np.asarray(exp)), check_eps))
    return outputs


def check_symbolic_backward(sym, location, out_grads, expected, check_eps=1e-4,
                            aux_states=None, grad_req="write", ctx=None):
    """(reference: test_utils.py check_symbolic_backward)"""
    location = _as_location(sym, location)
    exe = _bind(sym, location, {k: np.asarray(v) for k, v in (aux_states or {}).items()},
                grad_req=grad_req, ctx=ctx)
    exe.forward(is_train=True)
    exe.backward([array(np.asarray(g)) for g in out_grads])
    grads = {k: (g.asnumpy() if g is not None else None) for k, g in exe.grad_dict.items()}
    if not isinstance(expected, dict):
        expected = dict(zip(sym.list_arguments(), expected))
    for name, exp in expected.items():
        if exp is None:
            continue
        rd = reldiff(grads[name], np.asarray(exp))
        if rd > check_eps:
            raise AssertionError("backward check failed for %r: reldiff %g > %g"
                                 % (name, rd, check_eps))
    return grads


def check_consistency(sym, location, dtypes=("float32", "bfloat16"),
                      tol=None, aux_states=None, ctx=None):
    """Run the same graph at several dtypes and compare (the reference's
    cpu-vs-gpu check_consistency re-aimed at the fp32-vs-bf16 axis)."""
    from .base import np_dtype

    tol = tol or {"float32": 1e-5, "float16": 1e-2, "bfloat16": 5e-2}
    location = _as_location(sym, location)
    baseline = None
    for dt in dtypes:
        cast_loc = {k: v.astype(np_dtype(dt)) if np.issubdtype(v.dtype, np.floating) else v
                    for k, v in location.items()}
        exe = _bind(sym, cast_loc,
                    {k: np.asarray(v) for k, v in (aux_states or {}).items()},
                    grad_req="null", ctx=ctx)
        outs = [np.asarray(o.asnumpy(), dtype=np.float64) for o in exe.forward(is_train=False)]
        if baseline is None:
            baseline = outs
        else:
            t = tol[dt] if isinstance(tol, dict) else tol
            for b, o in zip(baseline, outs):
                rd = reldiff(b, o)
                if rd > t:
                    raise AssertionError("consistency failed at dtype %s: reldiff %g > %g"
                                         % (dt, rd, t))
    return baseline
