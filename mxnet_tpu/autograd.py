"""Imperative autograd: record NDArray ops, replay backward.

Counterpart of the reference's AutogradRuntime (src/ndarray/autograd.cc:73
RecordImperativeFCompute, :135 ComputeGradient) and the Python surface
python/mxnet/contrib/autograd.py (set_is_training, train_section,
mark_variables, backward, grad_and_loss). The reference records ops into an
NNVM graph and binds a GraphExecutor over the tape; here the tape is replayed
as a pure JAX function over the marked variables and differentiated with
``jax.vjp`` — one fused backward XLA program instead of a node-by-node engine
walk.

Limitations (documented, as in the 0.9.5 contrib API): arrays must not be
mutated in place between recording and ``backward``; views of marked arrays
are not tracked as the marked variable.
"""
from __future__ import annotations

import contextlib
from typing import List

from .base import MXNetError
from .ndarray import NDArray

__all__ = [
    "set_is_training",
    "is_training",
    "set_recording",
    "is_recording",
    "record",
    "train_section",
    "test_section",
    "mark_variables",
    "backward",
    "compute_gradient",
    "grad_and_loss",
    "grad",
]

_RECORDING = False
_TRAIN_MODE = True
_TAPE: List["_TapeEntry"] = []
_MARKED = {}  # id(NDArray) -> (ndarray, grad ndarray, grad_req)


class _TapeEntry:
    __slots__ = ("op", "attrs", "inputs", "in_vals", "n_aux", "outputs", "rng", "is_train")

    def __init__(self, op, attrs, inputs, in_vals, n_aux, outputs, rng, is_train):
        self.op = op
        self.attrs = attrs
        self.inputs = inputs
        self.in_vals = in_vals
        self.n_aux = n_aux
        self.outputs = outputs
        self.rng = rng
        self.is_train = is_train


# ------------------------------------------------------------------ recording
def is_recording() -> bool:
    return _RECORDING


def is_training() -> bool:
    return _TRAIN_MODE


def set_recording(flag: bool) -> bool:
    """Returns the previous state (reference: autograd.py set_is_recording)."""
    global _RECORDING
    prev, _RECORDING = _RECORDING, bool(flag)
    return prev


def set_is_training(flag: bool) -> bool:
    global _TRAIN_MODE
    prev, _TRAIN_MODE = _TRAIN_MODE, bool(flag)
    return prev


@contextlib.contextmanager
def record(train_mode=True):
    """Recording scope (reference: contrib/autograd.py train_section)."""
    prev_r = set_recording(True)
    prev_t = set_is_training(train_mode)
    try:
        yield
    finally:
        set_recording(prev_r)
        set_is_training(prev_t)


@contextlib.contextmanager
def train_section():
    with record(train_mode=True):
        yield


@contextlib.contextmanager
def test_section():
    with record(train_mode=False):
        yield


def _record_op(op_name, attrs, inputs, in_vals, n_aux, outputs, rng, is_train):
    """Called by imperative_invoke under recording."""
    _TAPE.append(_TapeEntry(op_name, dict(attrs), list(inputs), list(in_vals),
                            n_aux, list(outputs), rng, is_train))


def _clear_tape():
    _TAPE.clear()


# ------------------------------------------------------------------ variables
def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to arrays (reference: autograd.cc MarkVariables)."""
    if isinstance(variables, NDArray):
        variables = [variables]
    if isinstance(gradients, NDArray):
        gradients = [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    if not (len(variables) == len(gradients) == len(grad_reqs)):
        raise MXNetError("mark_variables: length mismatch")
    for v, g, r in zip(variables, gradients, grad_reqs):
        if not isinstance(v, NDArray) or not isinstance(g, NDArray):
            raise TypeError("mark_variables expects NDArrays")
        _MARKED[id(v)] = (v, g, r)


# ------------------------------------------------------------------- backward
def backward(outputs, out_grads=None, retain_graph=False):
    """Compute gradients of ``outputs`` w.r.t. all marked variables
    (reference: autograd.cc:135 ComputeGradient)."""
    import jax
    import jax.numpy as jnp

    from .ops.registry import get_op

    if isinstance(outputs, NDArray):
        outputs = [outputs]
    if out_grads is not None and isinstance(out_grads, NDArray):
        out_grads = [out_grads]

    produced = {}
    for ei, e in enumerate(_TAPE):
        for o in e.outputs:
            produced[id(o)] = ei

    # reverse reachability from heads → the slice of the tape that matters
    needed = set()
    stack = [id(o) for o in outputs]
    seen = set()
    while stack:
        oid = stack.pop()
        if oid in seen or oid not in produced:
            continue
        seen.add(oid)
        ei = produced[oid]
        needed.add(ei)
        e = _TAPE[ei]
        for x in e.inputs[: len(e.inputs) - e.n_aux]:
            stack.append(id(x))
    order = sorted(needed)

    marked = [(v, g, r) for (v, g, r) in _MARKED.values()]
    if not marked:
        raise MXNetError("backward: no marked variables (call mark_variables)")
    var_ids = [id(v) for v, _, _ in marked]
    var_vals = tuple(v._jax() for v, _, _ in marked)
    head_ids = {id(o): i for i, o in enumerate(outputs)}

    def replay(vals):
        env = dict(zip(var_ids, vals))
        for ei in order:
            e = _TAPE[ei]
            opdef = get_op(e.op)
            n_in = len(e.inputs) - e.n_aux
            ins = [env.get(id(x), e.in_vals[i]) for i, x in enumerate(e.inputs[:n_in])]
            aux = list(e.in_vals[n_in:])
            outs, _ = opdef.apply(e.attrs, ins, aux=aux, is_train=e.is_train, rng=e.rng)
            for o_nd, o_val in zip(e.outputs, outs):
                env[id(o_nd)] = o_val
        heads = []
        for o in outputs:
            if id(o) not in env:
                raise MXNetError("backward: output was not recorded on the tape")
            heads.append(env[id(o)])
        return tuple(heads)

    heads, vjp_fn = jax.vjp(replay, var_vals)
    if out_grads is None:
        cot = tuple(jnp.ones_like(h) for h in heads)
    else:
        if len(out_grads) != len(heads):
            raise MXNetError("backward: expected %d head grads" % len(heads))
        cot = tuple(g._jax().astype(h.dtype) for g, h in zip(out_grads, heads))
    (grads,) = vjp_fn(cot)

    for (v, gbuf, req), g in zip(marked, grads):
        if req == "null":
            continue
        if g.dtype == jax.dtypes.float0:
            continue
        if req == "add":
            gbuf._set_jax(gbuf._jax() + g.astype(gbuf.dtype))
        else:
            gbuf._set_jax(g.astype(gbuf.dtype))

    if not retain_graph:
        _clear_tape()


def compute_gradient(outputs):
    """(reference: contrib/autograd.py compute_gradient)"""
    backward(outputs)


# ------------------------------------------------------------------ decorators
def grad_and_loss(func, argnum=None):
    """Return a function computing both gradient of args and loss
    (reference: contrib/autograd.py grad_and_loss)."""
    import functools

    @functools.wraps(func)
    def wrapped(*args):
        variables = list(args)
        if argnum is not None:
            argnums = [argnum] if isinstance(argnum, int) else list(argnum)
            variables = [args[i] for i in argnums]
        for v in variables:
            if not isinstance(v, NDArray):
                raise TypeError("grad_and_loss: arguments must be NDArrays")
        from .ndarray import zeros

        grads = [zeros(v.shape, ctx=v.context, dtype=v.dtype) for v in variables]
        mark_variables(variables, grads)
        prev = list(_TAPE)
        _clear_tape()
        try:
            with record():
                outputs = func(*args)
            backward([outputs] if isinstance(outputs, NDArray) else list(outputs))
        finally:
            for v in variables:
                _MARKED.pop(id(v), None)
            _TAPE.extend(prev)
        return grads, outputs

    return wrapped


def grad(func, argnum=None):
    """(reference: contrib/autograd.py grad)"""
    fn = grad_and_loss(func, argnum)

    def wrapped(*args):
        return fn(*args)[0]

    return wrapped
