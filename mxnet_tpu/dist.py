"""Multi-process runtime bootstrap.

Counterpart of the reference's cluster env plumbing (`KVStore::InitPSEnv`,
include/mxnet/kvstore.h:158-164, consuming DMLC_ROLE/DMLC_PS_ROOT_URI/... set
by tools/launch.py). The ps-lite scheduler/server roles are gone — in the
SPMD design every process runs the same program — so the only bootstrap
needed is the JAX coordination service: ``tools/launch.py`` sets the three
``MXNET_TPU_*`` env vars below and ``init()`` wires them into
``jax.distributed.initialize``, after which ``jax.process_index()`` /
``jax.process_count()`` back KVStore ``rank``/``num_workers`` and XLA
collectives ride ICI/DCN across all hosts.
"""
from __future__ import annotations

import logging
import os

__all__ = ["init", "is_initialized", "rank", "num_workers", "shutdown",
           "num_dead_nodes", "elastic_enabled", "members", "generation",
           "orig_rank", "dead_members", "dead_timeout_seconds",
           "plan_reform", "plan_from_pause",
           "reform", "coordination_client", "propose_pause", "poll_pause",
           "stop_heartbeat", "is_heartbeating"]

# env contract with tools/launch.py (the DMLC_* vars of the reference)
ENV_COORDINATOR = "MXNET_TPU_COORDINATOR"  # host:port of process 0
ENV_NUM_WORKERS = "MXNET_TPU_NUM_WORKERS"
ENV_WORKER_ID = "MXNET_TPU_WORKER_ID"
# failure detection (reference: ps-lite heartbeats scanned by
# kvstore_dist.h:158-167 behind KVStore::get_num_dead_node,
# include/mxnet/kvstore.h:234-244): each worker touches
# $MXNET_TPU_HEARTBEAT_DIR/worker-<rank> on a timer; the launcher (and
# num_dead_nodes below) treat a stale file as a dead/hung worker
ENV_HEARTBEAT_DIR = "MXNET_TPU_HEARTBEAT_DIR"
ENV_HEARTBEAT_INTERVAL = "MXNET_TPU_HEARTBEAT_INTERVAL"
# elastic membership (docs/FAULT_TOLERANCE.md): worker death becomes a
# survivable event instead of a job-killing one
ENV_ELASTIC = "MXNET_ELASTIC"
ENV_REFORM_TIMEOUT = "MXNET_ELASTIC_REFORM_TIMEOUT"
ENV_MIN_WORKERS = "MXNET_ELASTIC_MIN_WORKERS"
ENV_DEAD_TIMEOUT = "MXNET_ELASTIC_DEAD_TIMEOUT"
ENV_PAUSE_MARGIN = "MXNET_ELASTIC_PAUSE_MARGIN"

_initialized = False
_heartbeat_thread = None
_heartbeat_stop = None  # threading.Event; set by stop_heartbeat()
_start_time = None  # job-start anchor for num_dead_nodes' startup grace
# ---- elastic state (meaningful only under MXNET_ELASTIC=1) ----
_elastic = False      # this job runs the survivable coordination layer
_generation = 0       # bumped by every successful reform()
_members = None       # ORIGINAL ranks of the current generation, sorted
_orig_rank = None     # this process's launcher rank (stable across reforms)
_orig_world = None    # the launch-time worker count


def _job_start_time():
    """When this job started, as far as this process can tell: pinned at
    ``init()`` (workers) or lazily at the first liveness query (monitors).
    Anchors the startup grace below."""
    global _start_time
    if _start_time is None:
        import time

        _start_time = time.time()
    return _start_time


def is_initialized() -> bool:
    return _initialized


def elastic_enabled() -> bool:
    """MXNET_ELASTIC=1 (docs/FAULT_TOLERANCE.md): run the survivable
    coordination layer — worker death pauses and re-forms the job instead of
    killing it. Death propagation through the JAX coordination service is
    disabled (its heartbeat tolerance is set effectively infinite) and
    failure detection moves to the launcher's heartbeat files, exactly the
    reference's ps-lite node-heartbeat semantics."""
    return os.environ.get(ENV_ELASTIC, "").lower() in ("1", "on", "true",
                                                       "yes")


def init(coordinator_address=None, num_processes=None, process_id=None):
    """Connect this process to the job's coordination service.

    Arguments default to the ``MXNET_TPU_*`` env vars; no-op when neither is
    present (single-process job) or when already initialized. Safe to call
    multiple times. Under ``MXNET_ELASTIC=1`` the coordination client is
    built directly (not via ``jax.distributed.initialize``) so its
    missed-heartbeat tolerance can be made effectively infinite — a dead
    peer must NOT abort the survivors; they detect it themselves
    (``num_dead_nodes``) and re-form (``reform``)."""
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(ENV_COORDINATOR)
    if coordinator_address is None:
        return  # single-process
    num_processes = int(num_processes if num_processes is not None
                        else os.environ.get(ENV_NUM_WORKERS, "1"))
    process_id = int(process_id if process_id is not None
                     else os.environ.get(ENV_WORKER_ID, "0"))
    import jax

    _enable_cpu_collectives()
    try:
        if elastic_enabled():
            _init_elastic(coordinator_address, num_processes, process_id)
        else:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
    except RuntimeError as e:
        from .base import MXNetError

        raise MXNetError(
            "mxnet_tpu.dist.init() must run before any JAX computation. "
            "Create the dist kvstore (mx.kv.create('dist_tpu_sync')) or call "
            "mx.dist.init() at the top of the worker script, before building "
            "NDArrays or binding modules. Original error: %s" % e
        ) from e
    _initialized = True
    _job_start_time()
    _start_heartbeat(process_id)
    logging.info("mxnet_tpu.dist: worker %d/%d connected to %s%s",
                 process_id, num_processes, coordinator_address,
                 " [elastic]" if _elastic else "")


def _init_elastic(coordinator_address, num_processes, process_id):
    """Elastic bootstrap: the same coordination service/client pair
    ``jax.distributed.initialize`` would build, but with death propagation
    disabled — ``max_missing_heartbeats`` effectively infinite on both ends
    and ``shutdown_on_destruction=False`` (a survivor tearing down its old
    backend must not shut the service down for its peers). The client and
    service OUTLIVE backend re-forms: ``reform()`` rebuilds the XLA backend
    over the survivor set while this client keeps its original node id for
    barriers and the membership KV protocol."""
    global _elastic, _members, _orig_rank, _orig_world, _generation
    from jax._src import distributed as jdist
    from jax._src.lib import xla_extension as xe

    gs = jdist.global_state
    if gs.client is not None:
        raise RuntimeError("jax.distributed already initialized")
    # ~heartbeat_interval * max_missing seconds of tolerance ≈ 3 years:
    # the coordination service never declares a node dead on its own
    never = 10 ** 7
    if process_id == 0:
        bind = "[::]:" + coordinator_address.rsplit(":", 1)[1]
        gs.service = xe.get_distributed_runtime_service(
            bind, num_processes, heartbeat_interval=10,
            max_missing_heartbeats=never)
    gs.client = xe.get_distributed_runtime_client(
        coordinator_address, process_id, init_timeout=300,
        heartbeat_interval=10, max_missing_heartbeats=never,
        shutdown_on_destruction=False, use_compression=True)
    gs.client.connect()
    gs.process_id = process_id
    gs.num_processes = num_processes
    gs.coordinator_address = coordinator_address
    _elastic = True
    _generation = 0
    _members = list(range(num_processes))
    _orig_rank = process_id
    _orig_world = num_processes


def _enable_cpu_collectives():
    """Multi-process collectives on the CPU backend need jax's gloo
    cross-process collectives implementation; without it every dist
    collective dies with "Multiprocess computations aren't implemented on
    the CPU backend". Selected here — before ``jax.distributed.initialize``
    — when the job is pinned to CPU (tests, CI, tools/launch.py
    --cpu-devices). No-op on TPU/GPU platforms and on jax builds without
    the option."""
    if not (os.environ.get("JAX_PLATFORMS", "") == "cpu"
            or os.environ.get("MXNET_DEFAULT_CONTEXT", "") == "cpu"):
        return
    import jax

    try:
        if getattr(jax.config, "jax_cpu_collectives_implementation", None):
            return  # the operator already chose an implementation
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as e:  # old jax / no gloo build
        logging.debug("mxnet_tpu.dist: cpu collectives unavailable: %s", e)


def _start_heartbeat(process_id):
    """Touch the per-worker heartbeat file on a timer (daemon thread). A
    killed/frozen/OOM-thrashed worker stops beating and the launcher's
    watchdog (tools/launch.py) sees the stale file. Note the limit: a worker
    whose MAIN thread is deadlocked in a collective keeps beating (the
    daemon thread is alive) — liveness here means 'process running', the
    same contract as the reference's ps-lite node heartbeats."""
    global _heartbeat_thread, _heartbeat_stop
    hb_dir = os.environ.get(ENV_HEARTBEAT_DIR)
    if not hb_dir or _heartbeat_thread is not None:
        return
    import threading
    import time

    interval = float(os.environ.get(ENV_HEARTBEAT_INTERVAL, "5"))
    path = os.path.join(hb_dir, "worker-%d" % process_id)
    stop = threading.Event()

    def beat():
        from . import faultinject as _fi

        while _initialized and not stop.is_set():
            try:
                # injection site dist.heartbeat (docs/RESILIENCE.md): a
                # `raise` skips this beat (one missed heartbeat), a
                # delay/hang stalls the thread so the file goes stale —
                # the exact signal the launcher watchdog and the elastic
                # dead-node scan act on
                _fi.fire("dist.heartbeat")
                os.makedirs(hb_dir, exist_ok=True)
                with open(path, "a"):
                    os.utime(path, None)
            except (OSError, _fi.FaultInjected):
                pass
            stop.wait(interval)

    _heartbeat_stop = stop
    _heartbeat_thread = threading.Thread(target=beat, daemon=True,
                                         name="mxtpu-heartbeat")
    _heartbeat_thread.start()


def is_heartbeating() -> bool:
    """Whether this worker's heartbeat thread is live (it stops at
    ``stop_heartbeat`` or ``shutdown``)."""
    return _heartbeat_thread is not None and _heartbeat_thread.is_alive()


def stop_heartbeat(remove=False):
    """Stop this worker's heartbeat — the first step of the DRAIN protocol
    (docs/FAULT_TOLERANCE.md): a SIGTERM'd worker stops beating, and with
    ``remove=True`` deletes its file outright, so the others' next scan
    classes it dead immediately instead of after the staleness timeout.
    The draining worker keeps participating in collectives until the agreed
    pause round; only then does it exit."""
    global _heartbeat_thread, _heartbeat_stop
    if _heartbeat_stop is not None:
        _heartbeat_stop.set()
    if _heartbeat_thread is not None:
        _heartbeat_thread.join(timeout=2.0)
        _heartbeat_thread = None
        _heartbeat_stop = None
    if remove:
        hb_dir = os.environ.get(ENV_HEARTBEAT_DIR)
        wid = _orig_rank if _orig_rank is not None \
            else os.environ.get(ENV_WORKER_ID)
        if hb_dir and wid is not None:
            try:
                os.unlink(os.path.join(hb_dir, "worker-%s" % wid))
            except OSError:
                pass


def num_dead_nodes(timeout=60.0, startup_grace=None):
    """Count workers whose heartbeat file is older than ``timeout`` seconds
    (reference: KVStore::get_num_dead_node,
    include/mxnet/kvstore.h:234-244). Returns 0 when heartbeating is not
    configured (single-process, or launcher without a heartbeat dir).

    A MISSING heartbeat file is treated as alive until ``startup_grace``
    seconds (default: ``timeout``) after the job start — workers come up
    staggered (backend init, first compile) and a peer that simply has not
    beaten YET is not dead. This matches the launcher's ``_stale_worker``
    semantics, where a not-yet-written file is startup, covered by process
    polling; after the grace a still-missing file counts as dead (it never
    came up). Job start is the EARLIEST evidence available: this process's
    anchor (``init()`` in workers, first query in monitors) or the
    heartbeat directory's mtime (set when the first worker file appeared) —
    so a monitor process started long after launch does not grant a dead
    worker a fresh grace window.

    In an elastic job the scan covers the CURRENT membership only: a worker
    already re-formed away stays dead forever (its file never refreshes)
    and must not be re-counted against the new generation."""
    dead, max_age = _scan_heartbeats(timeout, startup_grace)
    _note_liveness(len(dead), max_age)
    return len(dead)


def dead_timeout_seconds() -> float:
    """MXNET_ELASTIC_DEAD_TIMEOUT (default 60 s) — the heartbeat staleness
    past which a member counts dead."""
    try:
        return float(os.environ.get(ENV_DEAD_TIMEOUT, "60"))
    except ValueError:
        return 60.0


def dead_members(timeout=None, startup_grace=None):
    """ORIGINAL ranks of current members whose heartbeat is stale — the
    input to ``plan_reform``. Default timeout: MXNET_ELASTIC_DEAD_TIMEOUT
    (60 s)."""
    if timeout is None:
        timeout = dead_timeout_seconds()
    dead, _ = _scan_heartbeats(timeout, startup_grace)
    return dead


def _scan_heartbeats(timeout, startup_grace):
    """``(dead original-rank list, max heartbeat age)`` over the ranks this
    process currently considers members."""
    import time

    hb_dir = os.environ.get(ENV_HEARTBEAT_DIR)
    if not hb_dir or not os.path.isdir(hb_dir):
        return [], 0.0
    if startup_grace is None:
        startup_grace = timeout
    if _elastic and _members is not None:
        ranks = list(_members)
    else:
        ranks = list(range(int(os.environ.get(ENV_NUM_WORKERS, "1"))))
    now = time.time()
    start = _job_start_time()
    try:
        start = min(start, os.path.getmtime(hb_dir))
    except OSError:
        pass
    in_grace = now - start <= startup_grace
    dead = []
    max_age = 0.0
    for r in ranks:
        path = os.path.join(hb_dir, "worker-%d" % r)
        try:
            age = now - os.path.getmtime(path)
            max_age = max(max_age, age)
            if age > timeout:
                dead.append(r)
        except OSError:
            if not in_grace:
                dead.append(r)  # never heartbeated, grace period over
                # its effective staleness is the whole job lifetime — the
                # age gauge must not read 0 when every worker is missing
                max_age = max(max_age, now - start)
    return dead, max_age


_last_dead = 0  # previous num_dead_nodes result, for transition counting


def _note_liveness(dead, max_age):
    """Telemetry: current dead-worker count and oldest heartbeat age as
    gauges, plus a counter that ticks on every dead-count CHANGE — the
    'node died / node came back' transitions a dashboard alerts on."""
    global _last_dead
    from . import telemetry as _tm

    if not _tm.enabled():
        _last_dead = dead
        return
    _tm.gauge("dist.dead_nodes").set(dead)
    _tm.gauge("dist.heartbeat_age_s").set(round(max_age, 3))
    if dead != _last_dead:
        _tm.counter("dist.dead_node_transitions").inc()
        _tm.event("dist.dead_node_transition", dead=dead,
                  previous=_last_dead)
        _last_dead = dead


def rank() -> int:
    """This process's rank in the CURRENT generation (dense 0..W-1). Elastic
    jobs track it here — ``jax.process_index`` is lru_cached and a re-form
    must not depend on cache-poking order."""
    if _elastic and _members is not None:
        return _members.index(_orig_rank)
    import jax

    return jax.process_index()


def num_workers() -> int:
    if _elastic and _members is not None:
        return len(_members)
    import jax

    return jax.process_count()


# ----------------------------------------------------------------- elastic
def members():
    """ORIGINAL launcher ranks of the current generation, sorted; None when
    not an elastic job. Original ranks are the stable identity — heartbeat
    files and coordination-service node ids keep them across re-forms while
    the dense backend rank (``rank()``) is re-assigned per generation."""
    return list(_members) if _members is not None else None


def generation() -> int:
    """0 at launch; +1 per successful ``reform()``."""
    return _generation


def orig_rank():
    """This process's launch-time rank (stable across re-forms); None when
    not elastic."""
    return _orig_rank


def coordination_client():
    """The job's coordination-service client (elastic jobs only) — the
    barrier/KV substrate the re-form protocol runs on. It outlives backend
    re-forms; its node id is this process's ORIGINAL rank."""
    from .base import MXNetError

    if not _elastic:
        raise MXNetError(
            "coordination_client() needs an elastic job (MXNET_ELASTIC=1 "
            "before dist.init())")
    from jax._src import distributed as jdist

    return jdist.global_state.client


def _reform_timeout_ms() -> int:
    try:
        return int(1000 * float(os.environ.get(ENV_REFORM_TIMEOUT, "120")))
    except ValueError:
        return 120_000


def plan_reform(timeout=None, dead=None):
    """Decide the next generation's membership from the heartbeat files.

    Returns ``{"generation", "members", "dead", "rank", "world"}`` — the
    survivor set and this process's dense rank in it. Raises a structured
    ``MXNetError`` for the unrecoverable cases (docs/FAULT_TOLERANCE.md):

    * the coordinator (original rank 0 — its process HOSTS the coordination
      service; there is no job without it) is among the dead;
    * fewer than ``MXNET_ELASTIC_MIN_WORKERS`` (default 1) survivors;
    * this process itself is classed dead (its own heartbeat went stale —
      clock skew or an overloaded host; re-joining a generation that has
      already written us off would corrupt the collective).
    """
    from .base import MXNetError

    if not _elastic or _members is None:
        raise MXNetError("plan_reform() needs an elastic job "
                         "(MXNET_ELASTIC=1 before dist.init())")
    if dead is None:
        dead = dead_members(timeout=timeout)
    dead = sorted(set(dead) & set(_members))
    if not dead:
        raise MXNetError("plan_reform(): no dead members — nothing to "
                         "re-form (membership: %s)" % (_members,))
    survivors = [m for m in _members if m not in dead]
    if 0 in dead:
        raise MXNetError(
            "elastic re-form impossible: the coordinator (original rank 0) "
            "is dead — its process hosts the coordination service every "
            "barrier and KV exchange rides. Unrecoverable; restart the job "
            "from the last checkpoint (dead: %s)" % dead)
    try:
        min_workers = int(os.environ.get(ENV_MIN_WORKERS, "1"))
    except ValueError:
        min_workers = 1
    if len(survivors) < max(1, min_workers):
        raise MXNetError(
            "elastic re-form impossible: %d survivor(s) %s is below "
            "MXNET_ELASTIC_MIN_WORKERS=%d (dead: %s). Unrecoverable; "
            "restart the job from the last checkpoint"
            % (len(survivors), survivors, min_workers, dead))
    if _orig_rank in dead:
        raise MXNetError(
            "elastic re-form: THIS worker (original rank %d) is classed "
            "dead by its own heartbeat scan — clock skew or a stalled "
            "host. The survivors are re-forming without us; exiting is the "
            "only safe move" % _orig_rank)
    return {"generation": _generation + 1, "members": survivors,
            "dead": dead, "rank": survivors.index(_orig_rank),
            "world": len(survivors)}


def _pause_key(gen):
    return "mxtpu-elastic/gen-%d/pause" % gen


def _pause_margin() -> int:
    try:
        return max(1, int(os.environ.get(ENV_PAUSE_MARGIN, "3")))
    except ValueError:
        return 3


def propose_pause(dead, round_no, margin=None):
    """Publish the pause decision for the NEXT generation in the
    coordination KV (first-write-wins: a second proposal is a no-op and the
    FIRST payload stays in force — every worker acts on one decision even
    when two detect trouble in the same window). Two proposers exist:

    * the coordinator's per-round heartbeat scan (crashed/stalled peers);
    * a SIGTERM'd worker draining itself (``dead=[orig_rank()]``) — no
      staleness wait, the cleanest departure.

    ``pause_at = round_no + margin`` (MXNET_ELASTIC_PAUSE_MARGIN, default
    3): every worker — the proposer included — keeps training through round
    ``pause_at`` so the collective count stays identical across workers
    (hosts drift under async dispatch; the metric read in ``Module.fit``
    bounds the drift well under the default margin). Returns the payload in
    force."""
    import json

    from .base import MXNetError

    client = coordination_client()
    gen = _generation + 1
    payload = {"generation": gen, "dead": sorted(set(int(d) for d in dead)),
               "pause_at": int(round_no) + (_pause_margin() if margin is None
                                            else max(1, int(margin))),
               "proposer": _orig_rank}
    key = _pause_key(gen)
    try:
        client.key_value_set(key, json.dumps(payload))
        from . import telemetry as _tm

        if _tm.enabled():
            _tm.event("dist.pause_proposed", generation=gen,
                      pause_at=payload["pause_at"],
                      dead=",".join(map(str, payload["dead"])))
        return payload
    except Exception:
        # first writer won — adopt its decision
        try:
            return json.loads(client.blocking_key_value_get(key, 10_000))
        except Exception as e:
            raise MXNetError(
                "elastic pause: could not publish OR read the gen-%d pause "
                "payload (%s) — coordination service unreachable; the "
                "coordinator likely died. Unrecoverable; restart from the "
                "last checkpoint" % (gen, e)) from e


def poll_pause():
    """Non-blocking check for a published pause decision for the NEXT
    generation: the payload dict, or None. Cheap enough to call every
    round (one KV directory poll against the coordination service)."""
    import json

    client = coordination_client()
    prefix = "mxtpu-elastic/gen-%d/" % (_generation + 1)
    try:
        entries = client.key_value_dir_get(prefix)
    except Exception:
        return None
    for key, value in entries:
        if key.endswith("/pause"):
            try:
                return json.loads(value)
            except ValueError:
                return None
    return None


def plan_from_pause(payload):
    """Membership plan from an AGREED pause payload — every worker re-forms
    from the same dead set even when local heartbeat scans disagree at the
    staleness boundary. Raises ``EvictedError`` when the payload names THIS
    worker dead (drain after SIGTERM: expected, exit clean; stale heartbeat:
    the survivors have written us off and rejoining would corrupt the
    collective), and the same structured ``MXNetError``s as ``plan_reform``
    for the unrecoverable shapes (coordinator death, too few survivors)."""
    from .base import EvictedError, MXNetError

    if not _elastic or _members is None:
        raise MXNetError("plan_from_pause() needs an elastic job "
                         "(MXNET_ELASTIC=1 before dist.init())")
    gen = int(payload.get("generation", -1))
    if gen != _generation + 1:
        raise MXNetError(
            "elastic pause payload is for generation %d but this worker is "
            "at generation %d — membership drifted (a re-form happened "
            "without us?); unrecoverable" % (gen, _generation))
    dead = sorted(set(payload["dead"]) & set(_members))
    if _orig_rank in dead:
        raise EvictedError(
            "this worker (original rank %d) is in generation %d's dead set "
            "%s — draining (expected after SIGTERM) or written off by the "
            "survivors; stopping training" % (_orig_rank, gen, dead))
    return plan_reform(dead=dead)


def reform(plan=None):
    """Re-form the job over the survivor set: rebuild the XLA backend (and
    its gloo/ICI collective fabric) over ``plan["members"]``, keeping the
    coordination client. The protocol (docs/FAULT_TOLERANCE.md):

    1. the coordinator PUBLISHES the membership plan in the coordination KV
       (every worker scans heartbeats independently; borderline staleness
       must not let two workers re-form different worlds);
    2. survivors rendezvous at a generation-named barrier — a survivor
       wedged in a dead collective has ``MXNET_ELASTIC_REFORM_TIMEOUT`` to
       error out of it and arrive;
    3. the coordinator deletes the PREVIOUS generation's backend topology
       keys (the new backend re-exchanges topology under the same names);
    4. every survivor drops its local backend + compiled caches and
       re-initializes over ``world`` processes at its new dense rank.

    Callers must re-create device arrays afterwards (kvstore.elastic_reform
    snapshots + reseeds); anything built on the old backend is invalid.
    Raises ``MXNetError`` when the plan cannot be agreed or the barrier
    times out."""
    global _members, _generation
    import json
    import time as _time

    from . import telemetry as _tm
    from .base import MXNetError

    if plan is None:
        plan = plan_reform()
    client = coordination_client()
    gen = plan["generation"]
    timeout_ms = _reform_timeout_ms()
    t0 = _time.time()
    with _tm.span("dist.reform", generation=gen, world=plan["world"]):
        key = "mxtpu-elastic/gen-%d/members" % gen
        if _orig_rank == 0:
            client.key_value_set(key, json.dumps(plan["members"]))
            agreed = plan["members"]
        else:
            try:
                agreed = json.loads(
                    client.blocking_key_value_get(key, timeout_ms))
            except Exception as e:
                raise MXNetError(
                    "elastic re-form gen %d: coordinator never published "
                    "the membership plan within %.0fs — it likely died "
                    "mid-re-form. Unrecoverable; restart from the last "
                    "checkpoint (%s)" % (gen, timeout_ms / 1000, e)) from e
        if _orig_rank not in agreed:
            raise MXNetError(
                "elastic re-form gen %d: the coordinator's membership %s "
                "excludes this worker (original rank %d) — our heartbeat "
                "went stale from its point of view. Exiting is the only "
                "safe move" % (gen, agreed, _orig_rank))
        try:
            client.wait_at_barrier("mxtpu-reform-gen-%d" % gen, timeout_ms,
                                   list(agreed))
        except Exception as e:
            raise MXNetError(
                "elastic re-form gen %d: survivor barrier over %s did not "
                "complete within %.0fs — a survivor is wedged or died "
                "during the re-form. Unrecoverable; restart from the last "
                "checkpoint (%s)" % (gen, agreed, timeout_ms / 1000, e)
            ) from e
        prev_world = len(_members)
        _teardown_backend(agreed, prev_world, gen, client, timeout_ms)
        _members = list(agreed)
        _generation = gen
        # rebuild the backend NOW (lazily would hide failures until the
        # first collective) and check the new world actually formed
        import jax

        procs = {d.process_index for d in jax.devices()}
        # validate against the AGREED membership, not the local plan: a
        # borderline-staleness scan can class one extra member dead
        # locally, and the coordinator's publication exists precisely to
        # absorb that divergence — a successful re-form over `agreed`
        # must not be aborted because the local guess was wider
        if len(procs) != len(agreed):
            raise MXNetError(
                "elastic re-form gen %d: re-initialized backend spans %d "
                "process(es), expected %d — the survivor set disagrees "
                "with the backend topology" % (gen, len(procs),
                                               len(agreed)))
    # dead/world derive from what was AGREED, not the local scan
    dead = sorted(set(plan["dead"]) | (set(plan["members"]) - set(agreed)))
    dead = [d for d in dead if d not in agreed]
    if _tm.enabled():
        _tm.counter("dist.reforms").inc()
        _tm.gauge("dist.generation").set(gen)
        _tm.gauge("dist.world").set(len(agreed))
        _tm.event("dist.reform", generation=gen, world=len(agreed),
                  dead=",".join(map(str, dead)),
                  seconds=round(_time.time() - t0, 3))
    logging.info(
        "mxnet_tpu.dist: re-formed generation %d over %d worker(s) "
        "(original ranks %s, dead %s) in %.2fs", gen, len(agreed),
        agreed, dead, _time.time() - t0)
    return {"generation": gen, "members": list(agreed),
            "rank": agreed.index(_orig_rank), "world": len(agreed),
            "dead": dead}


def _teardown_backend(agreed, prev_world, gen, client, timeout_ms):
    """Drop the old backend and re-point the distributed globals at the new
    world. The old gloo sockets/executables die with the backend; the
    topology KV keys of the previous generation are deleted (coordinator)
    so the new backend's exchange starts clean under the same names."""
    import jax
    from jax._src import distributed as jdist
    from jax._src import xla_bridge as xb

    if _orig_rank == 0:
        # every platform the old backend exchanged topology for — the key
        # names are platform-qualified (jax has used both spellings across
        # versions), so a TPU job must delete tpu:* keys, not cpu:*
        plats = {"cpu"}
        try:
            plats.add(jax.default_backend())
        except Exception:
            pass
        for plat in sorted(plats):
            for r in range(prev_world):
                for prefix in ("%s:local_topology/%s/%d" % (plat, plat, r),
                               "local_topology:%s:%d" % (plat, r)):
                    try:
                        client.key_value_delete(prefix)
                    except Exception:
                        pass
            for prefix in ("%s:global_topology/%s" % (plat, plat),
                           "global_topology:%s" % plat):
                try:
                    client.key_value_delete(prefix)
                except Exception:
                    pass
    client.wait_at_barrier("mxtpu-reform-keys-gen-%d" % gen, timeout_ms,
                           list(agreed))
    jax.clear_caches()
    xb._clear_backends()
    # rank/world/DEVICE queries are lru_cached on top of the backend
    # caches — local_devices especially: it caches device OBJECTS, and a
    # stale hit hands old-client devices to the first post-re-form
    # collective ("Buffer ... is on device X, but replica is assigned to
    # device X" — same name, dead client)
    for fn in (xb.process_count, xb.process_index,
               getattr(xb, "device_count", None),
               getattr(xb, "local_device_count", None),
               getattr(xb, "local_devices", None),
               getattr(xb, "devices", None),
               getattr(xb, "process_indices", None)):
        if fn is not None and hasattr(fn, "cache_clear"):
            fn.cache_clear()
    gs = jdist.global_state
    gs.num_processes = len(agreed)
    gs.process_id = list(agreed).index(_orig_rank)
    # module-level arrays that survive the teardown must be re-materialized
    # on the new backend — the global PRNG key especially: dropout draws
    # split it every forward, and a poisoned old-backend key buffer would
    # fail the FIRST post-re-form step with the old generation's error
    from . import random as _random

    _random.refresh_backend()


def shutdown():
    global _initialized, _heartbeat_thread, _heartbeat_stop
    global _elastic, _members, _orig_rank, _orig_world, _generation
    if _initialized:
        import jax

        jax.distributed.shutdown()
        _initialized = False
        if _heartbeat_stop is not None:
            _heartbeat_stop.set()
        _heartbeat_thread = None  # a later init() must restart the beat
        _heartbeat_stop = None
        _elastic = False
        _members = None
        _orig_rank = None
        _orig_world = None
        _generation = 0
