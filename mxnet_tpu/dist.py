"""Multi-process runtime bootstrap.

Counterpart of the reference's cluster env plumbing (`KVStore::InitPSEnv`,
include/mxnet/kvstore.h:158-164, consuming DMLC_ROLE/DMLC_PS_ROOT_URI/... set
by tools/launch.py). The ps-lite scheduler/server roles are gone — in the
SPMD design every process runs the same program — so the only bootstrap
needed is the JAX coordination service: ``tools/launch.py`` sets the three
``MXNET_TPU_*`` env vars below and ``init()`` wires them into
``jax.distributed.initialize``, after which ``jax.process_index()`` /
``jax.process_count()`` back KVStore ``rank``/``num_workers`` and XLA
collectives ride ICI/DCN across all hosts.
"""
from __future__ import annotations

import logging
import os

__all__ = ["init", "is_initialized", "rank", "num_workers", "shutdown",
           "num_dead_nodes"]

# env contract with tools/launch.py (the DMLC_* vars of the reference)
ENV_COORDINATOR = "MXNET_TPU_COORDINATOR"  # host:port of process 0
ENV_NUM_WORKERS = "MXNET_TPU_NUM_WORKERS"
ENV_WORKER_ID = "MXNET_TPU_WORKER_ID"
# failure detection (reference: ps-lite heartbeats scanned by
# kvstore_dist.h:158-167 behind KVStore::get_num_dead_node,
# include/mxnet/kvstore.h:234-244): each worker touches
# $MXNET_TPU_HEARTBEAT_DIR/worker-<rank> on a timer; the launcher (and
# num_dead_nodes below) treat a stale file as a dead/hung worker
ENV_HEARTBEAT_DIR = "MXNET_TPU_HEARTBEAT_DIR"
ENV_HEARTBEAT_INTERVAL = "MXNET_TPU_HEARTBEAT_INTERVAL"

_initialized = False
_heartbeat_thread = None
_start_time = None  # job-start anchor for num_dead_nodes' startup grace


def _job_start_time():
    """When this job started, as far as this process can tell: pinned at
    ``init()`` (workers) or lazily at the first liveness query (monitors).
    Anchors the startup grace below."""
    global _start_time
    if _start_time is None:
        import time

        _start_time = time.time()
    return _start_time


def is_initialized() -> bool:
    return _initialized


def init(coordinator_address=None, num_processes=None, process_id=None):
    """Connect this process to the job's coordination service.

    Arguments default to the ``MXNET_TPU_*`` env vars; no-op when neither is
    present (single-process job) or when already initialized. Safe to call
    multiple times.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(ENV_COORDINATOR)
    if coordinator_address is None:
        return  # single-process
    num_processes = int(num_processes if num_processes is not None
                        else os.environ.get(ENV_NUM_WORKERS, "1"))
    process_id = int(process_id if process_id is not None
                     else os.environ.get(ENV_WORKER_ID, "0"))
    import jax

    _enable_cpu_collectives()
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        from .base import MXNetError

        raise MXNetError(
            "mxnet_tpu.dist.init() must run before any JAX computation. "
            "Create the dist kvstore (mx.kv.create('dist_tpu_sync')) or call "
            "mx.dist.init() at the top of the worker script, before building "
            "NDArrays or binding modules. Original error: %s" % e
        ) from e
    _initialized = True
    _job_start_time()
    _start_heartbeat(process_id)
    logging.info("mxnet_tpu.dist: worker %d/%d connected to %s",
                 process_id, num_processes, coordinator_address)


def _enable_cpu_collectives():
    """Multi-process collectives on the CPU backend need jax's gloo
    cross-process collectives implementation; without it every dist
    collective dies with "Multiprocess computations aren't implemented on
    the CPU backend". Selected here — before ``jax.distributed.initialize``
    — when the job is pinned to CPU (tests, CI, tools/launch.py
    --cpu-devices). No-op on TPU/GPU platforms and on jax builds without
    the option."""
    if not (os.environ.get("JAX_PLATFORMS", "") == "cpu"
            or os.environ.get("MXNET_DEFAULT_CONTEXT", "") == "cpu"):
        return
    import jax

    try:
        if getattr(jax.config, "jax_cpu_collectives_implementation", None):
            return  # the operator already chose an implementation
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as e:  # old jax / no gloo build
        logging.debug("mxnet_tpu.dist: cpu collectives unavailable: %s", e)


def _start_heartbeat(process_id):
    """Touch the per-worker heartbeat file on a timer (daemon thread). A
    killed/frozen/OOM-thrashed worker stops beating and the launcher's
    watchdog (tools/launch.py) sees the stale file. Note the limit: a worker
    whose MAIN thread is deadlocked in a collective keeps beating (the
    daemon thread is alive) — liveness here means 'process running', the
    same contract as the reference's ps-lite node heartbeats."""
    global _heartbeat_thread
    hb_dir = os.environ.get(ENV_HEARTBEAT_DIR)
    if not hb_dir or _heartbeat_thread is not None:
        return
    import threading
    import time

    interval = float(os.environ.get(ENV_HEARTBEAT_INTERVAL, "5"))
    path = os.path.join(hb_dir, "worker-%d" % process_id)

    def beat():
        while _initialized:
            try:
                os.makedirs(hb_dir, exist_ok=True)
                with open(path, "a"):
                    os.utime(path, None)
            except OSError:
                pass
            time.sleep(interval)

    _heartbeat_thread = threading.Thread(target=beat, daemon=True,
                                         name="mxtpu-heartbeat")
    _heartbeat_thread.start()


def num_dead_nodes(timeout=60.0, startup_grace=None):
    """Count workers whose heartbeat file is older than ``timeout`` seconds
    (reference: KVStore::get_num_dead_node,
    include/mxnet/kvstore.h:234-244). Returns 0 when heartbeating is not
    configured (single-process, or launcher without a heartbeat dir).

    A MISSING heartbeat file is treated as alive until ``startup_grace``
    seconds (default: ``timeout``) after the job start — workers come up
    staggered (backend init, first compile) and a peer that simply has not
    beaten YET is not dead. This matches the launcher's ``_stale_worker``
    semantics, where a not-yet-written file is startup, covered by process
    polling; after the grace a still-missing file counts as dead (it never
    came up). Job start is the EARLIEST evidence available: this process's
    anchor (``init()`` in workers, first query in monitors) or the
    heartbeat directory's mtime (set when the first worker file appeared) —
    so a monitor process started long after launch does not grant a dead
    worker a fresh grace window."""
    import time

    hb_dir = os.environ.get(ENV_HEARTBEAT_DIR)
    if not hb_dir or not os.path.isdir(hb_dir):
        return 0
    if startup_grace is None:
        startup_grace = timeout
    n = int(os.environ.get(ENV_NUM_WORKERS, "1"))
    now = time.time()
    start = _job_start_time()
    try:
        start = min(start, os.path.getmtime(hb_dir))
    except OSError:
        pass
    in_grace = now - start <= startup_grace
    dead = 0
    max_age = 0.0
    for r in range(n):
        path = os.path.join(hb_dir, "worker-%d" % r)
        try:
            age = now - os.path.getmtime(path)
            max_age = max(max_age, age)
            if age > timeout:
                dead += 1
        except OSError:
            if not in_grace:
                dead += 1  # never heartbeated and the grace period is over
                # its effective staleness is the whole job lifetime — the
                # age gauge must not read 0 when every worker is missing
                max_age = max(max_age, now - start)
    _note_liveness(dead, max_age)
    return dead


_last_dead = 0  # previous num_dead_nodes result, for transition counting


def _note_liveness(dead, max_age):
    """Telemetry: current dead-worker count and oldest heartbeat age as
    gauges, plus a counter that ticks on every dead-count CHANGE — the
    'node died / node came back' transitions a dashboard alerts on."""
    global _last_dead
    from . import telemetry as _tm

    if not _tm.enabled():
        _last_dead = dead
        return
    _tm.gauge("dist.dead_nodes").set(dead)
    _tm.gauge("dist.heartbeat_age_s").set(round(max_age, 3))
    if dead != _last_dead:
        _tm.counter("dist.dead_node_transitions").inc()
        _tm.event("dist.dead_node_transition", dead=dead,
                  previous=_last_dead)
        _last_dead = dead


def rank() -> int:
    import jax

    return jax.process_index()


def num_workers() -> int:
    import jax

    return jax.process_count()


def shutdown():
    global _initialized, _heartbeat_thread
    if _initialized:
        import jax

        jax.distributed.shutdown()
        _initialized = False
        _heartbeat_thread = None  # a later init() must restart the beat
