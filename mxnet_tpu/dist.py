"""Multi-process runtime bootstrap.

Counterpart of the reference's cluster env plumbing (`KVStore::InitPSEnv`,
include/mxnet/kvstore.h:158-164, consuming DMLC_ROLE/DMLC_PS_ROOT_URI/... set
by tools/launch.py). The ps-lite scheduler/server roles are gone — in the
SPMD design every process runs the same program — so the only bootstrap
needed is the JAX coordination service: ``tools/launch.py`` sets the three
``MXNET_TPU_*`` env vars below and ``init()`` wires them into
``jax.distributed.initialize``, after which ``jax.process_index()`` /
``jax.process_count()`` back KVStore ``rank``/``num_workers`` and XLA
collectives ride ICI/DCN across all hosts.
"""
from __future__ import annotations

import logging
import os

__all__ = ["init", "is_initialized", "rank", "num_workers", "shutdown"]

# env contract with tools/launch.py (the DMLC_* vars of the reference)
ENV_COORDINATOR = "MXNET_TPU_COORDINATOR"  # host:port of process 0
ENV_NUM_WORKERS = "MXNET_TPU_NUM_WORKERS"
ENV_WORKER_ID = "MXNET_TPU_WORKER_ID"

_initialized = False


def is_initialized() -> bool:
    return _initialized


def init(coordinator_address=None, num_processes=None, process_id=None):
    """Connect this process to the job's coordination service.

    Arguments default to the ``MXNET_TPU_*`` env vars; no-op when neither is
    present (single-process job) or when already initialized. Safe to call
    multiple times.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(ENV_COORDINATOR)
    if coordinator_address is None:
        return  # single-process
    num_processes = int(num_processes if num_processes is not None
                        else os.environ.get(ENV_NUM_WORKERS, "1"))
    process_id = int(process_id if process_id is not None
                     else os.environ.get(ENV_WORKER_ID, "0"))
    import jax

    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        from .base import MXNetError

        raise MXNetError(
            "mxnet_tpu.dist.init() must run before any JAX computation. "
            "Create the dist kvstore (mx.kv.create('dist_tpu_sync')) or call "
            "mx.dist.init() at the top of the worker script, before building "
            "NDArrays or binding modules. Original error: %s" % e
        ) from e
    _initialized = True
    logging.info("mxnet_tpu.dist: worker %d/%d connected to %s",
                 process_id, num_processes, coordinator_address)


def rank() -> int:
    import jax

    return jax.process_index()


def num_workers() -> int:
    import jax

    return jax.process_count()


def shutdown():
    global _initialized
    if _initialized:
        import jax

        jax.distributed.shutdown()
        _initialized = False
