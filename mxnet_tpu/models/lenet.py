"""LeNet-5 style convnet (reference: example/image-classification/symbols/
lenet.py) — the M3 MNIST gate network."""
from .. import symbol as sym


def get_symbol(num_classes=10, **kwargs):
    data = sym.Variable("data")
    c1 = sym.Convolution(data=data, kernel=(5, 5), num_filter=20, name="conv1")
    a1 = sym.Activation(data=c1, act_type="tanh", name="tanh1")
    p1 = sym.Pooling(data=a1, pool_type="max", kernel=(2, 2), stride=(2, 2), name="pool1")
    c2 = sym.Convolution(data=p1, kernel=(5, 5), num_filter=50, name="conv2")
    a2 = sym.Activation(data=c2, act_type="tanh", name="tanh2")
    p2 = sym.Pooling(data=a2, pool_type="max", kernel=(2, 2), stride=(2, 2), name="pool2")
    fl = sym.Flatten(data=p2, name="flatten")
    f1 = sym.FullyConnected(data=fl, num_hidden=500, name="fc1")
    a3 = sym.Activation(data=f1, act_type="tanh", name="tanh3")
    f2 = sym.FullyConnected(data=a3, num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(data=f2, name="softmax")
