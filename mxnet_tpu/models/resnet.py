"""ResNet v2 (pre-activation) — the benchmark flagship.

Counterpart of the reference's example/image-classification/symbols/resnet.py
(He et al., "Identity Mappings in Deep Residual Networks"). Re-authored
TPU-first: all convs are static-shaped NCHW ``lax.conv_general_dilated`` calls
that XLA tiles onto the MXU; BN running stats are functional aux carries; the
whole fwd+bwd step compiles to one XLA computation through the Executor.

Depth table matches the reference: 18/34 use the basic 2-conv block, 50/101/
152 the 1-3-1 bottleneck, with stage filter counts (64,128,256,512)×{1,4}.
"""
from .. import symbol as sym

_BN_MOM = 0.9
_BN_EPS = 2e-5


def _conv_bn_act(data, num_filter, kernel, stride, pad, name, act=True):
    bn = sym.BatchNorm(data=data, fix_gamma=False, eps=_BN_EPS, momentum=_BN_MOM, name=name + "_bn")
    if act:
        bn = sym.Activation(data=bn, act_type="relu", name=name + "_relu")
    return sym.Convolution(
        data=bn, num_filter=num_filter, kernel=kernel, stride=stride, pad=pad,
        no_bias=True, name=name + "_conv",
    )


def residual_unit(data, num_filter, stride, dim_match, name, bottle_neck=True):
    """One pre-activation residual unit (reference resnet.py residual_unit)."""
    if bottle_neck:
        bn1 = sym.BatchNorm(data=data, fix_gamma=False, eps=_BN_EPS, momentum=_BN_MOM, name=name + "_bn1")
        act1 = sym.Activation(data=bn1, act_type="relu", name=name + "_relu1")
        conv1 = sym.Convolution(data=act1, num_filter=num_filter // 4, kernel=(1, 1),
                                stride=(1, 1), pad=(0, 0), no_bias=True, name=name + "_conv1")
        bn2 = sym.BatchNorm(data=conv1, fix_gamma=False, eps=_BN_EPS, momentum=_BN_MOM, name=name + "_bn2")
        act2 = sym.Activation(data=bn2, act_type="relu", name=name + "_relu2")
        conv2 = sym.Convolution(data=act2, num_filter=num_filter // 4, kernel=(3, 3),
                                stride=stride, pad=(1, 1), no_bias=True, name=name + "_conv2")
        bn3 = sym.BatchNorm(data=conv2, fix_gamma=False, eps=_BN_EPS, momentum=_BN_MOM, name=name + "_bn3")
        act3 = sym.Activation(data=bn3, act_type="relu", name=name + "_relu3")
        conv3 = sym.Convolution(data=act3, num_filter=num_filter, kernel=(1, 1),
                                stride=(1, 1), pad=(0, 0), no_bias=True, name=name + "_conv3")
        if dim_match:
            shortcut = data
        else:
            shortcut = sym.Convolution(data=act1, num_filter=num_filter, kernel=(1, 1),
                                       stride=stride, no_bias=True, name=name + "_sc")
        return conv3 + shortcut
    bn1 = sym.BatchNorm(data=data, fix_gamma=False, eps=_BN_EPS, momentum=_BN_MOM, name=name + "_bn1")
    act1 = sym.Activation(data=bn1, act_type="relu", name=name + "_relu1")
    conv1 = sym.Convolution(data=act1, num_filter=num_filter, kernel=(3, 3),
                            stride=stride, pad=(1, 1), no_bias=True, name=name + "_conv1")
    bn2 = sym.BatchNorm(data=conv1, fix_gamma=False, eps=_BN_EPS, momentum=_BN_MOM, name=name + "_bn2")
    act2 = sym.Activation(data=bn2, act_type="relu", name=name + "_relu2")
    conv2 = sym.Convolution(data=act2, num_filter=num_filter, kernel=(3, 3),
                            stride=(1, 1), pad=(1, 1), no_bias=True, name=name + "_conv2")
    if dim_match:
        shortcut = data
    else:
        shortcut = sym.Convolution(data=act1, num_filter=num_filter, kernel=(1, 1),
                                   stride=stride, no_bias=True, name=name + "_sc")
    return conv2 + shortcut


_DEPTHS = {
    18: ([2, 2, 2, 2], False),
    34: ([3, 4, 6, 3], False),
    50: ([3, 4, 6, 3], True),
    101: ([3, 4, 23, 3], True),
    152: ([3, 8, 36, 3], True),
}


def get_symbol(num_classes=1000, num_layers=50, image_shape="3,224,224", **kwargs):
    """Build a ResNet Symbol (reference resnet.py get_symbol)."""
    if isinstance(image_shape, str):
        image_shape = tuple(int(x) for x in image_shape.split(","))
    if num_layers not in _DEPTHS:
        raise ValueError("resnet num_layers must be one of %s" % sorted(_DEPTHS))
    units, bottle_neck = _DEPTHS[num_layers]
    filter_list = [64, 256, 512, 1024, 2048] if bottle_neck else [64, 64, 128, 256, 512]

    data = sym.Variable("data")
    (_, height, _) = image_shape
    if height <= 32:  # cifar-style stem (reference resnet.py small-image path)
        body = sym.Convolution(data=data, num_filter=filter_list[0], kernel=(3, 3),
                               stride=(1, 1), pad=(1, 1), no_bias=True, name="conv0")
    else:
        body = sym.Convolution(data=data, num_filter=filter_list[0], kernel=(7, 7),
                               stride=(2, 2), pad=(3, 3), no_bias=True, name="conv0")
        body = sym.BatchNorm(data=body, fix_gamma=False, eps=_BN_EPS, momentum=_BN_MOM, name="bn0")
        body = sym.Activation(data=body, act_type="relu", name="relu0")
        body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                           pool_type="max", name="pool0")

    for stage, n_unit in enumerate(units):
        stride = (1, 1) if stage == 0 else (2, 2)
        body = residual_unit(body, filter_list[stage + 1], stride, False,
                             name="stage%d_unit1" % (stage + 1), bottle_neck=bottle_neck)
        for j in range(n_unit - 1):
            body = residual_unit(body, filter_list[stage + 1], (1, 1), True,
                                 name="stage%d_unit%d" % (stage + 1, j + 2),
                                 bottle_neck=bottle_neck)

    bn1 = sym.BatchNorm(data=body, fix_gamma=False, eps=_BN_EPS, momentum=_BN_MOM, name="bn1")
    relu1 = sym.Activation(data=bn1, act_type="relu", name="relu1")
    pool1 = sym.Pooling(data=relu1, global_pool=True, kernel=(7, 7), pool_type="avg", name="pool1")
    flat = sym.Flatten(data=pool1)
    fc1 = sym.FullyConnected(data=flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc1, name="softmax")
