"""DLRM-style two-tower recommender: sharded embedding tables + MLP.

The canonical "millions of users" training workload (docs/SPARSE.md) and
the first embedding-dominated member of the zoo: ~97% of the trainable
bytes live in two ``SparseEmbedding`` tables whose gradients are row-sparse
by contract — only the rows a batch looks up ever reach the optimizer or
the wire. That shape is what the whole sparse subsystem exists to exploit:

* training — the KVStore sparse round (``sparse/kvstore_sparse.py``) ships
  the batch's unique-row union instead of the (vocab, dim) tables;
* placement — the tables carry the ``row_sparse_embedding`` shard-rule
  category, so the plan lint prices a vocab-sharded table as output-psum
  traffic and autoplan's per-param search shards them over the model axis
  instead of paying the dp grad-sync on the full tables.

Architecture (DLRM's embedding+MLP scaffold at a CI-friendly scale):
sparse id features ``user``/``item`` → embedding rows; dense features →
bottom MLP projected to the embedding width; the three vectors concatenate
(with the explicit user·item dot — the two-tower affinity — appended) into
a top MLP ending in a logistic click head.

Inputs: ``user`` (B,), ``item`` (B,) integer ids; ``dense`` (B, dense_dim)
float features; ``label`` (B,) in {0,1}.
"""
from .. import symbol as sym

__all__ = ["get_symbol"]


def _mlp(x, dims, name, act="relu"):
    for i, d in enumerate(dims):
        x = sym.FullyConnected(x, num_hidden=d, name="%s_fc%d" % (name, i))
        x = sym.Activation(x, act_type=act, name="%s_act%d" % (name, i))
    return x


def get_symbol(num_users=65536, num_items=32768, embed_dim=64, dense_dim=16,
               bottom_hidden=(128,), top_hidden=(512, 256), **kwargs):
    """Build the recommender Symbol.

    Defaults are sized so (a) each table clears the tensor-parallel
    shard-or-replicate boundary (``vocab * dim >= MIN_SHARD_ELEMS``) with a
    vocab dim divisible by every mesh factor up to 8, and (b) the top-MLP
    weights are large enough that autoplan can Megatron-shard them too —
    a dp×tp plan then splits EVERY major tensor and the planner's
    compute-utilization term stays neutral (docs/PARALLEL_PLANNER.md).
    """
    user = sym.Variable("user")
    item = sym.Variable("item")
    dense = sym.Variable("dense")
    label = sym.Variable("label")

    u = sym.SparseEmbedding(data=user, input_dim=num_users,
                            output_dim=embed_dim, name="user_embed")
    v = sym.SparseEmbedding(data=item, input_dim=num_items,
                            output_dim=embed_dim, name="item_embed")

    # bottom MLP: dense features projected to the embedding width
    d = _mlp(dense, tuple(bottom_hidden) + (embed_dim,), "bot")

    # two-tower affinity: the explicit user·item interaction, kept as a
    # feature next to the raw vectors (the DLRM pairwise-dot idea at the
    # two-tower special case)
    dot_uv = sym.sum(u * v, axis=1, keepdims=True)

    z = sym.Concat(u, v, d, dot_uv, num_args=4, dim=1, name="interact")
    top = _mlp(z, tuple(top_hidden), "top")
    logit = sym.FullyConnected(top, num_hidden=1, name="click")
    return sym.LogisticRegressionOutput(data=logit, label=label,
                                        name="click_prob")
