"""LSTM language model over the fused RNN op.

Counterpart of the reference's example/rnn/lstm_bucketing.py network: embed →
multi-layer LSTM → per-timestep FC → softmax. Where the reference unrolls
LSTMCell timesteps into seq_len graph nodes (rnn_cell.py:90 unroll) or uses
the cuDNN ``RNN`` op, here the flagship path is the registry's ``RNN`` op — a
``lax.scan`` whose per-step matmuls XLA batches onto the MXU.

Layout: data is (batch, seq_len) int tokens; RNN runs time-major (T, N, I).
"""
from .. import symbol as sym
from ..ops.rnn import rnn_param_size


def get_symbol(num_classes=10000, num_embed=256, num_hidden=512, num_layers=2,
               seq_len=32, batch_size=32, dropout=0.0, **kwargs):
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    embed = sym.Embedding(data=data, input_dim=num_classes, output_dim=num_embed,
                          name="embed")
    tm = sym.SwapAxis(data=embed, dim1=0, dim2=1, name="time_major")  # (T,N,E)
    from ..initializer import Uniform

    params = sym.Variable(
        "lstm_parameters",
        shape=(rnn_param_size(num_layers, num_embed, num_hidden, False, "lstm"),),
        # the fused blob has no weight/bias suffix for the initializer's
        # dispatch; pin the classic LSTM uniform init on the variable
        # (reference pattern: Variable(init=mx.init.FusedRNN(...)))
        init=Uniform(0.1))
    # initial states carry the batch dimension explicitly, like the reference's
    # lstm_bucketing init_states entries in provide_data (example/rnn/lstm.py)
    init_h = sym.Variable("lstm_init_h", shape=(num_layers, batch_size, num_hidden))
    init_c = sym.Variable("lstm_init_c", shape=(num_layers, batch_size, num_hidden))
    out = sym.RNN(data=tm, parameters=params, state=init_h, state_cell=init_c,
                  mode="lstm", state_size=num_hidden, num_layers=num_layers,
                  p=dropout, state_outputs=False, name="lstm")
    out = sym.Reshape(data=out, shape=(-1, num_hidden), name="reshape_out")
    pred = sym.FullyConnected(data=out, num_hidden=num_classes, name="pred")
    label_flat = sym.Reshape(data=sym.SwapAxis(data=label, dim1=0, dim2=1), shape=(-1,),
                             name="label_flat")
    return sym.SoftmaxOutput(data=pred, label=label_flat, name="softmax")
