"""Inception-v3 (reference: example/image-classification/symbols/
inception-v3.py — Szegedy et al., "Rethinking the Inception Architecture",
299x299 input; BASELINE.json config 2).

Re-authored TPU-first: the factorized 1x7/7x1 and 1x3/3x1 convolutions each
lower to one MXU conv; BN rides the custom-vjp training path (ops/nn.py);
the whole net traces into a single XLA computation.
"""
from .. import symbol as sym


def _unit(data, num_filter, kernel, stride=(1, 1), pad=(0, 0), name=None):
    c = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, no_bias=True,
                        name="%s_conv" % name)
    bn = sym.BatchNorm(data=c, fix_gamma=False, eps=2e-5, name="%s_bn" % name)
    return sym.Activation(data=bn, act_type="relu", name="%s_relu" % name)


def _pool(data, kind, kernel=(3, 3), stride=(1, 1), pad=(0, 0), name=None):
    return sym.Pooling(data=data, kernel=kernel, stride=stride, pad=pad,
                       pool_type=kind, name=name)


def _block_a(data, proj, name):
    """35x35 module: 1x1 / 5x5 / double-3x3 / pooled-projection branches."""
    b0 = _unit(data, 64, (1, 1), name="%s_b0" % name)
    b1 = _unit(data, 48, (1, 1), name="%s_b1a" % name)
    b1 = _unit(b1, 64, (5, 5), pad=(2, 2), name="%s_b1b" % name)
    b2 = _unit(data, 64, (1, 1), name="%s_b2a" % name)
    b2 = _unit(b2, 96, (3, 3), pad=(1, 1), name="%s_b2b" % name)
    b2 = _unit(b2, 96, (3, 3), pad=(1, 1), name="%s_b2c" % name)
    b3 = _pool(data, "avg", pad=(1, 1), name="%s_pool" % name)
    b3 = _unit(b3, proj, (1, 1), name="%s_b3" % name)
    return sym.Concat(b0, b1, b2, b3, name="%s_concat" % name)


def _grid_reduce_a(data, name):
    """35x35 → 17x17."""
    b0 = _unit(data, 384, (3, 3), stride=(2, 2), name="%s_b0" % name)
    b1 = _unit(data, 64, (1, 1), name="%s_b1a" % name)
    b1 = _unit(b1, 96, (3, 3), pad=(1, 1), name="%s_b1b" % name)
    b1 = _unit(b1, 96, (3, 3), stride=(2, 2), name="%s_b1c" % name)
    b2 = _pool(data, "max", stride=(2, 2), name="%s_pool" % name)
    return sym.Concat(b0, b1, b2, name="%s_concat" % name)


def _block_b(data, c7, name):
    """17x17 module with factorized 7x7 (1x7 then 7x1) branches."""
    b0 = _unit(data, 192, (1, 1), name="%s_b0" % name)
    b1 = _unit(data, c7, (1, 1), name="%s_b1a" % name)
    b1 = _unit(b1, c7, (1, 7), pad=(0, 3), name="%s_b1b" % name)
    b1 = _unit(b1, 192, (7, 1), pad=(3, 0), name="%s_b1c" % name)
    b2 = _unit(data, c7, (1, 1), name="%s_b2a" % name)
    b2 = _unit(b2, c7, (7, 1), pad=(3, 0), name="%s_b2b" % name)
    b2 = _unit(b2, c7, (1, 7), pad=(0, 3), name="%s_b2c" % name)
    b2 = _unit(b2, c7, (7, 1), pad=(3, 0), name="%s_b2d" % name)
    b2 = _unit(b2, 192, (1, 7), pad=(0, 3), name="%s_b2e" % name)
    b3 = _pool(data, "avg", pad=(1, 1), name="%s_pool" % name)
    b3 = _unit(b3, 192, (1, 1), name="%s_b3" % name)
    return sym.Concat(b0, b1, b2, b3, name="%s_concat" % name)


def _grid_reduce_b(data, name):
    """17x17 → 8x8."""
    b0 = _unit(data, 192, (1, 1), name="%s_b0a" % name)
    b0 = _unit(b0, 320, (3, 3), stride=(2, 2), name="%s_b0b" % name)
    b1 = _unit(data, 192, (1, 1), name="%s_b1a" % name)
    b1 = _unit(b1, 192, (1, 7), pad=(0, 3), name="%s_b1b" % name)
    b1 = _unit(b1, 192, (7, 1), pad=(3, 0), name="%s_b1c" % name)
    b1 = _unit(b1, 192, (3, 3), stride=(2, 2), name="%s_b1d" % name)
    b2 = _pool(data, "max", stride=(2, 2), name="%s_pool" % name)
    return sym.Concat(b0, b1, b2, name="%s_concat" % name)


def _block_c(data, pool_kind, name):
    """8x8 module with expanded 1x3/3x1 fan-outs."""
    b0 = _unit(data, 320, (1, 1), name="%s_b0" % name)
    b1 = _unit(data, 384, (1, 1), name="%s_b1a" % name)
    b1l = _unit(b1, 384, (1, 3), pad=(0, 1), name="%s_b1b" % name)
    b1r = _unit(b1, 384, (3, 1), pad=(1, 0), name="%s_b1c" % name)
    b2 = _unit(data, 448, (1, 1), name="%s_b2a" % name)
    b2 = _unit(b2, 384, (3, 3), pad=(1, 1), name="%s_b2b" % name)
    b2l = _unit(b2, 384, (1, 3), pad=(0, 1), name="%s_b2c" % name)
    b2r = _unit(b2, 384, (3, 1), pad=(1, 0), name="%s_b2d" % name)
    b3 = _pool(data, pool_kind, pad=(1, 1), name="%s_pool" % name)
    b3 = _unit(b3, 192, (1, 1), name="%s_b3" % name)
    return sym.Concat(b0, b1l, b1r, b2l, b2r, b3, name="%s_concat" % name)


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable("data")
    # stem: 299x299x3 → 35x35x192
    net = _unit(data, 32, (3, 3), stride=(2, 2), name="stem1")
    net = _unit(net, 32, (3, 3), name="stem2")
    net = _unit(net, 64, (3, 3), pad=(1, 1), name="stem3")
    net = _pool(net, "max", stride=(2, 2), name="stem_pool1")
    net = _unit(net, 80, (1, 1), name="stem4")
    net = _unit(net, 192, (3, 3), name="stem5")
    net = _pool(net, "max", stride=(2, 2), name="stem_pool2")
    # 3 x A (35x35)
    net = _block_a(net, 32, "mixed")
    net = _block_a(net, 64, "mixed_1")
    net = _block_a(net, 64, "mixed_2")
    net = _grid_reduce_a(net, "mixed_3")
    # 4 x B (17x17)
    net = _block_b(net, 128, "mixed_4")
    net = _block_b(net, 160, "mixed_5")
    net = _block_b(net, 160, "mixed_6")
    net = _block_b(net, 192, "mixed_7")
    net = _grid_reduce_b(net, "mixed_8")
    # 2 x C (8x8)
    net = _block_c(net, "avg", "mixed_9")
    net = _block_c(net, "max", "mixed_10")
    net = sym.Pooling(data=net, kernel=(8, 8), global_pool=True,
                      pool_type="avg", name="global_pool")
    net = sym.Flatten(data=net, name="flatten")
    net = sym.FullyConnected(data=net, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=net, name="softmax")
