"""Model zoo: Symbol constructors for the reference's training configs.

Counterpart of the reference's ``example/image-classification/symbols/``
(resnet.py, alexnet.py, vgg.py, inception-bn.py, lenet.py, mlp.py) — same
capability, re-authored TPU-first: every network lowers through Symbol →
Executor into one fused XLA computation, with shapes static so the MXU tiles
matmuls/convs, and bf16-friendly dtypes threaded via the ``dtype`` argument.

``get_symbol(name, num_classes, **kwargs)`` mirrors the reference's per-script
``get_symbol`` entry points (e.g. example/image-classification/symbols/
resnet.py get_symbol).
"""
from . import (lenet, mlp, alexnet, vgg, resnet, inception_bn, inception_v3,
               lstm, transformer, vgg16_ssd, recommender)

_ZOO = {
    "lenet": lenet.get_symbol,
    "mlp": mlp.get_symbol,
    "alexnet": alexnet.get_symbol,
    "vgg": vgg.get_symbol,
    "vgg16": lambda **kw: vgg.get_symbol(num_layers=16, **kw),
    "vgg19": lambda **kw: vgg.get_symbol(num_layers=19, **kw),
    "inception-bn": inception_bn.get_symbol,
    "inception_bn": inception_bn.get_symbol,
    "inception-v3": inception_v3.get_symbol,
    "inception_v3": inception_v3.get_symbol,
    "resnet": resnet.get_symbol,
    "resnet-18": lambda **kw: resnet.get_symbol(num_layers=18, **kw),
    "resnet-34": lambda **kw: resnet.get_symbol(num_layers=34, **kw),
    "resnet-50": lambda **kw: resnet.get_symbol(num_layers=50, **kw),
    "resnet-101": lambda **kw: resnet.get_symbol(num_layers=101, **kw),
    "resnet-152": lambda **kw: resnet.get_symbol(num_layers=152, **kw),
    "lstm": lstm.get_symbol,
    "transformer": transformer.get_symbol,
    "transformer_mt": transformer.get_symbol_mt,
    "vgg16-ssd-300": vgg16_ssd.get_symbol,
    "vgg16-ssd-300-train": vgg16_ssd.get_symbol_train,
    "recommender": recommender.get_symbol,
    "dlrm": recommender.get_symbol,
}


def get_symbol(name, **kwargs):
    """Build a named network Symbol (reference: each symbols/<net>.py
    get_symbol). kwargs are passed to the network constructor
    (num_classes, image_shape, num_layers, dtype, ...)."""
    key = name.lower()
    if key not in _ZOO:
        raise ValueError("unknown model %r (have: %s)" % (name, sorted(_ZOO)))
    return _ZOO[key](**kwargs)
