"""VGG16-reduced SSD-300 training symbol (reference: example/ssd/symbol/
symbol_vgg16_ssd_300.py + common.py multibox_layer; BASELINE.json config 4).

The canonical anchor specification — six feature scales (conv4_3 with a
learnable L2-norm scale, fc7, conv8_2 ... conv11_2), SSD paper sizes/ratios —
with the fc6 hole-algorithm conv (3x3, dilation 6). Training losses follow
the reference exactly: hard-negative-mined SoftmaxOutput over anchor classes
plus smooth-L1 MakeLoss on masked location offsets; the whole multi-loss
graph is one XLA computation per step.
"""
import json

from .. import symbol as sym

# SSD-300 anchor spec (reference symbol_vgg16_ssd_300.py:118-122)
SIZES = [[.1, .141], [.2, .272], [.37, .447], [.54, .619], [.71, .79],
         [.88, .961]]
RATIOS = [[1, 2, .5], [1, 2, .5, 3, 1. / 3], [1, 2, .5, 3, 1. / 3],
          [1, 2, .5, 3, 1. / 3], [1, 2, .5], [1, 2, .5]]
NORMALIZATIONS = [20, -1, -1, -1, -1, -1]


def _conv_relu(data, name, num_filter, kernel=(3, 3), pad=(1, 1),
               stride=(1, 1), dilate=None):
    kw = {"dilate": dilate} if dilate else {}
    c = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                        pad=pad, stride=stride, name="conv%s" % name, **kw)
    return sym.Activation(data=c, act_type="relu", name="relu%s" % name)


def _vgg_stage(data, name, num_filter, convs, pool_kernel=(2, 2),
               pool_stride=(2, 2), pool_pad=(0, 0), pool_convention="valid"):
    net = data
    for i in range(convs):
        net = _conv_relu(net, "%s_%d" % (name, i + 1), num_filter)
    feat = net
    net = sym.Pooling(data=net, pool_type="max", kernel=pool_kernel,
                      stride=pool_stride, pad=pool_pad,
                      pooling_convention=pool_convention,
                      name="pool%s" % name)
    return net, feat


def _backbone(data):
    """VGG16 body with SSD modifications: pool5 3x3/1, dilated conv6 (the
    surgery replacing fc6/fc7), plus the extra pyramid layers."""
    net, _ = _vgg_stage(data, "1", 64, 2)
    net, _ = _vgg_stage(net, "2", 128, 2)
    # pool3 uses ceil-mode ('full') so 75 → 38, matching the reference
    net, _ = _vgg_stage(net, "3", 256, 3, pool_convention="full")
    net, conv4_3 = _vgg_stage(net, "4", 512, 3)
    net, _ = _vgg_stage(net, "5", 512, 3, pool_kernel=(3, 3),
                        pool_stride=(1, 1), pool_pad=(1, 1))
    net = _conv_relu(net, "6", 1024, pad=(6, 6), dilate=(6, 6))
    relu7 = _conv_relu(net, "7", 1024, kernel=(1, 1), pad=(0, 0))
    # extra layers: 1x1 squeeze then 3x3 (stride 2 for 8/9, valid for 10/11)
    net = _conv_relu(relu7, "8_1", 256, kernel=(1, 1), pad=(0, 0))
    conv8_2 = _conv_relu(net, "8_2", 512, stride=(2, 2))
    net = _conv_relu(conv8_2, "9_1", 128, kernel=(1, 1), pad=(0, 0))
    conv9_2 = _conv_relu(net, "9_2", 256, stride=(2, 2))
    net = _conv_relu(conv9_2, "10_1", 128, kernel=(1, 1), pad=(0, 0))
    conv10_2 = _conv_relu(net, "10_2", 256, pad=(0, 0))
    net = _conv_relu(conv10_2, "11_1", 128, kernel=(1, 1), pad=(0, 0))
    conv11_2 = _conv_relu(net, "11_2", 256, pad=(0, 0))
    return [conv4_3, relu7, conv8_2, conv9_2, conv10_2, conv11_2]


def multibox_layer(layers, num_classes, sizes, ratios, normalizations=None,
                   num_channels=()):
    """Per-scale class/location heads + anchors (reference: common.py
    multibox_layer). ``num_channels`` supplies the channel count for each
    normalized layer (consumed in order), sizing its learnable scale.
    Returns (cls_preds (B,C+1,N), loc_preds (B,4N), anchors (1,N,4))."""
    cls_layers, loc_layers, anchor_layers = [], [], []
    if normalizations is None:
        normalizations = [-1] * len(layers)
    channels = list(num_channels)
    for i, (feat, size, ratio, norm) in enumerate(
            zip(layers, sizes, ratios, normalizations)):
        if norm > 0:
            if not channels:
                raise ValueError(
                    "multibox_layer: normalizations[%d] > 0 needs a "
                    "num_channels entry to size the scale variable" % i)
            feat = sym.L2Normalization(data=feat, mode="channel",
                                       name="norm_%d" % i)
            scale = sym.Variable(
                "scale_%d" % i,
                attr={"__shape__": json.dumps([1, channels.pop(0), 1, 1]),
                      "__init__": json.dumps(["Constant", {"value": norm}])})
            feat = sym.broadcast_mul(scale, feat, name="scaled_%d" % i)
        na = len(size) + len(ratio) - 1
        cls = sym.Convolution(data=feat, num_filter=na * (num_classes + 1),
                              kernel=(3, 3), pad=(1, 1),
                              name="cls_pred_%d" % i)
        cls = sym.transpose(cls, axes=(0, 2, 3, 1))
        cls_layers.append(sym.Reshape(cls, shape=(0, -1, num_classes + 1)))
        loc = sym.Convolution(data=feat, num_filter=na * 4, kernel=(3, 3),
                              pad=(1, 1), name="loc_pred_%d" % i)
        loc = sym.transpose(loc, axes=(0, 2, 3, 1))
        loc_layers.append(sym.Reshape(loc, shape=(0, -1)))
        anchor_layers.append(sym.MultiBoxPrior(
            feat, sizes=size, ratios=ratio, name="anchors_%d" % i))
    cls_preds = sym.Concat(*cls_layers, dim=1, name="cls_preds_pre")
    cls_preds = sym.transpose(cls_preds, axes=(0, 2, 1), name="cls_preds")
    loc_preds = sym.Concat(*loc_layers, dim=1, name="loc_preds")
    anchors = sym.Concat(*anchor_layers, dim=1, name="anchors")
    return cls_preds, loc_preds, anchors


def ssd_losses(cls_preds, loc_preds, anchors, label):
    """The reference's SSD training tail: MultiBoxTarget with 3:1 hard
    negative mining → ignore-aware SoftmaxOutput + masked smooth-L1 MakeLoss
    (symbol_vgg16_ssd_300.py:129-147)."""
    loc_target, loc_target_mask, cls_target = sym.MultiBoxTarget(
        anchors, label, cls_preds, overlap_threshold=0.5, ignore_label=-1,
        negative_mining_ratio=3, negative_mining_thresh=0.5,
        variances=(0.1, 0.1, 0.2, 0.2), name="multibox_target")
    cls_prob = sym.SoftmaxOutput(data=cls_preds, label=cls_target,
                                 ignore_label=-1, use_ignore=True,
                                 multi_output=True, normalization="valid",
                                 name="cls_prob")
    loc_diff = loc_target_mask * (loc_preds - loc_target)
    loc_loss_ = sym.smooth_l1(data=loc_diff, scalar=1.0, name="loc_loss_")
    loc_loss = sym.MakeLoss(loc_loss_, grad_scale=1.0,
                            normalization="valid", name="loc_loss")
    cls_label = sym.MakeLoss(data=cls_target, grad_scale=0, name="cls_label")
    return sym.Group([cls_prob, loc_loss, cls_label])


def get_symbol_train(num_classes=20, **kwargs):
    """Training graph: backbone → heads → MultiBoxTarget → losses
    (reference: symbol_vgg16_ssd_300.py get_symbol_train)."""
    data = sym.Variable("data")
    label = sym.Variable("label")
    layers = _backbone(data)
    cls_preds, loc_preds, anchors = multibox_layer(
        layers, num_classes, SIZES, RATIOS, NORMALIZATIONS,
        num_channels=[512])
    return ssd_losses(cls_preds, loc_preds, anchors, label)


def get_symbol(num_classes=20, nms_thresh=0.5, nms_topk=400, **kwargs):
    """Deploy graph: heads → MultiBoxDetection (reference: get_symbol)."""
    data = sym.Variable("data")
    layers = _backbone(data)
    cls_preds, loc_preds, anchors = multibox_layer(
        layers, num_classes, SIZES, RATIOS, NORMALIZATIONS,
        num_channels=[512])
    cls_prob = sym.SoftmaxActivation(data=cls_preds, mode="channel",
                                     name="cls_prob")
    return sym.MultiBoxDetection(cls_prob, loc_preds, anchors,
                                 name="detection", nms_threshold=nms_thresh,
                                 variances=(0.1, 0.1, 0.2, 0.2),
                                 nms_topk=nms_topk)
