"""Decoder-only Transformer language model (BASELINE.md stretch config:
Transformer-base MT; built entirely from Symbol ops with the fused
MultiHeadAttention op from ops/attention.py).

Pre-norm blocks: x + MHA(LN(x)), x + FFN(LN(x)); LN via the registry's
LayerNorm-equivalent composition (InstanceNorm is channel-first, so LN here
is mean/var composed from broadcast ops to stay faithful to the op set)."""
import numpy as np

from .. import symbol as sym


def _layer_norm(x, name, dim):
    # Deliberately the naive frontend composition: the variance branch
    # recomputes its own mean/centering, and the square is spelled as a
    # self-multiply. Bit-identical to the canonical single-chain form (XLA
    # CSEs the duplicates; x*x IS jnp.square), but the norm_residual fusion
    # matcher cannot root it until the bind-time rewrite pipeline
    # (MXNET_GRAPHREWRITE: cse merges the duplicate mean/center,
    # canonicalize turns the self-multiply into square) normalizes it —
    # the sloppy-frontend contract docs/static_analysis.md §GL6xx gates.
    # Default-config perf is unaffected: pattern sites only ENGAGE via the
    # opt-in autotuner (MXNET_FUSION_TUNE_DIR) or a force, and a tuned
    # deployment turns rewrites on alongside it.
    mean = sym.mean(x, axis=-1, keepdims=True)
    cent = sym.broadcast_sub(x, mean, name="%s_cent" % name)
    cent_v = sym.broadcast_sub(x, sym.mean(x, axis=-1, keepdims=True))
    var = sym.mean(cent_v * cent_v, axis=-1, keepdims=True)
    inv = sym.rsqrt(var + 1e-5)
    normed = sym.broadcast_mul(cent, inv)
    gamma = sym.Variable("%s_gamma" % name, shape=(dim,))
    beta = sym.Variable("%s_beta" % name, shape=(dim,))
    return sym.broadcast_add(sym.broadcast_mul(normed, gamma), beta, name=name)


def _split_fused(fused, n_parts, seq_len, num_heads, dh):
    """Split one fused (B, T, n_parts·M) projection into n_parts head-major
    (B, H, T, dh) tensors — the single owner of the fused-weight layout."""
    fused = sym.Reshape(fused, shape=(-1, seq_len, n_parts, num_heads, dh))
    outs = []
    for i in range(n_parts):
        p = sym.Reshape(sym.slice_axis(fused, axis=2, begin=i, end=i + 1),
                        shape=(-1, seq_len, num_heads, dh))
        outs.append(sym.SwapAxis(p, dim1=1, dim2=2))  # (B,T,H,D)→(B,H,T,D)
    return outs


def _attention_block(x, name, num_heads, model_dim, seq_len, causal=True,
                     return_kv=False):
    """Self-attention with ONE fused 3·M-wide qkv GEMM (better MXU shape
    than three M-wide projections; used for every q==kv site).
    ``return_kv`` also hands back the head-major (B, H, T, dh) key/value
    tensors — the serving prefill graph (get_prefill_symbol) exports them
    to seed the decode path's ring KV buffer."""
    dh = model_dim // num_heads
    qkv = sym.FullyConnected(data=x, num_hidden=3 * model_dim, flatten=False,
                             name="%s_qkv" % name)
    q, k, v = _split_fused(qkv, 3, seq_len, num_heads, dh)
    att = sym.MultiHeadAttention(query=q, key=k, value=v, causal=causal,
                                 name="%s_att" % name)
    att = sym.SwapAxis(att, dim1=1, dim2=2)  # (B,T,H,D)
    att = sym.Reshape(att, shape=(-1, seq_len, model_dim))
    proj = sym.FullyConnected(data=att, num_hidden=model_dim, flatten=False,
                              name="%s_proj" % name)
    if return_kv:
        return proj, k, v
    return proj


def _split_heads(x, seq_len, num_heads, dh):
    """(B, T, M) → (B, H, T, dh) for the fused attention op."""
    x = sym.Reshape(x, shape=(-1, seq_len, num_heads, dh))
    return sym.SwapAxis(x, dim1=1, dim2=2)


def _merge_heads(att, seq_len, model_dim):
    att = sym.SwapAxis(att, dim1=1, dim2=2)
    return sym.Reshape(att, shape=(-1, seq_len, model_dim))


def _cross_attention(q_in, kv_in, name, num_heads, model_dim, q_len, kv_len):
    """Attention with separate query/key-value sources (the MT decoder's
    encoder-attention). Only the q projection is separate; k and v share
    one fused 2·M-wide GEMM on kv_in (same MXU-shape rationale as
    _attention_block's fused qkv; self-attention sites use that block)."""
    dh = model_dim // num_heads
    q = sym.FullyConnected(data=q_in, num_hidden=model_dim, flatten=False,
                           name="%s_q" % name)
    kv = sym.FullyConnected(data=kv_in, num_hidden=2 * model_dim,
                            flatten=False, name="%s_kv" % name)
    k, v = _split_fused(kv, 2, kv_len, num_heads, dh)
    att = sym.MultiHeadAttention(
        query=_split_heads(q, q_len, num_heads, dh),
        key=k, value=v,
        causal=False, name="%s_att" % name)
    att = _merge_heads(att, q_len, model_dim)
    return sym.FullyConnected(data=att, num_hidden=model_dim, flatten=False,
                              name="%s_proj" % name)


def _ffn(x, name, model_dim, ffn_dim):
    h = sym.FullyConnected(data=x, num_hidden=ffn_dim, flatten=False,
                           name="%s_ffn1" % name)
    h = sym.Activation(h, act_type="relu")
    return sym.FullyConnected(data=h, num_hidden=model_dim, flatten=False,
                              name="%s_ffn2" % name)


def _embed_with_pos(tokens, vocab_size, model_dim, seq_len, name):
    embed = sym.Embedding(data=tokens, input_dim=vocab_size,
                          output_dim=model_dim, name="%s_embed" % name)
    pos = sym.Variable("%s_pos_weight" % name, shape=(seq_len, model_dim))
    return sym.broadcast_add(
        embed, sym.Reshape(pos, shape=(1, seq_len, model_dim)),
        name="%s_pos_add" % name)


def get_symbol_mt(vocab_size=32000, num_layers=6, num_heads=8, model_dim=512,
                  ffn_dim=2048, src_len=64, tgt_len=64, **kwargs):
    """Encoder-decoder Transformer-base for MT (BASELINE.md stretch config:
    "Transformer-base MT"; the reference era predates Transformers — the
    closest ancestor is its seq2seq RNN stack — so the architecture here is
    the standard pre-norm Transformer built from this repo's Symbol ops and
    the fused MultiHeadAttention, not a translation of reference code).

    Inputs: ``data`` (B, src_len) source tokens, ``dec_data`` (B, tgt_len)
    shifted-right target tokens, ``softmax_label`` (B, tgt_len). Fixed
    lengths (pad to bucket shapes; BucketingModule handles the rest) —
    padding attends as ordinary tokens, the toy/bucketed regime this model
    targets."""
    src = sym.Variable("data")
    tgt = sym.Variable("dec_data")
    label = sym.Variable("softmax_label")

    # ---- encoder: pre-norm self-attention stack, non-causal
    x = _embed_with_pos(src, vocab_size, model_dim, src_len, "enc")
    for i in range(num_layers):
        n = "enc%d" % i
        ln = _layer_norm(x, "%s_ln1" % n, model_dim)
        x = x + _attention_block(ln, n + "_self", num_heads, model_dim,
                                 src_len, causal=False)
        x = x + _ffn(_layer_norm(x, "%s_ln2" % n, model_dim), n,
                     model_dim, ffn_dim)
    memory = _layer_norm(x, "enc_final_ln", model_dim)

    # ---- decoder: causal self-attention + cross-attention on the memory
    y = _embed_with_pos(tgt, vocab_size, model_dim, tgt_len, "dec")
    for i in range(num_layers):
        n = "dec%d" % i
        ln = _layer_norm(y, "%s_ln1" % n, model_dim)
        y = y + _attention_block(ln, n + "_self", num_heads, model_dim,
                                 tgt_len, causal=True)
        y = y + _cross_attention(_layer_norm(y, "%s_ln2" % n, model_dim),
                                 memory, n + "_cross", num_heads, model_dim,
                                 tgt_len, src_len)
        y = y + _ffn(_layer_norm(y, "%s_ln3" % n, model_dim), n,
                     model_dim, ffn_dim)
    y = _layer_norm(y, "dec_final_ln", model_dim)
    y = sym.Reshape(y, shape=(-1, model_dim))
    logits = sym.FullyConnected(data=y, num_hidden=vocab_size, name="mt_head")
    label_flat = sym.Reshape(label, shape=(-1,))
    return sym.SoftmaxOutput(data=logits, label=label_flat, name="softmax")


# --------------------------------------------------------------------- serving
def get_prefill_symbol(vocab_size=32000, num_layers=6, num_heads=8,
                       model_dim=512, ffn_dim=2048, prefill_len=64,
                       pos_len=None, **kwargs):
    """Serving prefill graph (docs/SERVING.md): the decoder-only LM of
    ``get_symbol`` over a fixed ``prefill_len`` bucket, additionally
    exporting every layer's head-major key/value tensors so the serving
    path can seed the decode executable's ring KV buffer.

    Weight names are IDENTICAL to ``get_symbol`` — a trained checkpoint
    loads into either. ``pos_len`` is the trained position table's length
    (defaults to ``prefill_len``); prompts are right-padded to
    ``prefill_len`` by the caller, and causality guarantees pad tokens
    cannot influence earlier positions.

    Outputs: ``[logits (B·P, vocab), k_0, v_0, ..., k_{L-1}, v_{L-1}]``
    with each k/v of shape (B, H, P, dh).
    """
    pos_len = pos_len or prefill_len
    data = sym.Variable("data")  # (B, P) int tokens, right-padded
    embed = sym.Embedding(data=data, input_dim=vocab_size,
                          output_dim=model_dim, name="embed")
    pos = sym.Variable("pos_embed_weight", shape=(pos_len, model_dim))
    if prefill_len != pos_len:
        pos = sym.slice_axis(pos, axis=0, begin=0, end=prefill_len)
    x = sym.broadcast_add(
        embed, sym.Reshape(pos, shape=(1, prefill_len, model_dim)),
        name="pos_add")
    kvs = []
    for i in range(num_layers):
        name = "layer%d" % i
        a, k, v = _attention_block(
            _layer_norm(x, "%s_ln1" % name, model_dim), name, num_heads,
            model_dim, prefill_len, causal=True, return_kv=True)
        kvs += [k, v]
        x = x + a
        x = x + _ffn(_layer_norm(x, "%s_ln2" % name, model_dim), name,
                     model_dim, ffn_dim)
    x = _layer_norm(x, "final_ln", model_dim)
    logits = sym.FullyConnected(
        data=sym.Reshape(x, shape=(-1, model_dim)), num_hidden=vocab_size,
        name="lm_head")
    return sym.Group([logits] + kvs)


def get_decode_symbol(vocab_size=32000, num_layers=6, num_heads=8,
                      model_dim=512, ffn_dim=2048, max_len=64, pos_len=None,
                      per_stream_slots=False, token_out=True, **kwargs):
    """Serving single-token decode graph (docs/SERVING.md): ONE token per
    stream through the ``get_symbol`` stack, attending over a preallocated
    ring KV buffer of ``max_len`` slots per layer. Compiles ONCE — every
    decode step replays the same executable regardless of position.

    Inputs beyond the weights:
      - ``data`` (B, 1): the current token ids.
      - ``pos_idx`` (B, 1): absolute positions (rows of the trained
        position table, so ``pos < pos_len``).
      - ``slot_onehot`` (max_len,): one-hot of the ring slot this token
        writes (``pos % max_len``). The KV update is in-graph:
        ``kv' = kv·(1-oh) + kv_new·oh`` — no per-step host scatter, no
        per-slot recompile.
      - ``kv_mask`` (max_len,): additive score mask — 0 on slots holding
        real context (INCLUDING the current slot), a large negative on
        empty slots.
      - ``kv_k_i`` / ``kv_v_i`` (B, H, max_len, dh) per layer: the ring
        buffers. The updated buffers are program OUTPUTS; the caller swaps
        them back in as the next step's inputs (KVCacheDecoder does).

    ``per_stream_slots=True`` is the paged/multiplexed variant
    (PagedKVDecoder): ``slot_onehot`` and ``kv_mask`` become (B, max_len)
    so every batch lane carries its OWN write slot, its own valid-slot set
    and its own position — one decode dispatch serves B *independent*
    sequences at arbitrary, different positions. An all-zero onehot row
    writes nothing (that lane's KV passes through unchanged), which is how
    idle lanes ride along for free. Attention over slots stays
    order-agnostic (positions live in the embeddings), so a lane's tokens
    may occupy ANY physical slots — the property the paged allocator's
    non-contiguous page placement relies on. The math per lane is
    identical to the shared-slot graph at the same position.

    T=1 collapses attention to a masked weighted sum, so it is composed
    from broadcast primitives (scores = Σ_d q·k, softmax, Σ_s p·v) instead
    of the fused MultiHeadAttention op — same math, fp32-exact against the
    full-sequence forward at matching positions.

    Outputs: ``[logits (B, vocab), k'_0, v'_0, ..., k'_{L-1}, v'_{L-1}]``,
    plus — with ``token_out=True`` (the default) — a trailing
    ``greedy_token (B,)`` head: ``argmax(logits, axis=-1)`` lowered ON
    DEVICE, so a greedy driver pulls one id per stream instead of the
    full (B, vocab) logits row (GL703; the KV outputs keep their
    ``1 + 2*i`` positions either way). The ``greedy_token`` NAME is a
    detection contract: ``KVCacheDecoder.warmup`` decides whether a
    (possibly disk-cached) compiled program carries the head by looking
    for it in ``output_dict`` by name — rename it and stale caches start
    masquerading as token-less programs.

    This graph is also the megastep building block
    (serving/kv_decode.py ``_DecodeMegastep``): the per-stream variant is
    pure in its (data, pos_idx, slot_onehot, kv_mask, kv_*) inputs, so K
    decode steps compose as a ``lax.scan`` over ONE compiled body — the
    scan carries the KV outputs back into the KV inputs and feeds each
    step's sampled token to the next, keeping the whole K-token loop
    device-resident (docs/SERVING.md §megasteps).
    """
    pos_len = pos_len or max_len
    dh = model_dim // num_heads
    scale = 1.0 / float(np.sqrt(dh))
    data = sym.Variable("data")
    pos_idx = sym.Variable("pos_idx")
    oh = sym.Variable("slot_onehot")
    msk = sym.Variable("kv_mask")
    if per_stream_slots:
        oh4 = sym.Reshape(oh, shape=(-1, 1, max_len, 1))
        msk3 = sym.Reshape(msk, shape=(-1, 1, max_len))
    else:
        oh4 = sym.Reshape(oh, shape=(1, 1, max_len, 1))
        msk3 = sym.Reshape(msk, shape=(1, 1, max_len))
    keep4 = 1.0 - oh4
    emb = sym.Embedding(data=data, input_dim=vocab_size,
                        output_dim=model_dim, name="embed")
    posrow = sym.Embedding(data=pos_idx, input_dim=pos_len,
                           output_dim=model_dim, name="pos_embed")
    x = emb + posrow  # (B, 1, M)
    kv_outs = []
    for i in range(num_layers):
        name = "layer%d" % i
        ln = _layer_norm(x, "%s_ln1" % name, model_dim)
        qkv = sym.FullyConnected(data=ln, num_hidden=3 * model_dim,
                                 flatten=False, name="%s_qkv" % name)
        q, k_new, v_new = _split_fused(qkv, 3, 1, num_heads, dh)
        kv_k = sym.Variable("kv_k_%d" % i)
        kv_v = sym.Variable("kv_v_%d" % i)
        k_upd = sym.broadcast_add(sym.broadcast_mul(kv_k, keep4),
                                  sym.broadcast_mul(k_new, oh4),
                                  name="%s_kupd" % name)
        v_upd = sym.broadcast_add(sym.broadcast_mul(kv_v, keep4),
                                  sym.broadcast_mul(v_new, oh4),
                                  name="%s_vupd" % name)
        kv_outs += [k_upd, v_upd]
        scores = sym.sum(sym.broadcast_mul(q, k_upd), axis=3) * scale
        scores = sym.broadcast_add(scores, msk3)  # (B, H, S)
        p = sym.softmax(scores, axis=-1)
        ctx = sym.sum(sym.broadcast_mul(sym.expand_dims(p, axis=3), v_upd),
                      axis=2)  # (B, H, dh)
        att = sym.Reshape(
            sym.SwapAxis(sym.Reshape(ctx, shape=(-1, num_heads, 1, dh)),
                         dim1=1, dim2=2),
            shape=(-1, 1, model_dim))
        x = x + sym.FullyConnected(data=att, num_hidden=model_dim,
                                   flatten=False, name="%s_proj" % name)
        x = x + _ffn(_layer_norm(x, "%s_ln2" % name, model_dim), name,
                     model_dim, ffn_dim)
    x = _layer_norm(x, "final_ln", model_dim)
    logits = sym.FullyConnected(
        data=sym.Reshape(x, shape=(-1, model_dim)), num_hidden=vocab_size,
        name="lm_head")
    outs = [logits] + kv_outs
    if token_out:
        outs.append(sym.argmax(logits, axis=-1, name="greedy_token"))
    return sym.Group(outs)


def get_symbol(vocab_size=32000, num_layers=6, num_heads=8, model_dim=512,
               ffn_dim=2048, seq_len=64, **kwargs):
    data = sym.Variable("data")  # (B, T) int tokens
    label = sym.Variable("softmax_label")
    embed = sym.Embedding(data=data, input_dim=vocab_size,
                          output_dim=model_dim, name="embed")
    pos = sym.Variable("pos_embed_weight", shape=(seq_len, model_dim))
    x = sym.broadcast_add(embed, sym.Reshape(pos, shape=(1, seq_len, model_dim)),
                          name="pos_add")
    for i in range(num_layers):
        name = "layer%d" % i
        a = _attention_block(_layer_norm(x, "%s_ln1" % name, model_dim),
                             name, num_heads, model_dim, seq_len)
        x = x + a
        h = _layer_norm(x, "%s_ln2" % name, model_dim)
        h = sym.FullyConnected(data=h, num_hidden=ffn_dim, flatten=False,
                               name="%s_ffn1" % name)
        h = sym.Activation(h, act_type="relu")
        h = sym.FullyConnected(data=h, num_hidden=model_dim, flatten=False,
                               name="%s_ffn2" % name)
        x = x + h
    x = _layer_norm(x, "final_ln", model_dim)
    x = sym.Reshape(x, shape=(-1, model_dim))
    logits = sym.FullyConnected(data=x, num_hidden=vocab_size, name="lm_head")
    label_flat = sym.Reshape(label, shape=(-1,))
    return sym.SoftmaxOutput(data=logits, label=label_flat, name="softmax")
