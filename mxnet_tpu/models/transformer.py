"""Decoder-only Transformer language model (BASELINE.md stretch config:
Transformer-base MT; built entirely from Symbol ops with the fused
MultiHeadAttention op from ops/attention.py).

Pre-norm blocks: x + MHA(LN(x)), x + FFN(LN(x)); LN via the registry's
LayerNorm-equivalent composition (InstanceNorm is channel-first, so LN here
is mean/var composed from broadcast ops to stay faithful to the op set)."""
import numpy as np

from .. import symbol as sym


def _layer_norm(x, name, dim):
    mean = sym.mean(x, axis=-1, keepdims=True)
    cent = sym.broadcast_sub(x, mean, name="%s_cent" % name)
    var = sym.mean(sym.square(cent), axis=-1, keepdims=True)
    inv = sym.rsqrt(var + 1e-5)
    normed = sym.broadcast_mul(cent, inv)
    gamma = sym.Variable("%s_gamma" % name, shape=(dim,))
    beta = sym.Variable("%s_beta" % name, shape=(dim,))
    return sym.broadcast_add(sym.broadcast_mul(normed, gamma), beta, name=name)


def _attention_block(x, name, num_heads, model_dim, seq_len):
    dh = model_dim // num_heads
    qkv = sym.FullyConnected(data=x, num_hidden=3 * model_dim, flatten=False,
                             name="%s_qkv" % name)
    qkv = sym.Reshape(qkv, shape=(-1, seq_len, 3, num_heads, dh))
    q = sym.Reshape(sym.slice_axis(qkv, axis=2, begin=0, end=1),
                    shape=(-1, seq_len, num_heads, dh))
    k = sym.Reshape(sym.slice_axis(qkv, axis=2, begin=1, end=2),
                    shape=(-1, seq_len, num_heads, dh))
    v = sym.Reshape(sym.slice_axis(qkv, axis=2, begin=2, end=3),
                    shape=(-1, seq_len, num_heads, dh))
    # (B,T,H,D) → (B,H,T,D)
    q = sym.SwapAxis(q, dim1=1, dim2=2)
    k = sym.SwapAxis(k, dim1=1, dim2=2)
    v = sym.SwapAxis(v, dim1=1, dim2=2)
    att = sym.MultiHeadAttention(query=q, key=k, value=v, causal=True,
                                 name="%s_att" % name)
    att = sym.SwapAxis(att, dim1=1, dim2=2)  # (B,T,H,D)
    att = sym.Reshape(att, shape=(-1, seq_len, model_dim))
    return sym.FullyConnected(data=att, num_hidden=model_dim, flatten=False,
                              name="%s_proj" % name)


def get_symbol(vocab_size=32000, num_layers=6, num_heads=8, model_dim=512,
               ffn_dim=2048, seq_len=64, **kwargs):
    data = sym.Variable("data")  # (B, T) int tokens
    label = sym.Variable("softmax_label")
    embed = sym.Embedding(data=data, input_dim=vocab_size,
                          output_dim=model_dim, name="embed")
    pos = sym.Variable("pos_embed_weight", shape=(seq_len, model_dim))
    x = sym.broadcast_add(embed, sym.Reshape(pos, shape=(1, seq_len, model_dim)),
                          name="pos_add")
    for i in range(num_layers):
        name = "layer%d" % i
        a = _attention_block(_layer_norm(x, "%s_ln1" % name, model_dim),
                             name, num_heads, model_dim, seq_len)
        x = x + a
        h = _layer_norm(x, "%s_ln2" % name, model_dim)
        h = sym.FullyConnected(data=h, num_hidden=ffn_dim, flatten=False,
                               name="%s_ffn1" % name)
        h = sym.Activation(h, act_type="relu")
        h = sym.FullyConnected(data=h, num_hidden=model_dim, flatten=False,
                               name="%s_ffn2" % name)
        x = x + h
    x = _layer_norm(x, "final_ln", model_dim)
    x = sym.Reshape(x, shape=(-1, model_dim))
    logits = sym.FullyConnected(data=x, num_hidden=vocab_size, name="lm_head")
    label_flat = sym.Reshape(label, shape=(-1,))
    return sym.SoftmaxOutput(data=logits, label=label_flat, name="softmax")
