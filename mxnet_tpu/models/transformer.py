"""Decoder-only Transformer language model (BASELINE.md stretch config:
Transformer-base MT; built entirely from Symbol ops with the fused
MultiHeadAttention op from ops/attention.py).

Pre-norm blocks: x + MHA(LN(x)), x + FFN(LN(x)); LN via the registry's
LayerNorm-equivalent composition (InstanceNorm is channel-first, so LN here
is mean/var composed from broadcast ops to stay faithful to the op set)."""
import numpy as np

from .. import symbol as sym


def _layer_norm(x, name, dim):
    # Deliberately the naive frontend composition: the variance branch
    # recomputes its own mean/centering, and the square is spelled as a
    # self-multiply. Bit-identical to the canonical single-chain form (XLA
    # CSEs the duplicates; x*x IS jnp.square), but the norm_residual fusion
    # matcher cannot root it until the bind-time rewrite pipeline
    # (MXNET_GRAPHREWRITE: cse merges the duplicate mean/center,
    # canonicalize turns the self-multiply into square) normalizes it —
    # the sloppy-frontend contract docs/static_analysis.md §GL6xx gates.
    # Default-config perf is unaffected: pattern sites only ENGAGE via the
    # opt-in autotuner (MXNET_FUSION_TUNE_DIR) or a force, and a tuned
    # deployment turns rewrites on alongside it.
    mean = sym.mean(x, axis=-1, keepdims=True)
    cent = sym.broadcast_sub(x, mean, name="%s_cent" % name)
    cent_v = sym.broadcast_sub(x, sym.mean(x, axis=-1, keepdims=True))
    var = sym.mean(cent_v * cent_v, axis=-1, keepdims=True)
    inv = sym.rsqrt(var + 1e-5)
    normed = sym.broadcast_mul(cent, inv)
    gamma = sym.Variable("%s_gamma" % name, shape=(dim,))
    beta = sym.Variable("%s_beta" % name, shape=(dim,))
    return sym.broadcast_add(sym.broadcast_mul(normed, gamma), beta, name=name)


def _split_fused(fused, n_parts, seq_len, num_heads, dh):
    """Split one fused (B, T, n_parts·M) projection into n_parts head-major
    (B, H, T, dh) tensors — the single owner of the fused-weight layout."""
    fused = sym.Reshape(fused, shape=(-1, seq_len, n_parts, num_heads, dh))
    outs = []
    for i in range(n_parts):
        p = sym.Reshape(sym.slice_axis(fused, axis=2, begin=i, end=i + 1),
                        shape=(-1, seq_len, num_heads, dh))
        outs.append(sym.SwapAxis(p, dim1=1, dim2=2))  # (B,T,H,D)→(B,H,T,D)
    return outs


def _attention_block(x, name, num_heads, model_dim, seq_len, causal=True,
                     return_kv=False):
    """Self-attention with ONE fused 3·M-wide qkv GEMM (better MXU shape
    than three M-wide projections; used for every q==kv site).
    ``return_kv`` also hands back the head-major (B, H, T, dh) key/value
    tensors — the serving prefill graph (get_prefill_symbol) exports them
    to seed the decode path's ring KV buffer."""
    dh = model_dim // num_heads
    qkv = sym.FullyConnected(data=x, num_hidden=3 * model_dim, flatten=False,
                             name="%s_qkv" % name)
    q, k, v = _split_fused(qkv, 3, seq_len, num_heads, dh)
    att = sym.MultiHeadAttention(query=q, key=k, value=v, causal=causal,
                                 name="%s_att" % name)
    att = sym.SwapAxis(att, dim1=1, dim2=2)  # (B,T,H,D)
    att = sym.Reshape(att, shape=(-1, seq_len, model_dim))
    proj = sym.FullyConnected(data=att, num_hidden=model_dim, flatten=False,
                              name="%s_proj" % name)
    if return_kv:
        return proj, k, v
    return proj


def _split_heads(x, seq_len, num_heads, dh):
    """(B, T, M) → (B, H, T, dh) for the fused attention op."""
    x = sym.Reshape(x, shape=(-1, seq_len, num_heads, dh))
    return sym.SwapAxis(x, dim1=1, dim2=2)


def _merge_heads(att, seq_len, model_dim):
    att = sym.SwapAxis(att, dim1=1, dim2=2)
    return sym.Reshape(att, shape=(-1, seq_len, model_dim))


def _cross_attention(q_in, kv_in, name, num_heads, model_dim, q_len, kv_len):
    """Attention with separate query/key-value sources (the MT decoder's
    encoder-attention). Only the q projection is separate; k and v share
    one fused 2·M-wide GEMM on kv_in (same MXU-shape rationale as
    _attention_block's fused qkv; self-attention sites use that block)."""
    dh = model_dim // num_heads
    q = sym.FullyConnected(data=q_in, num_hidden=model_dim, flatten=False,
                           name="%s_q" % name)
    kv = sym.FullyConnected(data=kv_in, num_hidden=2 * model_dim,
                            flatten=False, name="%s_kv" % name)
    k, v = _split_fused(kv, 2, kv_len, num_heads, dh)
    att = sym.MultiHeadAttention(
        query=_split_heads(q, q_len, num_heads, dh),
        key=k, value=v,
        causal=False, name="%s_att" % name)
    att = _merge_heads(att, q_len, model_dim)
    return sym.FullyConnected(data=att, num_hidden=model_dim, flatten=False,
                              name="%s_proj" % name)


def _ffn(x, name, model_dim, ffn_dim):
    h = sym.FullyConnected(data=x, num_hidden=ffn_dim, flatten=False,
                           name="%s_ffn1" % name)
    h = sym.Activation(h, act_type="relu")
    return sym.FullyConnected(data=h, num_hidden=model_dim, flatten=False,
                              name="%s_ffn2" % name)


def _embed_with_pos(tokens, vocab_size, model_dim, seq_len, name):
    embed = sym.Embedding(data=tokens, input_dim=vocab_size,
                          output_dim=model_dim, name="%s_embed" % name)
    pos = sym.Variable("%s_pos_weight" % name, shape=(seq_len, model_dim))
    return sym.broadcast_add(
        embed, sym.Reshape(pos, shape=(1, seq_len, model_dim)),
        name="%s_pos_add" % name)


def get_symbol_mt(vocab_size=32000, num_layers=6, num_heads=8, model_dim=512,
                  ffn_dim=2048, src_len=64, tgt_len=64, **kwargs):
    """Encoder-decoder Transformer-base for MT (BASELINE.md stretch config:
    "Transformer-base MT"; the reference era predates Transformers — the
    closest ancestor is its seq2seq RNN stack — so the architecture here is
    the standard pre-norm Transformer built from this repo's Symbol ops and
    the fused MultiHeadAttention, not a translation of reference code).

    Inputs: ``data`` (B, src_len) source tokens, ``dec_data`` (B, tgt_len)
    shifted-right target tokens, ``softmax_label`` (B, tgt_len). Fixed
    lengths (pad to bucket shapes; BucketingModule handles the rest) —
    padding attends as ordinary tokens, the toy/bucketed regime this model
    targets."""
    src = sym.Variable("data")
    tgt = sym.Variable("dec_data")
    label = sym.Variable("softmax_label")

    # ---- encoder: pre-norm self-attention stack, non-causal
    x = _embed_with_pos(src, vocab_size, model_dim, src_len, "enc")
    for i in range(num_layers):
        n = "enc%d" % i
        ln = _layer_norm(x, "%s_ln1" % n, model_dim)
        x = x + _attention_block(ln, n + "_self", num_heads, model_dim,
                                 src_len, causal=False)
        x = x + _ffn(_layer_norm(x, "%s_ln2" % n, model_dim), n,
                     model_dim, ffn_dim)
    memory = _layer_norm(x, "enc_final_ln", model_dim)

    # ---- decoder: causal self-attention + cross-attention on the memory
    y = _embed_with_pos(tgt, vocab_size, model_dim, tgt_len, "dec")
    for i in range(num_layers):
        n = "dec%d" % i
        ln = _layer_norm(y, "%s_ln1" % n, model_dim)
        y = y + _attention_block(ln, n + "_self", num_heads, model_dim,
                                 tgt_len, causal=True)
        y = y + _cross_attention(_layer_norm(y, "%s_ln2" % n, model_dim),
                                 memory, n + "_cross", num_heads, model_dim,
                                 tgt_len, src_len)
        y = y + _ffn(_layer_norm(y, "%s_ln3" % n, model_dim), n,
                     model_dim, ffn_dim)
    y = _layer_norm(y, "dec_final_ln", model_dim)
    y = sym.Reshape(y, shape=(-1, model_dim))
    logits = sym.FullyConnected(data=y, num_hidden=vocab_size, name="mt_head")
    label_flat = sym.Reshape(label, shape=(-1,))
    return sym.SoftmaxOutput(data=logits, label=label_flat, name="softmax")


# --------------------------------------------------------------------- serving
def get_prefill_symbol(vocab_size=32000, num_layers=6, num_heads=8,
                       model_dim=512, ffn_dim=2048, prefill_len=64,
                       pos_len=None, **kwargs):
    """Serving prefill graph (docs/SERVING.md): the decoder-only LM of
    ``get_symbol`` over a fixed ``prefill_len`` bucket, additionally
    exporting every layer's head-major key/value tensors so the serving
    path can seed the decode executable's ring KV buffer.

    Weight names are IDENTICAL to ``get_symbol`` — a trained checkpoint
    loads into either. ``pos_len`` is the trained position table's length
    (defaults to ``prefill_len``); prompts are right-padded to
    ``prefill_len`` by the caller, and causality guarantees pad tokens
    cannot influence earlier positions.

    Outputs: ``[logits (B·P, vocab), k_0, v_0, ..., k_{L-1}, v_{L-1}]``
    with each k/v of shape (B, H, P, dh).
    """
    pos_len = pos_len or prefill_len
    data = sym.Variable("data")  # (B, P) int tokens, right-padded
    embed = sym.Embedding(data=data, input_dim=vocab_size,
                          output_dim=model_dim, name="embed")
    pos = sym.Variable("pos_embed_weight", shape=(pos_len, model_dim))
    if prefill_len != pos_len:
        pos = sym.slice_axis(pos, axis=0, begin=0, end=prefill_len)
    x = sym.broadcast_add(
        embed, sym.Reshape(pos, shape=(1, prefill_len, model_dim)),
        name="pos_add")
    kvs = []
    for i in range(num_layers):
        name = "layer%d" % i
        a, k, v = _attention_block(
            _layer_norm(x, "%s_ln1" % name, model_dim), name, num_heads,
            model_dim, prefill_len, causal=True, return_kv=True)
        kvs += [k, v]
        x = x + a
        x = x + _ffn(_layer_norm(x, "%s_ln2" % name, model_dim), name,
                     model_dim, ffn_dim)
    x = _layer_norm(x, "final_ln", model_dim)
    logits = sym.FullyConnected(
        data=sym.Reshape(x, shape=(-1, model_dim)), num_hidden=vocab_size,
        name="lm_head")
    return sym.Group([logits] + kvs)


def get_decode_symbol(vocab_size=32000, num_layers=6, num_heads=8,
                      model_dim=512, ffn_dim=2048, max_len=64, pos_len=None,
                      per_stream_slots=False, global_slots=False,
                      token_out=True, **kwargs):
    """Serving single-token decode graph (docs/SERVING.md): ONE token per
    stream through the ``get_symbol`` stack, attending over a preallocated
    ring KV buffer of ``max_len`` slots per layer. Compiles ONCE — every
    decode step replays the same executable regardless of position.

    Inputs beyond the weights:
      - ``data`` (B, 1): the current token ids.
      - ``pos_idx`` (B, 1): absolute positions (rows of the trained
        position table, so ``pos < pos_len``).
      - ``slot_onehot`` (max_len,): one-hot of the ring slot this token
        writes (``pos % max_len``). The KV update is in-graph:
        ``kv' = kv·(1-oh) + kv_new·oh`` — no per-step host scatter, no
        per-slot recompile.
      - ``kv_mask`` (max_len,): additive score mask — 0 on slots holding
        real context (INCLUDING the current slot), a large negative on
        empty slots.
      - ``kv_k_i`` / ``kv_v_i`` (B, H, max_len, dh) per layer: the ring
        buffers. The updated buffers are program OUTPUTS; the caller swaps
        them back in as the next step's inputs (KVCacheDecoder does).

    ``per_stream_slots=True`` is the paged/multiplexed variant
    (PagedKVDecoder): ``slot_onehot`` and ``kv_mask`` become (B, max_len)
    so every batch lane carries its OWN write slot, its own valid-slot set
    and its own position — one decode dispatch serves B *independent*
    sequences at arbitrary, different positions. An all-zero onehot row
    writes nothing (that lane's KV passes through unchanged), which is how
    idle lanes ride along for free. Attention over slots stays
    order-agnostic (positions live in the embeddings), so a lane's tokens
    may occupy ANY physical slots — the property the paged allocator's
    non-contiguous page placement relies on. The math per lane is
    identical to the shared-slot graph at the same position.

    ``global_slots=True`` (implies per-stream staging) is the
    SHARED-POOL variant behind the copy-on-write prefix cache
    (docs/SERVING.md §Prefix cache): the KV buffers collapse from one
    ring per lane to ONE global slot axis — ``kv_k_i``/``kv_v_i`` become
    (H, max_len, dh) with ``max_len`` now the TOTAL pool slots — and
    ``slot_onehot``/``kv_mask`` stay (B, max_len) over that shared axis.
    Every lane's write is summed into the one pool (lane onehots are
    disjoint by construction — the page allocator hands a frame to one
    writer at a time), and every lane attends the whole pool under its
    own additive mask, so N lanes can read the SAME physical page: that
    is what makes a shared prefix page a refcount instead of a copy.
    Masked empty slots contribute exp(-1e9)=0 exactly, so per-lane math
    is unchanged from the per-lane-ring variant at equal positions.

    T=1 collapses attention to a masked weighted sum, so it is composed
    from broadcast primitives (scores = Σ_d q·k, softmax, Σ_s p·v) instead
    of the fused MultiHeadAttention op — same math, fp32-exact against the
    full-sequence forward at matching positions.

    Outputs: ``[logits (B, vocab), k'_0, v'_0, ..., k'_{L-1}, v'_{L-1}]``,
    plus — with ``token_out=True`` (the default) — a trailing
    ``greedy_token (B,)`` head: ``argmax(logits, axis=-1)`` lowered ON
    DEVICE, so a greedy driver pulls one id per stream instead of the
    full (B, vocab) logits row (GL703; the KV outputs keep their
    ``1 + 2*i`` positions either way). The ``greedy_token`` NAME is a
    detection contract: ``KVCacheDecoder.warmup`` decides whether a
    (possibly disk-cached) compiled program carries the head by looking
    for it in ``output_dict`` by name — rename it and stale caches start
    masquerading as token-less programs.

    This graph is also the megastep building block
    (serving/kv_decode.py ``_DecodeMegastep``): the per-stream variant is
    pure in its (data, pos_idx, slot_onehot, kv_mask, kv_*) inputs, so K
    decode steps compose as a ``lax.scan`` over ONE compiled body — the
    scan carries the KV outputs back into the KV inputs and feeds each
    step's sampled token to the next, keeping the whole K-token loop
    device-resident (docs/SERVING.md §megasteps).
    """
    pos_len = pos_len or max_len
    dh = model_dim // num_heads
    scale = 1.0 / float(np.sqrt(dh))
    data = sym.Variable("data")
    pos_idx = sym.Variable("pos_idx")
    oh = sym.Variable("slot_onehot")
    msk = sym.Variable("kv_mask")
    if per_stream_slots or global_slots:
        oh4 = sym.Reshape(oh, shape=(-1, 1, max_len, 1))
        msk3 = sym.Reshape(msk, shape=(-1, 1, max_len))
    else:
        oh4 = sym.Reshape(oh, shape=(1, 1, max_len, 1))
        msk3 = sym.Reshape(msk, shape=(1, 1, max_len))
    if global_slots:
        # every lane's write folds into the ONE pool: sum the per-lane
        # onehots over the batch axis (disjoint slots, so the sum is
        # still 0/1) for the keep mask, and sum the per-lane writes below
        keep3 = 1.0 - sym.Reshape(sym.sum(oh, axis=0),
                                  shape=(1, max_len, 1))
        keep4 = None
    else:
        keep4 = 1.0 - oh4
    emb = sym.Embedding(data=data, input_dim=vocab_size,
                        output_dim=model_dim, name="embed")
    posrow = sym.Embedding(data=pos_idx, input_dim=pos_len,
                           output_dim=model_dim, name="pos_embed")
    x = emb + posrow  # (B, 1, M)
    kv_outs = []
    for i in range(num_layers):
        name = "layer%d" % i
        ln = _layer_norm(x, "%s_ln1" % name, model_dim)
        qkv = sym.FullyConnected(data=ln, num_hidden=3 * model_dim,
                                 flatten=False, name="%s_qkv" % name)
        q, k_new, v_new = _split_fused(qkv, 3, 1, num_heads, dh)
        kv_k = sym.Variable("kv_k_%d" % i)
        kv_v = sym.Variable("kv_v_%d" % i)
        if global_slots:
            # pool buffers are (H, S, dh): blend each lane's (B,H,1,dh)
            # new K/V into its onehot slot, summed over lanes (slots are
            # writer-disjoint, so the sum IS the scatter)
            wr_k = sym.sum(sym.broadcast_mul(k_new, oh4), axis=0)
            wr_v = sym.sum(sym.broadcast_mul(v_new, oh4), axis=0)
            k_upd = sym.broadcast_add(sym.broadcast_mul(kv_k, keep3),
                                      wr_k, name="%s_kupd" % name)
            v_upd = sym.broadcast_add(sym.broadcast_mul(kv_v, keep3),
                                      wr_v, name="%s_vupd" % name)
            kv_outs += [k_upd, v_upd]
            k_att = sym.Reshape(k_upd, shape=(-1, num_heads, max_len, dh))
            v_att = sym.Reshape(v_upd, shape=(-1, num_heads, max_len, dh))
        else:
            k_upd = sym.broadcast_add(sym.broadcast_mul(kv_k, keep4),
                                      sym.broadcast_mul(k_new, oh4),
                                      name="%s_kupd" % name)
            v_upd = sym.broadcast_add(sym.broadcast_mul(kv_v, keep4),
                                      sym.broadcast_mul(v_new, oh4),
                                      name="%s_vupd" % name)
            kv_outs += [k_upd, v_upd]
            k_att, v_att = k_upd, v_upd
        scores = sym.sum(sym.broadcast_mul(q, k_att), axis=3) * scale
        scores = sym.broadcast_add(scores, msk3)  # (B, H, S)
        p = sym.softmax(scores, axis=-1)
        ctx = sym.sum(sym.broadcast_mul(sym.expand_dims(p, axis=3), v_att),
                      axis=2)  # (B, H, dh)
        att = sym.Reshape(
            sym.SwapAxis(sym.Reshape(ctx, shape=(-1, num_heads, 1, dh)),
                         dim1=1, dim2=2),
            shape=(-1, 1, model_dim))
        x = x + sym.FullyConnected(data=att, num_hidden=model_dim,
                                   flatten=False, name="%s_proj" % name)
        x = x + _ffn(_layer_norm(x, "%s_ln2" % name, model_dim), name,
                     model_dim, ffn_dim)
    x = _layer_norm(x, "final_ln", model_dim)
    logits = sym.FullyConnected(
        data=sym.Reshape(x, shape=(-1, model_dim)), num_hidden=vocab_size,
        name="lm_head")
    outs = [logits] + kv_outs
    if token_out:
        outs.append(sym.argmax(logits, axis=-1, name="greedy_token"))
    return sym.Group(outs)


def get_chunk_symbol(vocab_size=32000, num_layers=6, num_heads=8,
                     model_dim=512, ffn_dim=2048, chunk_len=8,
                     total_slots=64, pos_len=64, token_out=True, **kwargs):
    """Rectangular T-token chunk graph over the GLOBAL paged slot pool
    (docs/SERVING.md §Prefix cache & speculative decoding): ONE lane's
    next ``chunk_len`` positions scored — and optionally written — in a
    single dispatch. This is both the chunked-prefill program (admit
    computes only the un-cached tail of a prompt, chunk by chunk) and the
    speculative VERIFY program (the target model scores all γ+1 draft
    positions at once) — same symbol, different T.

    Inputs beyond the weights:
      - ``data`` (1, T): the chunk's token ids (pad rows = 0).
      - ``pos_idx`` (1, T): absolute positions per row (pad rows clamp
        to 0; their writes are zeroed so the value never lands).
      - ``write_onehot`` (T, total_slots): row j's write slot in the
        global pool. An ALL-ZERO row writes nothing — that is both the
        pad-row idiom and the zero-write REPLAY mode (a fully-cached
        prompt re-scores its last chunk against the stored pages:
        ``kv·1 + Σ(new·0) = kv`` bitwise, so replay logits are
        bit-identical to the cold chunked prefill that wrote them).
      - ``att_mask`` (T, total_slots): additive score mask per row — 0 on
        the lane's earlier slots AND on in-chunk slots of positions
        <= row j (intra-chunk causality is enforced HERE: all T writes
        land in ``k_upd`` before attention, the mask hides the future
        ones). A fully-masked pad row softmaxes uniformly over garbage
        and is discarded — finite, never NaN (max-subtraction zeroes the
        row first).
      - ``kv_k_i`` / ``kv_v_i`` (H, total_slots, dh): the global pool
        buffers, as in ``get_decode_symbol(global_slots=True)``.

    Outputs: ``[logits (T, vocab), k'_0, v'_0, ...]`` plus — with
    ``token_out=True`` — a trailing on-device ``chunk_token (T,)`` argmax
    head so the speculative accept loop pulls T ids, not T·vocab floats.
    """
    T, S = int(chunk_len), int(total_slots)
    dh = model_dim // num_heads
    scale = 1.0 / float(np.sqrt(dh))
    data = sym.Variable("data")
    pos_idx = sym.Variable("pos_idx")
    w_oh = sym.Variable("write_onehot")
    msk = sym.Variable("att_mask")
    w4 = sym.Reshape(w_oh, shape=(1, T, S, 1))
    keep3 = 1.0 - sym.Reshape(sym.sum(w_oh, axis=0), shape=(1, S, 1))
    msk3 = sym.Reshape(msk, shape=(1, T, S))
    emb = sym.Embedding(data=data, input_dim=vocab_size,
                        output_dim=model_dim, name="embed")
    posrow = sym.Embedding(data=pos_idx, input_dim=pos_len,
                           output_dim=model_dim, name="pos_embed")
    x = emb + posrow  # (1, T, M)
    kv_outs = []
    for i in range(num_layers):
        name = "layer%d" % i
        ln = _layer_norm(x, "%s_ln1" % name, model_dim)
        qkv = sym.FullyConnected(data=ln, num_hidden=3 * model_dim,
                                 flatten=False, name="%s_qkv" % name)
        q, k_new, v_new = _split_fused(qkv, 3, T, num_heads, dh)
        kv_k = sym.Variable("kv_k_%d" % i)
        kv_v = sym.Variable("kv_v_%d" % i)
        # scatter the T new rows into the pool: (H,T,1,dh)·(1,T,S,1)
        # summed over the row axis — writer-disjoint slots, so the sum
        # IS the scatter (all-zero rows vanish)
        k_rows = sym.Reshape(k_new, shape=(num_heads, T, 1, dh))
        v_rows = sym.Reshape(v_new, shape=(num_heads, T, 1, dh))
        wr_k = sym.sum(sym.broadcast_mul(k_rows, w4), axis=1)
        wr_v = sym.sum(sym.broadcast_mul(v_rows, w4), axis=1)
        k_upd = sym.broadcast_add(sym.broadcast_mul(kv_k, keep3), wr_k,
                                  name="%s_kupd" % name)
        v_upd = sym.broadcast_add(sym.broadcast_mul(kv_v, keep3), wr_v,
                                  name="%s_vupd" % name)
        kv_outs += [k_upd, v_upd]
        q4 = sym.Reshape(q, shape=(num_heads, T, 1, dh))
        k4 = sym.Reshape(k_upd, shape=(num_heads, 1, S, dh))
        v4 = sym.Reshape(v_upd, shape=(num_heads, 1, S, dh))
        scores = sym.sum(sym.broadcast_mul(q4, k4), axis=3) * scale
        scores = sym.broadcast_add(scores, msk3)  # (H, T, S)
        p = sym.softmax(scores, axis=-1)
        ctx = sym.sum(sym.broadcast_mul(sym.expand_dims(p, axis=3), v4),
                      axis=2)  # (H, T, dh)
        att = sym.Reshape(
            sym.SwapAxis(sym.Reshape(ctx, shape=(-1, num_heads, T, dh)),
                         dim1=1, dim2=2),
            shape=(-1, T, model_dim))
        x = x + sym.FullyConnected(data=att, num_hidden=model_dim,
                                   flatten=False, name="%s_proj" % name)
        x = x + _ffn(_layer_norm(x, "%s_ln2" % name, model_dim), name,
                     model_dim, ffn_dim)
    x = _layer_norm(x, "final_ln", model_dim)
    logits = sym.FullyConnected(
        data=sym.Reshape(x, shape=(-1, model_dim)), num_hidden=vocab_size,
        name="lm_head")
    outs = [logits] + kv_outs
    if token_out:
        outs.append(sym.argmax(logits, axis=-1, name="chunk_token"))
    return sym.Group(outs)


def draft_config(cfg, num_layers=1):
    """Speculative-decoding draft config: the FIRST ``num_layers`` blocks
    of a target model's config. Weight names are positional
    (``layer0..layer{k-1}`` plus the shared ``embed``/``pos_embed``/
    ``final_ln``/``lm_head``), so a target checkpoint's arg_params dict
    feeds a draft decoder unchanged — the draft simply stops looking up
    the deeper layers. docs/SERVING.md §speculative decoding."""
    k = int(num_layers)
    if not 0 < k <= int(cfg.get("num_layers", k)):
        raise ValueError(
            "draft_config: draft num_layers %d not in (0, %d]"
            % (k, int(cfg.get("num_layers", k))))
    out = dict(cfg)
    out["num_layers"] = k
    return out


def get_symbol(vocab_size=32000, num_layers=6, num_heads=8, model_dim=512,
               ffn_dim=2048, seq_len=64, **kwargs):
    data = sym.Variable("data")  # (B, T) int tokens
    label = sym.Variable("softmax_label")
    embed = sym.Embedding(data=data, input_dim=vocab_size,
                          output_dim=model_dim, name="embed")
    pos = sym.Variable("pos_embed_weight", shape=(seq_len, model_dim))
    x = sym.broadcast_add(embed, sym.Reshape(pos, shape=(1, seq_len, model_dim)),
                          name="pos_add")
    for i in range(num_layers):
        name = "layer%d" % i
        a = _attention_block(_layer_norm(x, "%s_ln1" % name, model_dim),
                             name, num_heads, model_dim, seq_len)
        x = x + a
        h = _layer_norm(x, "%s_ln2" % name, model_dim)
        h = sym.FullyConnected(data=h, num_hidden=ffn_dim, flatten=False,
                               name="%s_ffn1" % name)
        h = sym.Activation(h, act_type="relu")
        h = sym.FullyConnected(data=h, num_hidden=model_dim, flatten=False,
                               name="%s_ffn2" % name)
        x = x + h
    x = _layer_norm(x, "final_ln", model_dim)
    x = sym.Reshape(x, shape=(-1, model_dim))
    logits = sym.FullyConnected(data=x, num_hidden=vocab_size, name="lm_head")
    label_flat = sym.Reshape(label, shape=(-1,))
    return sym.SoftmaxOutput(data=logits, label=label_flat, name="softmax")
