"""Attribute scoping for symbol construction.

Counterpart of the reference's AttrScope (python/mxnet/attribute.py): a
thread-local ``with`` scope that stamps attributes (``__ctx_group__``,
``__lr_mult__``, ...) onto every symbol created inside it — the mechanism the
reference uses for model-parallel device placement and per-layer optimizer
multipliers.
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope"]


class AttrScope:
    """``with mx.AttrScope(ctx_group='dev1'):`` — attrs applied to new symbols."""

    _current = threading.local()

    def __init__(self, **kwargs):
        self._old_scope = None
        for value in kwargs.values():
            if not isinstance(value, str):
                raise ValueError("attributes need to be strings")
        self._attr = {"__%s__" % k if not k.startswith("__") else k: v for k, v in kwargs.items()}

    def get(self, attr):
        """Merge scope attrs under explicitly-given ``attr`` dict."""
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        self._old_scope = AttrScope.current()
        attr = AttrScope.current()._attr.copy()
        attr.update(self._attr)
        self._attr = attr
        AttrScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        AttrScope._current.value = self._old_scope

    @staticmethod
    def current() -> "AttrScope":
        cur = getattr(AttrScope._current, "value", None)
        if cur is None:
            cur = AttrScope()
            AttrScope._current.value = cur
        return cur
