"""Evaluation metrics.

Counterpart of the reference's python/mxnet/metric.py:22-427 (EvalMetric base,
Accuracy/TopKAccuracy/F1/Perplexity/MAE/MSE/RMSE/CrossEntropy, CustomMetric,
CompositeEvalMetric, np() wrapper, create registry). Metrics accumulate on
host numpy — the single host↔device sync point of the training loop, exactly
where the reference also blocks (executor_group.py:511 update_metric →
asnumpy).
"""
from __future__ import annotations

import numpy

__all__ = [
    "EvalMetric",
    "Accuracy",
    "TopKAccuracy",
    "F1",
    "Perplexity",
    "MAE",
    "MSE",
    "RMSE",
    "CrossEntropy",
    "Torch",
    "Caffe",
    "CustomMetric",
    "CompositeEvalMetric",
    "Loss",
    "np",
    "create",
]


def check_label_shapes(labels, preds, shape=False):
    if shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape[0], preds.shape[0]
    if label_shape != pred_shape:
        raise ValueError(
            "Shape of labels %d does not match shape of predictions %d" % (label_shape, pred_shape)
        )


class EvalMetric:
    """Base accumulator (reference: metric.py EvalMetric)."""

    def __init__(self, name, num=None):
        self.name = name
        self.num = num
        self.reset()

    def update(self, labels, preds):
        raise NotImplementedError()

    def reset(self):
        if self.num is None:
            self.num_inst = 0
            self.sum_metric = 0.0
        else:
            self.num_inst = [0] * self.num
            self.sum_metric = [0.0] * self.num

    def get(self):
        if self.num is None:
            if self.num_inst == 0:
                return (self.name, float("nan"))
            return (self.name, self.sum_metric / self.num_inst)
        names = ["%s_%d" % (self.name, i) for i in range(self.num)]
        values = [
            x / y if y != 0 else float("nan") for x, y in zip(self.sum_metric, self.num_inst)
        ]
        return (names, values)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))


class CompositeEvalMetric(EvalMetric):
    """Manage multiple metrics as one (reference: CompositeEvalMetric)."""

    def __init__(self, metrics=None, **kwargs):
        # before super(): EvalMetric.__init__ calls reset(), which iterates
        # self.metrics (the reference instead swallowed the AttributeError)
        self.metrics = [create(m) if isinstance(m, str) else m for m in (metrics or [])]
        super().__init__("composite", **kwargs)

    def add(self, metric):
        self.metrics.append(create(metric) if isinstance(metric, str) else metric)

    def get_metric(self, index):
        if not 0 <= index < len(self.metrics):
            # unlike the reference (which RETURNED the exception), raise it
            raise ValueError("metric index %d out of range [0, %d)"
                             % (index, len(self.metrics)))
        return self.metrics[index]

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in self.metrics:
            m.reset()

    def get(self):
        pairs = [m.get() for m in self.metrics]
        names, values = zip(*pairs) if pairs else ((), ())
        return (list(names), list(values))


def _asnumpy(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else numpy.asarray(x)


class Accuracy(EvalMetric):
    def __init__(self):
        super().__init__("accuracy")

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred_label in zip(labels, preds):
            pred_label = _asnumpy(pred_label)
            label = _asnumpy(label)
            if pred_label.ndim > label.ndim:
                pred_label = numpy.argmax(pred_label, axis=1)
            pred_label = pred_label.astype("int32").ravel()
            label = label.astype("int32").ravel()
            check_label_shapes(label, pred_label)
            self.sum_metric += (pred_label == label).sum()
            self.num_inst += len(pred_label)


class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, **kwargs):
        super().__init__("top_k_accuracy")
        self.top_k = top_k
        assert self.top_k > 1, "Please use Accuracy if top_k is no more than 1"
        self.name += "_%d" % self.top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred_label in zip(labels, preds):
            pred_label = _asnumpy(pred_label)
            label = _asnumpy(label).astype("int32")
            assert len(pred_label.shape) <= 2, "Predictions should be no more than 2 dims"
            pred_label = numpy.argsort(pred_label.astype("float32"), axis=1)
            check_label_shapes(label, pred_label)
            num_samples = pred_label.shape[0]
            num_dims = len(pred_label.shape)
            if num_dims == 1:
                self.sum_metric += (pred_label.ravel() == label.ravel()).sum()
            elif num_dims == 2:
                num_classes = pred_label.shape[1]
                top_k = min(num_classes, self.top_k)
                for j in range(top_k):
                    self.sum_metric += (pred_label[:, num_classes - 1 - j].ravel() == label.ravel()).sum()
            self.num_inst += num_samples


class F1(EvalMetric):
    """Binary-classification F1 (reference: metric.py F1)."""

    def __init__(self):
        super().__init__("f1")

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            pred = _asnumpy(pred)
            label = _asnumpy(label).astype("int32")
            pred_label = numpy.argmax(pred, axis=1)
            check_label_shapes(label, pred)
            if len(numpy.unique(label)) > 2:
                raise ValueError("F1 currently only supports binary classification.")
            true_positives, false_positives, false_negatives = 0.0, 0.0, 0.0
            for y_pred, y_true in zip(pred_label, label):
                if y_pred == 1 and y_true == 1:
                    true_positives += 1.0
                elif y_pred == 1 and y_true == 0:
                    false_positives += 1.0
                elif y_pred == 0 and y_true == 1:
                    false_negatives += 1.0
            if true_positives + false_positives > 0:
                precision = true_positives / (true_positives + false_positives)
            else:
                precision = 0.0
            if true_positives + false_negatives > 0:
                recall = true_positives / (true_positives + false_negatives)
            else:
                recall = 0.0
            if precision + recall > 0:
                f1_score = 2 * precision * recall / (precision + recall)
            else:
                f1_score = 0.0
            self.sum_metric += f1_score
            self.num_inst += 1


class Perplexity(EvalMetric):
    """exp(mean NLL) with optional ignored label (reference: Perplexity)."""

    def __init__(self, ignore_label, axis=-1):
        super().__init__("Perplexity")
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _asnumpy(label)
            pred = _asnumpy(pred)
            assert label.size == pred.size / pred.shape[-1], (
                "shape mismatch: %s vs. %s" % (label.shape, pred.shape)
            )
            label = label.reshape((label.size,)).astype("int32")
            probs = pred.reshape(-1, pred.shape[-1])[numpy.arange(label.size), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label).astype(probs.dtype)
                num -= int(numpy.sum(ignore))
                probs = probs * (1 - ignore) + ignore
            loss -= numpy.sum(numpy.log(numpy.maximum(1e-10, probs)))
            num += label.size
        if num > 0:
            self.sum_metric += numpy.exp(loss / num)
            self.num_inst += 1
        # num == 0 (every label ignored, e.g. an all-padding bucket batch)
        # contributes nothing rather than poisoning the epoch with NaN



def _align_regression(label, pred):
    """Shape-align a (label, pred) pair for elementwise error metrics: lift a
    rank-1 label to (B, 1) (reference layout) and reshape a same-size pred to
    match — otherwise (B,1)-(B,) broadcasts into a (B,B) matrix and the
    metric reports a constant ~sqrt(var(label)+var(pred))."""
    if len(label.shape) == 1:
        label = label.reshape(label.shape[0], 1)
    if pred.shape != label.shape and pred.size == label.size:
        pred = pred.reshape(label.shape)
    return label, pred


class MAE(EvalMetric):
    def __init__(self):
        super().__init__("mae")

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label = _asnumpy(label)
            pred = _asnumpy(pred)
            label, pred = _align_regression(label, pred)
            self.sum_metric += numpy.abs(label - pred).mean()
            self.num_inst += 1


class MSE(EvalMetric):
    def __init__(self):
        super().__init__("mse")

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label = _asnumpy(label)
            pred = _asnumpy(pred)
            label, pred = _align_regression(label, pred)
            self.sum_metric += ((label - pred) ** 2.0).mean()
            self.num_inst += 1


class RMSE(EvalMetric):
    def __init__(self):
        super().__init__("rmse")

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label = _asnumpy(label)
            pred = _asnumpy(pred)
            label, pred = _align_regression(label, pred)
            self.sum_metric += numpy.sqrt(((label - pred) ** 2.0).mean())
            self.num_inst += 1


class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-8):
        super().__init__("cross-entropy")
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label = _asnumpy(label)
            pred = _asnumpy(pred)
            label = label.ravel()
            assert label.shape[0] == pred.shape[0]
            prob = pred[numpy.arange(label.shape[0]), numpy.int64(label)]
            self.sum_metric += (-numpy.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


class Loss(EvalMetric):
    """Mean of raw outputs — for MakeLoss-style nets (later mxnet parity)."""

    def __init__(self):
        super().__init__("loss")

    def update(self, _, preds):
        for pred in preds:
            pred = _asnumpy(pred)
            self.sum_metric += pred.sum()
            self.num_inst += pred.size


class Torch(Loss):
    def __init__(self, name="torch"):
        super(Loss, self).__init__(name)


class Caffe(Torch):
    def __init__(self):
        super(Loss, self).__init__("caffe")


class CustomMetric(EvalMetric):
    """Wrap feval(label, pred) (reference: CustomMetric)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds, shape=True)
        for pred, label in zip(preds, labels):
            label = _asnumpy(label)
            pred = _asnumpy(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Lift a numpy feval into a metric (reference: metric.np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


def create(metric, **kwargs):
    """Create by name/callable/list (reference: metric.create)."""
    if callable(metric):
        return CustomMetric(metric)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite_metric = CompositeEvalMetric()
        for child_metric in metric:
            composite_metric.add(create(child_metric, **kwargs))
        return composite_metric
    metrics = {
        "acc": Accuracy,
        "accuracy": Accuracy,
        "ce": CrossEntropy,
        "f1": F1,
        "mae": MAE,
        "mse": MSE,
        "rmse": RMSE,
        "top_k_accuracy": TopKAccuracy,
        "perplexity": Perplexity,
        "loss": Loss,
    }
    try:
        return metrics[metric.lower()](**kwargs)
    except Exception:
        raise ValueError("Metric must be either callable or in %s" % sorted(metrics))
