"""Continuous-batching inference engine (docs/SERVING.md).

One batcher thread owns the device: requests land in a thread-safe FIFO
queue, the batcher assembles them into the smallest shape bucket that
covers the pending rows — admitting requests that arrive mid-assembly up
to a deadline (``MXNET_SERVE_MAX_DELAY_MS``) — pads the batch to the
bucket, and replays the bucket's pre-compiled executable from the
``PersistentExecutableCache``. Per-request outputs are sliced back out and
delivered through futures, so N concurrent callers cost ONE dispatch.

Why buckets instead of exact shapes: XLA compiles per shape. A fixed
bucket ladder (1, 2, 4, 8, ...) bounds the executable count, warmup
pre-compiles every rung, and the sealed cache turns "a request shape we
never warmed" into a structured error instead of a silent recompile.

Ordering: strict FIFO. A batch takes the queue head and every following
request that still fits the largest bucket; a request is never overtaken
by one submitted after it.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..base import MXNetError
from .. import telemetry as _tm
from .cache import PersistentExecutableCache

__all__ = ["InferenceEngine", "ServeFuture"]


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return float(default)


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return int(default)


class ServeFuture:
    """Delivery slot for one request's outputs. ``done_at`` is the
    ``time.perf_counter()`` stamp of delivery (None until done) — load
    generators read it for per-request latency without a waiter thread."""

    __slots__ = ("_event", "_result", "_error", "done_at")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._error = None
        self.done_at = None

    def done(self):
        return self._event.is_set()

    def set_result(self, result):
        self._result = result
        self.done_at = time.perf_counter()
        self._event.set()

    def set_error(self, exc):
        self._error = exc
        self.done_at = time.perf_counter()
        self._event.set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise MXNetError("serving: request timed out after %ss"
                             % timeout)
        if self._error is not None:
            raise self._error
        return self._result


class _Request:
    __slots__ = ("inputs", "rows", "future", "t_enq")

    def __init__(self, inputs, rows):
        self.inputs = inputs
        self.rows = rows
        self.future = ServeFuture()
        self.t_enq = time.perf_counter()


class InferenceEngine:
    """Continuous batching over shape buckets on one model.

    ``buckets`` are batch sizes (ascending after sort); ``item_shapes``
    maps each model input to its PER-ITEM shape (no batch dim) — bucket
    ``b`` binds input ``name`` at ``(b,) + item_shapes[name]``.
    """

    def __init__(self, cache: PersistentExecutableCache,
                 item_shapes: Dict[str, Sequence[int]],
                 buckets: Sequence[int] = (1, 2, 4, 8),
                 max_delay_ms: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 name: Optional[str] = None):
        if not buckets:
            raise MXNetError("serving: need at least one bucket")
        self.cache = cache
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if self.buckets[0] < 1:
            raise MXNetError("serving: buckets must be >= 1, got %s"
                             % (buckets,))
        self.item_shapes = {n: tuple(s) for n, s in item_shapes.items()}
        unknown = set(self.item_shapes) - set(cache.input_names)
        if unknown:
            raise MXNetError(
                "serving: item shapes name %s which are not model inputs %s"
                % (sorted(unknown), cache.input_names))
        # model inputs NOT in item_shapes (e.g. a SoftmaxOutput label) are
        # left to simple_bind's shape inference and stay zero-filled
        self.max_delay_s = (_env_float("MXNET_SERVE_MAX_DELAY_MS", 5.0)
                            if max_delay_ms is None else float(max_delay_ms)
                            ) / 1000.0
        self.max_queue = (_env_int("MXNET_SERVE_MAX_QUEUE", 1024)
                          if max_queue is None else int(max_queue))
        self.name = name or cache._model_key
        self._queue = deque()
        self._cond = threading.Condition()
        self._stop = False
        self._thread = None
        self._started = False
        self._fatal = None        # batcher-death latch; see _latch_failure
        self._row_factors = None  # per-output rows-per-item; see start()

    # ------------------------------------------------------------ lifecycle
    def bucket_shapes(self):
        return [{n: (b,) + s for n, s in self.item_shapes.items()}
                for b in self.buckets]

    def start(self, warmup=True):
        """Pre-compile every bucket executable (sealing the cache) and
        launch the batcher thread."""
        if self._fatal is not None:
            # mirror PrefetchingIter._shutdown: a latched engine stays
            # failed — restarting a batcher over state a dead thread left
            # mid-flight would race the executor
            raise self._fatal
        if self._started:
            return self
        if warmup:
            self.cache.warmup(self.bucket_shapes())
        self._row_factors = self._output_row_factors()
        self._stop = False
        self._thread = threading.Thread(target=self._batcher_loop,
                                        name="mxserve-batcher-%s" % self.name,
                                        daemon=True)
        self._started = True
        self._thread.start()
        return self

    def _output_row_factors(self):
        """Classify each model output as batch-major or not from STATIC
        shape inference at two probe batch sizes: output i is batch-major
        with k rows per item iff its leading dim is k*b for the same k at
        both probes (a (B*T, V) flattened head has k=T). A constant
        leading dim (time-major or aux outputs) fails the cross-probe
        check and is replicated whole to every request — a single-size
        divisibility test would mis-slice it whenever it happened to
        divide. Probing is pure inference (no bind/compile), so the second
        probe need not be a real bucket — this disambiguates even a
        one-bucket ladder."""
        b0 = self.buckets[-1]
        factors = None
        for b in (b0, b0 + 1):
            shapes = {n: (b,) + s for n, s in self.item_shapes.items()}
            try:
                outs = self.cache.output_shapes(shapes)
            except Exception:
                if factors is not None:
                    break  # off-bucket probe unsupported: keep probe 1
                raise
            ks = [None if not s or s[0] % b else s[0] // b for s in outs]
            factors = ks if factors is None else \
                [k if k == k2 else None for k, k2 in zip(factors, ks)]
        return factors

    def close(self, timeout=30.0):
        """Drain the queue (every accepted request still gets an answer),
        then stop the batcher. If the batcher is wedged past ``timeout``
        the engine stays in the stopped-but-not-joined state: submits keep
        raising and ``start()`` refuses to launch a second batcher beside
        the zombie (two threads would race on the shared executor)."""
        if not self._started:
            return
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise MXNetError(
                "serving: batcher %r did not drain within %.1fs; engine "
                "left stopped (not restartable) — a request is likely "
                "wedged in dispatch" % (self._thread.name, timeout))
        self._started = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # -------------------------------------------------------------- submit
    def _validate(self, inputs):
        arrs, rows = {}, None
        for n, shape in self.item_shapes.items():
            if n not in inputs:
                raise MXNetError("serving: missing input %r" % n)
            a = np.asarray(inputs[n])
            if a.ndim == len(shape):  # single item: implicit batch of 1
                a = a[None]
            if tuple(a.shape[1:]) != shape:
                raise MXNetError(
                    "serving: input %r item shape %s does not match the "
                    "engine's %s" % (n, tuple(a.shape[1:]), shape))
            if rows is None:
                rows = a.shape[0]
            elif a.shape[0] != rows:
                raise MXNetError(
                    "serving: inconsistent batch rows across inputs "
                    "(%d vs %d for %r)" % (rows, a.shape[0], n))
            arrs[n] = a
        if rows == 0:
            raise MXNetError("serving: empty request")
        if rows > self.buckets[-1]:
            raise MXNetError(
                "serving: request rows %d exceed the largest bucket %d "
                "(oversize requests must be split by the caller)"
                % (rows, self.buckets[-1]))
        return arrs, rows

    def submit(self, inputs) -> ServeFuture:
        """Enqueue one request ({input: array} or a bare array for
        single-input models); returns a ``ServeFuture``."""
        if not isinstance(inputs, dict):
            names = list(self.item_shapes)
            if len(names) != 1:
                raise MXNetError(
                    "serving: model has inputs %s; pass a dict" % names)
            inputs = {names[0]: inputs}
        try:
            arrs, rows = self._validate(inputs)
        except MXNetError:
            # every shed request counts: oversize/malformed here, queue
            # backpressure below — serving.rejected is the load-shedding
            # dashboard row (docs/OBSERVABILITY.md)
            if _tm.enabled():
                _tm.counter("serving.rejected").inc()
            raise
        req = _Request(arrs, rows)
        with self._cond:
            if self._fatal is not None:
                # without this latch every future after the batcher's death
                # would hang forever — fail fast instead
                raise self._fatal
            if not self._started or self._stop:
                raise MXNetError("serving: engine is not running "
                                 "(call start(), or already closed)")
            if len(self._queue) >= self.max_queue:
                if _tm.enabled():
                    _tm.counter("serving.rejected").inc()
                raise MXNetError(
                    "serving: queue full (%d requests); backpressure"
                    % len(self._queue))
            self._queue.append(req)
            depth = len(self._queue)
            self._cond.notify_all()
        if _tm.enabled():
            _tm.counter("serving.requests").inc()
            _tm.gauge("serving.queue_depth").set(depth)
        return req.future

    def infer(self, inputs, timeout=60.0):
        """Blocking convenience: submit + wait; returns the output list."""
        return self.submit(inputs).result(timeout=timeout)

    # ------------------------------------------------------------- batcher
    def _gather(self):
        """Take the queue head and every following request that still fits
        the largest bucket, waiting out the batching deadline for
        mid-flight arrivals. Returns a non-empty request list, or None on
        shutdown with an empty queue."""
        max_rows = self.buckets[-1]
        with self._cond:
            while not self._queue:
                if self._stop:
                    return None
                self._cond.wait(0.1)
            deadline = self._queue[0].t_enq + self.max_delay_s
            while True:
                rows = 0
                full = False
                for r in self._queue:
                    if rows + r.rows > max_rows:
                        full = True
                        break
                    rows += r.rows
                now = time.perf_counter()
                if full or rows >= max_rows or now >= deadline or self._stop:
                    break
                self._cond.wait(deadline - now)
            batch = []
            taken = 0
            while self._queue:
                r = self._queue[0]
                if taken + r.rows > max_rows:
                    break
                batch.append(self._queue.popleft())
                taken += r.rows
            depth = len(self._queue)
        if _tm.enabled():
            _tm.gauge("serving.queue_depth").set(depth)
        return batch

    def _dispatch(self, batch: List[_Request]):
        rows = sum(r.rows for r in batch)
        bucket = next(b for b in self.buckets if b >= rows)
        padded = {}
        for n, shape in self.item_shapes.items():
            buf = np.zeros((bucket,) + shape,
                           dtype=batch[0].inputs[n].dtype)
            off = 0
            for r in batch:
                buf[off:off + r.rows] = r.inputs[n]
                off += r.rows
            padded[n] = buf
        t0 = time.perf_counter()
        if _tm.enabled():
            _tm.counter("serving.batches").inc()
            _tm.counter("serving.batch_items").inc(rows)
            _tm.counter("serving.batch_capacity").inc(bucket)
            _tm.counter("serving.padded_rows").inc(bucket - rows)
            _tm.gauge("serving.batch_occupancy").set(rows / float(bucket))
            qw = _tm.timer("serving.queue_wait")
            for r in batch:
                qw.add(t0 - r.t_enq)
        with _tm.span("serving.dispatch", model=self.name, bucket=bucket,
                      rows=rows, requests=len(batch)):
            outs = self.cache.run(padded)
        if _tm.enabled():
            _tm.timer("serving.dispatch").add(time.perf_counter() - t0)
        # slice each output back out by its statically classified
        # rows-per-item factor (non-batch-major outputs replicate whole)
        per_row = self._row_factors
        off = 0
        for r in batch:
            res = []
            for o, k in zip(outs, per_row):
                res.append(o if k is None else o[off * k:(off + r.rows) * k])
            r.future.set_result(res)
            off += r.rows

    def _latch_failure(self, exc):
        """The batcher thread is dying: latch the failure so every pending
        queued future fails NOW and every later ``submit()``/``start()``
        raises promptly, instead of hanging forever on a thread that will
        never drain the queue (the PrefetchingIter._shutdown latch
        pattern)."""
        err = MXNetError(
            "serving: batcher thread of engine %r died: %r — engine "
            "latched, pending and future requests fail; build a new "
            "engine" % (self.name, exc))
        err.__cause__ = exc
        with self._cond:
            self._fatal = err
            pending = list(self._queue)
            self._queue.clear()
            self._stop = True
            self._cond.notify_all()
        for r in pending:
            r.future.set_error(err)
        if _tm.enabled():
            _tm.counter("serving.batcher_deaths").inc()
            _tm.gauge("serving.queue_depth").set(0)

    def _batcher_loop(self):
        batch = None
        try:
            while True:
                batch = self._gather()
                if batch is None:
                    return
                try:
                    with _tm.span("serving.batch", model=self.name,
                                  requests=len(batch)):
                        self._dispatch(batch)
                except Exception as exc:  # deliver, don't kill the loop
                    for r in batch:
                        if not r.future.done():
                            r.future.set_error(exc)
        except BaseException as exc:
            # anything that escapes the loop kills the thread: a
            # non-Exception from dispatch, a bug in _gather/slicing, OOM
            for r in batch or ():
                if not r.future.done():
                    r.future.set_error(exc)
            self._latch_failure(exc)
            raise
