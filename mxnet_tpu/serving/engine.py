"""Continuous-batching inference engine + resilience layer (docs/SERVING.md,
docs/RESILIENCE.md).

One batcher thread owns the device: requests land in a thread-safe FIFO
queue, the batcher assembles them into the smallest shape bucket that
covers the pending rows — admitting requests that arrive mid-assembly up
to a deadline (``MXNET_SERVE_MAX_DELAY_MS``) — pads the batch to the
bucket, and replays the bucket's pre-compiled executable from the
``PersistentExecutableCache``. Per-request outputs are sliced back out and
delivered through futures, so N concurrent callers cost ONE dispatch.

Why buckets instead of exact shapes: XLA compiles per shape. A fixed
bucket ladder (1, 2, 4, 8, ...) bounds the executable count, warmup
pre-compiles every rung, and the sealed cache turns "a request shape we
never warmed" into a structured error instead of a silent recompile.

Ordering: strict FIFO. A batch takes the queue head and every following
request that still fits the largest bucket; a request is never overtaken
by one submitted after it.

Resilience (docs/RESILIENCE.md has the full failure-mode matrix):

* **Deadlines** — ``submit(deadline_ms=)`` / ``MXNET_SERVE_DEADLINE_MS``.
  A request whose deadline passes while QUEUED is failed
  (``ServeDeadlineError``) and removed — never dispatched; work the caller
  has already given up on must not occupy the device. An in-flight
  overrun still delivers (the device time is already spent) and counts
  into ``serving.deadline_overrun``.
* **Load shedding** — admission control at ``submit()``: a
  time-decayed EWMA of observed queue waits estimates what a new request
  would wait; if that estimate exceeds the request's deadline budget (or
  the absolute ``MXNET_SERVE_SHED`` cap), the request is shed NOW with a
  ``ServeOverloadError`` carrying ``retry_after_ms`` — failing in
  microseconds at the edge beats failing after queueing work that was
  always going to miss.
* **Dispatch retry** — a batch whose dispatch raises is re-enqueued at
  the queue head (once per request, jittered backoff) before its
  requests fail: transient executor faults don't cost a request.
* **Hitless reload** — ``reload(arg_params)`` enqueues a weight-swap
  barrier: batches ahead of it finish on the old weights, everything
  after runs the new ones. The swap writes the cache's shared param
  buffers in place (same shapes/dtypes ⇒ zero retraces), and jax array
  immutability double-buffers the device memory — an executor output
  still materializing against the old buffers is untouched.
* **Health** — ``health()`` is a lock-cheap snapshot (state / queue depth
  / shed rate / batcher liveness) for external probes; ``degraded``
  decays back to ``healthy`` once the recent-fault window drains.
* **Fault injection** — ``serving.submit`` / ``serving.dispatch`` /
  ``serving.batcher`` sites (mxnet_tpu/faultinject.py) make every path
  above directly exercisable, deterministically.
"""
from __future__ import annotations

import math
import os
import random
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..base import MXNetError
from .. import telemetry as _tm
from .. import faultinject as _fi
from .cache import PersistentExecutableCache

__all__ = ["InferenceEngine", "ServeFuture", "ServeDeadlineError",
           "ServeOverloadError", "ServeClosedError"]


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return float(default)


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return int(default)


class ServeDeadlineError(MXNetError):
    """The request's deadline expired while it was still queued; it was
    removed and never dispatched. ``queued_ms`` is how long it waited."""

    def __init__(self, msg, queued_ms=None):
        super().__init__(msg)
        self.queued_ms = queued_ms

    def __reduce__(self):  # pickle-safe across the fleet RPC boundary
        return (type(self), (self.args[0] if self.args else "",
                             self.queued_ms))


class ServeOverloadError(MXNetError):
    """Shed at admission: the engine's queue-wait estimate says this
    request would miss its deadline (or the absolute shed cap). Carries
    ``retry_after_ms`` — the client's backoff hint."""

    def __init__(self, msg, retry_after_ms):
        super().__init__(msg)
        self.retry_after_ms = retry_after_ms

    def __reduce__(self):  # pickle-safe across the fleet RPC boundary
        return (type(self), (self.args[0] if self.args else "",
                             self.retry_after_ms))


class ServeClosedError(MXNetError):
    """The engine shut down (or latched) before this queued request could
    be dispatched."""


class ServeFuture:
    """Delivery slot for one request's outputs. ``done_at`` is the
    ``time.perf_counter()`` stamp of delivery (None until done) — load
    generators read it for per-request latency without a waiter thread."""

    __slots__ = ("_event", "_result", "_error", "done_at", "_engine")

    def __init__(self, engine=None):
        self._event = threading.Event()
        self._result = None
        self._error = None
        self.done_at = None
        self._engine = weakref.ref(engine) if engine is not None else None

    def done(self):
        return self._event.is_set()

    def set_result(self, result):
        self._result = result
        self.done_at = time.perf_counter()
        self._event.set()

    def set_error(self, exc):
        self._error = exc
        self.done_at = time.perf_counter()
        self._event.set()

    def result(self, timeout=None):
        if not self._event.is_set() and self._engine is not None:
            # a latched (batcher-dead) engine resolves every future it
            # knows about, so an unresolved future here can only mean a
            # delivery hole — raise the latch NOW rather than blocking a
            # timeout-less caller forever
            eng = self._engine()
            fatal = eng._fatal if eng is not None else None
            if fatal is not None and not self._event.is_set():
                raise fatal
        if not self._event.wait(timeout):
            raise MXNetError("serving: request timed out after %ss"
                             % timeout)
        if self._error is not None:
            raise self._error
        return self._result


class _Request:
    __slots__ = ("inputs", "rows", "future", "t_enq", "deadline", "retries",
                 "trace_id")

    def __init__(self, inputs, rows, engine=None, deadline=None):
        self.inputs = inputs
        self.rows = rows
        self.future = ServeFuture(engine)
        self.t_enq = time.perf_counter()
        self.deadline = deadline  # absolute perf_counter time, or None
        self.retries = 0
        # captured at submit time on the CALLER's thread (the batcher
        # runs elsewhere): lets dispatch/queue-wait spans join the
        # fleet-wide request trace (docs/OBSERVABILITY.md §Fleet)
        self.trace_id = _tm.trace_context()


class _ReloadRequest:
    """Queue barrier carrying a weight swap: the batcher applies it in
    FIFO position, so everything submitted before it runs old weights and
    everything after runs new ones — the hitless-reload ordering."""

    __slots__ = ("arg_params", "aux_params", "future", "t_enq")

    def __init__(self, arg_params, aux_params, engine=None):
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.future = ServeFuture(engine)
        self.t_enq = time.perf_counter()


class InferenceEngine:
    """Continuous batching over shape buckets on one model.

    ``buckets`` are batch sizes (ascending after sort); ``item_shapes``
    maps each model input to its PER-ITEM shape (no batch dim) — bucket
    ``b`` binds input ``name`` at ``(b,) + item_shapes[name]``.

    Resilience knobs (all optional; docs/RESILIENCE.md):

    * ``deadline_ms`` — default per-request deadline
      (``MXNET_SERVE_DEADLINE_MS``; 0/unset = none).
    * ``shed`` — admission control (``MXNET_SERVE_SHED``): ``"0"`` off;
      ``"1"`` (default) shed when the queue-wait estimate exceeds the
      request's deadline; a number > 1 additionally sheds ANY request once
      the estimate exceeds that many milliseconds.
    * ``max_dispatch_retries`` — re-enqueues per request after a failed
      dispatch before its future fails (default 1).
    * ``health_window_s`` — how long a shed/dispatch-fault keeps
      ``health()`` reporting ``degraded`` (default 5s).
    """

    # EWMA blend for observed queue waits, and its decay time constant:
    # with no dispatches the wait estimate halves every ~tau*ln2 seconds,
    # so a storm's estimate cannot shed traffic forever after the storm
    _EWMA_ALPHA = 0.2
    _EWMA_DECAY_TAU_S = 1.0

    def __init__(self, cache: PersistentExecutableCache,
                 item_shapes: Dict[str, Sequence[int]],
                 buckets: Sequence[int] = (1, 2, 4, 8),
                 max_delay_ms: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 name: Optional[str] = None,
                 deadline_ms: Optional[float] = None,
                 shed: Optional[str] = None,
                 max_dispatch_retries: int = 1,
                 retry_backoff_ms: float = 2.0,
                 health_window_s: float = 5.0):
        if not buckets:
            raise MXNetError("serving: need at least one bucket")
        self.cache = cache
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if self.buckets[0] < 1:
            raise MXNetError("serving: buckets must be >= 1, got %s"
                             % (buckets,))
        self.item_shapes = {n: tuple(s) for n, s in item_shapes.items()}
        unknown = set(self.item_shapes) - set(cache.input_names)
        if unknown:
            raise MXNetError(
                "serving: item shapes name %s which are not model inputs %s"
                % (sorted(unknown), cache.input_names))
        # model inputs NOT in item_shapes (e.g. a SoftmaxOutput label) are
        # left to simple_bind's shape inference and stay zero-filled
        self.max_delay_s = (_env_float("MXNET_SERVE_MAX_DELAY_MS", 5.0)
                            if max_delay_ms is None else float(max_delay_ms)
                            ) / 1000.0
        self.max_queue = (_env_int("MXNET_SERVE_MAX_QUEUE", 1024)
                          if max_queue is None else int(max_queue))
        dl = (_env_float("MXNET_SERVE_DEADLINE_MS", 0.0)
              if deadline_ms is None else float(deadline_ms))
        self.default_deadline_s = dl / 1000.0 if dl > 0 else None
        self._shed_enabled, self._shed_cap_s = self._parse_shed(
            os.environ.get("MXNET_SERVE_SHED", "1") if shed is None
            else str(shed))
        self.max_dispatch_retries = int(max_dispatch_retries)
        self.retry_backoff_s = float(retry_backoff_ms) / 1000.0
        self.health_window_s = float(health_window_s)
        self.name = name or cache._model_key
        self._queue = deque()
        self._cond = _tm.named_condition("serving.engine")
        self._stop = False
        self._thread = None
        self._started = False
        self._fatal = None        # batcher-death latch; see _latch_failure
        self._row_factors = None  # per-output rows-per-item; see start()
        self._ewma_wait_s = None  # decayed estimate of queue wait
        self._ewma_t = None       # last EWMA update stamp
        self._recent_faults = deque(maxlen=512)  # (t, kind) in window
        self._reloads = 0
        self._shed_count = 0
        self._submit_count = 0
        self._health_seq = 0  # monotonic snapshot counter; see health()
        self._last_return_t = None  # dispatch.host_gap interval start

    @staticmethod
    def _parse_shed(raw):
        """``(enabled, absolute_cap_s_or_None)`` from a MXNET_SERVE_SHED
        value: 0/off/false → disabled; 1/on/true → deadline-aware only;
        a number > 1 → deadline-aware + absolute estimate cap in ms."""
        raw = str(raw).strip().lower()
        if raw in ("0", "off", "false", "no", ""):
            return False, None
        if raw in ("1", "on", "true", "yes"):
            return True, None
        try:
            cap = float(raw)
        except ValueError:
            import logging

            logging.getLogger("mxnet_tpu.serving").warning(
                "MXNET_SERVE_SHED=%r is not 0|1|<ms>; shedding stays on "
                "without an absolute cap", raw)
            return True, None
        return True, (cap / 1000.0 if cap > 1 else None)

    # ------------------------------------------------------------ lifecycle
    def bucket_shapes(self):
        return [{n: (b,) + s for n, s in self.item_shapes.items()}
                for b in self.buckets]

    def start(self, warmup=True):
        """Pre-compile every bucket executable (sealing the cache) and
        launch the batcher thread."""
        if self._fatal is not None:
            # mirror PrefetchingIter._shutdown: a latched engine stays
            # failed — restarting a batcher over state a dead thread left
            # mid-flight would race the executor
            raise self._fatal
        if self._started:
            return self
        if warmup:
            self.cache.warmup(self.bucket_shapes())
        self._row_factors = self._output_row_factors()
        with self._cond:
            self._stop = False
        self._thread = threading.Thread(target=self._batcher_loop,
                                        name="mxserve-batcher-%s" % self.name,
                                        daemon=True)
        self._started = True
        self._thread.start()
        return self

    def _output_row_factors(self):
        """Classify each model output as batch-major or not from STATIC
        shape inference at two probe batch sizes: output i is batch-major
        with k rows per item iff its leading dim is k*b for the same k at
        both probes (a (B*T, V) flattened head has k=T). A constant
        leading dim (time-major or aux outputs) fails the cross-probe
        check and is replicated whole to every request — a single-size
        divisibility test would mis-slice it whenever it happened to
        divide. Probing is pure inference (no bind/compile), so the second
        probe need not be a real bucket — this disambiguates even a
        one-bucket ladder."""
        b0 = self.buckets[-1]
        factors = None
        for b in (b0, b0 + 1):
            shapes = {n: (b,) + s for n, s in self.item_shapes.items()}
            try:
                outs = self.cache.output_shapes(shapes)
            except Exception:
                if factors is not None:
                    break  # off-bucket probe unsupported: keep probe 1
                raise
            ks = [None if not s or s[0] % b else s[0] // b for s in outs]
            factors = ks if factors is None else \
                [k if k == k2 else None for k, k2 in zip(factors, ks)]
        return factors

    def close(self, timeout=30.0, drain=True):
        """Stop the batcher. ``drain=True`` (default) answers every
        accepted request first; ``drain=False`` fails
        queued-but-undispatched requests immediately with a structured
        ``ServeClosedError`` (graceful-vs-fast shutdown). If the batcher
        is wedged past ``timeout`` the engine stays in the
        stopped-but-not-joined state — submits keep raising, ``start()``
        refuses to launch a second batcher beside the zombie (two threads
        would race on the shared executor) — and whatever is still queued
        is failed rather than left to time out."""
        if not self._started:
            return
        pending = []
        with self._cond:
            self._stop = True
            if not drain:
                pending = [r for r in self._queue]
                self._queue.clear()
            self._cond.notify_all()
        self._fail_shutdown(pending)
        self._thread.join(timeout)
        if self._thread.is_alive():
            with self._cond:
                stuck = [r for r in self._queue]
                self._queue.clear()
            self._fail_shutdown(stuck)
            raise MXNetError(
                "serving: batcher %r did not drain within %.1fs; engine "
                "left stopped (not restartable) — a request is likely "
                "wedged in dispatch; %d queued request(s) failed with a "
                "shutdown error" % (self._thread.name, timeout, len(stuck)))
        self._started = False

    def _fail_shutdown(self, requests):
        if not requests:
            return
        for r in requests:
            if not r.future.done():
                r.future.set_error(ServeClosedError(
                    "serving: engine %r shut down before this request was "
                    "dispatched" % self.name))
        if _tm.enabled():
            _tm.gauge("serving.queue_depth").set(0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # -------------------------------------------------------------- submit
    def _validate(self, inputs):
        arrs, rows = {}, None
        for n, shape in self.item_shapes.items():
            if n not in inputs:
                raise MXNetError("serving: missing input %r" % n)
            a = np.asarray(inputs[n])
            if a.ndim == len(shape):  # single item: implicit batch of 1
                a = a[None]
            if tuple(a.shape[1:]) != shape:
                raise MXNetError(
                    "serving: input %r item shape %s does not match the "
                    "engine's %s" % (n, tuple(a.shape[1:]), shape))
            if rows is None:
                rows = a.shape[0]
            elif a.shape[0] != rows:
                raise MXNetError(
                    "serving: inconsistent batch rows across inputs "
                    "(%d vs %d for %r)" % (rows, a.shape[0], n))
            arrs[n] = a
        if rows == 0:
            raise MXNetError("serving: empty request")
        if rows > self.buckets[-1]:
            raise MXNetError(
                "serving: request rows %d exceed the largest bucket %d "
                "(oversize requests must be split by the caller)"
                % (rows, self.buckets[-1]))
        return arrs, rows

    def _est_wait_s_locked(self, now):
        """Time-decayed queue-wait estimate: the EWMA of observed waits,
        halved every ~0.7s of dispatch silence, floored at zero when the
        queue is empty and nothing is pending."""
        if self._ewma_wait_s is None:
            return None
        est = self._ewma_wait_s * math.exp(
            -(now - self._ewma_t) / self._EWMA_DECAY_TAU_S)
        if not self._queue:
            # an empty queue serves a new request within the batching
            # delay — a stale storm estimate must not shed into idleness
            est = min(est, self.max_delay_s)
        return est

    def submit(self, inputs, deadline_ms=None) -> ServeFuture:
        """Enqueue one request ({input: array} or a bare array for
        single-input models); returns a ``ServeFuture``. ``deadline_ms``
        overrides the engine default: past it the request fails server-side
        (``ServeDeadlineError`` if still queued — it is then never
        dispatched) and admission may shed it immediately
        (``ServeOverloadError``) when the wait estimate already exceeds
        the budget."""
        _fi.fire("serving.submit")
        if not isinstance(inputs, dict):
            names = list(self.item_shapes)
            if len(names) != 1:
                raise MXNetError(
                    "serving: model has inputs %s; pass a dict" % names)
            inputs = {names[0]: inputs}
        try:
            arrs, rows = self._validate(inputs)
        except MXNetError:
            # every shed request counts: oversize/malformed here, queue
            # backpressure below — serving.rejected is the load-shedding
            # dashboard row (docs/OBSERVABILITY.md)
            if _tm.enabled():
                _tm.counter("serving.rejected").inc()
            raise
        dl_s = (self.default_deadline_s if deadline_ms is None
                else (float(deadline_ms) / 1000.0
                      if float(deadline_ms) > 0 else None))
        req = _Request(arrs, rows, engine=self,
                       deadline=None if dl_s is None
                       else time.perf_counter() + dl_s)
        with self._cond:
            if self._fatal is not None:
                # without this latch every future after the batcher's death
                # would hang forever — fail fast instead
                raise self._fatal
            if not self._started or self._stop:
                raise MXNetError("serving: engine is not running "
                                 "(call start(), or already closed)")
            shed_err = None
            if self._shed_enabled:
                est = self._est_wait_s_locked(req.t_enq)
                over_dl = (est is not None and dl_s is not None
                           and est > dl_s)
                over_cap = (est is not None and self._shed_cap_s is not None
                            and est > self._shed_cap_s)
                if over_dl or over_cap:
                    retry_after = max(1, int(math.ceil(est * 1000.0)))
                    shed_err = ServeOverloadError(
                        "serving: shed at admission — estimated queue wait "
                        "%.1fms exceeds %s; retry after ~%dms"
                        % (est * 1000.0,
                           ("the %.0fms deadline" % (dl_s * 1000.0))
                           if over_dl else
                           ("the %.0fms shed cap" % (self._shed_cap_s
                                                     * 1000.0)),
                           retry_after),
                        retry_after_ms=retry_after)
                    self._shed_count += 1
                    self._record_fault_locked(req.t_enq, "shed")
            if shed_err is not None:
                pass  # raise outside the stats below
            elif len(self._queue) >= self.max_queue:
                shed_err = MXNetError(
                    "serving: queue full (%d requests); backpressure"
                    % len(self._queue))
            if shed_err is not None:
                if _tm.enabled():
                    _tm.counter("serving.rejected").inc()
                    if isinstance(shed_err, ServeOverloadError):
                        _tm.counter("serving.shed").inc()
                raise shed_err
            self._submit_count += 1
            self._queue.append(req)
            depth = len(self._queue)
            self._cond.notify_all()
        if _tm.enabled():
            _tm.counter("serving.requests").inc()
            _tm.gauge("serving.queue_depth").set(depth)
        return req.future

    def infer(self, inputs, timeout=60.0):
        """Blocking convenience: submit + wait; returns the output list."""
        return self.submit(inputs).result(timeout=timeout)

    # -------------------------------------------------------------- reload
    def reload(self, arg_params, aux_params=None):
        """Hitless weight hot-swap: enqueue a swap barrier and return its
        ``ServeFuture`` (resolves True once the new weights are live).
        Batches ahead of the barrier finish on the old weights; every
        submission after it runs the new ones. Shapes/dtypes must match
        the loaded model — the swap touches buffers only, never the
        executables, so it causes ZERO retraces and drops ZERO requests.
        A failed swap (unknown key, shape mismatch) fails only the
        returned future; serving continues on the old weights."""
        req = _ReloadRequest(arg_params, aux_params, engine=self)
        with self._cond:
            if self._fatal is not None:
                raise self._fatal
            if not self._started or self._stop:
                raise MXNetError("serving: engine is not running "
                                 "(call start(), or already closed)")
            # control-plane: a reload is admitted even at max_queue
            self._queue.append(req)
            self._cond.notify_all()
        return req.future

    # ------------------------------------------------------------- batcher
    def _purge_expired_locked(self, now, expired):
        """Remove queued requests whose deadline has passed (they are
        FAILED, never dispatched). Called under ``self._cond``."""
        kept = None
        for i, r in enumerate(self._queue):
            if isinstance(r, _Request) and r.deadline is not None \
                    and now >= r.deadline:
                if kept is None:
                    kept = list(self._queue)[:i]
                expired.append(r)
            elif kept is not None:
                kept.append(r)
        if kept is not None:
            self._queue = deque(kept)

    def _fail_expired(self, expired):
        if not expired:
            return
        now = time.perf_counter()
        for r in expired:
            queued_ms = (now - r.t_enq) * 1000.0
            if r.retries:
                # it DID reach the device before (failed dispatch, was
                # re-queued) — the error must not claim otherwise, or a
                # client doing safe-to-replay accounting is misled
                msg = ("serving: deadline expired after %.1fms (a failed "
                       "dispatch was retried %d time(s); the re-queued "
                       "request was removed before re-dispatch)"
                       % (queued_ms, r.retries))
            else:
                msg = ("serving: deadline expired after %.1fms in queue; "
                       "the request was removed and never dispatched"
                       % queued_ms)
            r.future.set_error(ServeDeadlineError(msg, queued_ms=queued_ms))
        if _tm.enabled():
            _tm.counter("serving.deadline_expired").inc(len(expired))

    def _gather(self):
        """Take the queue head and every following request that still fits
        the largest bucket, waiting out the batching deadline for
        mid-flight arrivals. Expired requests are purged (failed, never
        dispatched) along the way. Returns a non-empty request list, a
        ``_ReloadRequest`` barrier, or None on shutdown with an empty
        queue."""
        max_rows = self.buckets[-1]
        while True:
            expired = []
            batch = None
            reload_req = None
            stopping = False
            with self._cond:
                while True:
                    self._purge_expired_locked(time.perf_counter(), expired)
                    if self._queue or expired:
                        # expired-with-empty-queue must exit too: their
                        # futures are failed below, not after the next
                        # arrival wakes the batcher
                        break
                    if self._stop:
                        stopping = True
                        break
                    self._cond.wait(0.1)
                if stopping or not self._queue:
                    self._fail_expired(expired)
                    if stopping:
                        return None
                    continue
                head = self._queue[0]
                if isinstance(head, _ReloadRequest):
                    self._queue.popleft()
                    reload_req = head
                else:
                    deadline = head.t_enq + self.max_delay_s
                    while True:
                        rows = 0
                        full = False
                        for r in self._queue:
                            if isinstance(r, _ReloadRequest) \
                                    or rows + r.rows > max_rows:
                                full = True
                                break
                            rows += r.rows
                        now = time.perf_counter()
                        if full or rows >= max_rows or now >= deadline \
                                or self._stop:
                            break
                        self._cond.wait(deadline - now)
                    # final check: a request that expired while the batch
                    # assembled must not ride into the dispatch
                    self._purge_expired_locked(time.perf_counter(), expired)
                    batch = []
                    taken = 0
                    while self._queue:
                        r = self._queue[0]
                        if isinstance(r, _ReloadRequest) \
                                or taken + r.rows > max_rows:
                            break
                        batch.append(self._queue.popleft())
                        taken += r.rows
                depth = len(self._queue)
            self._fail_expired(expired)
            if _tm.enabled():
                _tm.gauge("serving.queue_depth").set(depth)
            if reload_req is not None:
                return reload_req
            if batch:
                return batch
            # every gathered request expired — go around again

    def _note_wait_locked(self, wait_s, now):
        prev = self._est_wait_s_locked(now)
        self._ewma_wait_s = wait_s if prev is None else \
            (1.0 - self._EWMA_ALPHA) * prev + self._EWMA_ALPHA * wait_s
        self._ewma_t = now

    def _record_fault_locked(self, now, kind):
        self._recent_faults.append((now, kind))

    def _recent_faults_snapshot(self, now):
        cutoff = now - self.health_window_s
        return [(t, k) for t, k in self._recent_faults if t >= cutoff]

    def _dispatch(self, batch: List[_Request]):
        _tm.note_dispatch()  # lock-witness seam: holds spanning this stall
        rows = sum(r.rows for r in batch)
        bucket = next(b for b in self.buckets if b >= rows)
        padded = {}
        for n, shape in self.item_shapes.items():
            buf = np.zeros((bucket,) + shape,
                           dtype=batch[0].inputs[n].dtype)
            off = 0
            for r in batch:
                buf[off:off + r.rows] = r.inputs[n]
                off += r.rows
            padded[n] = buf
        t0 = time.perf_counter()
        with self._cond:
            for r in batch:
                self._note_wait_locked(t0 - r.t_enq, t0)
        if _tm.enabled():
            _tm.counter("serving.batches").inc()
            _tm.counter("serving.batch_items").inc(rows)
            _tm.counter("serving.batch_capacity").inc(bucket)
            _tm.counter("serving.padded_rows").inc(bucket - rows)
            _tm.gauge("serving.batch_occupancy").set(rows / float(bucket))
            _tm.gauge("serving.ewma_queue_wait_ms").set(
                round((self._ewma_wait_s or 0.0) * 1000.0, 3))
            qw = _tm.timer("serving.queue_wait")
            for r in batch:
                qw.add(t0 - r.t_enq)
                # per-request queue-wait span on the request's own trace
                # (no-op unless tracing): the fleet timeline's
                # replica-queue segment
                _tm.record_span("serving.queue_wait", r.t_enq,
                                t0 - r.t_enq, trace_id=r.trace_id)
            # dispatch.host_gap: batching/padding/queue host time between
            # the previous batch's return and this enqueue
            if self._last_return_t is not None:
                gap = time.perf_counter() - self._last_return_t
                _tm.timer("dispatch.host_gap").add(gap)
                _tm.timer("dispatch.host_gap.serving.dispatch").add(gap)
        # a batch serves many requests, possibly many traces: one unique
        # trace_id → install it as context (nested decoder spans inherit);
        # a mixed batch stamps the id LIST on the dispatch span instead
        tids = {r.trace_id for r in batch if r.trace_id is not None}
        span_kw = dict(model=self.name, bucket=bucket, rows=rows,
                       requests=len(batch))
        batch_tid = None
        if len(tids) == 1:
            batch_tid = next(iter(tids))
        elif tids:
            span_kw["trace_ids"] = sorted(tids)
        with _tm.trace_scope(batch_tid), \
                _tm.span("serving.dispatch", **span_kw):
            _fi.fire("serving.dispatch")
            outs = self.cache.run(padded)
        if _tm.enabled():
            now = time.perf_counter()
            self._last_return_t = now
            _tm.timer("serving.dispatch").add(now - t0)
        # slice each output back out by its statically classified
        # rows-per-item factor (non-batch-major outputs replicate whole)
        per_row = self._row_factors
        off = 0
        overruns = 0
        req_timer = _tm.timer("serving.request") if _tm.enabled() else None
        for r in batch:
            res = []
            for o, k in zip(outs, per_row):
                res.append(o if k is None else o[off * k:(off + r.rows) * k])
            r.future.set_result(res)
            if req_timer is not None:
                # submit → delivery: the engine-side view of the same
                # latency clients measure, so serve_bench can cross-check
                # histogram quantiles against client-side percentiles
                req_timer.add(r.future.done_at - r.t_enq)
            if r.deadline is not None and r.future.done_at > r.deadline:
                overruns += 1  # delivered, but past its budget
            off += r.rows
        if overruns and _tm.enabled():
            _tm.counter("serving.deadline_overrun").inc(overruns)

    def _apply_reload(self, req: _ReloadRequest):
        try:
            with _tm.span("serving.reload", model=self.name):
                self.cache.swap_params(req.arg_params, req.aux_params)
        except Exception as exc:
            req.future.set_error(exc)
            return
        self._reloads += 1
        if _tm.enabled():
            _tm.counter("serving.reloads").inc()
        req.future.set_result(True)

    def _retry_or_fail(self, batch, exc):
        """A dispatch raised: re-enqueue the requests that still have
        retry budget at the queue HEAD (original order — FIFO holds), fail
        the rest. Jittered backoff before the retry keeps a hot failure
        from spinning the batcher."""
        now = time.perf_counter()
        retryable, failed = [], []
        for r in batch:
            if r.future.done():
                continue  # partially delivered before the fault
            if r.retries < self.max_dispatch_retries:
                r.retries += 1
                retryable.append(r)
            else:
                failed.append(r)
        with self._cond:
            self._record_fault_locked(now, "dispatch_error")
            if retryable:
                self._queue.extendleft(reversed(retryable))
                self._cond.notify_all()
        for r in failed:
            r.future.set_error(exc)
        if _tm.enabled():
            if retryable:
                _tm.counter("serving.dispatch_retries").inc(len(retryable))
            if failed:
                _tm.counter("serving.dispatch_failures").inc(len(failed))
        if retryable:
            time.sleep(self.retry_backoff_s * (0.5 + random.random()))

    def _latch_failure(self, exc):
        """The batcher thread is dying: latch the failure so every pending
        queued future fails NOW and every later ``submit()``/``start()``
        raises promptly, instead of hanging forever on a thread that will
        never drain the queue (the PrefetchingIter._shutdown latch
        pattern)."""
        err = MXNetError(
            "serving: batcher thread of engine %r died: %r — engine "
            "latched, pending and future requests fail; build a new "
            "engine" % (self.name, exc))
        err.__cause__ = exc
        with self._cond:
            self._fatal = err
            pending = list(self._queue)
            self._queue.clear()
            self._stop = True
            self._cond.notify_all()
        for r in pending:
            r.future.set_error(err)
        if _tm.enabled():
            _tm.counter("serving.batcher_deaths").inc()
            _tm.gauge("serving.queue_depth").set(0)

    def _batcher_loop(self):
        batch = None
        try:
            while True:
                _fi.fire("serving.batcher")
                batch = self._gather()
                if batch is None:
                    return
                if isinstance(batch, _ReloadRequest):
                    self._apply_reload(batch)
                    continue
                try:
                    with _tm.span("serving.batch", model=self.name,
                                  requests=len(batch)):
                        self._dispatch(batch)
                except Exception as exc:  # deliver/retry, don't kill the loop
                    self._retry_or_fail(batch, exc)
        except BaseException as exc:
            # anything that escapes the loop kills the thread: a
            # non-Exception from dispatch, a bug in _gather/slicing, OOM
            for r in (batch if isinstance(batch, list) else
                      [batch] if batch is not None else ()):
                if not r.future.done():
                    r.future.set_error(exc)
            self._latch_failure(exc)
            raise

    # -------------------------------------------------------------- health
    def health(self):
        """Point-in-time snapshot for external probes (docs/RESILIENCE.md):

        * ``state`` — ``healthy`` | ``degraded`` (a shed or dispatch fault
          inside ``health_window_s``) | ``latched`` (batcher dead,
          unrecoverable) | ``stopped``
        * ``queue_depth``, ``batcher_alive``, ``ewma_queue_wait_ms``
        * ``shed_rate`` — sheds / offered over the engine's lifetime, and
          ``recent_sheds`` / ``recent_dispatch_errors`` over the window
        * ``reloads`` — applied hot swaps
        * ``seq`` / ``snapshot_ms`` — a per-engine monotonic snapshot
          counter and the wall-clock stamp of THIS snapshot. A consumer
          that caches snapshots (the fleet router does) can tell a fresh
          report from a dead replica's last-good numbers: a repeated
          ``seq`` or an old ``snapshot_ms`` means nobody is answering —
          dispatching on those numbers would send traffic to a corpse.
        """
        now = time.perf_counter()
        with self._cond:
            fatal = self._fatal
            running = self._started and not self._stop
            depth = len(self._queue)
            est = self._est_wait_s_locked(now)
            recent = self._recent_faults_snapshot(now)
            sheds, submits = self._shed_count, self._submit_count
            reloads = self._reloads
            self._health_seq += 1
            seq = self._health_seq
        alive = self._thread is not None and self._thread.is_alive()
        if fatal is not None:
            state = "latched"
        elif not running:
            state = "stopped"
        elif recent:
            state = "degraded"
        else:
            state = "healthy"
        return {
            "state": state,
            "seq": seq,
            "snapshot_ms": time.time() * 1000.0,
            "queue_depth": depth,
            "batcher_alive": alive,
            "ewma_queue_wait_ms": None if est is None
            else round(est * 1000.0, 3),
            "shed_rate": round(sheds / (submits + sheds), 4)
            if (submits + sheds) else 0.0,
            "recent_sheds": sum(1 for _, k in recent if k == "shed"),
            "recent_dispatch_errors": sum(1 for _, k in recent
                                          if k == "dispatch_error"),
            "reloads": reloads,
            "deadline_ms": None if self.default_deadline_s is None
            else self.default_deadline_s * 1000.0,
            # fusion pattern surface of the warmed buckets (inference-mode
            # gating is per pattern per shape; see docs/PERF.md §13)
            "fusion": self.cache.fusion_sites(),
        }
