"""Speculative decoding over the paged pool (docs/SERVING.md §Prefix
cache & speculative decoding).

Decode latency is dispatch-bound, not FLOP-bound: the megastep work
amortized the per-token host gap, and speculation amortizes the
per-token DISPATCH. A small draft model (the target's first k blocks —
``models/transformer.py draft_config``; weight names are positional so
the TARGET checkpoint feeds it unchanged) proposes γ tokens inside its
own decode megastep, then the target scores all γ+1 candidate positions
in ONE rectangular chunk dispatch (``PagedKVDecoder.verify_chunk``).
Greedy acceptance keeps the longest prefix where the draft's token
equals the target's argmax, emits the target's own token at the first
disagreement, and ``rollback`` releases the rejected tail's pages —
a refcount decrement, no copy, no device work. Because every emitted
token is the target's argmax given the exact same visible KV, the
output stream is TOKEN-IDENTICAL to non-speculative greedy decode:
speculation only changes how many dispatches it takes to produce it —
the ci parity gate pins exactly that.

Round protocol (target and draft both at position p, next token ``cur``):

1. draft megastep(k=γ) from ``cur`` → proposals props[0..γ-1]
   (draft writes positions p..p+γ-1, i.e. cur and props[:-1])
2. target ``verify_chunk([cur] + props)`` → γ+1 logits rows in one
   dispatch (target writes positions p..p+γ)
3. accept props[j] while props[j] == argmax(row j); at the first miss
   emit the target's argmax instead; n_acc accepted → n_acc+1 emitted
4. rollback BOTH decoders to p + n_acc + 1 (whole rejected pages are
   released; a partial boundary page just masks its stale tail)
5. fully-accepted rounds advance the draft one extra plain step so it
   re-synchronizes (it never wrote props[γ-1])
"""
from __future__ import annotations

import os

import numpy as np

from ..base import MXNetError
from .. import telemetry as _tm
from .kv_decode import PagedKVDecoder

__all__ = ["SpeculativeDecoder", "spec_decode_enabled", "spec_gamma"]


def spec_decode_enabled():
    """``MXNET_SPEC_DECODE`` truthy -> serving loops that support it use
    draft-verify speculative decoding."""
    return os.environ.get("MXNET_SPEC_DECODE", "").strip().lower() \
        in ("1", "on", "true", "yes")


def spec_gamma(default=4):
    """Draft tokens proposed per round (``MXNET_SPEC_GAMMA``). Junk or
    non-positive values fall back to ``default``."""
    raw = os.environ.get("MXNET_SPEC_GAMMA", "").strip()
    if not raw:
        return int(default)
    try:
        g = int(raw)
    except ValueError:
        return int(default)
    return g if g >= 1 else int(default)


class SpeculativeDecoder:
    """Draft-verify speculative greedy decode over two paged decoders.

    ``target`` and ``draft`` are ``PagedKVDecoder``s sharing the vocab
    (normally the draft is the same checkpoint at fewer layers — see
    ``build``). Admission runs on both; each decode round costs one
    draft megastep + one target verify chunk instead of γ+1 target
    dispatches, recovering latency whenever the draft's agreement rate
    beats the draft's relative cost."""

    def __init__(self, target: PagedKVDecoder, draft: PagedKVDecoder,
                 gamma=None):
        if target.vocab_size != draft.vocab_size:
            raise MXNetError(
                "speculative: target vocab %d != draft vocab %d"
                % (target.vocab_size, draft.vocab_size))
        self.target = target
        self.draft = draft
        self.gamma = int(gamma) if gamma is not None else spec_gamma()
        if self.gamma < 1:
            raise MXNetError("speculative: gamma must be >= 1, got %d"
                             % self.gamma)
        self._pairs = {}  # target seq_id -> draft seq_id

    @classmethod
    def build(cls, arg_params, vocab_size, num_layers=2, draft_layers=1,
              gamma=None, model_key=None, **kw):
        """Target + draft from ONE checkpoint: the draft is the same
        config truncated to its first ``draft_layers`` blocks
        (positional weight names; extra checkpoint entries are ignored
        at bind, as in the predict API's allow_extra_params)."""
        from ..models.transformer import draft_config

        cfg = dict(vocab_size=vocab_size, num_layers=num_layers, **kw)
        dcfg = draft_config(cfg, draft_layers)
        target = PagedKVDecoder(arg_params, model_key=model_key, **cfg)
        draft = PagedKVDecoder(
            arg_params,
            model_key=(model_key or "transformer_paged_global_decode")
            + "-draft%d" % draft_layers, **dcfg)
        return cls(target, draft, gamma=gamma)

    # ------------------------------------------------------------ lifecycle
    def warmup(self):
        """Compile every program a decode round replays — the target's
        decode executable + (γ+1)-chunk verify, the draft's decode
        executable + γ-megastep — so the steady state is all cache
        hits."""
        from .kv_decode import _megastep_for, _sampler_from

        self.target.warmup()
        self.draft.warmup()
        self.target._chunk_for(self.gamma + 1)
        _megastep_for(self.draft, self.gamma,
                      _sampler_from(None, None, None))
        return self

    def admit(self, prompt):
        """Admit into BOTH decoders. Returns ``(seq_id, logits)`` in the
        target's namespace; the paired draft sequence is internal."""
        seq_id, logits = self.target.admit(prompt)
        try:
            d_id, _ = self.draft.admit(prompt)
        except BaseException:
            self.target.retire(seq_id)
            raise
        self._pairs[seq_id] = d_id
        return seq_id, logits

    def retire(self, seq_id):
        d_id = self._pairs.pop(seq_id, None)
        self.target.retire(seq_id)
        if d_id is not None:
            self.draft.retire(d_id)

    def stats(self):
        return {"gamma": self.gamma,
                "target": self.target.stats(),
                "draft": self.draft.stats()}

    # --------------------------------------------------------------- decode
    def _room(self, seq_id, d_id):
        """Largest γ a round can use at the current position: the target
        writes γ+1 positions, the draft γ+1 (γ in the megastep plus at
        most one catch-up step) — both bounded by their position tables
        and per-lane slot quotas."""
        p = self.target.position(seq_id)
        lim = min(self.target.pos_len, self.target.max_len,
                  self.draft.pos_len, self.draft.max_len)
        return min(self.gamma, lim - p - 1)

    def greedy(self, prompt, n_tokens):
        """Greedy-decode ``n_tokens`` continuation tokens for one
        prompt, speculatively. Returns a (n_tokens,) int64 array that is
        token-identical to ``PagedKVDecoder.greedy`` on the target
        alone."""
        seq_id, logits = self.admit(prompt)
        d_id = self._pairs[seq_id]
        try:
            out = np.zeros((n_tokens,), np.int64)
            if n_tokens == 0:
                return out
            cur = int(np.argmax(logits))
            out[0] = cur
            t = 1
            g = self.gamma
            while t < n_tokens:
                if self._room(seq_id, d_id) < g:
                    # not enough table room for a FULL γ round — a
                    # shorter round would compile fresh (γ'+1)-chunk and
                    # γ'-megastep programs post-warmup, so the tail runs
                    # plain warm single steps instead
                    fed = cur
                    # graphlint: waive GL702 -- position-table tail; single-step program is already warm
                    lg = self.target.step({seq_id: fed})
                    # graphlint: waive GL703 -- one id from already-pulled logits
                    cur = int(np.argmax(lg[seq_id]))
                    # keep the draft aligned in case room returns later
                    # graphlint: waive GL702 -- draft shadow step, same warm program
                    self.draft.step({d_id: fed})
                    out[t] = cur
                    t += 1
                    continue
                p = self.target.position(seq_id)
                # graphlint: waive GL702 -- the γ-token round IS the amortization: one scan dispatch proposes γ tokens
                props = self.draft.step_megastep({d_id: cur}, k=g)[d_id]
                rows = self.target.verify_chunk(
                    seq_id, np.concatenate(([cur], props)))
                # graphlint: waive GL703 -- γ+1 argmaxes on one already-pulled verify block, not per-token pulls
                ids = np.argmax(rows, axis=1).astype(np.int64)
                n_acc = 0
                while n_acc < g and props[n_acc] == ids[n_acc]:
                    n_acc += 1
                emitted = list(props[:n_acc]) + [int(ids[n_acc])] \
                    if n_acc < g else list(props) + [int(ids[g])]
                if n_acc < g:
                    self.target.rollback(seq_id, p + n_acc + 1)
                    self.draft.rollback(d_id, p + n_acc + 1)
                else:
                    # full accept: the draft never wrote props[-1] —
                    # one catch-up step re-synchronizes the pair
                    # graphlint: waive GL702 -- ≤1 catch-up step per γ-token round
                    self.draft.step({d_id: int(props[-1])})
                if _tm.enabled():
                    _tm.counter("spec.proposed_tokens").inc(int(g))
                    _tm.counter("spec.accepted_tokens").inc(n_acc)
                    _tm.counter("spec.rounds").inc()
                for tok in emitted:
                    if t >= n_tokens:
                        break
                    out[t] = tok
                    t += 1
                cur = int(emitted[-1])
            return out
        finally:
            self.retire(seq_id)
