"""KV-cache incremental decode for the transformer LM (docs/SERVING.md).

Autoregressive serving without per-step recompilation: ONE prefill
executable (prompt bucket, exports every layer's K/V) plus ONE
single-token decode executable over a preallocated ring KV buffer of
``max_len`` slots per layer. Both come from a sealed
``PersistentExecutableCache``, so after warmup a greedy decode of any
length replays exactly two XLA programs — the full-sequence re-forward it
replaces costs O(T) work per token and a recompile per prompt length.

Ring layout: position ``p`` writes slot ``p % max_len``; the write happens
IN-GRAPH (``slot_onehot`` blend, models/transformer.py
``get_decode_symbol``), and the updated buffers are program outputs the
decoder swaps back in as the next step's inputs — a device-side pointer
swap, no copy, no host round-trip. Attention over slots is
order-agnostic (position information lives in the embeddings), so ring
wraparound needs no rotation: once ``p >= max_len`` every slot is valid
and the oldest token is simply the one overwritten.

Megasteps (``MXNET_DECODE_MEGASTEP_K``, docs/SERVING.md §megasteps): the
per-token loop above still pays one host round-trip per token.
``decode_megastep``/``step_megastep`` fold K decode steps into ONE
compiled program — a ``lax.scan`` over the same decode graph with
on-device sampling (greedy argmax head, or temperature/top-k via the
PRNG machinery) — so only (K, B) token ids cross the host per dispatch.
Per-lane early exit reuses the all-zero ``slot_onehot`` idle-lane idiom:
once a lane emits ``eos_id`` its remaining scan steps write NOTHING to
its KV slots. K=1 keeps today's single-step path byte-for-byte.

Paged pool (``PagedKVDecoder``): the lanes share ONE global slot axis
(``lanes * max_len`` slots per layer), carved into refcounted page
frames. Physical sharing is then free — the prefix cache
(serving/prefix_cache.py) parks whole prompt chunks at a refcount and
cached admits adopt them without recompute; ``fork`` clones a sequence
by increfing its frames; a write into a shared page copy-on-writes; and
``rollback``/``verify_chunk`` give speculative decoding
(serving/speculative.py) its accept/reject primitives.
"""
from __future__ import annotations

import os
import time
from typing import Dict, Optional

import numpy as np

from ..base import MXNetError
from .. import telemetry as _tm
from .cache import PersistentExecutableCache

__all__ = ["KVCacheDecoder", "PagedKVDecoder", "PagedKVExhausted",
           "decode_megastep_k"]

_NEG = np.float32(-1e9)


def decode_megastep_k(default=1):
    """Decode tokens per dispatch (``MXNET_DECODE_MEGASTEP_K``). K=1 is
    the classic single-step path; K>1 routes the greedy loops through the
    scan megastep. Junk values fall back to ``default``."""
    raw = os.environ.get("MXNET_DECODE_MEGASTEP_K", "").strip()
    if not raw:
        return int(default)
    try:
        k = int(raw)
    except ValueError:
        return int(default)
    return k if k >= 1 else int(default)


def _gap_mark(dec, site):
    """``dispatch.host_gap``: host time from the previous executable's
    return (the blocking pull) to this dispatch's enqueue — the seam the
    GL7xx analyzer prices (docs/OBSERVABILITY.md). Recorded per call site
    and in aggregate. Off-mode cost is one predicate — no span objects,
    no clock reads."""
    if not _tm.enabled():
        return
    now = time.perf_counter()
    last = dec._last_return_t
    if last is not None:
        dt = now - last
        _tm.timer("dispatch.host_gap").add(dt)
        _tm.timer("dispatch.host_gap." + site).add(dt)


def _gap_return(dec):
    """Stamp the executable-return side of the ``dispatch.host_gap``
    interval (called right after the blocking pull completes)."""
    if _tm.enabled():
        dec._last_return_t = time.perf_counter()


# ------------------------------------------------------------------ megastep
class _Sampler:
    """On-device sampling config for megasteps: ``greedy`` takes the
    graph's argmax head; ``topk`` divides logits by ``temperature``,
    masks everything below the ``top_k``-th logit (0 = no truncation) and
    draws with ``jax.random.categorical``."""

    __slots__ = ("mode", "temperature", "top_k")

    def __init__(self, mode="greedy", temperature=1.0, top_k=0):
        if mode not in ("greedy", "topk"):
            raise MXNetError("decode sampler: mode must be 'greedy' or "
                             "'topk', got %r" % (mode,))
        self.mode = mode
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        if self.temperature <= 0:
            raise MXNetError("decode sampler: temperature must be > 0")
        if self.top_k < 0:
            raise MXNetError("decode sampler: top_k must be >= 0")

    def key(self):
        return (self.mode, self.temperature, self.top_k)


def _sampler_from(sample=None, temperature=None, top_k=None):
    """Resolve sampler knobs: explicit arguments win over the
    MXNET_DECODE_SAMPLE / _TEMP / _TOPK environment defaults."""
    mode = sample or os.environ.get("MXNET_DECODE_SAMPLE", "greedy")
    if temperature is None:
        temperature = float(os.environ.get("MXNET_DECODE_SAMPLE_TEMP",
                                           "1.0"))
    if top_k is None:
        top_k = int(os.environ.get("MXNET_DECODE_SAMPLE_TOPK", "0"))
    return _Sampler(mode, temperature, top_k)


def _sampling_key(dec):
    """Per-decoder PRNG base key for on-device sampling. Seeded from the
    decoder's ``sample_seed`` ctor arg, else MXNET_DECODE_SAMPLE_SEED,
    else split off the global PRNG stream. The base key is FIXED for the
    decoder's life — the megastep folds the absolute position and lane
    index into it per draw, so a seeded decode emits the same tokens no
    matter how the steps are partitioned into megasteps."""
    if dec._sample_key is None:
        import jax

        seed = dec._sample_seed
        if seed is None:
            raw = os.environ.get("MXNET_DECODE_SAMPLE_SEED", "").strip()
            seed = int(raw) if raw else None
        if seed is not None:
            dec._sample_key = jax.random.PRNGKey(int(seed))
        else:
            from .. import random as _rnd

            dec._sample_key = _rnd._next_key()
    return dec._sample_key


class _DecodeMegastep:
    """K decode steps folded into ONE compiled program.

    A ``jax.jit``-ted ``lax.scan`` over the per-stream decode graph
    (``_GraphProgram.interpret`` is pure and jit-safe): the scan carries
    (next token, done mask, attention mask, KV buffers), each step blends
    its KV write in-graph through the host-staged slot plan, samples the
    next token ON DEVICE, and only the stacked (K, B) ids + activity
    mask ever cross to the host. EOS'd / idle lanes carry an all-zero
    ``slot_onehot`` row — their KV passes through bitwise-unchanged (the
    idle-lane idiom the paged decoder already relies on).

    Shapes are fixed at build time, so after the warm-time compile every
    dispatch is a jit cache hit; input-signature drift is a hard retrace
    error, mirroring the sealed ``PersistentExecutableCache`` contract.
    """

    def __init__(self, dec, k, sampler):
        import jax
        import jax.numpy as jnp

        from ..executor import _GraphProgram
        from ..models import transformer as _tf

        self.k = int(k)
        self.sampler = sampler
        self.rows = dec.batch if hasattr(dec, "batch") else dec.lanes
        # the paged decoder's pool is ONE global slot axis shared by all
        # lanes (kv (H, S_tot, dh)); the classic decoders carry a ring
        # per lane (kv (B, H, S, dh)) — same scan, different slot space
        self.global_slots = bool(getattr(dec, "_global_slots", False))
        B, L = self.rows, dec.num_layers
        S = dec.total_slots if self.global_slots else dec.max_len
        self._S = S
        pos_len = dec.pos_len
        sym = _tf.get_decode_symbol(
            vocab_size=dec.vocab_size, num_layers=L,
            num_heads=dec.num_heads, model_dim=dec.model_dim,
            ffn_dim=dec.ffn_dim, max_len=S, pos_len=pos_len,
            per_stream_slots=True, global_slots=self.global_slots)
        prog = _GraphProgram(sym)
        if prog.aux_names:
            raise MXNetError("decode megastep: the decode graph must carry "
                             "no aux state, got %r" % (prog.aux_names,))
        self.kv_names = [n for i in range(L)
                         for n in ("kv_k_%d" % i, "kv_v_%d" % i)]
        step_inputs = {"data", "pos_idx", "slot_onehot", "kv_mask"}
        step_inputs.update(self.kv_names)
        # weight names are shared across every serving graph — the values
        # are pulled from the live executable at DISPATCH time, so a
        # hitless swap_params lands in the very next megastep
        self.weight_names = [n for n in prog.arg_names
                             if n not in step_inputs]
        arg_names = list(prog.arg_names)
        mode, temp, top_k = sampler.mode, sampler.temperature, sampler.top_k
        lane_ids = jnp.arange(B)

        def _sample(logits, pos_abs, base_key):
            lg = logits.astype(jnp.float32) / jnp.float32(temp)
            if top_k > 0:
                kth = jax.lax.top_k(lg, top_k)[0][:, -1:]
                lg = jnp.where(lg < kth, -jnp.inf, lg)

            def draw(p, lane, row):
                # fold ABSOLUTE position then lane: reproducible across
                # any K partitioning of the same decode
                return jax.random.categorical(
                    jax.random.fold_in(
                        jax.random.fold_in(base_key, p), lane), row)

            return jax.vmap(draw)(pos_abs, lane_ids, lg)

        def run(weights, kvs, tok0, pos, slots, base_mask, done0, key, eos):
            def body(carry, xs):
                tok, done, mask, kv = carry
                t, slot_col = xs
                act = jnp.logical_not(done)
                oh = jax.nn.one_hot(slot_col, S, dtype=jnp.float32) \
                    * act.astype(jnp.float32)[:, None]
                # the slot written this step becomes attendable now and
                # for the rest of the scan (the carried mask accumulates)
                mask = jnp.where(oh > 0, jnp.float32(0), mask)
                # idle/done lanes clamp their position into the trained
                # table; their onehot row is all-zero so the value is
                # never written anywhere
                pos_t = jnp.clip(pos + t, 0, pos_len - 1)
                feed = {"data": tok.astype(jnp.float32)[:, None],
                        "pos_idx": pos_t.astype(jnp.float32)[:, None],
                        "slot_onehot": oh, "kv_mask": mask}
                for i, name in enumerate(self.kv_names):
                    feed[name] = kv[i]
                args = [feed[n] if n in feed else weights[n]
                        for n in arg_names]
                outs, _ = prog.interpret(args, (), False, key)
                new_kv = tuple(outs[1 + j] for j in range(2 * L))
                if mode == "greedy":
                    nxt = outs[-1].astype(jnp.int32)  # on-device argmax head
                else:
                    nxt = _sample(outs[0], pos + t, key).astype(jnp.int32)
                nxt = jnp.where(act, nxt, jnp.maximum(eos, 0))
                done = jnp.logical_or(
                    done, jnp.logical_and(act, (eos >= 0) & (nxt == eos)))
                return (nxt, done, mask, new_kv), (nxt, act)

            xs = (jnp.arange(self.k), jnp.transpose(slots))
            (_tok, done_f, _mask, kv_f), (toks, acts) = jax.lax.scan(
                body, (tok0, done0, base_mask, kvs), xs)
            return toks, acts, kv_f, done_f

        self._fn = jax.jit(run)
        self._sig = None

    @staticmethod
    def _sig_of(*arrays):
        return tuple((tuple(a.shape), str(a.dtype)) for a in arrays)

    def _zero_inputs(self):
        B, S = self.rows, self._S
        tok0 = np.zeros((B,), np.int32)
        pos = np.zeros((B,), np.int32)
        slots = np.zeros((B, self.k), np.int32)
        base_mask = np.full((B, S), _NEG, np.float32)
        done0 = np.ones((B,), bool)  # every lane idle: compiles, writes nothing
        return tok0, pos, slots, base_mask, done0

    def warm(self, dec):
        """Compile the megastep NOW (a dummy all-idle dispatch), counted
        as the one ``executor.compile`` this program ever charges — bench
        warmup snapshots see it, the steady state never does."""
        import jax
        import jax.numpy as jnp

        B, S = self.rows, self._S
        H, dh = dec.num_heads, dec.dh
        weights = {n: dec._dec_exe.arg_dict[n]._jax()
                   for n in self.weight_names}
        kv_shape = (H, S, dh) if self.global_slots else (B, H, S, dh)
        kvs = tuple(jnp.zeros(kv_shape, jnp.float32)
                    for _ in self.kv_names)
        z = self._zero_inputs()
        with _tm.span("serving.megastep_compile", k=self.k, rows=B,
                      sampler=self.sampler.mode):
            out = self._fn(weights, kvs, *z, _sampling_key(dec),
                           np.int32(-1))
            # graphlint: waive GL7xx -- warm-time compile barrier, not the dispatch path
            jax.block_until_ready(out)
        self._sig = self._sig_of(*z)
        if _tm.enabled():
            _tm.counter("executor.compile").inc()

    def run(self, dec, tok0, pos, slots, base_mask, done0, eos):
        """One megastep dispatch. Returns device-resident
        ``(toks (K,B) i32, acts (K,B) bool, new_kvs, done)`` — the caller
        pulls the ids (the only host transfer) and pointer-swaps the KV."""
        sig = self._sig_of(tok0, pos, slots, base_mask, done0)
        if self._sig is not None and sig != self._sig:
            if _tm.enabled():
                _tm.counter("executor.retrace").inc()
            raise MXNetError(
                "decode megastep (K=%d): input signature drifted from the "
                "warmed shapes (%r != %r) — megastep programs are sealed "
                "like the executable cache" % (self.k, sig, self._sig))
        if _tm.enabled():
            _tm.counter("executor.cache_hit").inc()
        weights = {n: dec._dec_exe.arg_dict[n]._jax()
                   for n in self.weight_names}
        kvs = tuple(dec._dec_exe.arg_dict[n]._jax() for n in self.kv_names)
        return self._fn(weights, kvs, tok0, pos, slots, base_mask, done0,
                        _sampling_key(dec), eos)


def _megastep_for(dec, k, sampler):
    """The decoder's cached megastep program for ``(K, sampler)`` —
    built + warm-compiled once, a jit cache hit forever after."""
    cache_key = (int(k), sampler.key())
    ms = dec._megasteps.get(cache_key)
    if ms is None:
        ms = _DecodeMegastep(dec, k, sampler)
        ms.warm(dec)
        dec._megasteps[cache_key] = ms
    return ms


class _ChunkProgram:
    """T tokens of ONE lane scored (and optionally written) in a single
    rectangular dispatch over the global paged pool
    (models/transformer.py ``get_chunk_symbol``). Chunked prefill — admit
    computes only a prompt's un-cached tail, C tokens per dispatch — and
    the speculative draft-verify pass (γ+1 candidate positions at once)
    are the SAME program at different T. Sealed exactly like the
    megastep: one warm-time compile, signature drift is a hard retrace
    error, weights are pulled from the live decode executable at dispatch
    time so a hitless swap_params lands in the next chunk."""

    def __init__(self, dec, t):
        import jax

        from ..executor import _GraphProgram
        from ..models import transformer as _tf

        self.t = int(t)
        S, L = dec.total_slots, dec.num_layers
        self._S = S
        symb = _tf.get_chunk_symbol(
            vocab_size=dec.vocab_size, num_layers=L,
            num_heads=dec.num_heads, model_dim=dec.model_dim,
            ffn_dim=dec.ffn_dim, chunk_len=self.t, total_slots=S,
            pos_len=dec.pos_len)
        prog = _GraphProgram(symb)
        if prog.aux_names:
            raise MXNetError("chunk program: the chunk graph must carry "
                             "no aux state, got %r" % (prog.aux_names,))
        self.kv_names = [n for i in range(L)
                         for n in ("kv_k_%d" % i, "kv_v_%d" % i)]
        step_inputs = {"data", "pos_idx", "write_onehot", "att_mask"}
        step_inputs.update(self.kv_names)
        self.weight_names = [n for n in prog.arg_names
                             if n not in step_inputs]
        arg_names = list(prog.arg_names)

        def run(weights, kvs, data, pos_idx, w_oh, mask, key):
            feed = {"data": data, "pos_idx": pos_idx,
                    "write_onehot": w_oh, "att_mask": mask}
            for i, name in enumerate(self.kv_names):
                feed[name] = kvs[i]
            args = [feed[n] if n in feed else weights[n]
                    for n in arg_names]
            outs, _ = prog.interpret(args, (), False, key)
            new_kv = tuple(outs[1 + j] for j in range(2 * L))
            return outs[0], new_kv, outs[-1]

        self._fn = jax.jit(run)
        self._sig = None

    def _zero_inputs(self):
        T, S = self.t, self._S
        data = np.zeros((1, T), np.float32)
        pos_idx = np.zeros((1, T), np.float32)
        w_oh = np.zeros((T, S), np.float32)
        mask = np.full((T, S), _NEG, np.float32)
        return data, pos_idx, w_oh, mask

    def warm(self, dec):
        """Compile NOW with an all-pad (zero-write, fully-masked) chunk,
        counted as this program's one ``executor.compile``."""
        import jax

        weights = {n: dec._dec_exe.arg_dict[n]._jax()
                   for n in self.weight_names}
        kvs = tuple(dec._dec_exe.arg_dict[n]._jax() for n in self.kv_names)
        z = self._zero_inputs()
        with _tm.span("serving.chunk_compile", t=self.t):
            out = self._fn(weights, kvs, *z, _sampling_key(dec))
            # graphlint: waive GL7xx -- warm-time compile barrier, not the dispatch path
            jax.block_until_ready(out)
        self._sig = _DecodeMegastep._sig_of(*z)
        if _tm.enabled():
            _tm.counter("executor.compile").inc()

    def run(self, dec, data, pos_idx, w_oh, mask):
        """One chunk dispatch. Returns device-resident
        ``(logits (T, vocab), new_kvs, tokens (T,))`` — the caller
        pointer-swaps the KV and pulls only what it needs."""
        sig = _DecodeMegastep._sig_of(data, pos_idx, w_oh, mask)
        if self._sig is not None and sig != self._sig:
            if _tm.enabled():
                _tm.counter("executor.retrace").inc()
            raise MXNetError(
                "chunk program (T=%d): input signature drifted from the "
                "warmed shapes (%r != %r) — chunk programs are sealed "
                "like the executable cache" % (self.t, sig, self._sig))
        if _tm.enabled():
            _tm.counter("executor.cache_hit").inc()
        weights = {n: dec._dec_exe.arg_dict[n]._jax()
                   for n in self.weight_names}
        kvs = tuple(dec._dec_exe.arg_dict[n]._jax() for n in self.kv_names)
        return self._fn(weights, kvs, data, pos_idx, w_oh, mask,
                        _sampling_key(dec))


class KVCacheDecoder:
    """Batched greedy/streaming decode over the serving transformer.

    ``arg_params`` is the trained {name: array} dict of
    ``models/transformer.get_symbol`` (embed/pos/layerN_*/final_ln/lm_head
    weights — the serving graphs share those names exactly).
    """

    def __init__(self, arg_params: Dict[str, object], vocab_size,
                 num_layers=2, num_heads=2, model_dim=32, ffn_dim=64,
                 max_len=64, prefill_len: Optional[int] = None,
                 pos_len: Optional[int] = None, batch=1, ctx=None,
                 dtype="float32", cache_dir=None, model_key=None,
                 sample_seed=None):
        from ..models import transformer as _tf

        self.vocab_size = int(vocab_size)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.model_dim = int(model_dim)
        self.ffn_dim = int(ffn_dim)
        self.max_len = int(max_len)
        self.prefill_len = int(prefill_len or max_len)
        self.pos_len = int(pos_len or max_len)
        self.batch = int(batch)
        self.dh = self.model_dim // self.num_heads
        if self.prefill_len > self.max_len:
            raise MXNetError("kv_decode: prefill_len %d > max_len %d"
                             % (self.prefill_len, self.max_len))
        cfg = dict(vocab_size=self.vocab_size, num_layers=self.num_layers,
                   num_heads=self.num_heads, model_dim=self.model_dim,
                   ffn_dim=int(ffn_dim), pos_len=self.pos_len)
        key = model_key or "transformer_decode"
        self._pf_cache = PersistentExecutableCache(
            _tf.get_prefill_symbol(prefill_len=self.prefill_len, **cfg),
            arg_params, {}, ctx=ctx, dtype=dtype, cache_dir=cache_dir,
            model_key=key + "-prefill")
        self._dec_cache = PersistentExecutableCache(
            _tf.get_decode_symbol(max_len=self.max_len, **cfg),
            arg_params, {}, ctx=ctx, dtype=dtype, cache_dir=cache_dir,
            model_key=key + "-decode")
        self._dec_exe = None
        self._pos = 0
        self._warm = False
        self._token_out = False
        self._last_return_t = None  # dispatch.host_gap interval start
        self._megasteps = {}        # (K, sampler) -> _DecodeMegastep
        self._sample_seed = sample_seed
        self._sample_key = None

    # ------------------------------------------------------------ lifecycle
    def _decode_shapes(self):
        B, S, H, dh = self.batch, self.max_len, self.num_heads, self.dh
        shapes = {"data": (B, 1), "pos_idx": (B, 1), "slot_onehot": (S,),
                  "kv_mask": (S,)}
        for i in range(self.num_layers):
            shapes["kv_k_%d" % i] = (B, H, S, dh)
            shapes["kv_v_%d" % i] = (B, H, S, dh)
        return shapes

    def warmup(self):
        """Compile the prefill and decode executables; seal both caches —
        any later shape drift is a hard retrace error, not a recompile."""
        if self._warm:
            return self
        self._pf_cache.warmup([{"data": (self.batch, self.prefill_len)}])
        self._dec_cache.warmup([self._decode_shapes()])
        self._dec_exe = self._dec_cache.executable(self._decode_shapes())
        # trailing greedy_token head (transformer.get_decode_symbol
        # token_out=True)? A stale on-disk cache may hold the old program,
        # so trust the compiled executable, not the symbol we asked for —
        # and detect the head BY NAME, not by output count: a count check
        # misreads any program whose output arity merely coincides (e.g.
        # a cached token-less program at a different layer count)
        self._token_out = any(
            name.startswith("greedy_token")
            for name in self._dec_exe.output_dict)
        self._warm = True
        return self

    def reset(self):
        """Forget all context (the KV slots are masked out, not zeroed —
        the mask is the source of truth for validity)."""
        self._pos = 0
        self._last_return_t = None

    @property
    def position(self):
        return self._pos

    # -------------------------------------------------------------- prefill
    def prefill(self, tokens):
        """Consume a (B, L<=prefill_len) prompt in one executable call:
        seeds the ring KV buffer with positions 0..L-1 and returns the
        (B, vocab) logits at position L-1 (the first generation step's
        distribution)."""
        self.warmup()
        tokens = np.asarray(tokens, dtype=np.float32)
        if tokens.ndim == 1:
            tokens = tokens[None]
        B, L = tokens.shape
        if B != self.batch:
            raise MXNetError("kv_decode: prefill batch %d != engine batch %d"
                             % (B, self.batch))
        if not 0 < L <= self.prefill_len:
            raise MXNetError("kv_decode: prompt length %d not in "
                             "(0, %d]" % (L, self.prefill_len))
        P = self.prefill_len
        padded = np.zeros((B, P), np.float32)
        padded[:, :L] = tokens
        with _tm.span("serving.prefill", rows=B, prompt_len=L):
            pf = self._pf_cache.executable({"data": (B, P)})
            pf.arg_dict["data"][:] = padded
            pf.forward(is_train=False)
            # only the last real position's logits cross to the host
            logits = np.asarray(
                pf.outputs[0]._jax().reshape(
                    B, P, self.vocab_size)[:, L - 1, :])
        # seed the decode ring: slots 0..P-1 <- prefill K/V, entirely
        # device-side — pointer swap when the ring is exactly the prefill
        # window, a device scatter otherwise; the K/V tensors never round-
        # trip through the host (slots >= L are garbage but masked until
        # their positions are actually written)
        exe = self._dec_exe
        for i in range(self.num_layers):
            for tag, out in (("kv_k_%d" % i, pf.outputs[1 + 2 * i]),
                             ("kv_v_%d" % i, pf.outputs[2 + 2 * i])):
                if P == self.max_len:
                    exe.arg_dict[tag]._set_jax(out._jax())
                else:
                    ring = exe.arg_dict[tag]._jax()
                    exe.arg_dict[tag]._set_jax(
                        ring.at[:, :, 0:P, :].set(out._jax()))
        self._pos = L
        self._last_return_t = None  # new sequence: no prior decode return
        if _tm.enabled():
            _tm.counter("serving.prefill_tokens").inc(B * L)
        return logits

    # --------------------------------------------------------------- decode
    def _stage_step(self, tokens):
        """Host-side staging shared by ``decode_step``/``greedy_step``:
        validate position, write the step's inputs, note the host gap.
        Returns ``(exe, position)`` ready to dispatch."""
        self.warmup()
        p, S = self._pos, self.max_len
        if p >= self.pos_len:
            raise MXNetError(
                "kv_decode: position %d exceeds the trained position table "
                "(%d rows)" % (p, self.pos_len))
        tok = np.asarray(tokens, dtype=np.float32).reshape(self.batch, 1)
        slot = p % S
        oh = np.zeros((S,), np.float32)
        oh[slot] = 1.0
        mask = np.zeros((S,), np.float32)
        if p + 1 < S:
            mask[p + 1:] = _NEG  # slots beyond the history are empty
        exe = self._dec_exe
        exe.arg_dict["data"][:] = tok
        exe.arg_dict["pos_idx"][:] = np.full((self.batch, 1), p, np.float32)
        exe.arg_dict["slot_onehot"][:] = oh
        exe.arg_dict["kv_mask"][:] = mask
        _gap_mark(self, "serving.decode_step")
        return exe, p

    def _finish_step(self, exe):
        """Post-pull bookkeeping: ring KV write-back (device pointer
        swaps), position advance, counters."""
        for i in range(self.num_layers):
            exe.arg_dict["kv_k_%d" % i]._set_jax(
                exe.outputs[1 + 2 * i]._jax())
            exe.arg_dict["kv_v_%d" % i]._set_jax(
                exe.outputs[2 + 2 * i]._jax())
        self._pos += 1
        if _tm.enabled():
            _tm.counter("serving.decode_tokens").inc(self.batch)
            _tm.gauge("decode.tokens_per_dispatch").set(self.batch)

    def decode_step(self, tokens):
        """One token per stream through the decode executable. ``tokens``
        is (B,) or (B, 1); returns (B, vocab) logits for the NEXT
        position. The ring KV update happens in-graph; host-side this is
        arg/output pointer swaps only."""
        exe, p = self._stage_step(tokens)
        t0 = time.perf_counter()
        with _tm.span("serving.decode_step", rows=self.batch, pos=p):
            exe.forward(is_train=False)
            logits = exe.outputs[0].asnumpy()
        if _tm.enabled():
            _tm.timer("serving.decode_step").add(time.perf_counter() - t0)
        _gap_return(self)
        self._finish_step(exe)
        return logits

    def greedy_step(self, tokens):
        """One GREEDY token per stream: same dispatch as ``decode_step``
        but only the on-device ``greedy_token`` head crosses to the host —
        (B,) int64 ids, one scalar per stream, instead of the full
        (B, vocab) logits row (the first GL703 fix). Falls back to a host
        argmax when the compiled decode program has no token head."""
        if not self._token_out:
            self.warmup()
            if not self._token_out:
                # graphlint: waive GL703 -- fallback for stale token-less programs
                return np.argmax(self.decode_step(tokens), axis=-1)
        exe, p = self._stage_step(tokens)
        t0 = time.perf_counter()
        with _tm.span("serving.decode_step", rows=self.batch, pos=p,
                      greedy=True):
            exe.forward(is_train=False)
            # graphlint: waive GL701 -- single-step tail of the megastep loop; the K-amortized body is the lax.scan in decode_megastep
            nxt = exe.outputs[-1].asnumpy()
        if _tm.enabled():
            _tm.timer("serving.decode_step").add(time.perf_counter() - t0)
        _gap_return(self)
        self._finish_step(exe)
        return nxt.astype(np.int64)

    def decode_megastep(self, tokens, k=None, eos_id=None, sample=None,
                        temperature=None, top_k=None):
        """K tokens per stream in ONE dispatch: the K-step decode loop
        runs as a ``lax.scan`` INSIDE the compiled program — in-graph
        ring writes, on-device sampling (greedy argmax by default;
        ``sample='topk'`` with ``temperature``/``top_k`` draws through
        the PRNG machinery) — and only the (B, K) token ids cross back
        to the host. ``eos_id`` arms per-lane early exit: once a lane
        emits it, its later scan steps write NOTHING to the KV buffers
        (all-zero slot_onehot rows) and its remaining outputs are eos
        filler; the lockstep position still advances by K for every
        lane. Returns (B, K) int64 ids. ``tokens`` is the (B,) step
        input, exactly as for ``greedy_step``."""
        self.warmup()
        k = int(k) if k is not None else decode_megastep_k()
        if k < 1:
            raise MXNetError("decode_megastep: K must be >= 1, got %d" % k)
        p, S, B = self._pos, self.max_len, self.batch
        if p + k > self.pos_len:
            raise MXNetError(
                "decode_megastep: positions %d..%d exceed the trained "
                "position table (%d rows)" % (p, p + k - 1, self.pos_len))
        ms = _megastep_for(self, k,
                           _sampler_from(sample, temperature, top_k))
        tok0 = np.asarray(tokens, np.int32).reshape(B)
        posv = np.full((B,), p, np.int32)
        # slot plan: K consecutive ring slots, staged host-side exactly
        # like _stage_step stages one
        slots = np.tile((np.arange(p, p + k) % S).astype(np.int32), (B, 1))
        valid = np.arange(S) < min(p, S)
        base_mask = np.broadcast_to(
            np.where(valid, np.float32(0), _NEG), (B, S)) \
            .astype(np.float32).copy()
        done0 = np.zeros((B,), bool)
        eos = np.int32(-1 if eos_id is None else int(eos_id))
        _gap_mark(self, "serving.decode_megastep")
        t0 = time.perf_counter()
        with _tm.span("serving.decode_megastep", rows=B, pos=p, k=k):
            toks, acts, new_kvs, _done = ms.run(
                self, tok0, posv, slots, base_mask, done0, eos)
            ids = np.asarray(toks)       # (K, B): the only host pull
            acts_h = np.asarray(acts)
        if _tm.enabled():
            _tm.timer("serving.decode_megastep").add(
                time.perf_counter() - t0)
        _gap_return(self)
        for name, arr in zip(ms.kv_names, new_kvs):
            self._dec_exe.arg_dict[name]._set_jax(arr)
        self._pos = p + k
        if _tm.enabled():
            _tm.counter("serving.decode_tokens").inc(int(acts_h.sum()))
            _tm.counter("serving.megasteps").inc()
            _tm.gauge("decode.tokens_per_dispatch").set(ids.size)
        return ids.T.astype(np.int64)

    def greedy(self, prompt, n_tokens, k=None, eos_id=None):
        """Greedy-decode ``n_tokens`` continuations of a (B, L) prompt.
        With ``k`` > 1 (default ``MXNET_DECODE_MEGASTEP_K``) the loop
        advances K tokens per dispatch through ``decode_megastep``; the
        sub-K tail reuses the single-step program (both are warm — no
        extra compiles). K=1 reproduces the classic per-token loop call
        for call. Returns (B, n_tokens) int64 token ids."""
        k = int(k) if k is not None else decode_megastep_k()
        logits = self.prefill(prompt)
        # prompt-head argmax: once per SEQUENCE, and the prefill API hands
        # these logits to the host anyway; the per-token loop below stays
        # on device via greedy_step
        nxt = np.argmax(logits, axis=-1)  # graphlint: waive GL703 -- once per sequence, logits already host-side
        out = np.zeros((self.batch, n_tokens), np.int64)
        if n_tokens:
            out[:, 0] = nxt
        t = 1
        while t < n_tokens:
            if k > 1 and n_tokens - t >= k:
                # graphlint: waive GL702 -- K steps already folded into one lax.scan dispatch; the carried token is K-amortized
                chunk = self.decode_megastep(nxt, k=k, eos_id=eos_id)
                out[:, t:t + k] = chunk
                nxt = chunk[:, -1]
                t += k
            else:
                # graphlint: waive GL702 -- sub-K tail: fewer than K tokens left, single-step program is already warm
                nxt = self.greedy_step(nxt)
                out[:, t] = nxt
                t += 1
        return out


# --------------------------------------------------------------- paged decode
class PagedKVExhausted(MXNetError):
    """The paged KV pool cannot satisfy an allocation: no free lane for a
    new sequence, or no free page for a growing one. Retire a sequence (or
    size the pool larger) and retry — this is admission backpressure, not
    corruption."""


class _PagePool:
    """REFCOUNTED block allocator over ONE global slot axis
    (docs/SERVING.md §Prefix cache).

    The pool's ``lanes * slots`` KV slots form a single physical space
    carved into fixed-size page frames; any lane (and the prefix index)
    may reference any frame, which is what lets N concurrent sequences —
    and the cache — point at ONE physical copy of a shared prompt
    prefix. Every holder owns a reference: ``acquire`` hands out a frame
    at refcount 1, ``incref`` adds a holder, ``release`` drops one and
    returns the frame to the free list only when the LAST holder lets
    go — so eviction/retire can never free a page some other lane still
    attends (refcount > 1 just decrements).

    Frames come off a LIFO free list, and ``release`` pushes them back
    REVERSED so a retire-then-readmit (or rollback-then-regrow) replays
    the original placement order — physical placement is routinely
    non-contiguous (attention is slot-order-agnostic) but DETERMINISTIC,
    which the bitwise cached-admit parity gate leans on. A ``budget``
    below the physical frame count models admission control against a
    smaller HBM reservation: a shared frame counts ONCE no matter how
    many holders it has."""

    def __init__(self, lanes, slots, page_size, budget=None):
        if slots % page_size:
            raise MXNetError("paged_kv: page_size %d must divide the %d "
                             "slots per lane" % (page_size, slots))
        self.lanes = int(lanes)
        self.page_size = int(page_size)
        self.frames_per_lane = slots // page_size
        self.total_frames = self.lanes * self.frames_per_lane
        self.budget = int(budget) if budget else self.total_frames
        # LIFO: pop() serves the highest-numbered frame first; release()
        # re-stacks reversed so re-acquisition replays acquisition order
        self._free = list(range(self.total_frames))
        self._ref: Dict[int, int] = {}  # frame -> holder count

    @property
    def in_use(self):
        """Frames with at least one holder (each counts once — sharing
        is free under the budget)."""
        return len(self._ref)

    def can_acquire(self, n=1):
        return len(self._free) >= n and self.in_use + n <= self.budget

    def acquire(self):
        """One free frame at refcount 1, or raise ``PagedKVExhausted``."""
        if self.in_use >= self.budget:
            raise PagedKVExhausted(
                "paged_kv: page budget exhausted (%d/%d frames in use); "
                "retire a sequence and retry" % (self.in_use, self.budget))
        if not self._free:
            raise PagedKVExhausted(
                "paged_kv: no free page frame (%d frames all referenced) "
                "— retire a sequence or evict cached prefixes and retry"
                % self.total_frames)
        f = self._free.pop()
        self._ref[f] = 1
        return f

    def incref(self, frame):
        """Add a holder to an allocated frame (page sharing)."""
        self._ref[frame] += 1

    def refcount(self, frame):
        return self._ref.get(frame, 0)

    def release(self, frames):
        """Drop ONE reference per listed frame; frames whose last holder
        left go back on the free list (reversed — see class docstring)."""
        freed = []
        for f in frames:
            n = self._ref[f] - 1
            if n:
                self._ref[f] = n
            else:
                del self._ref[f]
                freed.append(f)
        self._free.extend(reversed(freed))


class _Lane:
    __slots__ = ("seq_id", "pos", "frames")

    def __init__(self, seq_id):
        self.seq_id = seq_id
        self.pos = 0            # next position to be written
        self.frames = []        # logical page -> physical frame index


class PagedKVDecoder:
    """Multiplexed KV-cache decode: ONE decode batch serves many
    concurrent, independently-positioned sequences (docs/SERVING.md).

    ``KVCacheDecoder`` is per-request-shaped — all B streams march in
    lockstep from one prefill. This decoder instead treats the decode
    executable's batch rows as ``lanes``: sequences are admitted one at a
    time (a batch-1 prefill seeds that lane's slots), advance at their own
    positions, and retire independently — the continuous-batching idea
    applied to autoregressive decode. Slot storage is paged: each lane's
    ring is carved into ``page_size``-slot frames allocated on demand from
    a ``_PagePool`` (and freed at retire), so short sequences don't
    reserve ``max_len`` slots of KV for their whole life and admission
    fails with a structured ``PagedKVExhausted`` instead of an OOM.

    Per-lane math is identical to a batch-1 ``KVCacheDecoder`` at the same
    position (the per-stream decode graph differs only in carrying one
    slot_onehot/kv_mask row per lane), so multiplexed decode is
    token-identical to sequential per-request decode — the acceptance
    test pins exactly that.

    KV storage is ONE global slot pool (``get_decode_symbol``
    ``global_slots=True``): per layer the buffers are
    (H, lanes·max_len, dh) and every lane's onehot/mask row indexes the
    shared axis, so a page frame is just a slot range ANY lane can
    reference. That is the substrate for cross-request prefix reuse
    (serving/prefix_cache.py): with ``prefix_cache=True`` (or
    ``MXNET_SERVE_PREFIX_CACHE=1``) admit hashes the prompt in
    ``prefix_chunk``-token chunks, adopts the cached pages of the longest
    matched chunk chain at a refcount (no copy, no recompute), and
    chunk-prefills ONLY the unmatched tail through the rectangular chunk
    program. A lane's first write into a page some other holder still
    references triggers a copy-on-write private copy (``fork`` shares
    all pages this way). ``rollback`` truncates a sequence by releasing
    whole rejected pages — the speculative-decoding accept/reject
    primitive (serving/speculative.py).
    """

    def __init__(self, arg_params: Dict[str, object], vocab_size,
                 num_layers=2, num_heads=2, model_dim=32, ffn_dim=64,
                 max_len=64, page_size=8, lanes=4, page_budget=None,
                 prefill_len: Optional[int] = None,
                 pos_len: Optional[int] = None, prefix_cache=None,
                 prefix_chunk=None, ctx=None,
                 dtype="float32", cache_dir=None, model_key=None,
                 sample_seed=None):
        from ..models import transformer as _tf

        self.vocab_size = int(vocab_size)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.model_dim = int(model_dim)
        self.ffn_dim = int(ffn_dim)
        self.max_len = int(max_len)
        self.lanes = int(lanes)
        self.prefill_len = int(prefill_len or max_len)
        self.pos_len = int(pos_len or max_len)
        self.dh = self.model_dim // self.num_heads
        if self.prefill_len > self.max_len:
            raise MXNetError("paged_kv: prefill_len %d > max_len %d"
                             % (self.prefill_len, self.max_len))
        self.pool = _PagePool(self.lanes, self.max_len, page_size,
                              budget=page_budget)
        self.page_size = self.pool.page_size
        self.total_slots = self.lanes * self.max_len
        self._global_slots = True
        if prefix_cache is None:
            prefix_cache = os.environ.get(
                "MXNET_SERVE_PREFIX_CACHE", "").strip().lower() \
                in ("1", "on", "true", "yes")
        if prefix_cache:
            from .prefix_cache import PrefixCache

            if prefix_chunk is None:
                raw = os.environ.get("MXNET_SERVE_PREFIX_CHUNK",
                                     "").strip()
                prefix_chunk = int(raw) if raw else self.page_size
            self.prefix_chunk = int(prefix_chunk)
            self._prefix = PrefixCache(self.pool, self.prefix_chunk)
        else:
            self.prefix_chunk = None
            self._prefix = None
        self._prefix_hits = 0
        self._prefix_misses = 0
        cfg = dict(vocab_size=self.vocab_size, num_layers=self.num_layers,
                   num_heads=self.num_heads, model_dim=self.model_dim,
                   ffn_dim=int(ffn_dim), pos_len=self.pos_len)
        # NOTE the default key differs from the pre-global-pool layout on
        # purpose: the decode graph's KV shapes changed, and a stale
        # on-disk cache under the old key must not satisfy this one
        key = model_key or "transformer_paged_global_decode"
        self._pf_cache = PersistentExecutableCache(
            _tf.get_prefill_symbol(prefill_len=self.prefill_len, **cfg),
            arg_params, {}, ctx=ctx, dtype=dtype, cache_dir=cache_dir,
            model_key=key + "-prefill")
        self._dec_cache = PersistentExecutableCache(
            _tf.get_decode_symbol(max_len=self.total_slots,
                                  per_stream_slots=True,
                                  global_slots=True, **cfg),
            arg_params, {}, ctx=ctx, dtype=dtype, cache_dir=cache_dir,
            model_key=key + "-decode")
        self._dec_exe = None
        self._lanes: Dict[int, _Lane] = {}   # lane index -> _Lane
        self._seq_lane: Dict[int, int] = {}  # seq_id -> lane index
        self._next_seq = 0
        self._warm = False
        self._last_return_t = None  # dispatch.host_gap interval start
        self._megasteps = {}        # (K, sampler) -> _DecodeMegastep
        self._chunks = {}           # T -> _ChunkProgram
        self._sample_seed = sample_seed
        self._sample_key = None

    # ------------------------------------------------------------ lifecycle
    def _decode_shapes(self):
        B, S, H, dh = self.lanes, self.total_slots, self.num_heads, self.dh
        shapes = {"data": (B, 1), "pos_idx": (B, 1),
                  "slot_onehot": (B, S), "kv_mask": (B, S)}
        for i in range(self.num_layers):
            shapes["kv_k_%d" % i] = (H, S, dh)
            shapes["kv_v_%d" % i] = (H, S, dh)
        return shapes

    def warmup(self):
        """Compile the multiplexed decode executable plus the admit-side
        program — the batch-1 prefill bucket classically, the C-token
        chunk program when the prefix cache is on (chunked admit never
        touches the prefill bucket: cold and cached admits must replay
        the SAME program for the bitwise parity gate to hold)."""
        if self._warm:
            return self
        self._dec_cache.warmup([self._decode_shapes()])
        self._dec_exe = self._dec_cache.executable(self._decode_shapes())
        self._warm = True
        if self._prefix is None:
            self._pf_cache.warmup([{"data": (1, self.prefill_len)}])
        else:
            self._chunk_for(self.prefix_chunk)
        return self

    def stats(self):
        out = {"lanes": self.lanes,
               "active": len(self._lanes),
               "pages_in_use": self.pool.in_use,
               "page_budget": self.pool.budget,
               "page_size": self.page_size}
        if self._prefix is not None:
            out["prefix_cache"] = self._prefix.stats()
            tot = self._prefix_hits + self._prefix_misses
            out["prefix_hit_rate"] = \
                (self._prefix_hits / tot) if tot else 0.0
        return out

    # ------------------------------------------------------------ admission
    def _acquire_frame(self):
        """One page frame from the pool, evicting cached prefixes (LRU,
        leaf-first) to make room before giving up."""
        try:
            return self.pool.acquire()
        except PagedKVExhausted:
            if self._prefix is not None and self._prefix.evict_for(1):
                return self.pool.acquire()
            raise

    def _cow_page(self, lane: _Lane, page):
        """Copy-on-write: give ``lane`` a private copy of logical page
        ``page`` when some other holder (another lane, or the prefix
        index) still references its frame. Device-side slot-range copy in
        every layer's K/V buffer; the shared frame just loses one ref."""
        frame = lane.frames[page]
        if self.pool.refcount(frame) <= 1:
            return frame
        fresh = self._acquire_frame()
        P = self.page_size
        src = frame * P + np.arange(P)
        dst = fresh * P + np.arange(P)
        exe = self._dec_exe
        for i in range(self.num_layers):
            for tag in ("kv_k_%d" % i, "kv_v_%d" % i):
                ring = exe.arg_dict[tag]._jax()
                exe.arg_dict[tag]._set_jax(
                    ring.at[:, dst, :].set(ring[:, src, :]))
        self.pool.release([frame])
        lane.frames[page] = fresh
        if _tm.enabled():
            _tm.counter("serving.cow_copies").inc()
        return fresh

    def _phys_slot(self, lane: _Lane, pos):
        """Physical slot of logical position ``pos`` FOR WRITING: acquires
        a new page frame when the position crosses into an unallocated
        page, and resolves copy-on-write when the page it lands in is
        still shared (the caller is about to write into it)."""
        if pos >= self.max_len:
            raise MXNetError(
                "paged_kv: position %d exceeds the per-sequence slot "
                "quota (max_len %d)" % (pos, self.max_len))
        page, off = divmod(pos, self.page_size)
        while len(lane.frames) <= page:
            lane.frames.append(self._acquire_frame())
        frame = self._cow_page(lane, page)
        return frame * self.page_size + off

    def _lane_slots(self, lane: _Lane, upto=None):
        """Physical slots of positions 0..n-1 (n = ``lane.pos`` unless
        ``upto`` given) — derived from the frame table, never stored:
        positions are always contiguous, so the slot list IS the page
        map."""
        n = lane.pos if upto is None else int(upto)
        if n <= 0:
            return np.zeros((0,), np.int64)
        P = self.page_size
        pages = np.asarray(lane.frames[:(n + P - 1) // P], np.int64)
        slots = pages[:, None] * P + np.arange(P, dtype=np.int64)[None, :]
        return slots.reshape(-1)[:n]

    def admit(self, prompt):
        """Admit one sequence. ``prompt`` is a (L,) or (1, L) token
        array, 0 < L <= prefill_len. Returns ``(seq_id, logits)`` with
        logits the (vocab,) distribution for the sequence's next token.
        Raises ``PagedKVExhausted`` when no lane or not enough page
        frames are free.

        Without the prefix cache a batch-1 prefill seeds the lane's
        pages (classic path). With it, admit is CHUNKED: the prompt's
        chunk-hash chain is matched against the prefix index, matched
        chunks are adopted at a refcount (zero recompute, zero copy) and
        only the unmatched tail runs through the C-token chunk program —
        cold and cached admits replay the same program over the same
        physical slots, so their logits are bitwise identical."""
        self.warmup()
        prompt = np.asarray(prompt, dtype=np.float32).reshape(1, -1)
        L = prompt.shape[1]
        if not 0 < L <= self.prefill_len:
            raise MXNetError("paged_kv: prompt length %d not in (0, %d]"
                             % (L, self.prefill_len))
        free_lanes = [i for i in range(self.lanes) if i not in self._lanes]
        if not free_lanes:
            raise PagedKVExhausted(
                "paged_kv: all %d lanes occupied; retire a sequence first"
                % self.lanes)
        idx = free_lanes[0]
        seq_id = self._next_seq
        self._next_seq += 1
        lane = _Lane(seq_id)
        self._lanes[idx] = lane
        self._seq_lane[seq_id] = idx
        try:
            if self._prefix is not None:
                logits = self._admit_chunked(prompt, lane)
            else:
                logits = self._admit_prefill(prompt, lane, idx)
        except BaseException:
            # ANY admit failure (pool exhaustion, a prefill/scatter
            # error) must release the lane and its frames — the caller
            # has no seq_id to retire, so a leak here would bleed the
            # pool dry one failed admit at a time
            self._evict(idx)
            raise
        lane.pos = L
        self._last_return_t = None  # admit breaks the steady decode chain
        if _tm.enabled():
            _tm.counter("serving.paged_admits").inc()
            _tm.counter("serving.prefill_tokens").inc(L)
            _tm.gauge("serving.paged_pages_in_use").set(self.pool.in_use)
        return seq_id, logits

    def _admit_prefill(self, prompt, lane, idx):
        """Classic admit: one batch-1 prefill dispatch, device-side
        scatter of the prompt's K/V into the lane's physical slots."""
        L = prompt.shape[1]
        phys = [self._phys_slot(lane, p) for p in range(L)]
        padded = np.zeros((1, self.prefill_len), np.float32)
        padded[:, :L] = prompt
        with _tm.span("serving.paged_admit", seq=lane.seq_id,
                      prompt_len=L, lane=idx):
            pf = self._pf_cache.executable(
                {"data": (1, self.prefill_len)})
            pf.arg_dict["data"][:] = padded
            pf.forward(is_train=False)
            logits = np.asarray(
                pf.outputs[0]._jax().reshape(
                    1, self.prefill_len, self.vocab_size)[0, L - 1, :])
            # scatter the prompt's K/V into THIS lane's physical
            # slots — device-side; only the last position's logits
            # crossed above
            phys_idx = np.asarray(phys)
            exe = self._dec_exe
            for i in range(self.num_layers):
                for tag, out in (("kv_k_%d" % i,
                                  pf.outputs[1 + 2 * i]),
                                 ("kv_v_%d" % i,
                                  pf.outputs[2 + 2 * i])):
                    ring = exe.arg_dict[tag]._jax()
                    exe.arg_dict[tag]._set_jax(
                        ring.at[:, phys_idx, :].set(
                            out._jax()[0, :, :L, :]))
        return logits

    def _chunk_for(self, t):
        """The sealed T-token chunk program, compiled on first use."""
        prog = self._chunks.get(t)
        if prog is None:
            prog = _ChunkProgram(self, t)
            prog.warm(self)
            self._chunks[t] = prog
        return prog

    def _run_chunk(self, lane: _Lane, tokens, base, write, prog=None):
        """Dispatch ``tokens`` (length <= T) of ``lane`` at positions
        ``base..base+len-1`` through the chunk program, writing K/V when
        ``write`` (rows past ``len`` are pad: zero write-onehot, fully
        masked — they soak up a uniform softmax and touch nothing).
        Returns host logits rows (len, vocab)."""
        prog = prog or self._chunk_for(self.prefix_chunk)
        T, S = prog.t, self.total_slots
        n = len(tokens)
        data = np.zeros((1, T), np.float32)
        pos_idx = np.zeros((1, T), np.float32)
        w_oh = np.zeros((T, S), np.float32)
        mask = np.full((T, S), _NEG, np.float32)
        data[0, :n] = tokens
        pos_idx[0, :n] = np.arange(base, base + n)
        if write:
            phys = [self._phys_slot(lane, base + j) for j in range(n)]
        else:
            phys = self._lane_slots(lane, base + n)[base:]
        seen = self._lane_slots(lane, base)
        for j in range(n):
            if write:
                w_oh[j, phys[j]] = 1.0
            mask[j, seen] = 0.0
            mask[j, phys[: j + 1]] = 0.0
        _gap_mark(self, "serving.chunk_prefill")
        with _tm.span("serving.chunk_prefill", t=T, rows=n,
                      write=bool(write)):
            logits, new_kvs, _tok = prog.run(self, data, pos_idx, w_oh,
                                             mask)
            out = np.asarray(logits)[:n]
        _gap_return(self)
        if write:
            for name, arr in zip(prog.kv_names, new_kvs):
                self._dec_exe.arg_dict[name]._set_jax(arr)
        return out

    def _admit_chunked(self, prompt, lane):
        """Prefix-cache admit: match the prompt's chunk-hash chain,
        adopt matched pages at a refcount, chunk-prefill only the tail.
        A fully-matched prompt replays its last chunk with a ZERO
        write-onehot — ``kv*1 + new*0`` leaves every buffer bitwise
        untouched while producing the exact logits a cold admit did."""
        C = self.prefix_chunk
        toks = np.asarray(prompt, np.int64).reshape(-1)
        L = toks.shape[0]
        n_full = L // C
        hashes = self._prefix.chain_hashes(toks[:n_full * C])
        matched, frames = self._prefix.match(hashes)
        for f in frames:
            self.pool.incref(f)
        lane.frames = list(frames)
        if _tm.enabled() and frames:
            _tm.counter("serving.pages_shared").inc(len(frames))
        if matched:
            self._prefix_hits += 1
            if _tm.enabled():
                _tm.counter("serving.prefix_hits").inc(matched)
                _tm.counter("serving.prefill_tokens_saved").inc(
                    matched * C)
        else:
            self._prefix_misses += 1
        if _tm.enabled():
            _tm.counter("serving.prefix_misses").inc(n_full - matched)
        logits = None
        with _tm.span("serving.paged_admit", seq=lane.seq_id,
                      prompt_len=L, cached_tokens=matched * C):
            for c in range(matched, n_full):
                base = c * C
                rows = self._run_chunk(lane, toks[base:base + C], base,
                                       write=True)
                logits = rows[-1]
                # whole chunks become cache currency the moment they
                # are computed — the index increfs the frames itself
                self._prefix.insert(
                    hashes[c],
                    lane.frames[base // self.page_size:
                                (base + C) // self.page_size],
                    parent=hashes[c - 1] if c else None)
            tail = L - n_full * C
            if tail:
                rows = self._run_chunk(lane, toks[L - tail:], L - tail,
                                       write=True)
                logits = rows[-1]
            elif logits is None:
                # full match: zero-write replay of the last chunk
                base = (n_full - 1) * C
                rows = self._run_chunk(lane, toks[base:base + C], base,
                                       write=False)
                logits = rows[-1]
        if _tm.enabled():
            tot = self._prefix_hits + self._prefix_misses
            _tm.gauge("serving.prefix_hit_rate").set(
                self._prefix_hits / tot if tot else 0.0)
        return logits

    def _evict(self, idx):
        lane = self._lanes.pop(idx)
        self._seq_lane.pop(lane.seq_id, None)
        self.pool.release(lane.frames)

    def retire(self, seq_id):
        """Free a finished sequence's lane and page frames (the slots are
        masked out for every other lane already; no zeroing needed)."""
        idx = self._seq_lane.get(seq_id)
        if idx is None:
            raise MXNetError("paged_kv: unknown seq_id %r" % (seq_id,))
        self._evict(idx)
        if _tm.enabled():
            _tm.counter("serving.paged_retires").inc()
            _tm.gauge("serving.paged_pages_in_use").set(self.pool.in_use)

    @property
    def active(self):
        return sorted(self._seq_lane)

    def position(self, seq_id):
        return self._lanes[self._seq_lane[seq_id]].pos

    # ----------------------------------------------------- fork / rollback
    def fork(self, seq_id):
        """Clone a sequence into a free lane by SHARING every page frame
        at a refcount — zero copy, zero recompute (the parallel-sampling
        idiom). Either side's next write into a shared page triggers its
        private copy-on-write. Returns the clone's seq_id."""
        idx = self._seq_lane.get(seq_id)
        if idx is None:
            raise MXNetError("paged_kv: unknown seq_id %r" % (seq_id,))
        src = self._lanes[idx]
        free_lanes = [i for i in range(self.lanes) if i not in self._lanes]
        if not free_lanes:
            raise PagedKVExhausted(
                "paged_kv: all %d lanes occupied; retire a sequence first"
                % self.lanes)
        new_idx = free_lanes[0]
        new_id = self._next_seq
        self._next_seq += 1
        lane = _Lane(new_id)
        lane.pos = src.pos
        lane.frames = list(src.frames)
        for f in lane.frames:
            self.pool.incref(f)
        self._lanes[new_idx] = lane
        self._seq_lane[new_id] = new_idx
        if _tm.enabled():
            _tm.counter("serving.pages_shared").inc(len(lane.frames))
            _tm.gauge("serving.paged_pages_in_use").set(self.pool.in_use)
        return new_id

    def rollback(self, seq_id, pos):
        """Truncate a sequence back to ``pos`` written positions: whole
        pages past the boundary are RELEASED (decref — a frame another
        holder shares just loses this lane's ref), the partial boundary
        page is kept with its stale tail slots simply excluded from the
        derived valid-slot set. No copy, no device work — this is the
        speculative-decoding reject primitive."""
        idx = self._seq_lane.get(seq_id)
        if idx is None:
            raise MXNetError("paged_kv: unknown seq_id %r" % (seq_id,))
        lane = self._lanes[idx]
        pos = int(pos)
        if not 0 <= pos <= lane.pos:
            raise MXNetError(
                "paged_kv: rollback target %d outside [0, %d]"
                % (pos, lane.pos))
        keep = (pos + self.page_size - 1) // self.page_size
        dropped = lane.frames[keep:]
        del lane.frames[keep:]
        self.pool.release(dropped)
        lane.pos = pos
        if _tm.enabled():
            _tm.counter("spec.rollbacks").inc()
            _tm.gauge("serving.paged_pages_in_use").set(self.pool.in_use)

    def verify_chunk(self, seq_id, tokens):
        """Score ``tokens`` (length T) at the sequence's next T positions
        in ONE rectangular dispatch, writing their K/V (row j attends to
        everything before it plus rows 0..j — exactly T successive
        ``step`` calls fused). Advances the position by T; the caller
        accepts a prefix and ``rollback``s the rest. Returns (T, vocab)
        logits. This is the speculative-decoding verify pass."""
        self.warmup()
        idx = self._seq_lane.get(seq_id)
        if idx is None:
            raise MXNetError("paged_kv: unknown seq_id %r" % (seq_id,))
        lane = self._lanes[idx]
        toks = np.asarray(tokens, np.int64).reshape(-1)
        t = toks.shape[0]
        if t < 1:
            raise MXNetError("verify_chunk: need at least one token")
        if lane.pos + t > self.pos_len:
            raise MXNetError(
                "paged_kv: seq %d verify positions %d..%d exceed the "
                "trained position table (%d rows)"
                % (seq_id, lane.pos, lane.pos + t - 1, self.pos_len))
        prog = self._chunk_for(t)
        rows = self._run_chunk(lane, toks, lane.pos, write=True,
                               prog=prog)
        lane.pos += t
        if _tm.enabled():
            _tm.gauge("serving.paged_pages_in_use").set(self.pool.in_use)
        return rows

    # --------------------------------------------------------------- decode
    def step(self, tokens: Dict[int, object]):
        """One multiplexed decode dispatch: ``tokens`` maps seq_id -> next
        token id for any subset of active sequences; every stepped
        sequence advances at ITS OWN position in the one batch. Returns
        {seq_id: (vocab,) logits}. Lanes not stepped (or unoccupied) ride
        along with an all-zero write-onehot — their KV is untouched and
        their logits discarded."""
        self.warmup()
        if not tokens:
            return {}
        B, S = self.lanes, self.total_slots
        data = np.zeros((B, 1), np.float32)
        pos_idx = np.zeros((B, 1), np.float32)
        oh = np.zeros((B, S), np.float32)
        mask = np.full((B, S), _NEG, np.float32)
        stepped = []
        for seq_id, tok in tokens.items():
            idx = self._seq_lane.get(seq_id)
            if idx is None:
                raise MXNetError("paged_kv: unknown seq_id %r" % (seq_id,))
            lane = self._lanes[idx]
            if lane.pos >= self.pos_len:
                raise MXNetError(
                    "paged_kv: seq %d at position %d exceeds the trained "
                    "position table (%d rows)"
                    % (seq_id, lane.pos, self.pos_len))
            phys = self._phys_slot(lane, lane.pos)
            data[idx, 0] = float(np.asarray(tok).reshape(()))
            pos_idx[idx, 0] = lane.pos
            oh[idx, phys] = 1.0
            mask[idx, self._lane_slots(lane)] = 0.0
            mask[idx, phys] = 0.0
            stepped.append((seq_id, idx, lane, phys))
        exe = self._dec_exe
        exe.arg_dict["data"][:] = data
        exe.arg_dict["pos_idx"][:] = pos_idx
        exe.arg_dict["slot_onehot"][:] = oh
        exe.arg_dict["kv_mask"][:] = mask
        _gap_mark(self, "serving.paged_step")
        with _tm.span("serving.decode_step", rows=len(stepped),
                      paged=True):
            exe.forward(is_train=False)
            # graphlint: waive GL701 -- single-step tail of the megastep loop; the K-amortized body is the lax.scan in step_megastep
            logits = exe.outputs[0].asnumpy()
        _gap_return(self)
        for i in range(self.num_layers):
            exe.arg_dict["kv_k_%d" % i]._set_jax(
                exe.outputs[1 + 2 * i]._jax())
            exe.arg_dict["kv_v_%d" % i]._set_jax(
                exe.outputs[2 + 2 * i]._jax())
        out = {}
        for seq_id, idx, lane, phys in stepped:
            lane.pos += 1
            out[seq_id] = logits[idx]
        if _tm.enabled():
            _tm.counter("serving.decode_tokens").inc(len(stepped))
            _tm.counter("serving.paged_steps").inc()
            _tm.gauge("decode.tokens_per_dispatch").set(len(stepped))
            _tm.gauge("serving.paged_pages_in_use").set(self.pool.in_use)
        return out

    def step_megastep(self, tokens: Dict[int, object], k=None, eos_id=None,
                      sample=None, temperature=None, top_k=None):
        """K multiplexed decode steps in ONE dispatch: every stepped
        sequence advances K positions at ITS OWN offsets through the
        ``lax.scan`` megastep, sampling on device (greedy argmax default,
        temperature/top-k via ``sample='topk'``). Page frames for ALL K
        positions are acquired UP FRONT, so pool exhaustion
        (``PagedKVExhausted``) surfaces BEFORE any device work — megastep
        backpressure is admission backpressure: already-acquired frames
        stay with their lanes (a retry after ``retire`` reuses them) and
        the KV state is untouched. Unstepped lanes ride along idle
        (all-zero onehot rows); with ``eos_id`` a lane that emits eos
        mid-megastep writes nothing for its remaining steps and only its
        pre-eos slots become valid. Returns {seq_id: (K,) int64 ids}."""
        self.warmup()
        k = int(k) if k is not None else decode_megastep_k()
        if k < 1:
            raise MXNetError("step_megastep: K must be >= 1, got %d" % k)
        if not tokens:
            return {}
        B, S = self.lanes, self.total_slots
        stepped = []
        for seq_id, tok in tokens.items():
            idx = self._seq_lane.get(seq_id)
            if idx is None:
                raise MXNetError("paged_kv: unknown seq_id %r" % (seq_id,))
            lane = self._lanes[idx]
            if lane.pos + k > self.pos_len:
                raise MXNetError(
                    "paged_kv: seq %d megastep positions %d..%d exceed the "
                    "trained position table (%d rows)"
                    % (seq_id, lane.pos, lane.pos + k - 1, self.pos_len))
            stepped.append((seq_id, idx, lane, tok))
        phys = {}
        for seq_id, idx, lane, tok in stepped:
            phys[seq_id] = [self._phys_slot(lane, lane.pos + i)
                            for i in range(k)]
        ms = _megastep_for(self, k,
                           _sampler_from(sample, temperature, top_k))
        tok0 = np.zeros((B,), np.int32)
        posv = np.zeros((B,), np.int32)
        slots = np.zeros((B, k), np.int32)
        base_mask = np.full((B, S), _NEG, np.float32)
        done0 = np.ones((B,), bool)  # idle unless stepped
        for seq_id, idx, lane, tok in stepped:
            tok0[idx] = int(np.asarray(tok).reshape(()))
            posv[idx] = lane.pos
            slots[idx] = phys[seq_id]
            base_mask[idx, self._lane_slots(lane)] = 0.0
            done0[idx] = False
        eos = np.int32(-1 if eos_id is None else int(eos_id))
        _gap_mark(self, "serving.paged_megastep")
        with _tm.span("serving.decode_megastep", rows=len(stepped),
                      paged=True, k=k):
            toks, acts, new_kvs, _done = ms.run(
                self, tok0, posv, slots, base_mask, done0, eos)
            ids = np.asarray(toks)       # (K, B): the only host pull
            acts_h = np.asarray(acts)
        _gap_return(self)
        for name, arr in zip(ms.kv_names, new_kvs):
            self._dec_exe.arg_dict[name]._set_jax(arr)
        out = {}
        written = 0
        for seq_id, idx, lane, tok in stepped:
            # active steps form a prefix (done latches): exactly the
            # steps whose KV write landed — only THOSE positions advance
            n_w = int(acts_h[:, idx].sum())
            lane.pos += n_w
            written += n_w
            out[seq_id] = ids[:, idx].astype(np.int64)
        if _tm.enabled():
            _tm.counter("serving.decode_tokens").inc(written)
            _tm.counter("serving.megasteps").inc()
            _tm.gauge("decode.tokens_per_dispatch").set(k * len(stepped))
            _tm.gauge("serving.paged_pages_in_use").set(self.pool.in_use)
        return out

    def greedy(self, prompts, n_tokens, k=None):
        """Greedy-decode ``n_tokens`` continuations for several prompts AT
        ONCE through the multiplexed batch (admitted together, stepped
        together). With ``k`` > 1 (default ``MXNET_DECODE_MEGASTEP_K``)
        the loop advances K tokens per dispatch via ``step_megastep``;
        K=1 reproduces the classic one-dispatch-per-token loop call for
        call. ``prompts`` is a list of (L_i,) token arrays (lengths may
        differ). Returns a list of (n_tokens,) int64 arrays. Convenience
        for tests/bench."""
        k = int(k) if k is not None else decode_megastep_k()
        seqs = []
        logits = {}
        try:
            for p in prompts:
                sid, lg = self.admit(p)
                seqs.append(sid)
                logits[sid] = lg
            out = {sid: np.zeros((n_tokens,), np.int64) for sid in seqs}
            nxt = {sid: int(np.argmax(logits[sid])) for sid in seqs}
            for sid in seqs:
                if n_tokens:
                    out[sid][0] = nxt[sid]
            t = 1
            while t < n_tokens:
                if k > 1 and n_tokens - t >= k:
                    # graphlint: waive GL702 -- K steps already folded into one lax.scan dispatch; the carried token is K-amortized
                    chunk = self.step_megastep(nxt, k=k)
                    for sid in seqs:
                        out[sid][t:t + k] = chunk[sid]
                        nxt[sid] = int(chunk[sid][-1])
                    t += k
                else:
                    # graphlint: waive GL702 -- sub-K tail: fewer than K tokens left, single-step program is already warm
                    lg = self.step(nxt)
                    # graphlint: waive GL703 -- sub-K tail host argmax, one id per lane on already-pulled logits
                    nxt = {sid: int(np.argmax(lg[sid])) for sid in seqs}
                    for sid in seqs:
                        out[sid][t] = nxt[sid]
                    t += 1
            return [out[sid] for sid in seqs]
        finally:
            # retire on EVERY exit: a partial admit/step failure must not
            # strand the already-admitted lanes (the caller has no
            # seq_ids to clean up)
            for sid in seqs:
                if sid in self._seq_lane:
                    self.retire(sid)
