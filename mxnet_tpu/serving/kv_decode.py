"""KV-cache incremental decode for the transformer LM (docs/SERVING.md).

Autoregressive serving without per-step recompilation: ONE prefill
executable (prompt bucket, exports every layer's K/V) plus ONE
single-token decode executable over a preallocated ring KV buffer of
``max_len`` slots per layer. Both come from a sealed
``PersistentExecutableCache``, so after warmup a greedy decode of any
length replays exactly two XLA programs — the full-sequence re-forward it
replaces costs O(T) work per token and a recompile per prompt length.

Ring layout: position ``p`` writes slot ``p % max_len``; the write happens
IN-GRAPH (``slot_onehot`` blend, models/transformer.py
``get_decode_symbol``), and the updated buffers are program outputs the
decoder swaps back in as the next step's inputs — a device-side pointer
swap, no copy, no host round-trip. Attention over slots is
order-agnostic (position information lives in the embeddings), so ring
wraparound needs no rotation: once ``p >= max_len`` every slot is valid
and the oldest token is simply the one overwritten.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..base import MXNetError
from .. import telemetry as _tm
from .cache import PersistentExecutableCache

__all__ = ["KVCacheDecoder"]

_NEG = np.float32(-1e9)


class KVCacheDecoder:
    """Batched greedy/streaming decode over the serving transformer.

    ``arg_params`` is the trained {name: array} dict of
    ``models/transformer.get_symbol`` (embed/pos/layerN_*/final_ln/lm_head
    weights — the serving graphs share those names exactly).
    """

    def __init__(self, arg_params: Dict[str, object], vocab_size,
                 num_layers=2, num_heads=2, model_dim=32, ffn_dim=64,
                 max_len=64, prefill_len: Optional[int] = None,
                 pos_len: Optional[int] = None, batch=1, ctx=None,
                 dtype="float32", cache_dir=None, model_key=None):
        from ..models import transformer as _tf

        self.vocab_size = int(vocab_size)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.model_dim = int(model_dim)
        self.max_len = int(max_len)
        self.prefill_len = int(prefill_len or max_len)
        self.pos_len = int(pos_len or max_len)
        self.batch = int(batch)
        self.dh = self.model_dim // self.num_heads
        if self.prefill_len > self.max_len:
            raise MXNetError("kv_decode: prefill_len %d > max_len %d"
                             % (self.prefill_len, self.max_len))
        cfg = dict(vocab_size=self.vocab_size, num_layers=self.num_layers,
                   num_heads=self.num_heads, model_dim=self.model_dim,
                   ffn_dim=int(ffn_dim), pos_len=self.pos_len)
        key = model_key or "transformer_decode"
        self._pf_cache = PersistentExecutableCache(
            _tf.get_prefill_symbol(prefill_len=self.prefill_len, **cfg),
            arg_params, {}, ctx=ctx, dtype=dtype, cache_dir=cache_dir,
            model_key=key + "-prefill")
        self._dec_cache = PersistentExecutableCache(
            _tf.get_decode_symbol(max_len=self.max_len, **cfg),
            arg_params, {}, ctx=ctx, dtype=dtype, cache_dir=cache_dir,
            model_key=key + "-decode")
        self._dec_exe = None
        self._pos = 0
        self._warm = False

    # ------------------------------------------------------------ lifecycle
    def _decode_shapes(self):
        B, S, H, dh = self.batch, self.max_len, self.num_heads, self.dh
        shapes = {"data": (B, 1), "pos_idx": (B, 1), "slot_onehot": (S,),
                  "kv_mask": (S,)}
        for i in range(self.num_layers):
            shapes["kv_k_%d" % i] = (B, H, S, dh)
            shapes["kv_v_%d" % i] = (B, H, S, dh)
        return shapes

    def warmup(self):
        """Compile the prefill and decode executables; seal both caches —
        any later shape drift is a hard retrace error, not a recompile."""
        if self._warm:
            return self
        self._pf_cache.warmup([{"data": (self.batch, self.prefill_len)}])
        self._dec_cache.warmup([self._decode_shapes()])
        self._dec_exe = self._dec_cache.executable(self._decode_shapes())
        self._warm = True
        return self

    def reset(self):
        """Forget all context (the KV slots are masked out, not zeroed —
        the mask is the source of truth for validity)."""
        self._pos = 0

    @property
    def position(self):
        return self._pos

    # -------------------------------------------------------------- prefill
    def prefill(self, tokens):
        """Consume a (B, L<=prefill_len) prompt in one executable call:
        seeds the ring KV buffer with positions 0..L-1 and returns the
        (B, vocab) logits at position L-1 (the first generation step's
        distribution)."""
        self.warmup()
        tokens = np.asarray(tokens, dtype=np.float32)
        if tokens.ndim == 1:
            tokens = tokens[None]
        B, L = tokens.shape
        if B != self.batch:
            raise MXNetError("kv_decode: prefill batch %d != engine batch %d"
                             % (B, self.batch))
        if not 0 < L <= self.prefill_len:
            raise MXNetError("kv_decode: prompt length %d not in "
                             "(0, %d]" % (L, self.prefill_len))
        P = self.prefill_len
        padded = np.zeros((B, P), np.float32)
        padded[:, :L] = tokens
        with _tm.span("serving.prefill", rows=B, prompt_len=L):
            pf = self._pf_cache.executable({"data": (B, P)})
            pf.arg_dict["data"][:] = padded
            pf.forward(is_train=False)
            # only the last real position's logits cross to the host
            logits = np.asarray(
                pf.outputs[0]._jax().reshape(
                    B, P, self.vocab_size)[:, L - 1, :])
        # seed the decode ring: slots 0..P-1 <- prefill K/V, entirely
        # device-side — pointer swap when the ring is exactly the prefill
        # window, a device scatter otherwise; the K/V tensors never round-
        # trip through the host (slots >= L are garbage but masked until
        # their positions are actually written)
        exe = self._dec_exe
        for i in range(self.num_layers):
            for tag, out in (("kv_k_%d" % i, pf.outputs[1 + 2 * i]),
                             ("kv_v_%d" % i, pf.outputs[2 + 2 * i])):
                if P == self.max_len:
                    exe.arg_dict[tag]._set_jax(out._jax())
                else:
                    ring = exe.arg_dict[tag]._jax()
                    exe.arg_dict[tag]._set_jax(
                        ring.at[:, :, 0:P, :].set(out._jax()))
        self._pos = L
        if _tm.enabled():
            _tm.counter("serving.prefill_tokens").inc(B * L)
        return logits

    # --------------------------------------------------------------- decode
    def decode_step(self, tokens):
        """One token per stream through the decode executable. ``tokens``
        is (B,) or (B, 1); returns (B, vocab) logits for the NEXT
        position. The ring KV update happens in-graph; host-side this is
        arg/output pointer swaps only."""
        self.warmup()
        p, S = self._pos, self.max_len
        if p >= self.pos_len:
            raise MXNetError(
                "kv_decode: position %d exceeds the trained position table "
                "(%d rows)" % (p, self.pos_len))
        tok = np.asarray(tokens, dtype=np.float32).reshape(self.batch, 1)
        slot = p % S
        oh = np.zeros((S,), np.float32)
        oh[slot] = 1.0
        mask = np.zeros((S,), np.float32)
        if p + 1 < S:
            mask[p + 1:] = _NEG  # slots beyond the history are empty
        exe = self._dec_exe
        exe.arg_dict["data"][:] = tok
        exe.arg_dict["pos_idx"][:] = np.full((self.batch, 1), p, np.float32)
        exe.arg_dict["slot_onehot"][:] = oh
        exe.arg_dict["kv_mask"][:] = mask
        with _tm.span("serving.decode_step", rows=self.batch, pos=p):
            exe.forward(is_train=False)
            logits = exe.outputs[0].asnumpy()
        for i in range(self.num_layers):
            exe.arg_dict["kv_k_%d" % i]._set_jax(
                exe.outputs[1 + 2 * i]._jax())
            exe.arg_dict["kv_v_%d" % i]._set_jax(
                exe.outputs[2 + 2 * i]._jax())
        self._pos = p + 1
        if _tm.enabled():
            _tm.counter("serving.decode_tokens").inc(self.batch)
        return logits

    def greedy(self, prompt, n_tokens):
        """Greedy-decode ``n_tokens`` continuations of a (B, L) prompt.
        Returns (B, n_tokens) int64 token ids."""
        logits = self.prefill(prompt)
        out = np.zeros((self.batch, n_tokens), np.int64)
        for t in range(n_tokens):
            nxt = np.argmax(logits, axis=-1)
            out[:, t] = nxt
            if t + 1 < n_tokens:
                logits = self.decode_step(nxt)
        return out
