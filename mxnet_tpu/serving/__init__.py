"""mxnet_tpu.serving: the production inference engine (docs/SERVING.md).

The "millions of users" leg of the roadmap: the training side compiles one
XLA executable per step and replays it; serving gets the same discipline.
PyGraph's thesis (PAPERS.md) — per-call dispatch overhead disappears when
the compiled graph is captured once and replayed — maps here onto a
``PersistentExecutableCache``: one pre-compiled executable per
(model, shape bucket, dtype), kept hot across requests, persisted per
device kind, with any post-warmup recompile a HARD error diagnosed by the
GL201-203 retrace guard. ``InferenceEngine`` feeds those executables from a
thread-safe request queue with continuous batching over the buckets
(pad-to-bucket, admit mid-flight until ``MXNET_SERVE_MAX_DELAY_MS``).
``KVCacheDecoder`` is the autoregressive variant: a prefill-bucket
executable plus a single-token decode executable over a preallocated ring
KV buffer (models/transformer.py serving symbols).

    cache = serving.PersistentExecutableCache(sym, arg_params, aux_params)
    eng = serving.InferenceEngine(cache, buckets=(1, 2, 4, 8),
                                  item_shapes={"data": (3, 28, 28)})
    eng.start()
    probs = eng.infer({"data": batch})          # blocking convenience
    fut = eng.submit({"data": batch})           # or async
    probs = fut.result(timeout=5.0)
"""
from __future__ import annotations

from .cache import PersistentExecutableCache
from .engine import (InferenceEngine, ServeFuture, ServeDeadlineError,
                     ServeOverloadError, ServeClosedError)
from .kv_decode import KVCacheDecoder, PagedKVDecoder, PagedKVExhausted
from .prefix_cache import PrefixCache
from .speculative import SpeculativeDecoder, spec_decode_enabled, spec_gamma
from . import fleet

__all__ = ["PersistentExecutableCache", "InferenceEngine", "ServeFuture",
           "ServeDeadlineError", "ServeOverloadError", "ServeClosedError",
           "KVCacheDecoder", "PagedKVDecoder", "PagedKVExhausted",
           "PrefixCache", "SpeculativeDecoder", "spec_decode_enabled",
           "spec_gamma", "fleet"]
