"""Cross-request KV prefix cache over the paged pool (docs/SERVING.md
§Prefix cache & speculative decoding).

Serving traffic is massively redundant — system prompts, few-shot
preambles, re-sent chat histories — so most prefill FLOPs recompute KV
pages some lane already produced. This index parks those pages: a
prompt is hashed in fixed C-token chunks with CHAINED digests (chunk
i's hash folds in chunk i-1's, so a hash names the entire prefix up to
and including its chunk, never the chunk in isolation), and each cached
chunk holds its page frames at a pool refcount. ``PagedKVDecoder.admit``
walks the chain, adopts every matched chunk's frames at +1 ref (zero
recompute, zero copy — the global slot axis makes physical sharing
legal), and chunk-prefills only the unmatched tail, registering each
freshly computed full chunk back into the index.

Eviction is LRU over LEAF entries only (an interior chunk's children
would become unreachable-by-match garbage if it left first), triggered
on demand when the pool can't serve an allocation. Evicting an entry
merely drops the CACHE's reference — a frame some lane still attends
keeps its other holders and never returns to the free list, which is
the "eviction never frees a shared page" invariant the tests pin.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict

import numpy as np

from .. import telemetry as _tm

__all__ = ["PrefixCache"]


class _Entry:
    __slots__ = ("frames", "parent", "children")

    def __init__(self, frames, parent):
        self.frames = list(frames)
        self.parent = parent     # parent chunk's hash (None for chunk 0)
        self.children = 0        # live child entries (0 == evictable leaf)


class PrefixCache:
    """LRU index of chained chunk hashes -> refcounted page frames."""

    def __init__(self, pool, chunk):
        self.pool = pool
        self.chunk = int(chunk)
        if self.chunk < 1:
            raise ValueError("prefix_cache: chunk must be >= 1")
        if self.chunk % pool.page_size:
            raise ValueError(
                "prefix_cache: chunk %d must be a multiple of the page "
                "size %d (cache entries own whole frames)"
                % (self.chunk, pool.page_size))
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._evictions = 0

    # ------------------------------------------------------------- hashing
    def chain_hashes(self, tokens):
        """Chained digests for every FULL chunk of ``tokens`` (length a
        multiple of the chunk size): ``h[i] = md5(h[i-1] || chunk_i)``.
        Content-addressed and position-addressed at once — two prompts
        share ``h[i]`` iff their first (i+1)*C tokens are identical."""
        toks = np.ascontiguousarray(np.asarray(tokens, np.int64))
        n = toks.shape[0] // self.chunk
        hashes = []
        prev = b""
        for i in range(n):
            h = hashlib.md5(
                prev + toks[i * self.chunk:(i + 1) * self.chunk].tobytes()
            ).hexdigest()
            hashes.append(h)
            prev = h.encode()
        return hashes

    # -------------------------------------------------------------- lookup
    def match(self, hashes):
        """Longest cached prefix of the hash chain. Returns
        ``(n_matched_chunks, flat_frames)`` — the frames of every matched
        chunk in position order, NOT yet increfed (the adopting lane does
        that). Matched entries are touched most-recently-used."""
        matched = 0
        frames = []
        for h in hashes:
            e = self._entries.get(h)
            if e is None:
                break
            self._entries.move_to_end(h)
            frames.extend(e.frames)
            matched += 1
        return matched, frames

    def insert(self, h, frames, parent=None):
        """Register a freshly computed chunk under its chain hash,
        taking the cache's OWN reference on each frame. ``parent`` is
        the previous chunk's chain hash (None for chunk 0) — it gains a
        child and stops being an evictable leaf. A hash already present
        (computed, evicted, recomputed) keeps its existing entry."""
        if h in self._entries:
            self._entries.move_to_end(h)
            return
        e = _Entry(frames, parent)
        self._entries[h] = e
        if parent is not None and parent in self._entries:
            self._entries[parent].children += 1
        for f in e.frames:
            self.pool.incref(f)
        if _tm.enabled():
            _tm.gauge("serving.prefix_entries").set(len(self._entries))

    # ------------------------------------------------------------- eviction
    def evict_for(self, n):
        """Evict LRU leaf entries until the pool can serve ``n`` frames
        (or nothing evictable remains). Returns True when the pool can
        now allocate. Dropping an entry releases only the CACHE's
        reference — shared frames survive with their other holders."""
        while not self.pool.can_acquire(n):
            victim = None
            for h, e in self._entries.items():   # OrderedDict = LRU order
                if e.children == 0:
                    victim = h
                    break
            if victim is None:
                return False
            e = self._entries.pop(victim)
            if e.parent is not None and e.parent in self._entries:
                self._entries[e.parent].children -= 1
            self.pool.release(e.frames)
            self._evictions += 1
            if _tm.enabled():
                _tm.counter("serving.prefix_evictions").inc()
                _tm.gauge("serving.prefix_entries").set(len(self._entries))
        return True

    def stats(self):
        return {"entries": len(self._entries),
                "frames_held": sum(len(e.frames)
                                   for e in self._entries.values()),
                "evictions": self._evictions}
