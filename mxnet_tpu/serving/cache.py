"""Persistent per-bucket executable cache (docs/SERVING.md).

The serving analogue of the training side's one-executable-per-step
discipline. Each (model, input-shape bucket, dtype) gets ONE grad-less
executor, bound and compiled at warmup and kept hot for the life of the
process — a request never pays bind/trace/compile. After ``seal()`` a
lookup miss (a shape no warmed bucket covers — the request that WOULD have
recompiled) is a hard ``MXNetError`` carrying the GL201-203 retrace-guard
diagnosis, so a production server can never silently degrade into
per-request compilation.

Persistence (TVM's measure-and-cache discipline, PAPERS.md): the warmed
bucket set is written as a JSON manifest under
``{cache_dir}/{device_kind}/{model_key}.json`` so the next process warms
the same buckets without being told, and JAX's persistent compilation
cache is pointed at ``{cache_dir}/xla`` so the XLA *artifacts* themselves
survive restarts on the same device kind (compile once per fleet rollout,
not once per process).
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import re
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..base import MXNetError
from .. import telemetry as _tm

__all__ = ["PersistentExecutableCache", "serve_cache_dir"]

log = logging.getLogger("mxnet_tpu.serving")

_xla_cache_lock = _tm.named_lock("serving.cache.xla_compile")
_xla_cache_dir = None


def serve_cache_dir():
    """The configured on-disk cache root (``MXNET_SERVE_CACHE_DIR``), or
    None when persistence is off (the default)."""
    d = os.environ.get("MXNET_SERVE_CACHE_DIR", "").strip()
    return d or None


def _enable_xla_persistence(root):
    """Point JAX's persistent compilation cache at ``{root}/xla`` (once per
    process — the setting is global). Best-effort: serving must work on jax
    builds without the feature."""
    global _xla_cache_dir
    with _xla_cache_lock:
        if _xla_cache_dir is not None:
            return
        import jax

        target = os.path.join(root, "xla")
        try:
            os.makedirs(target, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", target)
            # serving executables are small; without this the default
            # min-compile-time floor would skip persisting exactly them
            try:
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0)
            except Exception:
                pass
            _xla_cache_dir = target
        except Exception as exc:
            log.warning("serving: XLA persistent cache unavailable (%s); "
                        "manifest-only persistence", exc)
            _xla_cache_dir = ""


def _device_kind():
    import jax

    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        kind = "unknown"
    return re.sub(r"[^A-Za-z0-9_.-]+", "_", str(kind))


def _shape_key(input_shapes):
    return tuple(sorted((str(n), tuple(int(d) for d in s))
                        for n, s in input_shapes.items()))


def _plan_pattern_sites(exe):
    """Static summary of one bound executor's fusion plan: generic-pattern
    site counts, conv+BN directive count, and whether the conv+BN plan is
    ACTIVE at inference — what a serving operator needs to know about the
    fusion surface of a warmed bucket (per-site engage decisions land on
    the ``fusion.pattern_*`` counters and trace events). Reads the
    inventory the program computed once at plan time
    (``_GraphProgram.pattern_sites``) — never re-walks the directive map."""
    try:
        return {"pattern_sites": dict(exe._prog.pattern_sites),
                "conv_bn_directives": exe._prog.conv_bn_directives,
                "conv_bn_infer_active": bool(exe._prog._infer_fusion)}
    except Exception:  # observability must never sink a warmup
        return {}


class PersistentExecutableCache:
    """One pre-compiled grad-less executor per input-shape bucket.

    ``arg_params``/``aux_params`` are {name: NDArray-or-ndarray}; every
    symbol argument that is not a param is an INPUT whose shape the bucket
    key carries. ``model_key`` names the on-disk manifest (defaults to a
    digest of the symbol JSON + dtype).
    """

    def __init__(self, symbol, arg_params=None, aux_params=None, ctx=None,
                 dtype="float32", model_key=None, cache_dir=None,
                 max_executables=None):
        from ..context import current_context

        self._sym = symbol
        self._ctx = ctx or current_context()
        self._dtype = str(dtype)
        self._arg_params = dict(arg_params or {})
        self._aux_params = dict(aux_params or {})
        # ONE set of param/aux device arrays shared by every bucket
        # executor (a per-bucket simple_bind would hold len(buckets) full
        # weight copies); populated lazily by the first _bind
        self._shared_args: Dict[str, object] = {}
        self._shared_aux: Optional[Dict[str, object]] = None
        # LRU bound for UNSEALED use (the predict API's open-ended reshape
        # surface): past the cap the least-recently-used executor is
        # dropped so distinct shapes can't grow device memory without
        # bound. A sealed cache is fixed-size by construction and never
        # evicts. None/0 = unbounded.
        self._max_exes = int(max_executables or 0) or None
        self._exes: "OrderedDict[tuple, object]" = OrderedDict()
        # per-bucket fusion pattern-site summary (filled at compile time):
        # which patterns the plan rooted in this model's graph, per-pattern
        # site counts, and whether the conv+BN inference plan is active —
        # the serving-side observability of the inference-mode gates.
        # Guarded by its OWN lock: health() reads it, and the main _lock is
        # held for the full duration of a warmup compile (+ autotune) — a
        # liveness probe must never block on a compile.
        self._fusion_sites: Dict[tuple, dict] = {}
        self._sites_lock = _tm.named_lock("serving.cache.sites")
        self._lock = _tm.named_rlock("serving.cache")
        self._sealed = False
        digest = hashlib.sha1(
            (symbol.tojson() + "|" + self._dtype).encode()).hexdigest()[:16]
        self._model_key = re.sub(r"[^A-Za-z0-9_.-]+", "_",
                                 model_key or digest)
        self._digest = digest
        self._cache_dir = cache_dir if cache_dir is not None \
            else serve_cache_dir()
        if self._cache_dir:
            _enable_xla_persistence(self._cache_dir)

    # ------------------------------------------------------------- binding
    @property
    def input_names(self) -> List[str]:
        params = set(self._arg_params)
        return [n for n in self._sym.list_arguments() if n not in params]

    @property
    def sealed(self):
        return self._sealed

    def keys(self):
        with self._lock:
            return list(self._exes)

    def _infer_full(self, input_shapes):
        """Full static shape/type inference at these input shapes (the
        param/aux hints come from the checkpoint) — no bind, no compile."""
        from ..base import np_dtype

        shapes = {n: tuple(s) for n, s in input_shapes.items()}
        types = {}
        arg_names = set(self._sym.list_arguments())
        for n, v in self._arg_params.items():
            if n not in arg_names:
                continue  # extra checkpoint entries are ignored, as in
                # the predict API's allow_extra_params behavior
            shapes.setdefault(n, tuple(v.shape))
            types[n] = np.dtype(getattr(v, "dtype", self._dtype)).name
        for n in shapes:
            types.setdefault(n, self._dtype)
        return self._sym._infer_impl(
            shapes, {k: np_dtype(v) for k, v in types.items()},
            partial=False)

    def output_shapes(self, input_shapes) -> List[tuple]:
        """Statically inferred output shapes at these input shapes.
        Pure inference: safe to probe batch sizes that are not buckets."""
        return [tuple(s) for s in self._infer_full(input_shapes)[1]]

    def _bind(self, input_shapes):
        from ..ndarray import zeros

        arg_name_list = self._sym.list_arguments()
        res = self._infer_full(input_shapes)
        arg_shapes, _, aux_shapes, arg_types, _, aux_types = res
        inputs = set(self.input_names)
        args = {}
        for n, s, t in zip(arg_name_list, arg_shapes, arg_types):
            if n in inputs:
                # input slots are per-bucket: their shape IS the cache key
                args[n] = zeros(s, ctx=self._ctx, dtype=t)
                continue
            arr = self._shared_args.get(n)
            if arr is None:
                arr = zeros(s, ctx=self._ctx, dtype=t)
                if n in self._arg_params:
                    arr[:] = self._arg_params[n]
                self._shared_args[n] = arr
            args[n] = arr
        if self._shared_aux is None:
            self._shared_aux = {}
            for n, s, t in zip(self._sym.list_auxiliary_states(),
                               aux_shapes, aux_types):
                arr = zeros(s, ctx=self._ctx, dtype=t)
                if n in self._aux_params:
                    arr[:] = self._aux_params[n]
                self._shared_aux[n] = arr
        # each bucket gets its OWN graph program (no shared_exec): sharing
        # the jit entry would classify buckets 2..N's warmup compiles as
        # retraces in telemetry, polluting the zero-retrace contract
        return self._sym.bind(self._ctx, args, args_grad=None,
                              grad_req="null",
                              aux_states=dict(self._shared_aux))

    def _retrace_diagnosis(self):
        try:
            from ..analysis import lint

            rep = lint(self._sym, passes=["retrace_guard"])
            return "; ".join("%s: %s" % (d.code, d.message) for d in rep) \
                or ("no GL201-203 pattern in the graph: the shape change "
                    "came from the caller (an unwarmed bucket)")
        except Exception as exc:  # diagnosis must never mask the miss
            return "retrace-guard diagnosis failed: %s" % exc

    def executable(self, input_shapes):
        """Get (or, before ``seal()``, bind+compile) the executor for this
        exact input-shape bucket. A post-seal miss is a hard error: it is
        precisely the call that would have retraced."""
        key = _shape_key(input_shapes)
        exe = self._exes.get(key)
        if exe is not None:
            if self._max_exes and not self._sealed:
                with self._lock:  # LRU recency only matters when evicting
                    if key in self._exes:
                        self._exes.move_to_end(key)
            if _tm.enabled():
                _tm.counter("serving.executable_hit").inc()
            return exe
        with self._lock:
            exe = self._exes.get(key)
            if exe is not None:
                if _tm.enabled():
                    _tm.counter("serving.executable_hit").inc()
                return exe
            if self._sealed:
                raise MXNetError(
                    "serving: post-warmup executable-cache miss for input "
                    "shapes %s (warmed buckets: %s). A miss here would "
                    "retrace+recompile on the request path; retrace-guard "
                    "diagnosis: %s"
                    % (dict(input_shapes),
                       [dict(k) for k in self._exes],
                       self._retrace_diagnosis()))
            with _tm.span("serving.compile", model=self._model_key,
                          shapes=str(dict(input_shapes))):
                exe = self._bind(input_shapes)
                # force the XLA compile NOW (bind only traces lazily):
                # warmup pays it, the request path never does — this is
                # also where the fusion pattern engine's per-site
                # inference gates run (and, with MXNET_FUSION_TUNE_DIR
                # set, where a cold site gets tuned: warmup pays the
                # measurement, the request path reuses the verdict)
                exe.forward(is_train=False)
                np.asarray(exe.outputs[0].asnumpy())
            with self._sites_lock:
                self._fusion_sites[key] = _plan_pattern_sites(exe)
            if _tm.enabled():
                _tm.counter("serving.executable_compile").inc()
            self._exes[key] = exe
            if self._max_exes and not self._sealed \
                    and len(self._exes) > self._max_exes:
                old_key, _ = self._exes.popitem(last=False)
                with self._sites_lock:
                    self._fusion_sites.pop(old_key, None)
                log.info("serving: evicted LRU executable %s from %r "
                         "(cap %d)", dict(old_key), self._model_key,
                         self._max_exes)
                if _tm.enabled():
                    _tm.counter("serving.executable_evict").inc()
            if _tm.enabled():
                # after any eviction, so the gauge is the true live count
                _tm.gauge("serving.executables").set(len(self._exes))
            return exe

    # -------------------------------------------------------------- warmup
    def warmup(self, bucket_shapes: Optional[Sequence[dict]] = None,
               seal=True):
        """Pre-compile one executable per bucket. ``bucket_shapes`` is a
        list of {input_name: shape} dicts; None replays the persisted
        manifest (restart path). Returns the number of warmed buckets.

        Warming ZERO buckets (no/stale manifest on the restart path, or an
        empty list) neither seals nor persists: sealing an empty cache
        would turn every future request into a hard miss with no way back
        — the caller must warm explicit buckets instead."""
        if bucket_shapes is None:
            bucket_shapes = self._load_manifest()
        if not bucket_shapes:
            log.warning("serving: warmup(%s) found no buckets for %r; "
                        "cache left UNSEALED (an empty sealed cache would "
                        "reject every request)",
                        "manifest" if bucket_shapes == [] else bucket_shapes,
                        self._model_key)
            return 0
        with _tm.span("serving.warmup", model=self._model_key,
                      buckets=len(bucket_shapes)):
            for shapes in bucket_shapes:
                self.executable(shapes)
        if seal:
            self.seal()
        self._save_manifest()
        return len(bucket_shapes)

    def seal(self):
        """Freeze the bucket set: from now on any lookup miss raises."""
        self._sealed = True

    def fusion_sites(self):
        """Per-bucket fusion pattern-site summaries (compile-time static
        view; see ``_plan_pattern_sites``). Keys are the bucket shape keys
        rendered as dicts. Non-blocking with respect to warmup compiles
        (own lock — safe for health probes)."""
        with self._sites_lock:
            return {str(dict(k)): v
                    for k, v in self._fusion_sites.items()}

    # --------------------------------------------------------- persistence
    def _manifest_path(self):
        if not self._cache_dir:
            return None
        return os.path.join(self._cache_dir, _device_kind(),
                            self._model_key + ".json")

    def _save_manifest(self):
        path = self._manifest_path()
        if path is None:
            return
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            buckets = [{n: list(s) for n, s in key} for key in self._exes]
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"model_key": self._model_key,
                           "digest": self._digest, "dtype": self._dtype,
                           "device_kind": _device_kind(),
                           "buckets": buckets}, f, indent=1)
            os.replace(tmp, path)
        except OSError as exc:
            log.warning("serving: could not persist manifest %s (%s)",
                        path, exc)

    def _load_manifest(self):
        path = self._manifest_path()
        if path is None or not os.path.exists(path):
            return []
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError) as exc:
            log.warning("serving: unreadable manifest %s (%s)", path, exc)
            return []
        if rec.get("digest") != self._digest:
            # a different model (or dtype) under the same key: stale
            log.warning("serving: manifest %s digest mismatch "
                        "(model changed); ignoring", path)
            return []
        return [{n: tuple(s) for n, s in b.items()}
                for b in rec.get("buckets", [])]

    # ------------------------------------------------------------ hot swap
    def snapshot_params(self, arg_names=None, aux_names=None):
        """Host-side copies of the named (default: all) loaded arg/aux
        params, consistent under the swap lock — the pre-swap snapshot a
        rollback restores (the fleet replica's ``reload`` takes one
        before applying, so a fleet rollout abort can put the old
        weights back). Unknown names are skipped: ``swap_params`` would
        have refused them before writing anything, so they cannot need
        restoring. Returns ``(arg_params, aux_params)``."""

        def _host(v):
            return np.array(getattr(v, "asnumpy", lambda: v)())

        with self._lock:
            args = {n: _host(self._arg_params[n])
                    for n in (self._arg_params if arg_names is None
                              else arg_names)
                    if n in self._arg_params}
            aux = {n: _host(self._aux_params[n])
                   for n in (self._aux_params if aux_names is None
                             else aux_names)
                   if n in self._aux_params}
        return args, aux

    @staticmethod
    def _swap_value(name, value, target, what):
        """Validate ONE incoming swap value against its target buffer:
        shape must match exactly and the value must be materializable in
        the target's dtype. Both checks (and the cast) happen here, in the
        validation phase, so the later write loop cannot raise halfway and
        leave a mixed old/new weight set."""
        host = np.asarray(getattr(value, "asnumpy", lambda: value)())
        want = tuple(getattr(target, "shape", None) or np.shape(target))
        if tuple(host.shape) != want:
            raise MXNetError(
                "serving: swap_params shape mismatch for %r: got %s, %s "
                "has %s — a reshape would retrace; reload refused"
                % (name, tuple(host.shape), what, want))
        dtype = getattr(target, "dtype", None) or np.asarray(target).dtype
        try:
            return np.asarray(host, dtype=dtype)
        except (TypeError, ValueError) as exc:
            raise MXNetError(
                "serving: swap_params value for %r is not castable to the "
                "bound dtype %s (%s) — reload refused"
                % (name, np.dtype(dtype).name, exc)) from exc

    def swap_params(self, arg_params, aux_params=None):
        """Hitless weight swap (docs/RESILIENCE.md): overwrite the SHARED
        param/aux buffers every bucket executor reads, in place. Shapes
        must match exactly (values are cast to the bound dtype) — a shape
        or unknown-key mismatch raises BEFORE anything is written, so a
        failed swap leaves the old weights fully intact. Same
        shapes/dtypes means the executables' jit signatures are untouched:
        ZERO retraces. jax arrays are immutable, so the in-place NDArray
        assignment allocates fresh device buffers — an in-flight batch
        still materializing against the old buffers is double-buffered by
        construction. Keys absent from ``arg_params`` keep their current
        values (partial swaps are legal)."""
        with self._lock:
            input_names = set(self.input_names)
            updates = []
            for store, incoming, what in (
                    (self._shared_args, arg_params or {}, "argument"),
                    (self._shared_aux, aux_params or {}, "aux state")):
                for n, v in incoming.items():
                    if n in input_names:
                        raise MXNetError(
                            "serving: swap_params(%r) names a model INPUT, "
                            "not a parameter" % n)
                    cur = (store or {}).get(n)
                    if cur is None:
                        # not bound yet (pre-warmup swap): stage into the
                        # source dicts so the first bind picks it up below
                        src = self._arg_params if what == "argument" \
                            else self._aux_params
                        if n not in src:
                            raise MXNetError(
                                "serving: swap_params got unknown %s %r "
                                "(loaded params: %s...)"
                                % (what, n, sorted(src)[:8]))
                        host = self._swap_value(n, v, src[n],
                                                "the loaded checkpoint")
                        updates.append((None, host, n, what))
                        continue
                    updates.append((cur,
                                    self._swap_value(n, v, cur,
                                                     "the loaded model"),
                                    n, what))
            # validation passed for EVERY key — now write (all or nothing)
            for cur, host, n, what in updates:
                if cur is None:
                    (self._arg_params if what == "argument"
                     else self._aux_params)[n] = host
                else:
                    cur[:] = host
                    # keep the source dict consistent for any later bind
                    (self._arg_params if what == "argument"
                     else self._aux_params)[n] = host
        return len(updates)

    # ------------------------------------------------------------- running
    def run(self, inputs: Dict[str, np.ndarray]):
        """One batch through the bucket executable matching the inputs'
        exact shapes. Returns the outputs as numpy arrays."""
        exe = self.executable({n: np.shape(v) for n, v in inputs.items()})
        for n, v in inputs.items():
            exe.arg_dict[n][:] = v
        exe.forward(is_train=False)
        return [o.asnumpy() for o in exe.outputs]
