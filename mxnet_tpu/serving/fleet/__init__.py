"""mxnet_tpu.serving.fleet: the multi-replica serving tier
(docs/SERVING.md §Fleet).

PR 6's ``InferenceEngine`` is one process — one batcher, one queue, one
failure domain. This package composes the machinery of four prior PRs
into a replicated tier: a ``ReplicaSupervisor`` spawns and babysits N
engine processes (heartbeat-file liveness, capped-backoff restart), a
``Router`` load-balances requests over them by each replica's own
``health()`` EWMA queue-wait (skipping degraded/latched/stale replicas,
shedding with ``retry_after_ms`` when the whole fleet is saturated, and
RE-dispatching a dead replica's in-flight requests so nothing is lost),
and ``Router.rollout()`` applies a fleet-wide hitless weight swap one
drained replica at a time, aborting — with rollback — on any failed
swap. ``Fleet`` glues the two together:

    spec = {"model": "mlp", "item_shapes": {"data": [784]},
            "buckets": [1, 2, 4, 8], "params": "/path/params.npz"}
    with Fleet(spec, n_replicas=4) as fleet:
        out = fleet.router.infer({"data": batch})
        fleet.router.rollout(new_arg_params)       # hitless, fleet-wide

Chaos is a first-class input: ``fleet.dispatch`` / ``fleet.health`` /
``fleet.replica_spawn`` are deterministic fault-injection sites
(mxnet_tpu/faultinject.py), and ``supervisor.kill_replica()`` is the
kill-one chaos vector ``serve_bench --fleet`` drives in CI.

Observability (docs/OBSERVABILITY.md §Fleet): the router mints a
``trace_id`` per request that RPC frames propagate into replica spans,
``Router.collect_fleet_trace()`` merges per-process chrome dumps onto
one clock-aligned timeline, ``Router.metrics()`` folds the replicas'
delta-encoded telemetry snapshots into fleet rollups (qps, shed rate,
merged latency histograms), and ``MXNET_SLO`` arms a burn-rate monitor
with structured violation events.
"""
from __future__ import annotations

from ...base import MXNetError
from .rpc import (RpcServer, RpcClient, RpcError, RpcConnectionError,
                  RpcRemoteError)
from .replica import (ReplicaApp, build_model, save_params_npz,
                      load_params_npz)
from .supervisor import ReplicaSupervisor, ReplicaHandle
from .router import Router, FleetRolloutError, FleetDispatchError

__all__ = ["Fleet", "Router", "ReplicaSupervisor", "ReplicaHandle",
           "ReplicaApp", "RpcServer", "RpcClient", "RpcError",
           "RpcConnectionError", "RpcRemoteError", "FleetRolloutError",
           "FleetDispatchError", "build_model", "save_params_npz",
           "load_params_npz"]


class Fleet:
    """Supervisor + router in one handle. ``start()`` spawns the
    replicas, waits for ``min_ready`` (default: all) to publish their
    RPC addresses, then starts the router over the supervisor's live
    address book — a restarted replica re-enters rotation as soon as the
    router's next health poll sees its fresh snapshot."""

    def __init__(self, spec, n_replicas=None, workdir=None,
                 min_ready=None, ready_timeout_s=240.0,
                 supervisor_kwargs=None, router_kwargs=None):
        self.supervisor = ReplicaSupervisor(
            spec, n_replicas=n_replicas, workdir=workdir,
            **(supervisor_kwargs or {}))
        self.router = Router(self.supervisor.addresses,
                             **(router_kwargs or {}))
        self.min_ready = (self.supervisor.n_replicas
                          if min_ready is None else int(min_ready))
        self.ready_timeout_s = float(ready_timeout_s)
        self._started = False

    def start(self):
        if self._started:
            return self
        self.supervisor.start()
        try:
            self.supervisor.wait_ready(self.min_ready,
                                       timeout_s=self.ready_timeout_s)
            self.router.start()
        except MXNetError:
            self.supervisor.stop()
            raise
        self._started = True
        return self

    def rollout(self, arg_params, aux_params=None, **kw):
        """Fleet-wide hitless rollout that CONVERGES across restarts.
        ``Router.rollout`` can only swap replicas it can see — one that
        died moments ago (or is mid-restart, having already loaded the
        OLD param file) would silently rejoin on old weights and leave
        the fleet mixed. This wrapper closes that hole: after the
        router-level rollout succeeds, the spec's param file is
        REWRITTEN with the new weights (every restart from now on loads
        them), and any replica the router did NOT swap is recycled
        through the supervisor (killed → auto-restarted onto the new
        file). Returns {"applied": [rids swapped live],
        "recycled": [rids restarted onto the new weights]}. An aborted
        router rollout propagates ``FleetRolloutError`` with the spec
        file untouched — old weights stay live fleet-wide."""
        from .replica import save_params_npz

        res = self.router.rollout(arg_params, aux_params, **kw)
        applied = set(res["applied"])
        save_params_npz(self.supervisor.base_spec["params"],
                        arg_params, aux_params)
        recycled = sorted(set(range(self.supervisor.n_replicas))
                          - applied)
        for rid in recycled:
            # dead/starting replicas loaded (or will load) a param file;
            # make sure it is the NEW one — a no-op kill on an
            # already-dead slot still respawns onto the rewritten file
            self.supervisor.kill_replica(rid)
        return {"applied": sorted(applied), "recycled": recycled}

    def metrics(self):
        """Fleet rollups (``Router.metrics()``)."""
        return self.router.metrics()

    def collect_fleet_trace(self):
        """Merged, clock-aligned fleet chrome trace
        (``Router.collect_fleet_trace()``)."""
        return self.router.collect_fleet_trace()

    def close(self):
        self.router.close()
        self.supervisor.stop()
        self._started = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
