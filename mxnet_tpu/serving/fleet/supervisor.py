"""ReplicaSupervisor: spawn / monitor / restart the replica tier
(docs/SERVING.md §Fleet).

One supervisor owns N replica worker processes (``replica.py``), each a
single-engine failure domain. Detection follows the PR 7/8 heartbeat
idiom: every replica touches a per-replica heartbeat file on a timer, and
the monitor loop classifies a replica dead when EITHER its process has
exited OR its heartbeat mtime goes stale past ``MXNET_FLEET_DEAD_MS`` (a
wedged process with a live PID is dead for serving purposes — it gets a
SIGKILL and a restart). Restarts back off exponentially from
``MXNET_FLEET_RESTART_BACKOFF_MS`` up to a cap, so a crash-looping
replica cannot burn the host, and the backoff resets once a replica
reaches READY (published its RPC address after warmup) — a flaky start
is forgiven, a tight crash loop is not.

The supervisor never touches request traffic: the Router reads
``addresses()`` every health-poll tick and routes around anything not
READY. ``fleet.replica_spawn`` is a fault-injection site
(mxnet_tpu/faultinject.py): an injected raise fails that spawn attempt
and the backoff machinery retries it — deterministically testable
restart logic.
"""
from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

from ...base import MXNetError
from ... import telemetry as _tm
from ... import faultinject as _fi
from ..engine import _env_float, _env_int

__all__ = ["ReplicaSupervisor", "ReplicaHandle"]

log = logging.getLogger("mxnet_tpu.serving.fleet")


class ReplicaHandle:
    """Supervisor-side view of one replica slot. ``state`` is
    ``starting`` (spawned, warming) | ``ready`` (address published) |
    ``dead`` (waiting out restart backoff)."""

    __slots__ = ("rid", "spec_path", "port_file", "hb_path", "proc",
                 "addr", "state", "restarts", "backoff_exp",
                 "next_spawn_t", "spawned_t", "ready_t")

    def __init__(self, rid, spec_path, port_file, hb_path):
        self.rid = rid
        self.spec_path = spec_path
        self.port_file = port_file
        self.hb_path = hb_path
        self.proc = None
        self.addr = None
        self.state = "dead"
        self.restarts = 0      # lifetime restart count (telemetry)
        self.backoff_exp = 0   # consecutive failures since last READY
        self.next_spawn_t = 0.0
        self.spawned_t = 0.0
        self.ready_t = 0.0


class ReplicaSupervisor:
    """Spawn and babysit ``n_replicas`` replica processes from one model
    spec (see ``replica.py`` for the spec schema; the supervisor fills in
    the per-replica ``replica_id`` / ``heartbeat_path`` / ``port_file``).
    """

    def __init__(self, spec, n_replicas=None, workdir=None,
                 restart_backoff_ms=None, restart_backoff_max_ms=None,
                 dead_after_ms=None, spawn_timeout_s=180.0,
                 poll_interval_s=0.2):
        self.n_replicas = (_env_int("MXNET_FLEET_REPLICAS", 2)
                           if n_replicas is None else int(n_replicas))
        if self.n_replicas < 1:
            raise MXNetError("fleet: need at least one replica")
        self.base_spec = dict(spec)
        self._own_workdir = workdir is None
        self.workdir = workdir or tempfile.mkdtemp(prefix="mxtpu_fleet_")
        os.makedirs(self.workdir, exist_ok=True)
        self.restart_backoff_s = (
            _env_float("MXNET_FLEET_RESTART_BACKOFF_MS", 200.0)
            if restart_backoff_ms is None else float(restart_backoff_ms)
        ) / 1000.0
        self.restart_backoff_max_s = (
            _env_float("MXNET_FLEET_RESTART_BACKOFF_MAX_MS", 5000.0)
            if restart_backoff_max_ms is None
            else float(restart_backoff_max_ms)) / 1000.0
        self.dead_after_s = (
            _env_float("MXNET_FLEET_DEAD_MS", 3000.0)
            if dead_after_ms is None else float(dead_after_ms)) / 1000.0
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self._handles = []
        for rid in range(self.n_replicas):
            h = ReplicaHandle(
                rid,
                os.path.join(self.workdir, "replica-%d.json" % rid),
                os.path.join(self.workdir, "replica-%d.port" % rid),
                os.path.join(self.workdir, "replica-%d.hb" % rid))
            self._handles.append(h)
        self._lock = _tm.named_lock("fleet.supervisor")
        self._stop = threading.Event()
        self._monitor = None
        self._started = False

    # ------------------------------------------------------------- spawning
    def _write_spec(self, h: ReplicaHandle):
        spec = dict(self.base_spec)
        spec.update(replica_id=h.rid, heartbeat_path=h.hb_path,
                    port_file=h.port_file)
        tmp = h.spec_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(spec, f, indent=1)
        os.replace(tmp, h.spec_path)

    def _spawn_cmd(self, h: ReplicaHandle):
        """The replica launch command — a seam tests override to spawn a
        lightweight stand-in instead of a full jax-importing worker."""
        return [sys.executable, "-c",
                "import sys; from mxnet_tpu.serving.fleet.replica "
                "import main; sys.exit(main(sys.argv[1:]))", h.spec_path]

    def _spawn_locked(self, h: ReplicaHandle):
        for stale in (h.port_file, h.hb_path):
            try:
                os.unlink(stale)
            except OSError:
                pass
        self._write_spec(h)
        now = time.perf_counter()
        try:
            _fi.fire("fleet.replica_spawn")
            # the child must import THIS mxnet_tpu even when the parent
            # found it via sys.path manipulation rather than an install
            env = dict(os.environ)
            pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))))
            env["PYTHONPATH"] = pkg_root + os.pathsep + \
                env.get("PYTHONPATH", "")
            h.proc = subprocess.Popen(self._spawn_cmd(h), env=env)
        except Exception as exc:
            # injected or organic spawn failure: back off and retry — the
            # slot is not abandoned
            h.proc = None
            self._note_death_locked(h, "spawn failed: %s" % exc, now)
            return
        h.state = "starting"
        h.addr = None
        h.spawned_t = now
        log.info("fleet: spawned replica %d (pid %s, attempt %d)",
                 h.rid, h.proc.pid, h.backoff_exp + 1)

    def _note_death_locked(self, h: ReplicaHandle, why, now):
        delay = min(self.restart_backoff_s * (2 ** h.backoff_exp),
                    self.restart_backoff_max_s)
        h.backoff_exp += 1
        h.restarts += 1
        h.state = "dead"
        h.addr = None
        h.next_spawn_t = now + delay
        log.warning("fleet: replica %d down (%s); restart in %.0fms",
                    h.rid, why, delay * 1000.0)
        if _tm.enabled():
            _tm.counter("fleet.replica_deaths").inc()
            _tm.counter("fleet.replica_restarts").inc()

    # ------------------------------------------------------------ lifecycle
    def start(self):
        if self._started:
            return self
        with self._lock:
            for h in self._handles:
                self._spawn_locked(h)
        self._stop.clear()
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="fleet-supervisor",
                                         daemon=True)
        self._monitor.start()
        self._started = True
        return self

    def _check_one_locked(self, h: ReplicaHandle, now):
        if h.proc is None:
            if h.state == "dead" and now >= h.next_spawn_t:
                self._spawn_locked(h)
            return
        rc = h.proc.poll()
        if rc is not None:
            h.proc = None
            self._note_death_locked(h, "exit rc=%s" % rc, now)
            return
        if h.addr is None:
            if os.path.exists(h.port_file):
                try:
                    with open(h.port_file) as f:
                        h.addr = f.read().strip()
                except OSError:
                    return
                if h.addr:
                    h.state = "ready"
                    h.ready_t = now
                    h.backoff_exp = 0  # clean start forgives past crashes
                    log.info("fleet: replica %d ready at %s",
                             h.rid, h.addr)
            elif now - h.spawned_t > self.spawn_timeout_s:
                self._kill_locked(h)
                self._note_death_locked(h, "spawn timed out", now)
            return
        # ready: heartbeat staleness (wedged-but-alive) — the mtime is
        # the liveness signal, exactly the dist heartbeat contract
        try:
            age = time.time() - os.stat(h.hb_path).st_mtime
        except OSError:
            age = now - h.ready_t
        if age > self.dead_after_s:
            self._kill_locked(h)
            self._note_death_locked(
                h, "heartbeat stale %.1fs" % age, now)

    def _kill_locked(self, h: ReplicaHandle):
        if h.proc is None:
            return
        try:
            h.proc.kill()
            h.proc.wait(timeout=5.0)
        except (OSError, subprocess.TimeoutExpired):
            pass
        h.proc = None

    def _monitor_loop(self):
        while not self._stop.is_set():
            now = time.perf_counter()
            with self._lock:
                for h in self._handles:
                    self._check_one_locked(h, now)
                ready = sum(1 for h in self._handles
                            if h.state == "ready")
            if _tm.enabled():
                _tm.gauge("fleet.replicas_ready").set(ready)
            self._stop.wait(self.poll_interval_s)

    # -------------------------------------------------------------- queries
    def addresses(self):
        """{replica_id: "host:port"} of READY replicas — the router's
        replica-provider view."""
        with self._lock:
            return {h.rid: h.addr for h in self._handles
                    if h.state == "ready" and h.addr}

    def states(self):
        with self._lock:
            return {h.rid: {"state": h.state, "addr": h.addr,
                            "restarts": h.restarts,
                            "pid": h.proc.pid if h.proc else None}
                    for h in self._handles}

    def wait_ready(self, n=None, timeout_s=240.0):
        """Block until ``n`` (default: all) replicas are READY."""
        need = self.n_replicas if n is None else int(n)
        deadline = time.perf_counter() + timeout_s
        while time.perf_counter() < deadline:
            if len(self.addresses()) >= need:
                return True
            time.sleep(0.1)
        raise MXNetError(
            "fleet: only %d/%d replicas ready within %.0fs (states: %s)"
            % (len(self.addresses()), need, timeout_s, self.states()))

    def kill_replica(self, rid, sig=signal.SIGKILL):
        """Chaos helper: kill one replica's process (the monitor notices
        and restarts it with backoff). Returns the killed pid or None."""
        with self._lock:
            h = self._handles[rid]
            if h.proc is None:
                return None
            pid = h.proc.pid
            try:
                os.kill(pid, sig)
            except OSError:
                return None
            return pid

    def stop(self, timeout_s=10.0):
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
        with self._lock:
            procs = [(h, h.proc) for h in self._handles
                     if h.proc is not None]
            for h, p in procs:
                try:
                    p.terminate()
                except OSError:
                    pass
            deadline = time.perf_counter() + timeout_s
            for h, p in procs:
                try:
                    p.wait(timeout=max(0.1,
                                       deadline - time.perf_counter()))
                except subprocess.TimeoutExpired:
                    try:
                        p.kill()
                        p.wait(timeout=2.0)
                    except (OSError, subprocess.TimeoutExpired):
                        pass
                h.proc = None
                h.state = "dead"
                h.addr = None
        self._started = False
