"""Front-end Router: load-aware dispatch over the replica tier
(docs/SERVING.md §Fleet has the architecture diagram and the
router-vs-replica failure-mode matrix).

Dispatch policy, in order:

* **Eligibility** — a replica is dispatchable only while its last health
  snapshot is FRESH (accepted within ``MXNET_FLEET_STALE_MS`` and passing
  the seq/snapshot_ms staleness check below) and its state is not
  ``latched``/``stopped``. ``degraded`` replicas are skipped whenever a
  healthy one exists (they still beat shedding when the whole fleet is
  degraded). Draining replicas (mid-rollout) are never picked.
* **Prefix affinity** — a request submitted with a ``prefix_key`` (the
  prompt's chunk-hash stem, docs/SERVING.md §Prefix cache) prefers the
  replica that rendezvous-hashing (HRW over the registered replica set)
  assigns that key: repeat prefixes keep landing where their KV pages
  are already cached. Affinity NEVER overrides eligibility — when the
  assigned replica is stale, unhealthy, draining, or already tried, the
  pick falls back to the load-aware EWMA policy below
  (``fleet.affinity_hits`` / ``fleet.affinity_fallbacks``). Disable
  with ``MXNET_FLEET_AFFINITY=0``.
* **Load-awareness** — among eligible replicas, lowest EWMA queue wait
  (each engine's own admission-control estimate, exported by
  ``health()``), tie-broken by the router's in-flight count then
  round-robin.
* **Shedding** — when the best eligible replica's wait estimate exceeds
  the request's deadline budget (or the absolute ``MXNET_FLEET_SHED_MS``
  cap), or when NO replica is eligible at all, the request is shed at
  admission with ``ServeOverloadError`` carrying ``retry_after_ms`` —
  the fleet-level analogue of the engine's EWMA shed.
* **Re-dispatch** — a transport failure mid-request (replica died, RPC
  timed out, injected ``fleet.dispatch`` fault) marks the replica
  suspect (its view is invalidated; the supervisor decides if it is
  really dead) and RE-dispatches the request to another replica, up to
  ``MXNET_FLEET_REDISPATCH`` times. Inference is idempotent, so replay
  is safe — a dead replica's in-flight requests are never lost.

Staleness: the router trusts a snapshot only if it proves the replica is
still answering — a new engine incarnation (pid change), a strictly
higher ``seq``, or a newer ``snapshot_ms``. A poll that merely re-reads
a dead replica's last-good numbers fails all three and is discarded
(``fleet.stale_health_discards``), so traffic never routes on a corpse's
flattering statistics.

Rollout: ``rollout(arg_params)`` applies a fleet-wide hitless weight swap
ONE replica at a time — drain it (stop picking it, wait in-flight → 0),
RPC ``reload`` (the engine's zero-retrace barrier swap), verify, move on.
Any failed swap ABORTS: already-swapped replicas are rolled back to the
snapshot their replica kept, so the fleet is never left serving mixed
weights — old weights stay live everywhere.
"""
from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
import uuid
from collections import deque
from concurrent.futures import ThreadPoolExecutor, wait as _fut_wait

from ...base import MXNetError
from ... import telemetry as _tm
from ...telemetry import histogram as _hg
from ...telemetry.slo import SloMonitor, SloSpec
from ... import faultinject as _fi
from ..engine import (ServeFuture, ServeOverloadError, ServeDeadlineError,
                      ServeClosedError, _env_float, _env_int)
from .rpc import RpcClient, RpcConnectionError

__all__ = ["Router", "FleetRolloutError", "FleetDispatchError"]

log = logging.getLogger("mxnet_tpu.serving.fleet")


class FleetDispatchError(MXNetError):
    """Every eligible replica was tried and none could serve the request
    (the terminal form of the re-dispatch path)."""


class FleetRolloutError(MXNetError):
    """A fleet rollout aborted. ``result`` carries the per-replica
    outcome; old weights are live fleet-wide (already-swapped replicas
    were rolled back)."""

    def __init__(self, msg, result=None):
        super().__init__(msg)
        self.result = result or {}


class _View:
    """Router-side cache of one replica's last ACCEPTED health snapshot."""

    __slots__ = ("rid", "target", "health", "seq", "pid", "received_t")

    def __init__(self, rid, target):
        self.rid = rid
        self.target = target     # "host:port" or an in-process client
        self.health = None
        self.seq = -1
        self.pid = None
        self.received_t = 0.0    # perf_counter of last accepted snapshot


class _FleetRequest:
    __slots__ = ("inputs", "future", "t_enq", "deadline", "deadline_ms",
                 "tried", "redispatches", "trace_id", "prefix_key")

    def __init__(self, inputs, deadline=None, deadline_ms=None,
                 trace_id=None, prefix_key=None):
        self.inputs = inputs
        self.future = ServeFuture()
        self.t_enq = time.perf_counter()
        self.deadline = deadline          # absolute perf_counter or None
        self.deadline_ms = deadline_ms    # forwarded to the replica engine
        self.tried = set()
        self.redispatches = 0
        self.trace_id = trace_id          # router-minted request trace id
        self.prefix_key = prefix_key      # prefix-affinity routing key


class Router:
    """Load-aware request router over a set of replicas.

    ``provider`` is a zero-arg callable returning ``{replica_id:
    target}`` where target is either an ``"host:port"`` RPC address
    (``ReplicaSupervisor.addresses``) or any in-process object exposing
    the replica protocol (``infer``/``health``/``reload``/``rollback``
    RPC-handler signatures) — which is how the tests drive the router
    against fake replicas with scripted failure behavior.
    """

    def __init__(self, provider, workers=None, max_queue=None,
                 health_interval_ms=None, stale_ms=None, shed_ms=None,
                 max_redispatch=None, rpc_timeout_ms=None,
                 dispatch_wait_ms=None, deadline_ms=None, name="fleet",
                 slo=None):
        self.provider = provider
        self.name = name
        self.workers = (_env_int("MXNET_FLEET_WORKERS", 8)
                        if workers is None else int(workers))
        self.max_queue = (_env_int("MXNET_FLEET_MAX_QUEUE", 4096)
                          if max_queue is None else int(max_queue))
        self.health_interval_s = (
            _env_float("MXNET_FLEET_HEALTH_INTERVAL_MS", 100.0)
            if health_interval_ms is None
            else float(health_interval_ms)) / 1000.0
        self.stale_s = (_env_float("MXNET_FLEET_STALE_MS", 1000.0)
                        if stale_ms is None else float(stale_ms)) / 1000.0
        shed = (_env_float("MXNET_FLEET_SHED_MS", 0.0)
                if shed_ms is None else float(shed_ms))
        self.shed_cap_ms = shed if shed > 0 else None
        self.max_redispatch = (_env_int("MXNET_FLEET_REDISPATCH", 3)
                               if max_redispatch is None
                               else int(max_redispatch))
        self.rpc_timeout_s = (
            _env_float("MXNET_FLEET_RPC_TIMEOUT_MS", 30000.0)
            if rpc_timeout_ms is None else float(rpc_timeout_ms)) / 1000.0
        # how long a dispatch worker waits for SOME replica to become
        # eligible before failing the request (covers the window where
        # the only replica died and its restart is still warming)
        self.dispatch_wait_s = (
            _env_float("MXNET_FLEET_DISPATCH_WAIT_MS", 10000.0)
            if dispatch_wait_ms is None
            else float(dispatch_wait_ms)) / 1000.0
        dl = (_env_float("MXNET_FLEET_DEADLINE_MS", 0.0)
              if deadline_ms is None else float(deadline_ms))
        self.default_deadline_s = dl / 1000.0 if dl > 0 else None
        # prefix-affinity dispatch is on by default; it only engages for
        # requests that carry a prefix_key, so plain traffic is untouched
        self.affinity_enabled = os.environ.get(
            "MXNET_FLEET_AFFINITY", "1").strip().lower() \
            not in ("0", "off", "false", "no")
        self._views = {}
        self._inflight = {}
        self._draining = set()
        self._poll_pool = None     # per-replica poll concurrency; start()
        self._poll_pending = set()  # rids with an in-flight poll
        self._rr = 0
        self._queue = deque()
        self._cond = _tm.named_condition("fleet.router.queue")
        self._stop = False
        self._started = False
        self._threads = []
        self._tls = threading.local()
        self._counts = {"submitted": 0, "completed": 0, "shed": 0,
                        "redispatched": 0, "failed": 0}
        self._rollout_lock = _tm.named_lock("fleet.router.rollout")
        # ---- fleet observability plane (docs/OBSERVABILITY.md §Fleet)
        self._t_start = None
        # router's own request-latency histogram, recorded regardless of
        # telemetry mode so SLO latency objectives and the metrics()
        # rollup always have truth (one bucket increment per delivery)
        self._req_hist = _hg.Histogram()
        self._tel_lock = _tm.named_lock("fleet.router.telemetry")
        self._fleet_counters = {}      # folded replica counter deltas
        self._fleet_hists = {}         # timer -> merged sparse buckets
        self._replica_tel = {}         # rid -> {"counters", "dropped"}
        self._per_replica_done = {}    # rid -> deliveries via this router
        self._clock_offsets = {}       # rid -> (offset_s, remote_pid)
        # SLO gate: explicit spec (SloSpec | spec string) wins; else the
        # MXNET_SLO env; else no monitor
        if slo is not None and not isinstance(slo, SloSpec):
            slo = SloSpec.parse(slo)
        self._slo_spec = slo if slo is not None else SloSpec.from_env()
        self._slo_monitor = (SloMonitor(self._slo_spec)
                             if self._slo_spec is not None else None)
        self._slo_last = {"completed": 0, "failed": 0, "buckets": {}}
        self._slo_status = None

    # ------------------------------------------------------------ lifecycle
    def start(self):
        if self._started:
            return self
        with self._cond:
            self._stop = False
        if self._t_start is None:
            self._t_start = time.perf_counter()
        self._poll_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="%s-health" % self.name)
        self._poll_once(wait_s=5.0)  # seed views before accepting traffic
        t = threading.Thread(target=self._poll_loop,
                             name="%s-health-poller" % self.name,
                             daemon=True)
        t.start()
        self._threads = [t]
        for i in range(self.workers):
            w = threading.Thread(target=self._worker_loop,
                                 name="%s-dispatch-%d" % (self.name, i),
                                 daemon=True)
            w.start()
            self._threads.append(w)
        with self._cond:
            self._started = True
        return self

    def close(self):
        with self._cond:
            self._stop = True
            pending = list(self._queue)
            self._queue.clear()
            self._cond.notify_all()
        for r in pending:
            if not r.future.done():
                r.future.set_error(ServeClosedError(
                    "fleet: router closed before this request was "
                    "dispatched"))
        for t in self._threads:
            t.join(timeout=2.0)
        with self._cond:
            if self._poll_pool is not None:
                self._poll_pool.shutdown(wait=False)
                self._poll_pool = None
            self._started = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # -------------------------------------------------------------- clients
    def _client(self, view: _View):
        """Per-worker-thread client for a replica target. In-process
        targets (test fakes) are used directly; addresses get one
        ``RpcClient`` per (worker thread, address) so concurrent requests
        to one replica pipeline over separate connections."""
        if not isinstance(view.target, str):
            return view.target
        cache = getattr(self._tls, "clients", None)
        if cache is None:
            cache = self._tls.clients = {}
        key = (view.rid, view.target)
        cli = cache.get(key)
        if cli is None:
            # drop clients for dead incarnations of this replica id
            for k in [k for k in cache if k[0] == view.rid and k != key]:
                cache.pop(k).close()
            cli = cache[key] = RpcClient(view.target,
                                         timeout_s=self.rpc_timeout_s)
        return cli

    @staticmethod
    def _call(client, method, rpc_timeout_s=None, **kw):
        """Uniform invocation for RPC clients and in-process fakes.
        ``rpc_timeout_s`` bounds the SOCKET wait (RPC targets only);
        everything in ``kw`` — including a handler-side ``timeout_s`` —
        reaches the replica method on both paths, so tests exercise the
        same call contract production does."""
        if isinstance(client, RpcClient):
            return client.call(method, rpc_timeout_s=rpc_timeout_s, **kw)
        return getattr(client, method)(**kw)

    # -------------------------------------------------------- health views
    def _accept_snapshot(self, view: _View, h, now):
        """The staleness contract: accept only a snapshot that proves the
        replica answered — new incarnation (pid), higher seq, or newer
        snapshot_ms. Anything else is a replay of last-good numbers."""
        seq = h.get("seq", 0)
        pid = h.get("pid")
        prev = view.health
        fresh_incarnation = pid is not None and pid != view.pid
        if prev is not None and not fresh_incarnation:
            if seq <= view.seq and \
                    h.get("snapshot_ms", 0) <= prev.get("snapshot_ms", 0):
                if _tm.enabled():
                    _tm.counter("fleet.stale_health_discards").inc()
                return False
        view.health = h
        view.seq = seq
        view.pid = pid
        view.received_t = now
        return True

    def _poll_once(self, wait_s=None):
        """One poll round: each replica polled on its OWN pool task, so a
        wedged replica (slow/hung health RPC) costs itself freshness but
        can never stale the rest of the fleet's views. A replica whose
        previous poll is still in flight is skipped, so a hang cannot
        pile up tasks either. ``wait_s`` blocks for the round's results
        (the start() seed and the rollout refresh want settled views)."""
        try:
            targets = dict(self.provider())
        except Exception as exc:
            log.warning("fleet: replica provider failed: %s", exc)
            return
        with self._cond:
            for rid in list(self._views):
                if rid not in targets:
                    del self._views[rid]
            for rid, target in targets.items():
                v = self._views.get(rid)
                if v is None or v.target != target:
                    self._views[rid] = _View(rid, target)
            views = [v for v in self._views.values()
                     if v.rid not in self._poll_pending]
            for v in views:
                self._poll_pending.add(v.rid)
        pool = self._poll_pool
        if pool is None:  # pre-start probe: poll inline
            for v in views:
                self._poll_replica(v)
            return
        futs = [pool.submit(self._poll_replica, v) for v in views]
        if wait_s is not None and futs:
            _fut_wait(futs, timeout=wait_s)

    def _poll_replica(self, v: _View):
        if _tm.enabled():
            _tm.counter("fleet.health_polls").inc()
        try:
            _fi.fire("fleet.health")
            # RPC timeout well under the rpc default: a slow replica's
            # snapshot just ages out, it must not tie up a poll slot
            cli = self._client(v)
            h = self._call(cli, "health",
                           rpc_timeout_s=min(5.0, max(0.5, self.stale_s)))
        except Exception:
            if _tm.enabled():
                _tm.counter("fleet.health_poll_errors").inc()
            with self._cond:
                self._poll_pending.discard(v.rid)
            return  # view ages out; staleness does the skipping
        if isinstance(cli, RpcClient) and cli.clock_offset_s is not None:
            with self._tel_lock:
                self._clock_offsets[v.rid] = (cli.clock_offset_s,
                                              cli.remote_pid)
        now = time.perf_counter()
        with self._cond:
            self._poll_pending.discard(v.rid)
            accepted = (self._views.get(v.rid) is v
                        and self._accept_snapshot(v, h, now))
            if accepted:
                if _tm.enabled():
                    _tm.gauge("fleet.replica.%s.queue_wait_ms"
                              % v.rid).set(
                        h.get("ewma_queue_wait_ms") or 0.0)
                self._cond.notify_all()
        if accepted and h.get("telemetry"):
            self._fold_telemetry(v.rid, h["telemetry"])

    def _fold_telemetry(self, rid, tel):
        """Fold one ACCEPTED delta-encoded replica snapshot into the
        fleet rollups. The staleness contract guarantees each snapshot
        folds at most once (every health() gets a fresh seq; replays are
        discarded before reaching here), so counters stay exact and
        histogram merges stay associative."""
        with self._tel_lock:
            for k, dv in (tel.get("counters") or {}).items():
                if isinstance(dv, (int, float)):
                    self._fleet_counters[k] = \
                        self._fleet_counters.get(k, 0) + dv
            for name, db in (tel.get("hist") or {}).items():
                self._fleet_hists[name] = _hg.merge_bucket_maps(
                    self._fleet_hists.get(name), db)
            per = self._replica_tel.setdefault(
                rid, {"counters": {}, "dropped": 0})
            for k, dv in (tel.get("counters") or {}).items():
                if isinstance(dv, (int, float)):
                    per["counters"][k] = per["counters"].get(k, 0) + dv
            per["dropped"] = tel.get("dropped", per["dropped"])

    def _poll_loop(self):
        while not self._stop:
            t0 = time.perf_counter()
            self._poll_once()
            if self._slo_monitor is not None:
                self._slo_tick()
            delay = self.health_interval_s - (time.perf_counter() - t0)
            if delay > 0:
                time.sleep(delay)

    def _slo_tick(self):
        """One SLO sample per poll round: request/error deltas since the
        last tick, the request-latency bucket delta, and an availability
        sample (any eligible replica?). Sheds are admission control, not
        server errors — they hit availability/throughput, not err_pct."""
        now = time.perf_counter()
        with self._cond:
            completed = self._counts["completed"]
            failed = self._counts["failed"]
            avail = 1.0 if self._eligible_locked(now) else 0.0
        buckets = self._req_hist.to_dict()["buckets"]
        last = self._slo_last
        d_done = completed - last["completed"]
        d_fail = failed - last["failed"]
        db = {k: v - last["buckets"].get(k, 0) for k, v in buckets.items()
              if v - last["buckets"].get(k, 0) > 0}
        self._slo_last = {"completed": completed, "failed": failed,
                          "buckets": buckets}
        self._slo_monitor.observe(total=d_done + d_fail, errors=d_fail,
                                  latency_buckets=db, available=avail)
        self._slo_status = self._slo_monitor.evaluate()

    def _invalidate(self, rid):
        """Mark a replica suspect after a transport fault: its view goes
        stale immediately so no new request picks it until a FRESH
        snapshot proves it back."""
        with self._cond:
            v = self._views.get(rid)
            if v is not None:
                v.received_t = 0.0

    # ------------------------------------------------------------- picking
    def _eligible_locked(self, now, exclude=()):
        healthy, degraded = [], []
        for v in self._views.values():
            if v.rid in exclude or v.rid in self._draining:
                continue
            if v.health is None or now - v.received_t > self.stale_s:
                continue
            state = v.health.get("state")
            if state == "healthy":
                healthy.append(v)
            elif state == "degraded":
                degraded.append(v)
        return healthy if healthy else degraded

    def _affinity_target(self, prefix_key):
        """Rendezvous (HRW) hash over the REGISTERED replica set: every
        router instance maps a prefix key to the same replica without
        coordination, and a membership change only remaps the keys that
        hashed to the departed replica. md5, not ``hash()`` — Python's
        string hash is per-process salted and would shatter the
        cross-router agreement this exists for."""
        best, best_score = None, None
        for rid in self._views:
            score = hashlib.md5(
                ("%s|%s" % (prefix_key, rid)).encode()).hexdigest()
            if best_score is None or score > best_score:
                best, best_score = rid, score
        return best

    def _pick_locked(self, now, exclude=(), prefix_key=None):
        """(view, est_wait_ms) of the best eligible replica, or (None,
        None). A prefix_key prefers its rendezvous-assigned replica IF
        that replica is currently eligible; otherwise — and for plain
        requests — lowest EWMA queue wait wins; in-flight count then
        round-robin break ties."""
        cands = self._eligible_locked(now, exclude)
        if not cands:
            return None, None
        if prefix_key is not None and self.affinity_enabled:
            target = self._affinity_target(prefix_key)
            for v in cands:
                if v.rid == target:
                    if _tm.enabled():
                        _tm.counter("fleet.affinity_hits").inc()
                    return v, v.health.get("ewma_queue_wait_ms") or 0.0
            # assigned replica is stale/unhealthy/draining/excluded:
            # health and freshness rules outrank page locality
            if _tm.enabled():
                _tm.counter("fleet.affinity_fallbacks").inc()
        self._rr += 1
        best, best_key = None, None
        for i, v in enumerate(cands):
            est = v.health.get("ewma_queue_wait_ms") or 0.0
            key = (round(est, 1), self._inflight.get(v.rid, 0),
                   (i + self._rr) % len(cands))
            if best_key is None or key < best_key:
                best, best_key = v, key
        return best, best.health.get("ewma_queue_wait_ms") or 0.0

    # -------------------------------------------------------------- submit
    def submit(self, inputs, deadline_ms=None,
               prefix_key=None) -> ServeFuture:
        """Enqueue one request for load-aware dispatch; returns a
        ``ServeFuture``. Sheds at admission (``ServeOverloadError`` with
        ``retry_after_ms``) when no replica is eligible or the best
        replica's wait estimate exceeds the deadline budget / shed cap.
        ``prefix_key`` (any stable string — normally the prompt's prefix
        chunk hash) opts the request into affinity dispatch: repeat
        keys land on the replica whose KV pages already hold them."""
        if deadline_ms is None and self.default_deadline_s is not None:
            deadline_ms = self.default_deadline_s * 1000.0
        dl_s = (float(deadline_ms) / 1000.0
                if deadline_ms and float(deadline_ms) > 0 else None)
        now = time.perf_counter()
        with self._cond:
            if self._stop or not self._started:
                raise MXNetError("fleet: router is not running")
            _, est = self._pick_locked(now)
            if est is None:
                self._counts["shed"] += 1
                shed_err = ServeOverloadError(
                    "fleet: no replica eligible (all dead, latched, "
                    "stale, or draining); retry after ~%dms"
                    % int(self.stale_s * 1000),
                    retry_after_ms=int(self.stale_s * 1000))
            elif (dl_s is not None and est > dl_s * 1000.0) or \
                    (self.shed_cap_ms is not None
                     and est > self.shed_cap_ms):
                self._counts["shed"] += 1
                shed_err = ServeOverloadError(
                    "fleet: saturated — best replica's queue-wait "
                    "estimate %.1fms exceeds %s; retry after ~%dms"
                    % (est,
                       "the %.0fms deadline" % (dl_s * 1000.0)
                       if dl_s is not None and est > dl_s * 1000.0
                       else "the %.0fms shed cap" % self.shed_cap_ms,
                       max(1, int(est))),
                    retry_after_ms=max(1, int(est)))
            elif len(self._queue) >= self.max_queue:
                # queue-full IS saturation backpressure: same error type
                # (and retry hint) as the estimate-driven shed, so
                # clients back off uniformly
                self._counts["shed"] += 1
                shed_err = ServeOverloadError(
                    "fleet: router queue full (%d requests); retry "
                    "after ~%dms" % (len(self._queue),
                                     max(1, int(est or 100))),
                    retry_after_ms=max(1, int(est or 100)))
            else:
                shed_err = None
            if shed_err is not None:
                if _tm.enabled():
                    _tm.counter("fleet.sheds").inc()
                raise shed_err
            req = _FleetRequest(
                inputs,
                deadline=None if dl_s is None else now + dl_s,
                deadline_ms=deadline_ms,
                # trace_id minted at admission (trace mode only): every
                # span this request touches — router dispatch, RPC frame,
                # replica engine/decoder — inherits it
                trace_id=(uuid.uuid4().hex[:16] if _tm.tracing()
                          else None),
                prefix_key=prefix_key)
            self._queue.append(req)
            self._counts["submitted"] += 1
            depth = len(self._queue)
            self._cond.notify_all()
        if _tm.enabled():
            _tm.gauge("fleet.queue_depth").set(depth)
        return req.future

    def infer(self, inputs, timeout=60.0, deadline_ms=None,
              prefix_key=None):
        return self.submit(inputs, deadline_ms=deadline_ms,
                           prefix_key=prefix_key).result(timeout=timeout)

    # ------------------------------------------------------------ dispatch
    def _worker_loop(self):
        while True:
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait(0.2)
                if self._stop:
                    return
                req = self._queue.popleft()
            try:
                self._dispatch_one(req)
            except BaseException as exc:  # a worker must never die silent
                if not req.future.done():
                    req.future.set_error(exc)

    def _dispatch_one(self, req: _FleetRequest):
        overload = None
        wait_deadline = req.t_enq + self.dispatch_wait_s
        while True:
            now = time.perf_counter()
            if req.deadline is not None and now >= req.deadline:
                req.future.set_error(ServeDeadlineError(
                    "fleet: deadline expired after %.1fms in the router "
                    "(%d dispatch attempt(s))"
                    % ((now - req.t_enq) * 1000.0, len(req.tried)),
                    queued_ms=(now - req.t_enq) * 1000.0))
                if _tm.enabled():
                    _tm.counter("fleet.deadline_expired").inc()
                return
            with self._cond:
                view, _ = self._pick_locked(now, exclude=req.tried,
                                            prefix_key=req.prefix_key)
                if view is None and req.tried:
                    # every replica tried once: forget the exclusions and
                    # allow a retried replica a second look (it may have
                    # recovered) as long as redispatch budget remains
                    view, _ = self._pick_locked(
                        now, prefix_key=req.prefix_key)
                if view is not None:
                    self._inflight[view.rid] = \
                        self._inflight.get(view.rid, 0) + 1
            if view is None:
                if now >= wait_deadline:
                    if overload is not None:
                        # the last word was a replica shed: this is
                        # saturation backpressure, not a dispatch failure
                        req.future.set_error(overload)
                        with self._cond:
                            self._counts["shed"] += 1
                        if _tm.enabled():
                            _tm.counter("fleet.sheds").inc()
                    else:
                        req.future.set_error(FleetDispatchError(
                            "fleet: no replica became eligible within "
                            "%.1fs (%d tried)" % (self.dispatch_wait_s,
                                                  len(req.tried))))
                        self._count_fail()
                    return
                time.sleep(min(0.05, self.health_interval_s))
                continue
            rid = view.rid
            req.tried.add(rid)
            try:
                timeout_s = self.rpc_timeout_s
                if req.deadline is not None:
                    timeout_s = min(timeout_s,
                                    max(0.05, req.deadline - now) + 5.0)
                # the time this request sat in the ROUTER queue, as a
                # trace span (start was observed on the submit thread)
                _tm.record_span("fleet.queue_wait", req.t_enq,
                                now - req.t_enq, trace_id=req.trace_id,
                                replica=rid)
                with _tm.trace_scope(req.trace_id), \
                        _tm.span("fleet.dispatch", replica=rid):
                    _fi.fire("fleet.dispatch")
                    # timeout_s is the REPLICA-side result wait; the
                    # socket bound sits strictly above it so the remote
                    # timeout error (not a transport cut) comes back
                    outs = self._call(self._client(view), "infer",
                                      rpc_timeout_s=timeout_s + 5.0,
                                      inputs=req.inputs,
                                      deadline_ms=req.deadline_ms,
                                      timeout_s=timeout_s)
            except (RpcConnectionError, _fi.FaultInjected, OSError) as exc:
                # transport-class fault: replica suspect; re-dispatch
                self._invalidate(rid)
                if req.redispatches < self.max_redispatch:
                    req.redispatches += 1
                    with self._cond:
                        self._counts["redispatched"] += 1
                    if _tm.enabled():
                        _tm.counter("fleet.redispatches").inc()
                    log.info("fleet: re-dispatching after fault on "
                             "replica %s (%s)", rid, exc)
                    continue
                req.future.set_error(FleetDispatchError(
                    "fleet: request failed after %d re-dispatches; last "
                    "replica %s fault: %s" % (req.redispatches, rid, exc)))
                self._count_fail()
                return
            except ServeOverloadError as exc:
                overload = exc  # that replica is saturated; try another
                if _tm.enabled():
                    _tm.counter("fleet.replica_overloads").inc()
                with self._cond:
                    untried = [v.rid for v in self._eligible_locked(
                        time.perf_counter()) if v.rid not in req.tried]
                if not untried:
                    # the WHOLE eligible fleet shed this request: the
                    # saturation is global — propagate the shed (with its
                    # retry_after_ms) instead of spinning on hot replicas
                    req.future.set_error(exc)
                    with self._cond:
                        self._counts["shed"] += 1
                    if _tm.enabled():
                        _tm.counter("fleet.sheds").inc()
                    return
                continue
            except ServeDeadlineError as exc:
                req.future.set_error(exc)  # terminal: the budget is spent
                if _tm.enabled():
                    _tm.counter("fleet.deadline_expired").inc()
                return
            except Exception as exc:
                # non-transport failure (validation, latched engine...):
                # terminal — replaying a request the replica REJECTED
                # would loop forever
                req.future.set_error(exc)
                self._count_fail()
                return
            finally:
                with self._cond:
                    n = self._inflight.get(rid, 1) - 1
                    self._inflight[rid] = max(0, n)
                    self._cond.notify_all()
            # books BEFORE the future resolves: a client that wakes on
            # set_result and immediately reads health() must already see
            # this delivery counted
            dur = time.perf_counter() - req.t_enq
            self._req_hist.record(dur)
            with self._cond:
                self._counts["completed"] += 1
            with self._tel_lock:
                self._per_replica_done[rid] = \
                    self._per_replica_done.get(rid, 0) + 1
            req.future.set_result(outs)
            if _tm.enabled():
                _tm.counter("fleet.dispatches").inc()
                _tm.timer("fleet.request").add(dur)
            return

    def _count_fail(self):
        with self._cond:
            self._counts["failed"] += 1
        if _tm.enabled():
            _tm.counter("fleet.dispatch_failures").inc()

    # ------------------------------------------------------------- rollout
    def rollout(self, arg_params, aux_params=None, drain_timeout_s=30.0,
                reload_timeout_s=120.0):
        """Fleet-wide hitless weight rollout, one replica at a time:
        drain → reload → verify → next. Returns {"applied": [rids],
        "skipped": [rids]} on success. On ANY failed swap the rollout
        ABORTS: replicas already swapped are rolled back (each kept its
        pre-swap snapshot), and ``FleetRolloutError`` is raised — old
        weights stay live fleet-wide. Replicas that are not currently
        eligible (dead/restarting) are SKIPPED, not failed: they reload
        from their spec's param file on restart, and the caller decides
        whether a partial fleet is acceptable (the result lists them)."""
        if not self._rollout_lock.acquire(blocking=False):
            raise FleetRolloutError("fleet: a rollout is already running")
        try:
            with _tm.span("fleet.rollout"):
                return self._rollout_locked(arg_params, aux_params,
                                            drain_timeout_s,
                                            reload_timeout_s)
        finally:
            self._rollout_lock.release()

    def _rollout_locked(self, arg_params, aux_params, drain_timeout_s,
                        reload_timeout_s):
        # refresh the fleet view NOW: a replica invalidated moments ago by
        # a transport blip (but alive) must be rolled out, not skipped
        self._poll_once(wait_s=3.0)
        now = time.perf_counter()
        with self._cond:
            targets = [v.rid for v in self._views.values()
                       if v.health is not None
                       and now - v.received_t <= self.stale_s]
            all_known = set(self._views)
        applied, skipped = [], sorted(all_known - set(targets))
        failure = None
        for rid in sorted(targets):
            with self._cond:
                self._draining.add(rid)
            try:
                if not self._wait_drained(rid, drain_timeout_s):
                    failure = (rid, MXNetError(
                        "fleet: replica %s did not drain within %.0fs"
                        % (rid, drain_timeout_s)))
                    break
                view = self._views.get(rid)
                if view is None:
                    skipped.append(rid)
                    continue
                ok = self._call(self._client(view), "reload",
                                rpc_timeout_s=reload_timeout_s + 10.0,
                                arg_params=arg_params,
                                aux_params=aux_params,
                                timeout_s=reload_timeout_s)
                if not ok:
                    failure = (rid, MXNetError(
                        "fleet: replica %s reload returned %r"
                        % (rid, ok)))
                    break
                applied.append(rid)
                if _tm.enabled():
                    _tm.counter("fleet.rollout_replicas").inc()
            except Exception as exc:
                failure = (rid, exc)
                break
            finally:
                with self._cond:
                    self._draining.discard(rid)
        if failure is None:
            if _tm.enabled():
                _tm.counter("fleet.rollouts").inc()
            return {"applied": applied, "skipped": skipped}
        # ---- abort: restore old weights on every already-swapped replica
        bad_rid, exc = failure
        rollback_failed = []
        for rid in applied:
            view = self._views.get(rid)
            try:
                if view is None:
                    raise MXNetError("replica %s vanished" % rid)
                self._call(self._client(view), "rollback",
                           rpc_timeout_s=reload_timeout_s + 10.0,
                           timeout_s=reload_timeout_s)
            except Exception as rexc:
                rollback_failed.append((rid, str(rexc)))
        if _tm.enabled():
            _tm.counter("fleet.rollout_aborts").inc()
        result = {"applied": [], "skipped": skipped,
                  "failed_replica": bad_rid,
                  "rolled_back": [r for r in applied
                                  if r not in
                                  [x[0] for x in rollback_failed]],
                  "rollback_failed": rollback_failed}
        raise FleetRolloutError(
            "fleet: rollout aborted at replica %s (%s: %s); %d "
            "already-swapped replica(s) rolled back to old weights%s"
            % (bad_rid, type(exc).__name__, exc, len(applied)
               - len(rollback_failed),
               "" if not rollback_failed else
               "; ROLLBACK FAILED on %s — restart those replicas"
               % [x[0] for x in rollback_failed]),
            result=result)

    def _wait_drained(self, rid, timeout_s):
        deadline = time.perf_counter() + timeout_s
        with self._cond:
            while self._inflight.get(rid, 0) > 0:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.2))
        return True

    # ------------------------------------------------------------- metrics
    def metrics(self):
        """Fleet-wide observability rollup (docs/OBSERVABILITY.md §Fleet):
        router books (qps, shed rate, redispatches), replica telemetry
        folded from the delta-encoded health() snapshots (counters +
        merged latency histograms with p50/p95/p99), per-replica rows
        with measured clock offsets, and — when an SLO spec is live —
        the burn-rate status and structured violation log. JSON-safe;
        ``serve_bench --fleet`` stamps it into the trace dump's
        ``otherData.fleet`` for ``mxtrace --fleet``."""
        now = time.perf_counter()
        with self._cond:
            counts = dict(self._counts)
            views = dict(self._views)
            fresh = [rid for rid, v in views.items()
                     if v.health is not None
                     and now - v.received_t <= self.stale_s]
        elapsed = max(1e-9, now - (self._t_start or now))
        with self._tel_lock:
            fleet_counters = dict(self._fleet_counters)
            fleet_hists = {k: dict(v)
                           for k, v in self._fleet_hists.items()}
            per_tel = {rid: {"counters": dict(d["counters"]),
                             "dropped": d.get("dropped", 0)}
                       for rid, d in self._replica_tel.items()}
            per_done = dict(self._per_replica_done)
            offsets = dict(self._clock_offsets)
        # the router's own request-latency histogram IS the fleet view of
        # submit→delivery (it brackets queue + rpc + replica service)
        fleet_hists["fleet.request"] = _hg.merge_bucket_maps(
            fleet_hists.get("fleet.request"),
            self._req_hist.to_dict()["buckets"])
        latency = {}
        for name, b in sorted(fleet_hists.items()):
            if not b:
                continue
            q = _hg.quantiles_from_buckets(b)
            latency[name] = {"count": sum(b.values()),
                             "p50": round(q.get("p50", 0.0), 3),
                             "p95": round(q.get("p95", 0.0), 3),
                             "p99": round(q.get("p99", 0.0), 3)}
        tokens = fleet_counters.get("serving.decode_tokens", 0)
        dispatches = (fleet_counters.get("serving.megasteps", 0)
                      or fleet_counters.get("serving.dispatches", 0))
        replicas = {}
        for rid, v in sorted(views.items()):
            off = offsets.get(rid)
            done = per_done.get(rid, 0)
            replicas[str(rid)] = {
                "state": (v.health or {}).get("state", "unknown"),
                "requests": done, "qps": round(done / elapsed, 3),
                "clock_offset_ms": round(
                    (off[0] if off else 0.0) * 1000.0, 3),
                "dropped": per_tel.get(rid, {}).get("dropped", 0)}
        attempts = counts["submitted"] + counts["shed"]
        out = {"qps": round(counts["completed"] / elapsed, 3),
               "requests": counts["completed"],
               "errors": counts["failed"],
               "shed": counts["shed"],
               "shed_rate": round(counts["shed"] / attempts, 4)
               if attempts else 0.0,
               "redispatches": counts["redispatched"],
               "submitted": counts["submitted"],
               "replicas_fresh": len(fresh),
               "tokens_per_dispatch": round(tokens / dispatches, 3)
               if tokens and dispatches else None,
               "elapsed_s": round(elapsed, 3),
               "latency_ms": latency,
               "counters": fleet_counters,
               "replicas": replicas,
               "dropped_events": (_tm.dropped_events()
                                  + sum(d.get("dropped", 0)
                                        for d in per_tel.values()))}
        if self._slo_monitor is not None:
            out["slo"] = self._slo_status or self._slo_monitor.evaluate()
            out["violations"] = self._slo_monitor.violations()
        return out

    def slo_violations(self):
        """Structured slo.violation/slo.clear events, oldest first
        (empty without an SLO spec)."""
        return ([] if self._slo_monitor is None
                else self._slo_monitor.violations())

    def collect_fleet_trace(self):
        """ONE merged fleet chrome trace: the router's own dump plus each
        reachable replica's (``dump_trace`` RPC), re-pidded and aligned
        onto the router's wall clock via the per-connection midpoint
        offsets. ``otherData.fleet`` carries ``metrics()``; unreachable
        replicas are skipped with a log line (their spans simply don't
        appear — the trace stays honest about ``dropped``)."""
        with self._cond:
            views = list(self._views.values())
        with self._tel_lock:
            off_by_rid = dict(self._clock_offsets)
        dumps = [_tm.build_trace(extra={"label": "router"})]
        labels = {os.getpid(): "router"}
        offsets = {}
        for v in views:
            try:
                d = self._call(self._client(v), "dump_trace",
                               rpc_timeout_s=10.0)
            except Exception as exc:
                log.warning("fleet: dump_trace from replica %s failed: "
                            "%s", v.rid, exc)
                continue
            if not isinstance(d, dict):
                continue
            pid = (d.get("otherData") or {}).get("pid")
            off = off_by_rid.get(v.rid)
            if pid is not None:
                labels[pid] = "replica-%s" % v.rid
                if off is not None:
                    offsets[pid] = off[0]
            dumps.append(d)
        merged = _tm.merge_traces(dumps, offsets_s=offsets,
                                  labels=labels)
        merged["otherData"]["fleet"] = self.metrics()
        return merged

    # -------------------------------------------------------------- health
    def health(self):
        """Aggregate fleet snapshot: per-replica state/freshness/wait +
        router counters."""
        now = time.perf_counter()
        with self._cond:
            reps = {}
            for rid, v in sorted(self._views.items()):
                fresh = (v.health is not None
                         and now - v.received_t <= self.stale_s)
                reps[rid] = {
                    "state": (v.health or {}).get("state", "unknown"),
                    "fresh": fresh,
                    "ewma_queue_wait_ms":
                        (v.health or {}).get("ewma_queue_wait_ms"),
                    "inflight": self._inflight.get(rid, 0),
                    "draining": rid in self._draining,
                }
            counts = dict(self._counts)
        eligible = [r for r, d in reps.items()
                    if d["fresh"] and not d["draining"]
                    and d["state"] in ("healthy", "degraded")]
        state = ("healthy" if any(reps[r]["state"] == "healthy"
                                  for r in eligible)
                 else "degraded" if eligible else "unavailable")
        return {"state": state, "replicas": reps,
                "eligible": len(eligible), "counts": counts}
