"""Replica worker process: ONE InferenceEngine behind an RPC endpoint
(docs/SERVING.md §Fleet).

Launched by ``ReplicaSupervisor`` as ``python -m
mxnet_tpu.serving.fleet.replica <spec.json>``. The spec names the model,
its per-item input shapes, the bucket ladder, and a ``.npz`` of trained
params; the process builds the model, warms + seals its executable cache,
starts the RPC server on an OS-assigned loopback port, and only THEN
commits its address to ``port_file`` (atomic write) — so the supervisor
never routes to a replica that has not finished compiling. Liveness is a
heartbeat file touched on a timer (the PR 7 ps-lite idiom: mtime IS the
signal; a wedged process stops touching it even though the PID exists).

RPC surface: ``ping`` / ``infer`` / ``health`` / ``reload`` /
``rollback`` / ``stop`` / ``dump_trace``. ``health`` additionally ships
a delta-encoded telemetry snapshot (counter + histogram-bucket
increments keyed to the engine seq) the router folds into fleet
rollups; ``dump_trace`` returns this process's chrome-trace dict for
``telemetry.merge_traces``. ``reload`` snapshots the prior values of every
key it is about to swap before applying the engine's hitless
``reload()`` — ``rollback`` restores that snapshot, which is what lets
the router abort a fleet-wide rollout and leave the OLD weights live
everywhere even on replicas that had already swapped.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import threading

import numpy as np

from ... import telemetry
from ...base import MXNetError
from .rpc import RpcServer

__all__ = ["ReplicaApp", "build_model", "save_params_npz",
           "load_params_npz", "main"]

_AUX_PREFIX = "aux:"


def save_params_npz(path, arg_params, aux_params=None):
    """Persist {name: array} arg/aux params into one npz the replica spec
    points at (aux keys carry an ``aux:`` prefix)."""
    flat = {n: np.asarray(getattr(v, "asnumpy", lambda: v)())
            for n, v in (arg_params or {}).items()}
    for n, v in (aux_params or {}).items():
        flat[_AUX_PREFIX + n] = np.asarray(
            getattr(v, "asnumpy", lambda: v)())
    np.savez(path, **flat)


def load_params_npz(path):
    with np.load(path) as z:
        arg, aux = {}, {}
        for n in z.files:
            if n.startswith(_AUX_PREFIX):
                aux[n[len(_AUX_PREFIX):]] = z[n]
            else:
                arg[n] = z[n]
    return arg, aux


def build_model(name, **kwargs):
    """Model-zoo symbol for a serving replica (mirrors serve_bench's
    builder so the bench and the fleet agree on model construction)."""
    from ... import models

    return models.get_symbol(name, **kwargs)


def _atomic_write(path, text):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


class ReplicaApp:
    """The replica process body; separable from ``main`` so tests can run
    a replica in-process (the serve_bench fleet harness uses real
    subprocesses)."""

    def __init__(self, spec):
        self.spec = spec
        self.replica_id = spec.get("replica_id", 0)
        self.engine = None
        self.server = None
        self._stop = threading.Event()
        self._hb_thread = None
        self._rollback_args = None
        self._rollback_aux = None
        # delta-encoding state for the health() telemetry snapshot: the
        # counter values / histogram buckets already shipped, so each
        # snapshot carries only the increment since the last one
        self._tel_lock = telemetry.named_lock("fleet.replica.telemetry")
        self._tel_last_counters = {}
        self._tel_last_buckets = {}

    # ------------------------------------------------------------- assembly
    def _build_engine(self):
        from ..cache import PersistentExecutableCache
        from ..engine import InferenceEngine

        spec = self.spec
        arg_params, aux_params = load_params_npz(spec["params"])
        net = build_model(spec["model"], **spec.get("model_kwargs", {}))
        cache = PersistentExecutableCache(
            net, arg_params, aux_params,
            cache_dir=spec.get("cache_dir"),
            model_key=spec.get("model_key")
            or "%s-r%s" % (spec["model"], self.replica_id))
        eng_kw = dict(spec.get("engine", {}))
        item_shapes = {n: tuple(s)
                       for n, s in spec["item_shapes"].items()}
        self.engine = InferenceEngine(
            cache, item_shapes,
            buckets=tuple(spec.get("buckets", (1, 2, 4, 8))),
            name="fleet-r%s" % self.replica_id, **eng_kw)
        self.engine.start()  # warms + seals before the port is published

    # ------------------------------------------------------------- handlers
    def _h_ping(self):
        return {"pid": os.getpid(), "replica_id": self.replica_id}

    def _h_infer(self, inputs, deadline_ms=None, timeout_s=60.0):
        fut = self.engine.submit(inputs, deadline_ms=deadline_ms)
        return fut.result(timeout=timeout_s)

    def _telemetry_snapshot(self):
        """Compact telemetry increment for health(): counter deltas and
        sparse histogram-bucket deltas since the LAST snapshot shipped.

        Delta encoding leans on the router's staleness contract: every
        ``health()`` bumps the engine seq, and ``_accept_snapshot``
        accepts a given seq at most once — so an accepted delta folds
        into the fleet rollup exactly once. A poll whose response is
        lost (or rejected as stale) drops that window's increments: the
        rollup skews low by one poll interval and self-heals on the
        next accepted snapshot — bounded, and the right trade against
        shipping full monotonic state every 100 ms."""
        if not telemetry.enabled():
            return None
        counters = telemetry.counters()
        buckets = telemetry.hist_buckets()
        with self._tel_lock:
            dc = {k: v - self._tel_last_counters.get(k, 0)
                  for k, v in counters.items()
                  if v - self._tel_last_counters.get(k, 0)}
            db = {}
            for name, b in buckets.items():
                prev = self._tel_last_buckets.get(name, {})
                d = {k: v - prev.get(k, 0) for k, v in b.items()
                     if v - prev.get(k, 0) > 0}
                if d:
                    db[name] = d
            self._tel_last_counters = counters
            self._tel_last_buckets = buckets
        return {"counters": dc, "hist": db,
                "dropped": telemetry.dropped_events()}

    def _h_health(self):
        h = self.engine.health()
        h["pid"] = os.getpid()
        h["replica_id"] = self.replica_id
        tel = self._telemetry_snapshot()
        if tel is not None:
            h["telemetry"] = tel
        return h

    def _h_dump_trace(self):
        """The replica's chrome-trace dict (router/serve_bench fetches
        one per replica and ``merge_traces`` aligns them)."""
        return telemetry.build_trace(
            extra={"label": "replica-%s" % self.replica_id})

    def _h_reload(self, arg_params, aux_params=None, timeout_s=60.0):
        # snapshot the PRIOR value of every key about to be swapped — the
        # rollout-abort path restores exactly these
        self._rollback_args, self._rollback_aux = \
            self.engine.cache.snapshot_params(
                list(arg_params or {}), list(aux_params or {}))
        ok = self.engine.reload(arg_params, aux_params).result(
            timeout=timeout_s)
        return bool(ok)

    def _h_rollback(self, timeout_s=60.0):
        if self._rollback_args is None and self._rollback_aux is None:
            raise MXNetError("fleet.replica: nothing to roll back "
                             "(no reload applied)")
        ok = self.engine.reload(self._rollback_args or {},
                                self._rollback_aux or None).result(
            timeout=timeout_s)
        self._rollback_args = self._rollback_aux = None
        return bool(ok)

    def _h_stop(self):
        self._stop.set()
        return True

    # ------------------------------------------------------------ lifecycle
    def _heartbeat_loop(self):
        path = self.spec["heartbeat_path"]
        interval = float(self.spec.get("heartbeat_ms", 500)) / 1000.0
        while not self._stop.is_set():
            try:
                with open(path, "a"):
                    os.utime(path, None)
            except OSError:
                pass
            self._stop.wait(interval)

    def start(self):
        # replica subprocesses do not inherit the parent's in-process
        # set_mode(): the spec carries the telemetry mode the fleet runs
        # under (serve_bench --check sets "trace")
        if self.spec.get("telemetry"):
            telemetry.set_mode(self.spec["telemetry"])
        self._build_engine()
        self.server = RpcServer({
            "ping": self._h_ping,
            "infer": self._h_infer,
            "health": self._h_health,
            "reload": self._h_reload,
            "rollback": self._h_rollback,
            "stop": self._h_stop,
            "dump_trace": self._h_dump_trace,
        }).start()
        if self.spec.get("heartbeat_path"):
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, name="fleet-heartbeat",
                daemon=True)
            self._hb_thread.start()
        # address committed LAST: a published replica can actually serve
        if self.spec.get("port_file"):
            _atomic_write(self.spec["port_file"], self.server.addr + "\n")
        return self

    def run_forever(self):
        try:
            while not self._stop.is_set():
                self._stop.wait(0.5)
        finally:
            self.close()

    def close(self):
        self._stop.set()
        if self.server is not None:
            self.server.stop()
        if self.engine is not None:
            try:
                self.engine.close(timeout=5.0, drain=False)
            except MXNetError:
                pass


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        sys.stderr.write(
            "usage: python -m mxnet_tpu.serving.fleet.replica <spec.json>\n")
        return 2
    with open(argv[0]) as f:
        spec = json.load(f)
    app = ReplicaApp(spec)
    signal.signal(signal.SIGTERM, lambda *_: app._stop.set())
    try:
        app.start()
    except BaseException as exc:  # the supervisor reads this breadcrumb
        sys.stderr.write("fleet.replica %s failed to start: %s: %s\n"
                         % (spec.get("replica_id"),
                            type(exc).__name__, exc))
        raise
    app.run_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
