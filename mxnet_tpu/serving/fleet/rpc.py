"""Minimal framed RPC for the serving fleet (docs/SERVING.md §Fleet).

One replica process = one ``RpcServer`` wrapping its ``InferenceEngine``;
the router and supervisor talk to it through ``RpcClient``. The protocol
is deliberately tiny: a 4-byte big-endian length prefix followed by a
pickled ``{"method": str, "kw": dict}`` request and a pickled
``{"ok": bool, "result"| "error"}`` response over a loopback TCP socket.
Pickle is acceptable here — and ONLY here — because both ends are the
same codebase run by the same user on the same host (the server binds
127.0.0.1 exclusively); numpy arrays ride through with zero translation
layers, and structured serving errors (``ServeOverloadError`` with its
``retry_after_ms``, ``ServeDeadlineError``) arrive on the router side as
the same exception types the in-process engine raises.

Failure semantics are the part that matters for the fleet: any socket
error (peer died, connection refused, recv timeout) surfaces as
``RpcConnectionError`` — the router's signal to mark the replica suspect
and RE-DISPATCH the in-flight request elsewhere. A request is therefore
never lost to a replica death; at-most-once execution is NOT promised
(inference is idempotent, so replay is safe), which is exactly the
trade the re-dispatch path wants.

Observability plane (docs/OBSERVABILITY.md §Fleet): every ``call()``
carries the caller's trace context in a ``trace`` field on the request
frame; the server installs it thread-local around the handler so replica
spans inherit the router-minted ``trace_id`` with no per-handler
plumbing. Each connection also measures the peer's wall-clock offset on
connect (and again after every reconnect) with the midpoint method —
``offset = server_wall - (send + recv) / 2``, median over a few round
trips — which ``telemetry.merge_traces`` uses to align per-process
chrome dumps onto one timeline.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time

from ... import telemetry
from ...base import MXNetError

__all__ = ["RpcServer", "RpcClient", "RpcError", "RpcConnectionError",
           "RpcRemoteError"]

_LEN = struct.Struct(">I")
_MAX_MSG = 1 << 30  # 1 GiB frame cap: a corrupt length prefix must not
#                     drive a multi-GiB allocation


class RpcError(MXNetError):
    """Base class for fleet RPC failures."""


class RpcConnectionError(RpcError):
    """Transport failure (peer dead / refused / timed out). The router
    treats this as 'replica suspect': re-dispatch, let the supervisor's
    heartbeat scan decide whether it is actually dead."""


class RpcRemoteError(RpcError):
    """The remote handler raised something that could not be pickled back
    verbatim; carries the remote repr."""


def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise RpcConnectionError("fleet.rpc: peer closed mid-message")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock):
    (n,) = _LEN.unpack(_recv_exact(sock, 4))
    if n > _MAX_MSG:
        raise RpcError("fleet.rpc: frame length %d exceeds cap" % n)
    return pickle.loads(_recv_exact(sock, n))


class RpcClient:
    """One persistent connection to a replica; thread-compatible but NOT
    thread-safe (the router gives each dispatch worker its own client so
    concurrent requests to one replica pipeline through separate
    connections). Reconnects lazily after any failure."""

    def __init__(self, addr, timeout_s=30.0, connect_timeout_s=2.0,
                 clock_samples=3):
        host, port = addr.rsplit(":", 1)
        self.addr = addr
        self._host, self._port = host, int(port)
        self.timeout_s = float(timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self._sock = None
        # midpoint clock-offset handshake: seconds to ADD to the peer's
        # wall clock to land on ours; None until a connection measured it
        # (re-measured on every reconnect, so drift across a replica
        # restart is picked up)
        self.clock_offset_s = None
        self.remote_pid = None
        self._clock_samples = int(clock_samples)

    def _measure_clock(self, s):
        """Median midpoint offset over a few __clock__ round trips.

        A server without the builtin answers with a clean unknown-method
        error frame (stream stays in sync) — the offset just stays
        unknown. A TRANSPORT failure mid-handshake leaves the stream
        desynchronized, so it escalates to ``RpcConnectionError`` like
        any other call-path failure."""
        offsets = []
        try:
            s.settimeout(self.connect_timeout_s)
            for _ in range(max(1, self._clock_samples)):
                t0 = time.time()
                _send_msg(s, {"method": "__clock__", "kw": {}})
                resp = _recv_msg(s)
                t1 = time.time()
                if not (isinstance(resp, dict) and resp.get("ok")):
                    return
                r = resp.get("result") or {}
                self.remote_pid = r.get("pid", self.remote_pid)
                offsets.append((t0 + t1) / 2.0 - r.get("wall", t0))
        except RpcError:
            try:
                s.close()
            except OSError:
                pass
            raise
        except (OSError, EOFError, pickle.UnpicklingError) as exc:
            try:
                s.close()
            except OSError:
                pass
            raise RpcConnectionError(
                "fleet.rpc: clock handshake with %s failed (%s: %s)"
                % (self.addr, type(exc).__name__, exc)) from exc
        offsets.sort()
        self.clock_offset_s = offsets[len(offsets) // 2]

    def _ensure(self):
        if self._sock is not None:
            return self._sock
        try:
            s = socket.create_connection(
                (self._host, self._port), timeout=self.connect_timeout_s)
        except OSError as exc:
            raise RpcConnectionError(
                "fleet.rpc: cannot connect to %s (%s)"
                % (self.addr, exc)) from exc
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._measure_clock(s)
        self._sock = s
        return s

    def call(self, method, rpc_timeout_s=None, **kw):
        """Invoke ``method`` on the replica; ``kw`` (including any
        ``timeout_s`` the remote HANDLER consumes) crosses the wire
        verbatim — ``rpc_timeout_s`` is this side's socket receive bound
        only, and callers that forward a handler timeout must size it
        strictly larger. Remote exceptions re-raise here as their
        original type (pickled through); transport failures — including a
        frame-cap violation, after which the stream is desynchronized —
        drop the connection and raise ``RpcConnectionError``/
        ``RpcError``."""
        sock = self._ensure()
        sock.settimeout(self.timeout_s if rpc_timeout_s is None
                        else float(rpc_timeout_s))
        req = {"method": method, "kw": kw}
        trace_id = telemetry.trace_context()
        if trace_id is not None:
            req["trace"] = {"id": trace_id}
        try:
            with telemetry.span("fleet.rpc", method=method,
                                addr=self.addr):
                _send_msg(sock, req)
                resp = _recv_msg(sock)
        except RpcError:
            self.close()  # incl. frame-cap: the stream is mid-payload
            raise
        except (OSError, EOFError, pickle.UnpicklingError) as exc:
            self.close()
            raise RpcConnectionError(
                "fleet.rpc: %s to %s failed in transport (%s: %s)"
                % (method, self.addr, type(exc).__name__, exc)) from exc
        if resp.get("ok"):
            return resp.get("result")
        err = resp.get("error")
        if isinstance(err, BaseException):
            raise err
        raise RpcRemoteError("fleet.rpc: %s on %s failed remotely: %s"
                             % (method, self.addr, err))

    def close(self):
        s, self._sock = self._sock, None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass


class RpcServer:
    """Loopback-only threaded RPC server: one daemon thread accepts, one
    per connection serves request/response frames until the peer hangs
    up. ``handlers`` maps method name -> callable(**kw)."""

    def __init__(self, handlers, host="127.0.0.1", port=0):
        self._handlers = dict(handlers)
        # clock-offset handshake builtin (RpcClient._measure_clock): the
        # peer's view of this process's wall clock + identity
        self._handlers.setdefault(
            "__clock__", lambda: {"wall": time.time(), "pid": os.getpid()})
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(64)
        self._host = host
        self._stop = threading.Event()
        self._accept_thread = None

    @property
    def port(self):
        return self._sock.getsockname()[1]

    @property
    def addr(self):
        return "%s:%d" % (self._host, self.port)

    def start(self):
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fleet-rpc-accept", daemon=True)
        self._accept_thread.start()
        return self

    def _accept_loop(self):
        self._sock.settimeout(0.25)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="fleet-rpc-conn", daemon=True).start()

    def _serve_conn(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while not self._stop.is_set():
                try:
                    req = _recv_msg(conn)
                except (RpcError, OSError, EOFError,
                        pickle.UnpicklingError):
                    return  # peer hung up / garbage: drop the connection
                method = req.get("method")
                fn = self._handlers.get(method)
                trace_id = (req.get("trace") or {}).get("id")
                if fn is None:
                    resp = {"ok": False,
                            "error": MXNetError(
                                "fleet.rpc: unknown method %r" % method)}
                else:
                    try:
                        # install the caller's trace context around the
                        # handler: spans recorded on this thread inherit
                        # the router-minted trace_id
                        with telemetry.trace_scope(trace_id):
                            resp = {"ok": True,
                                    "result": fn(**req.get("kw", {}))}
                    except BaseException as exc:  # noqa: BLE001 — every
                        # handler failure must cross back as a response,
                        # or the caller's recv would hang
                        try:
                            pickle.dumps(exc)
                            resp = {"ok": False, "error": exc}
                        except Exception:
                            resp = {"ok": False,
                                    "error": "%s: %s"
                                    % (type(exc).__name__, exc)}
                try:
                    _send_msg(conn, resp)
                except OSError:
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
