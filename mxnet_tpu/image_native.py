"""ctypes bindings for the native image pipeline (src/image_native.cc).

The C++ pipeline (threaded libjpeg/libpng decode → augment → batch,
reference: src/io/iter_image_recordio_2.cc:559) is compiled on first use
and cached under ``build/``; ``ImageRecordIter`` uses it
automatically when the requested augmentation set is expressible natively,
falling back to the Python/PIL path otherwise (or when
``MXNET_NATIVE_IMAGE_PIPELINE=0``).
"""
from __future__ import annotations

import ctypes
import os
import threading

import numpy as np

__all__ = ["available", "NativeImagePipeline"]

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_ROOT, "src", "image_native.cc")

_lib = None
_lock = threading.Lock()
_build_failed = False


def _load():
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        from ._native_build import build_lib

        path = build_lib(_SRC, "libmxtpu_image.so",
                         extra_flags=["-ljpeg", "-lpng"], opt="-O3")
        if path is None:
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(path)
        except Exception:
            _build_failed = True
            return None
        lib.mximg_open.restype = ctypes.c_void_p
        lib.mximg_open.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_int,
            ctypes.c_int, ctypes.c_ulonglong]
        lib.mximg_file_error.restype = ctypes.c_int
        lib.mximg_file_error.argtypes = [ctypes.c_void_p]
        lib.mximg_next_batch.restype = ctypes.c_int
        lib.mximg_next_batch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float)]
        lib.mximg_next_batch_aug.restype = ctypes.c_int
        lib.mximg_next_batch_aug.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float)]
        lib.mximg_reset.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.mximg_decode_errors.restype = ctypes.c_long
        lib.mximg_decode_errors.argtypes = [ctypes.c_void_p]
        lib.mximg_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available():
    return (os.environ.get("MXNET_NATIVE_IMAGE_PIPELINE", "1") != "0"
            and _load() is not None)


class NativeImagePipeline:
    """Batches of decoded+augmented CHW float32 images from a .rec file,
    produced entirely in C++ worker threads."""

    def __init__(self, path, batch_size, data_shape, num_workers=4,
                 resize=0, rand_crop=False, rand_mirror=False,
                 mean=(0.0, 0.0, 0.0), std=(1.0, 1.0, 1.0), label_width=1,
                 shuffle_buf=0, seed=0, idx_path=None):
        lib = _load()
        if lib is None:
            raise RuntimeError("native image pipeline unavailable")
        c, h, w = data_shape
        if c != 3:
            raise ValueError("native pipeline is RGB-only (C=3)")
        self._lib = lib
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._epoch = 0
        self._handle = lib.mximg_open(
            path.encode(), (idx_path or "").encode(), num_workers,
            batch_size, h, w, resize,
            int(bool(rand_crop)), int(bool(rand_mirror)),
            mean[0], mean[1], mean[2], std[0], std[1], std[2],
            label_width, shuffle_buf, seed)
        if not self._handle:
            raise IOError("cannot open %r" % path)
        self._data = np.empty((batch_size, c, h, w), np.float32)
        self._labels = np.empty((batch_size, label_width), np.float32)
        self._aug = np.empty((batch_size, 6), np.float32)

    def next_batch(self, with_aug=False):
        """(data, labels, n) — n < batch_size marks the epoch's tail; n == 0
        means exhausted. With ``with_aug``: (data, labels, aug, n) where aug
        is (batch, 6) float {pre-crop W, pre-crop H, crop x0, crop y0,
        mirror, true label length} per sample — the geometry a bbox-aware
        consumer (ImageDetIter) needs to transform detection labels. The
        returned arrays are reused between calls. Raises on mid-file
        corruption (the Python reader's invalid-magic contract)."""
        dp = self._data.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        lp = self._labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        if with_aug:
            n = self._lib.mximg_next_batch_aug(
                self._handle, dp, lp,
                self._aug.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        else:
            n = self._lib.mximg_next_batch(self._handle, dp, lp)
        if self._lib.mximg_file_error(self._handle):
            raise IOError("invalid RecordIO framing mid-file (corrupt .rec)")
        if with_aug:
            return self._data, self._labels, self._aug, int(n)
        return self._data, self._labels, int(n)

    def reset(self):
        self._epoch += 1
        self._lib.mximg_reset(self._handle, self._epoch)

    @property
    def decode_errors(self):
        return int(self._lib.mximg_decode_errors(self._handle))

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.mximg_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
