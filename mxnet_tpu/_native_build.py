"""One compile-if-stale helper for every native component.

All the runtime's C++ pieces (src/engine_native.cc, io_native.cc,
image_native.cc, predict_api.cc) share the same lifecycle: compile on first
use with the system toolchain, cache under build/, rebuild when the source
is newer, degrade gracefully (return None) when no compiler exists. The
publish is atomic (temp file + os.replace) so concurrent processes never
dlopen a half-written .so.
"""
from __future__ import annotations

import os
import subprocess

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BUILD_DIR = os.path.join(_ROOT, "build")


def source_path(name):
    return os.path.join(_ROOT, "src", name)


def build_lib(src, libname, extra_flags=(), opt="-O2", force=False):
    """Compile ``src`` (absolute path) into build/<libname> if stale.
    Returns the .so path, or None when the toolchain/compile fails.
    ``force`` rebuilds even when mtimes say fresh (compile inputs the
    staleness check can't see — e.g. a Python version switch)."""
    out = os.path.join(_BUILD_DIR, libname)
    try:
        if not force and os.path.isfile(out) and (
                not os.path.isfile(src)
                or os.path.getmtime(src) <= os.path.getmtime(out)):
            return out
        os.makedirs(_BUILD_DIR, exist_ok=True)
        tmp = out + ".%d.tmp" % os.getpid()
        subprocess.run(
            ["g++", "-std=c++17", opt, "-shared", "-fPIC", "-pthread", src,
             "-o", tmp] + list(extra_flags),
            check=True, capture_output=True)
        os.replace(tmp, out)
        return out
    except Exception:
        return None
