"""Persistent measure-and-cache autotuning for the fusion pattern engine.

TVM's thesis (PAPERS.md) applied to the pattern fuser: instead of a
hand-curated, committed WINS table per kernel family, every (pattern, shape,
dtype) site is MEASURED against its unfused baseline on first encounter —
fused and baseline run as standalone jitted computations on synthetic
inputs, forward and backward, exactly the PR 2 ``tools/fused_stats_bench.py``
contract — and the verdict (engage or not, winning lowering, measured µs,
backward policy) is persisted to a per-device-kind JSON cache so every later
run, in this process or any other, reuses it with zero re-tunes.

Cache layout: ``$MXNET_FUSION_TUNE_DIR/<device_kind>.json`` holding

    {"version": 2, "device_kind": ..., "digest": sha256(entries-json),
     "entries": {"<pattern>|<variant>|<sig>": {record}, ...}}

Schema v2 (this round) upgrades records from a binary engage/fallback
VERDICT to a measured SCHEDULE: candidate lowerings carry block-size/grid
variants (``name@k=v,...``), and the winning record stores the parsed
``schedule`` dict plus ``schedules_searched``. Version-1 files (PR 9's
binary verdicts) still LOAD — their records are valid verdicts for the
planner-default schedule, never re-tuned, never misread as a searched
winner (``schedule`` absent marks them). Files from an UNKNOWN (future)
version are invalidated with one warning, never a crash.

Writes are atomic (temp + ``os.replace``, the checkpoint.py discipline) and
merge-on-write, so concurrent processes tuning disjoint sites compose. A
corrupt or digest-mismatched file is IGNORED with a one-time warning —
never a crash, never a poisoned verdict; the next tune rewrites it whole.

Verdicts are device-generation-scoped by construction (one file per
``device_kind``): a cache tuned on v5e never gates a v4 run.

Gating env (docs/ENV_VARS.md):

- ``MXNET_FUSION_TUNE_DIR``  — cache directory; setting it ENABLES tuning.
- ``MXNET_FUSION_TUNE=0``    — kill-switch: never measure, never read.
- ``MXNET_FUSION_TUNE_ITERS``— timing iterations per measurement (default 10).
- ``MXNET_FUSION_TUNE_SCHEDULES`` — schedule-search width: how many
  block-size/grid variants each pattern may enumerate per candidate family
  beyond the planner-default (default 4); ``0`` restores the PR 9
  binary-verdict behavior (default candidate only).

Telemetry (docs/OBSERVABILITY.md): ``fusion.tune`` counts actual
measurements (a warm cache keeps this at zero), ``fusion.tune_cache_hit``
counts verdicts served from the cache.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import threading
import time

from . import telemetry as _tm

__all__ = ["enabled", "cache_dir", "device_kind", "lookup", "peek",
           "verdict", "measure_candidates", "synth_like", "reset",
           "cache_path", "entries_digest", "schedule_budget",
           "parse_schedule", "sched_name"]

log = logging.getLogger("mxnet_tpu")

_VERSION = 2
#: prior schema whose entries remain readable: PR 9's binary verdicts are
#: valid records for the planner-default schedule (no ``schedule`` field)
_COMPAT_VERSIONS = (1,)

_lock = threading.Lock()
# device_kind -> {key: record}; None means "not loaded yet"
_mem = {}
_warned_paths = set()


# ------------------------------------------------------------------- gating
def cache_dir():
    """The configured cache directory (``MXNET_FUSION_TUNE_DIR``), or None
    when persistence/tuning is off (the default)."""
    d = os.environ.get("MXNET_FUSION_TUNE_DIR", "").strip()
    return d or None


def enabled():
    """Whether the autotuner may MEASURE: a cache dir is configured and the
    kill-switch (``MXNET_FUSION_TUNE=0``) is not set."""
    if os.environ.get("MXNET_FUSION_TUNE", "auto").strip() == "0":
        return False
    return cache_dir() is not None


def tune_iters():
    try:
        return max(1, int(os.environ.get("MXNET_FUSION_TUNE_ITERS", "10")))
    except ValueError:
        return 10


def schedule_budget():
    """How many block-size/grid-shape variants each pattern may enumerate
    per candidate family beyond the planner-default candidate
    (``MXNET_FUSION_TUNE_SCHEDULES``, default 4). ``0`` = binary-verdict
    mode: only the planner-default schedule is measured (the PR 9
    contract)."""
    try:
        return max(0, int(os.environ.get("MXNET_FUSION_TUNE_SCHEDULES",
                                         "4")))
    except ValueError:
        return 4


def sched_name(base, **kv):
    """The canonical schedule-variant candidate name: ``base@k=v,...``
    (sorted keys, so the name is deterministic and round-trips through
    ``parse_schedule``)."""
    return "%s@%s" % (base, ",".join(
        "%s=%d" % (k, v) for k, v in sorted(kv.items())))


def parse_schedule(name):
    """The schedule dict a candidate name encodes (``base@k=v,...``), or
    ``"default"`` for a bare (planner-default) candidate name, or None for
    no lowering at all."""
    if not name:
        return None
    _, sep, tail = str(name).partition("@")
    if not sep:
        return "default"
    out = {}
    for item in tail.split(","):
        k, _, v = item.partition("=")
        try:
            out[k] = int(v)
        except ValueError:
            out[k] = v
    return out


def device_kind():
    """The current device generation (the cache scope)."""
    import jax

    try:
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def cache_path(kind=None):
    d = cache_dir()
    if d is None:
        return None
    kind = kind if kind is not None else device_kind()
    safe = re.sub(r"[^A-Za-z0-9._-]+", "_", str(kind)) or "unknown"
    return os.path.join(d, safe + ".json")


def entries_digest(entries):
    """The integrity digest over the canonical entries JSON. A hand-edited
    (or torn) cache file fails this check and is ignored — measured verdicts
    are trusted precisely because nothing else can masquerade as one."""
    blob = json.dumps(entries, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def reset():
    """Drop the in-process memo (tests). The on-disk cache is untouched."""
    with _lock:
        _mem.clear()
        _warned_paths.clear()


# ------------------------------------------------------------------ storage
def _warn_once(path, msg):
    if path not in _warned_paths:
        _warned_paths.add(path)
        log.warning("fusion_tune: ignoring cache file %s: %s", path, msg)


def _load_file(path, kind):
    """Entries from one cache file, or {} when absent/corrupt/mismatched."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except FileNotFoundError:
        return {}
    except (OSError, ValueError) as exc:
        _warn_once(path, "unreadable or not JSON (%s)" % exc)
        return {}
    version = payload.get("version") if isinstance(payload, dict) else None
    if version != _VERSION and version not in _COMPAT_VERSIONS:
        # a FUTURE (or garbage) schema: cleanly invalidate with one warning
        # — never a crash, and never a silently-misread winner
        _warn_once(path, "unknown schema version %r (this build reads "
                   "v%d and the compatible v%s)"
                   % (version if isinstance(payload, dict)
                      else type(payload).__name__, _VERSION,
                      "/v".join(str(v) for v in _COMPAT_VERSIONS)))
        return {}
    if payload.get("device_kind") != kind:
        _warn_once(path, "stamped for device_kind %r, this process runs %r"
                   % (payload.get("device_kind"), kind))
        return {}
    entries = payload.get("entries")
    if not isinstance(entries, dict):
        _warn_once(path, "entries missing or not a dict")
        return {}
    if payload.get("digest") != entries_digest(entries):
        _warn_once(path, "digest mismatch (torn write or hand edit)")
        return {}
    if version in _COMPAT_VERSIONS:
        # v1 (binary-verdict) records load as-is: engage/lowering/timings
        # keep their meaning, and the ABSENT ``schedule`` field marks them
        # as default-schedule verdicts — a warm run still does zero
        # re-tunes, and nothing misreports them as a searched winner
        log.info("fusion_tune: cache file %s is schema v%s (binary "
                 "verdicts); records load as default-schedule entries",
                 path, version)
    return entries


def _entries(kind):
    """The in-memory entry map for this device kind, loading the file once
    per process (warm-process verdicts never re-read the disk)."""
    ent = _mem.get(kind)
    if ent is None:
        path = cache_path(kind)
        ent = _load_file(path, kind) if path is not None else {}
        _mem[kind] = ent
    return ent


def _persist(kind, new_entries):
    """Merge ``new_entries`` into the on-disk file atomically. The
    read-merge-replace runs under an advisory flock on a sidecar lock file
    so concurrent PROCESSES tuning disjoint sites compose (without it, two
    simultaneous writers would each replace the other's fresh verdicts —
    a lost update the zero-retune contract cannot absorb); our fresh
    measurements win ties. In-process serialization comes from ``_lock``."""
    path = cache_path(kind)
    if path is None:
        return
    from .checkpoint import atomic_write_bytes

    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        lock_fd = None
        try:
            import fcntl

            lock_fd = os.open(path + ".lock", os.O_CREAT | os.O_RDWR, 0o644)
            fcntl.flock(lock_fd, fcntl.LOCK_EX)
        except (ImportError, OSError):
            pass  # best effort: no flock on this platform/filesystem
        try:
            merged = _load_file(path, kind)
            merged.update(new_entries)
            payload = {"version": _VERSION, "device_kind": kind,
                       "digest": entries_digest(merged), "entries": merged}
            atomic_write_bytes(path, json.dumps(
                payload, sort_keys=True, indent=1).encode())
            _mem[kind] = merged
        finally:
            if lock_fd is not None:
                os.close(lock_fd)  # closing releases the flock
    except OSError as exc:  # a read-only dir must not sink the step
        log.warning("fusion_tune: could not persist cache %s: %s", path, exc)


# ------------------------------------------------------------------ lookups
def peek(key):
    """The cached record for ``key`` (no telemetry, no measurement) — the
    explain path (``gate_explain``/GL302) reads rejected verdicts here."""
    if cache_dir() is None:
        return None
    kind = device_kind()
    with _lock:
        return _entries(kind).get(key)


def lookup(key):
    """The cached record for ``key``, counting ``fusion.tune_cache_hit``."""
    rec = peek(key)
    if rec is not None and _tm.enabled():
        _tm.counter("fusion.tune_cache_hit").inc()
    return rec


def verdict(key, measure):
    """The record for ``key``: cache hit, else (when tuning is enabled)
    measure NOW via ``measure()`` → record, persist, return. Returns None
    when no verdict exists and tuning is disabled.

    ``measure()`` returns the record dict (see ``measure_candidates``); a
    measurement failure is itself cached (``engage: False`` with the error)
    so a broken site costs one attempt per device kind, not one per trace.
    """
    rec = lookup(key)
    if rec is not None:
        return rec
    if not enabled():
        return None
    if _tm.enabled():
        _tm.counter("fusion.tune").inc()
    t0 = time.perf_counter()
    try:
        rec = measure()
    except Exception as exc:  # noqa: BLE001 — a tune failure must not sink a trace
        rec = {"engage": False, "lowering": None,
               "error": "%s: %s" % (type(exc).__name__, exc)}
    rec.setdefault("engage", False)
    rec["tune_s"] = round(time.perf_counter() - t0, 4)
    # schedule-search annotations (schema v2): the winner's parsed schedule
    # and how many schedule variants were actually timed at this site
    sched = parse_schedule(rec.get("lowering"))
    if sched is not None:
        rec["schedule"] = sched
    rec["schedules_searched"] = sum(
        1 for n in (rec.get("measured") or {}) if "@" in n)
    kind = device_kind()
    with _lock:
        _entries(kind)[key] = rec
        _persist(kind, {key: rec})
    return rec


# -------------------------------------------------------------- measurement
_ROUNDS = 3


def _prepare(fn, operands, iters):
    """A timed runner for ``iters`` executions of ``fn(*operands)`` inside
    one jitted scan (the fused_stats_bench discipline: the scan amortizes
    dispatch, the scalar fetch is the device barrier). ``operands`` are jit
    ARGUMENTS, never closure constants — XLA would constant-fold (or
    loop-hoist) the entire measured computation otherwise. The scan carry
    feeds the first element of every output back into the next iteration's
    probe so the body is loop-VARIANT: invariant code motion cannot lift
    the measured computation out of the loop. Compiles + warms up now; each
    call returns one amortized wall time."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    @jax.jit
    def many(*ops):
        def probe_of(out):
            leaves = [l for l in jax.tree_util.tree_leaves(out)
                      if hasattr(l, "ravel") and l.size]
            return sum(l.ravel()[0].astype(jnp.float32) for l in leaves)

        def body(carry, _):
            # fold the carry into the first floating leaf (one scalar add —
            # noise next to the measured op) so every iteration's inputs
            # depend on the previous iteration's outputs
            jitter = carry * jnp.float32(1e-30)
            leaves, treedef = jax.tree_util.tree_flatten(ops)
            salted, out = False, []
            for l in leaves:
                if (not salted and hasattr(l, "dtype") and hasattr(l, "size")
                        and l.size
                        and jnp.issubdtype(l.dtype, jnp.floating)):
                    out.append(l + jitter.astype(l.dtype))
                    salted = True
                else:
                    out.append(l)
            return probe_of(fn(*jax.tree_util.tree_unflatten(treedef, out))), None

        out, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), None,
                              length=iters)
        return out

    np.asarray(many(*operands))  # compile + warmup

    def run():
        t0 = time.perf_counter()
        np.asarray(many(*operands))
        return (time.perf_counter() - t0) / iters

    return run


def synth_like(args, seed=0):
    """Concrete standard-normal arrays matching ``args``' shapes/dtypes.

    A gate invoked MID jit-trace holds TRACERS for the site's real inputs —
    those cannot be timed (and must not leak into the eager measurement),
    so the measurement runs on synthetic data of the same contract."""
    import numpy as np

    rs = np.random.RandomState(seed)
    return tuple(rs.randn(*[int(d) for d in a.shape]).astype(
        np.dtype(a.dtype)) for a in args)


def _rel_err(a, b):
    """Max relative error over corresponding pytree leaves (an output may
    be a tuple — e.g. conv_block's (c, Σc, Σc²))."""
    import jax
    import jax.numpy as jnp

    worst = 0.0
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        a32 = la.astype(jnp.float32)
        b32 = lb.astype(jnp.float32)
        denom = float(jnp.max(jnp.abs(b32))) + 1e-9
        worst = max(worst, float(jnp.max(jnp.abs(a32 - b32))) / denom)
    return worst


def min_speedup():
    """The fused-vs-baseline margin a candidate must clear to engage
    (``MXNET_FUSION_TUNE_MIN_SPEEDUP``, default 1.05): a 5% guard band so
    timer noise cannot flip a neutral site into a phantom win."""
    try:
        return float(os.environ.get("MXNET_FUSION_TUNE_MIN_SPEEDUP", "1.05"))
    except ValueError:
        return 1.05


def measure_candidates(baseline, candidates, args, train=True, iters=None,
                       rel_tol=2e-2, margin=None):
    """Measure ``candidates`` ([(name, fn)]) against ``baseline`` on the
    concrete ``args``, forward and (``train``) backward, and return the
    verdict record.

    Every fn maps ``*args -> array`` (or pytree). The backward times the
    jax.vjp closure with ones-cotangents — residuals resident, exactly a
    training step's backward. All timers run in INTERLEAVED rounds
    (baseline, cand1, cand2, baseline, ...; min per fn) so host-speed drift
    hits every contestant equally. A candidate is eligible when its outputs
    AND grads stay within ``rel_tol`` of baseline; it wins when its fwd+bwd
    time beats baseline by the ``margin`` (default ``min_speedup()``).
    Record fields: ``engage``, ``lowering``, ``base_fwd_us``/
    ``fused_fwd_us``, ``base_bwd_us``/``fused_bwd_us``, ``engage_fwd`` (the
    inference gate: forward-only win), per-candidate ``measured`` rows.

    Runs in a FRESH THREAD: JAX trace state is thread-local, so a gate
    invoked MID jit-trace (the usual case — gates fire while the training
    step is being traced) still measures at top level, with real compiled
    executions; neither ``ensure_compile_time_eval`` (which cannot nest
    vjp-inside-jit) nor the ambient trace is involved.
    """
    box = {}

    def work():
        try:
            box["rec"] = _measure_impl(baseline, candidates, args, train,
                                       iters, rel_tol, margin)
        except BaseException as exc:  # noqa: BLE001 — re-raised on the caller thread
            box["exc"] = exc

    t = threading.Thread(target=work, name="fusion-tune-measure")
    t.start()
    t.join()
    if "exc" in box:
        raise box["exc"]
    return box["rec"]


def _measure_impl(baseline, candidates, args, train, iters, rel_tol,
                  margin):
    import jax
    import jax.numpy as jnp

    iters = iters if iters is not None else tune_iters()
    margin = margin if margin is not None else min_speedup()

    args = tuple(jnp.asarray(a) for a in args)

    def prepare(fn):
        """(fwd_runner, fwdbwd_runner_or_None) for one contestant. The
        backward is timed as a self-contained fwd+bwd program (vjp
        taken INSIDE the jitted runner over argument-passed operands —
        a pre-built vjp closure would ride in as foldable constants),
        so the reported bwd time is (fwd+bwd) − fwd."""
        runners = [_prepare(fn, args, iters)]
        if train:
            out = fn(*args)
            cts = jax.tree_util.tree_map(jnp.ones_like, out)

            def fwdbwd(*ops):
                a, c = ops[:-1], ops[-1]
                _, vjp_fn = jax.vjp(fn, *a)
                return vjp_fn(c)

            runners.append(_prepare(fwdbwd, args + (cts,), iters))
        else:
            runners.append(None)
        return runners

    def grads(fn):
        out, vjp_fn = jax.vjp(fn, *args)
        cts = jax.tree_util.tree_map(jnp.ones_like, out)
        return out, vjp_fn(cts)

    out_ref, g_ref = grads(baseline) if train else (baseline(*args), ())
    rec = {"engage": False, "engage_fwd": False, "lowering": None,
           "iters": iters, "train": bool(train), "measured": {}}
    table = [("__baseline__", prepare(baseline))]
    errs = {}
    for name, fn in candidates:
        try:
            runners = prepare(fn)
            if train:
                out, g = grads(fn)
                err = max([_rel_err(out, out_ref)]
                          + [_rel_err(a, b) for a, b in zip(g, g_ref)])
            else:
                err = _rel_err(fn(*args), out_ref)
            errs[name] = err
            table.append((name, runners))
        except Exception as exc:  # noqa: BLE001 — one bad candidate ≠ no verdict
            rec["measured"][name] = {
                "error": "%s: %s" % (type(exc).__name__, exc)}
    times = {name: [float("inf"), float("inf")] for name, _ in table}
    for _ in range(_ROUNDS):
        for name, runners in table:
            times[name][0] = min(times[name][0], runners[0]())
            if runners[1] is not None:
                times[name][1] = min(times[name][1], runners[1]())
    b_fwd, b_tot = times["__baseline__"]
    b_bwd = max(b_tot - b_fwd, 0.0) if train else 0.0
    rec["base_fwd_us"] = round(b_fwd * 1e6, 2)
    if train:
        rec["base_bwd_us"] = round(b_bwd * 1e6, 2)
    best = best_fwd = None
    for name, _ in table[1:]:
        f_fwd, f_tot = times[name]
        f_bwd = max(f_tot - f_fwd, 0.0) if train else 0.0
        err = errs[name]
        row = {"fwd_us": round(f_fwd * 1e6, 2),
               "rel_err": round(err, 6)}
        if train:
            row["bwd_us"] = round(f_bwd * 1e6, 2)
        if err <= rel_tol:
            total = f_tot if train else f_fwd
            base_total = b_tot if train else b_fwd
            if (base_total / total >= margin
                    and (best is None or total < best[0])):
                best = (total, name, f_fwd, f_bwd, err)
            if (b_fwd / f_fwd >= margin
                    and (best_fwd is None or f_fwd < best_fwd[0])):
                best_fwd = (f_fwd, name)
        else:
            row["rejected"] = "parity (rel_err %.2g > %.2g)" % (
                err, rel_tol)
        rec["measured"][name] = row
    if best is not None:
        _, name, f_fwd, f_bwd, err = best
        rec.update({"engage": True, "lowering": name,
                    "fused_fwd_us": round(f_fwd * 1e6, 2),
                    "rel_err": round(err, 6)})
        if train:
            rec["fused_bwd_us"] = round(f_bwd * 1e6, 2)
    if best_fwd is not None:
        rec["engage_fwd"] = True
        rec.setdefault("lowering_fwd", best_fwd[1])
    return rec
