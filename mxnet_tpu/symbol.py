"""Symbol: declarative graph composition.

TPU-native redesign of the reference's Symbol layer (nnvm ``Symbol`` +
python/mxnet/symbol.py). The reference builds an nnvm::Graph and runs C++
passes (InferShape/InferType, reference src/executor/graph_executor.cc:423-424);
here a Symbol is a lightweight Python DAG over the single op registry, and
shape/type inference *is* ``jax.eval_shape`` over each op's JAX function —
the op implementation is the one source of truth, exactly how XLA wants
tracing to work. Backward-flowing parameter shapes (FC weights etc.) come
from declarative rules in ``ops/shape_rules.py``.

Graph JSON save/load keeps the reference's ``*-symbol.json`` nnvm format
(nodes / arg_nodes / heads / node_row_ptr; python/mxnet/symbol.py:745-769)
so checkpoints interoperate.
"""
from __future__ import annotations

import builtins
import functools
import json
import sys
from typing import Dict, List, Optional, Tuple

import numpy as np

from .attribute import AttrScope
from .base import MXNetError, np_dtype
from .context import current_context
from .name import NameManager
from .ops import registry as _registry
from .ops.registry import get_op, parse_attrs
from .ops.shape_rules import RULES as _SHAPE_RULES

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json", "pow", "maximum", "minimum"]


class _Node:
    """One graph node: an operator application or a variable (op=None)."""

    __slots__ = ("op", "name", "attrs", "inputs", "_parsed")

    def __init__(self, op: Optional[str], name: str, attrs: dict, inputs):
        self.op = op  # canonical registry name, or None for variables
        self.name = name
        self.attrs = dict(attrs or {})
        self.inputs = list(inputs)  # list[(node, out_index)]
        self._parsed = None

    @property
    def is_variable(self):
        return self.op is None

    def parsed_attrs(self) -> dict:
        if self._parsed is None:
            self._parsed = parse_attrs(get_op(self.op), self.attrs) if self.op else {}
        return self._parsed

    def opdef(self):
        return get_op(self.op)

    def num_outputs(self) -> int:
        if self.op is None:
            return 1
        return self.opdef().num_outputs(self.parsed_attrs())


def _topo_order(head_nodes) -> List[_Node]:
    """Iterative post-order DFS preserving input order (nnvm DFSVisit)."""
    order: List[_Node] = []
    visited = set()
    stack = [(n, False) for n in reversed(head_nodes)]
    while stack:
        node, expanded = stack.pop()
        if id(node) in visited:
            continue
        if expanded:
            visited.add(id(node))
            order.append(node)
        else:
            stack.append((node, True))
            for inp, _ in reversed(node.inputs):
                if id(inp) not in visited:
                    stack.append((inp, False))
    return order


def _aux_positions(node: _Node) -> int:
    """Number of trailing inputs of ``node`` that are aux states."""
    if node.op is None:
        return 0
    return len(node.opdef().aux_names(node.parsed_attrs()))


class Symbol:
    """A list of output entries over the graph (reference: nnvm Symbol)."""

    __slots__ = ("_outputs",)

    def __init__(self, outputs):
        self._outputs = list(outputs)  # list[(node, out_index)]

    # ------------------------------------------------------------- structure
    @property
    def name(self):
        if len(self._outputs) != 1:
            return None
        return self._outputs[0][0].name

    def __repr__(self):
        name = self.name
        return "<Symbol %s>" % (name if name else "Grouped")

    def __iter__(self):
        return (Symbol([o]) for o in self._outputs)

    def __len__(self):
        return len(self._outputs)

    def _head_nodes(self):
        seen, heads = set(), []
        for node, _ in self._outputs:
            if id(node) not in seen:
                seen.add(id(node))
                heads.append(node)
        return heads

    def _topo(self) -> List[_Node]:
        return _topo_order(self._head_nodes())

    def _classified_variables(self):
        """Topo-ordered (args, auxs) variable name lists. A variable feeding an
        aux slot of any consumer is an auxiliary state (the reference derives
        this from FMutateInputs, src/nnvm/legacy_op_util.cc)."""
        topo = self._topo()
        aux_vars = set()
        for node in topo:
            n_aux = _aux_positions(node)
            if n_aux:
                for inp, _ in node.inputs[len(node.inputs) - n_aux :]:
                    if inp.is_variable:
                        aux_vars.add(id(inp))
        args, auxs = [], []
        for node in topo:
            if node.is_variable:
                (auxs if id(node) in aux_vars else args).append(node)
        return args, auxs

    def list_arguments(self) -> List[str]:
        args, _ = self._classified_variables()
        return [n.name for n in args]

    def list_auxiliary_states(self) -> List[str]:
        _, auxs = self._classified_variables()
        return [n.name for n in auxs]

    def list_inputs(self) -> List[str]:
        return [n.name for n in self._topo() if n.is_variable]

    def list_outputs(self) -> List[str]:
        out = []
        for node, idx in self._outputs:
            if node.is_variable:
                out.append(node.name)
            else:
                out.append("%s_%s" % (node.name, node.opdef().output_names(node.parsed_attrs())[idx]))
        return out

    def get_internals(self) -> "Symbol":
        """All intermediate outputs as a grouped symbol (reference:
        symbol.py get_internals)."""
        outs = []
        for node in self._topo():
            for i in range(node.num_outputs()):
                outs.append((node, i))
        return Symbol(outs)

    def get_children(self) -> Optional["Symbol"]:
        outs = []
        for node in self._head_nodes():
            outs.extend(node.inputs)
        return Symbol(outs) if outs else None

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError("cannot find output %r in %s" % (index, names))
            index = names.index(index)
        # NB: builtins — module-level op functions shadow names like `slice`
        if isinstance(index, builtins.slice):
            return Symbol(self._outputs[index])
        return Symbol([self._outputs[index]])

    # ------------------------------------------------------------------ attrs
    def attr(self, key):
        if len(self._outputs) != 1:
            raise MXNetError("attr() requires a single-output symbol")
        v = self._outputs[0][0].attrs.get(key)
        return None if v is None else str(v)

    def list_attr(self):
        if len(self._outputs) != 1:
            raise MXNetError("list_attr() requires a single-output symbol")
        return {k: str(v) for k, v in self._outputs[0][0].attrs.items()}

    def attr_dict(self):
        return {n.name: {k: str(v) for k, v in n.attrs.items()} for n in self._topo() if n.attrs}

    def _set_attr(self, **kwargs):
        for node, _ in self._outputs:
            node.attrs.update({k: str(v) for k, v in kwargs.items()})
            node._parsed = None

    # -------------------------------------------------------------- arithmetic
    def _binary(self, other, op, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _create(op, [a, b], {})
        return _create(scalar_op, [self], {"scalar": float(other)})

    def __add__(self, other):
        return self._binary(other, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, other):
        return self._binary(other, "elemwise_sub", "_rminus_scalar", reverse=True)

    def __mul__(self, other):
        return self._binary(other, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "elemwise_div", "_div_scalar")

    def __rtruediv__(self, other):
        return self._binary(other, "elemwise_div", "_rdiv_scalar", reverse=True)

    __div__ = __truediv__
    __rdiv__ = __rtruediv__

    def __pow__(self, other):
        if isinstance(other, Symbol):
            return _create("_power", [self, other], {})
        return _create("_power_scalar", [self], {"scalar": float(other)})

    def __neg__(self):
        return _create("negative", [self], {})

    def __copy__(self):
        return Symbol(list(self._outputs))

    def __eq__(self, other):
        if isinstance(other, (Symbol, int, float)):
            return self._binary(other, "_equal", "_equal_scalar")
        return NotImplemented

    def __ne__(self, other):
        if isinstance(other, (Symbol, int, float)):
            return self._binary(other, "_not_equal", "_not_equal_scalar")
        return NotImplemented

    def __gt__(self, other):
        return self._binary(other, "_greater", "_greater_scalar")

    def __ge__(self, other):
        return self._binary(other, "_greater_equal", "_greater_equal_scalar")

    def __lt__(self, other):
        return self._binary(other, "_lesser", "_lesser_scalar")

    def __le__(self, other):
        return self._binary(other, "_lesser_equal", "_lesser_equal_scalar")

    __hash__ = object.__hash__

    # -------------------------------------------------------------- inference
    def _resolve_kwargs_shapes(self, args, kwargs):
        known = {}
        if args:
            arg_names = self.list_arguments()
            for name, sh in zip(arg_names, args):
                if sh is not None:
                    known[name] = tuple(sh)
        for k, v in kwargs.items():
            if v is not None:
                known[k] = tuple(v)
        return known

    def infer_shape(self, *args, **kwargs):
        """Infer shapes of arguments/outputs/aux states. Returns
        (arg_shapes, out_shapes, aux_shapes); (None, None, None) when
        underdetermined (reference: symbol.py:597 infer_shape)."""
        try:
            arg_s, out_s, aux_s = self._infer_impl(self._resolve_kwargs_shapes(args, kwargs), {}, partial=False)[:3]
            return arg_s, out_s, aux_s
        except _IncompleteInference:
            return None, None, None

    def infer_shape_partial(self, *args, **kwargs):
        arg_s, out_s, aux_s = self._infer_impl(self._resolve_kwargs_shapes(args, kwargs), {}, partial=True)[:3]
        return arg_s, out_s, aux_s

    def infer_type(self, *args, **kwargs):
        known = {}
        if args:
            for name, t in zip(self.list_arguments(), args):
                if t is not None:
                    known[name] = np_dtype(t)
        for k, v in kwargs.items():
            if v is not None:
                known[k] = np_dtype(v)
        # dtype inference must work without shapes (reference: infer_type is
        # independent of infer_shape) — partial mode falls back to dtype
        # promotion rules where eval_shape can't run
        res = self._infer_impl({}, known, partial=True)
        return res[3], res[4], res[5]

    def _infer_impl(self, shape_hints: dict, type_hints: dict, partial: bool):
        """Single pass computing shapes+dtypes for every graph entry."""
        topo = self._topo()
        args, auxs = self._classified_variables()
        entries_shape: Dict[Tuple[int, int], Optional[tuple]] = {}
        entries_dtype: Dict[Tuple[int, int], Optional[np.dtype]] = {}
        var_shape: Dict[str, Optional[tuple]] = {}
        var_dtype: Dict[str, Optional[np.dtype]] = {}

        for node in topo:
            if node.is_variable:
                sh = shape_hints.get(node.name)
                if sh is None and "__shape__" in node.attrs:
                    sh = _parse_shape_attr(node.attrs["__shape__"])
                dt = type_hints.get(node.name)
                if dt is None and "__dtype__" in node.attrs:
                    dt = np_dtype(node.attrs["__dtype__"])
                var_shape[node.name] = tuple(sh) if sh is not None else None
                var_dtype[node.name] = dt

        for node in topo:
            if node.is_variable:
                entries_shape[(id(node), 0)] = var_shape[node.name]
                entries_dtype[(id(node), 0)] = var_dtype[node.name]
                continue
            parsed = node.parsed_attrs()
            in_entries = [(id(n), i) for n, i in node.inputs]
            in_shapes = [entries_shape.get(e) for e in in_entries]
            rule = _SHAPE_RULES.get(node.op)
            if rule is not None and any(s is None for s in in_shapes):
                filled = rule(parsed, list(in_shapes))
                for (inp, out_i), old, new in zip(node.inputs, in_shapes, filled):
                    if old is None and new is not None:
                        new = tuple(int(x) for x in new)
                        entries_shape[(id(inp), out_i)] = new
                        if inp.is_variable:
                            if var_shape.get(inp.name) is not None and var_shape[inp.name] != new:
                                raise MXNetError(
                                    "inferred shape %s for %r conflicts with %s"
                                    % (new, inp.name, var_shape[inp.name])
                                )
                            var_shape[inp.name] = new
                in_shapes = [entries_shape.get(e) for e in in_entries]
            in_dtypes = [entries_dtype.get(e) for e in in_entries]
            if any(s is None for s in in_shapes):
                if partial:
                    # shapes unknown: still propagate dtypes by promotion so
                    # infer_type works standalone (Cast/creation ops override)
                    dt = _fallback_dtype(node, parsed, in_dtypes)
                    # inputs take the promotion of the KNOWN inputs — never the
                    # output dtype, which dtype-forcing ops (Cast) decouple
                    known_in = [d for d in in_dtypes if d is not None]
                    in_promo = np.dtype(np.result_type(*known_in)) if known_in else None
                    for (inp, _), d in zip(node.inputs, in_dtypes):
                        if inp.is_variable and var_dtype.get(inp.name) is None and in_promo is not None:
                            var_dtype[inp.name] = in_promo
                            entries_dtype[(id(inp), 0)] = in_promo
                    for i in range(node.num_outputs()):
                        entries_shape[(id(node), i)] = None
                        entries_dtype[(id(node), i)] = dt
                    continue
                missing = [
                    node.inputs[i][0].name
                    for i, s in enumerate(in_shapes)
                    if s is None and node.inputs[i][0].is_variable
                ]
                raise _IncompleteInference(
                    "cannot infer shapes at node %r (op %s): unknown inputs %s"
                    % (node.name, node.op, missing)
                )
            # unknown dtypes default to float32 (the reference's default_dtype)
            in_dtypes = [np.dtype(np.float32) if d is None else d for d in in_dtypes]
            for (inp, out_i), d in zip(node.inputs, in_dtypes):
                if inp.is_variable and var_dtype.get(inp.name) is None:
                    var_dtype[inp.name] = d
                    entries_dtype[(id(inp), 0)] = d
            out_structs = _eval_node_shape(
                node.op,
                _freeze(parsed),
                tuple(in_shapes),
                tuple(str(d) for d in in_dtypes),
                _aux_positions(node),
            )
            for i, st in enumerate(out_structs[: node.num_outputs()]):
                entries_shape[(id(node), i)] = tuple(st[0])
                entries_dtype[(id(node), i)] = np.dtype(st[1])

        def _var_results(var_nodes):
            return (
                [var_shape.get(n.name) for n in var_nodes],
                [var_dtype.get(n.name) or np.dtype(np.float32) for n in var_nodes],
            )

        arg_shapes, arg_types = _var_results(args)
        aux_shapes, aux_types = _var_results(auxs)
        out_shapes = [entries_shape.get((id(n), i)) for n, i in self._outputs]
        out_types = [entries_dtype.get((id(n), i)) for n, i in self._outputs]
        if not partial and any(s is None for s in arg_shapes + out_shapes + aux_shapes):
            missing = [n.name for n, s in zip(args, arg_shapes) if s is None]
            raise _IncompleteInference("underdetermined shapes for arguments %s" % missing)
        return arg_shapes, out_shapes, aux_shapes, arg_types, out_types, aux_types

    # --------------------------------------------------------------- binding
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None, group2ctx=None, **kwargs):
        from .executor import simple_bind as _sb

        return _sb(self, ctx or current_context(), grad_req=grad_req, type_dict=type_dict, group2ctx=group2ctx, **kwargs)

    def bind(self, ctx, args, args_grad=None, grad_req="write", aux_states=None, group2ctx=None, shared_exec=None):
        from .executor import bind as _bind

        return _bind(self, ctx, args, args_grad=args_grad, grad_req=grad_req, aux_states=aux_states, shared_exec=shared_exec, group2ctx=group2ctx)

    def eval(self, ctx=None, **kwargs):
        """One-shot forward on NDArray kwargs (reference: symbol.py eval)."""
        ex = self.bind(ctx or current_context(), kwargs)
        return ex.forward(is_train=False)

    # ------------------------------------------------------------------ JSON
    def tojson(self) -> str:
        topo = self._topo()
        ids = {id(n): i for i, n in enumerate(topo)}
        nodes = []
        arg_nodes = []
        row_ptr = [0]
        for n in topo:
            entry = {
                "op": n.op if n.op else "null",
                "name": n.name,
                "inputs": [[ids[id(inp)], oi, 0] for inp, oi in n.inputs],
            }
            if n.attrs:
                entry["attr"] = {k: str(v) for k, v in n.attrs.items()}
            nodes.append(entry)
            if n.op is None:
                arg_nodes.append(ids[id(n)])
            row_ptr.append(row_ptr[-1] + n.num_outputs())
        graph = {
            "nodes": nodes,
            "arg_nodes": arg_nodes,
            "node_row_ptr": row_ptr,
            "heads": [[ids[id(n)], i, 0] for n, i in self._outputs],
            "attrs": {"mxnet_version": ["int", 905]},
        }
        return json.dumps(graph, indent=2)

    def save(self, fname: str):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # ------------------------------------------------------------- debug info
    def debug_str(self) -> str:
        lines = []
        for n in self._topo():
            if n.is_variable:
                lines.append("Variable:%s" % n.name)
            else:
                ins = ", ".join("%s[%d]" % (inp.name, oi) for inp, oi in n.inputs)
                lines.append("Op:%s, Name=%s\nInputs:\n\t%s" % (n.op, n.name, ins))
        return "\n".join(lines)


class _IncompleteInference(MXNetError):
    pass


def _fallback_dtype(node, parsed, in_dtypes):
    """Dtype of a node's outputs when shapes are unknown: attr-declared dtype
    (Cast, creation ops) or numpy promotion of the known input dtypes."""
    if isinstance(parsed.get("dtype"), (np.dtype, type, str)):
        try:
            return np.dtype(np_dtype(parsed["dtype"]))
        except TypeError:
            pass
    known = [d for d in in_dtypes if d is not None]
    if not known:
        return np.dtype(np.float32)
    return np.dtype(np.result_type(*known))


def _parse_shape_attr(v):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    s = str(v).strip().lstrip("([").rstrip(")]")
    if not s:
        return ()
    return tuple(int(float(x)) for x in s.split(",") if x.strip())


def _freeze(attrs: dict):
    def fr(v):
        if isinstance(v, (list, tuple)):
            return tuple(fr(x) for x in v)
        if isinstance(v, np.dtype):
            return v.name
        return v

    return tuple(sorted((k, fr(v)) for k, v in attrs.items()))


@functools.lru_cache(maxsize=16384)
def _eval_node_shape(op_name, attrs_key, in_shapes, in_dtypes, n_aux):
    """Abstract-evaluate one node via jax.eval_shape — the FInferShape/FInferType
    pass collapsed into the op function itself."""
    import jax

    opdef = get_op(op_name)
    attrs = dict(attrs_key)
    n_in = len(in_shapes) - n_aux
    structs = [
        jax.ShapeDtypeStruct(tuple(s), np_dtype(d)) for s, d in zip(in_shapes, in_dtypes)
    ]
    key = jax.random.PRNGKey(0) if opdef.needs_rng else None

    def run(*arrays):
        outs, new_aux = opdef.apply(attrs, arrays[:n_in], aux=arrays[n_in:], is_train=True, rng=key)
        return tuple(outs)

    out = jax.eval_shape(run, *structs)
    return tuple((tuple(o.shape), np.dtype(o.dtype).name) for o in out)


# ----------------------------------------------------------------- creation
def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None, init=None, **kwargs) -> Symbol:
    """Create a named variable placeholder (reference: symbol.py Variable)."""
    if not isinstance(name, str):
        raise TypeError("Expect a string for variable name")
    attr = AttrScope.current().get(attr)
    attr = dict(attr or {})
    if shape is not None:
        attr["__shape__"] = str(tuple(shape))
    if lr_mult is not None:
        attr["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        attr["__wd_mult__"] = str(wd_mult)
    if dtype is not None:
        attr["__dtype__"] = np.dtype(np_dtype(dtype)).name
    if init is not None:
        if not isinstance(init, str):
            init = init.dumps()
        attr["__init__"] = init
    for k, v in kwargs.items():
        if k.startswith("__") and k.endswith("__"):
            attr[k] = str(v)
        else:
            raise ValueError("Attribute name=%s is not supported." % k)
    return Symbol([(_Node(None, name, attr, []), 0)])


var = Variable


def Group(symbols) -> Symbol:
    """Group symbols into one multi-output symbol (reference: symbol.py Group)."""
    outputs = []
    for s in symbols:
        if not isinstance(s, Symbol):
            raise TypeError("Group: expected Symbol, got %r" % (s,))
        outputs.extend(s._outputs)
    return Symbol(outputs)


def _create(op_name, input_syms, attrs, name=None, attr=None) -> Symbol:
    """Create an op node over single-output input symbols."""
    opdef = get_op(op_name)
    canonical = opdef.name
    parsed = parse_attrs(opdef, attrs)
    hint = canonical.lower().lstrip("_")
    name = NameManager.current().get(name, hint if hint else canonical.lower())
    node_attrs = dict(attrs)
    scope_attrs = AttrScope.current().get(attr)
    if scope_attrs:
        node_attrs.update(scope_attrs)
    inputs = []
    for s in input_syms:
        if len(s._outputs) != 1:
            raise MXNetError("op %s: input symbols must have a single output" % op_name)
        inputs.append(s._outputs[0])
    node = _Node(canonical, name, node_attrs, inputs)
    return Symbol([(node, i) for i in range(opdef.num_outputs(parsed))])


def _make_symbol_function(op_name):
    opdef = get_op(op_name)

    def creator(*args, **kwargs):
        name = kwargs.pop("name", None)
        attr = kwargs.pop("attr", None)
        sym_args = []
        for a in args:
            if isinstance(a, Symbol):
                sym_args.append(a)
            else:
                raise TypeError("%s: positional args must be Symbols; use kwargs for attrs" % op_name)
        sym_kwargs = {}
        attrs = {}
        for k, v in kwargs.items():
            if isinstance(v, Symbol):
                sym_kwargs[k] = v
            else:
                attrs[k] = v
        if "num_args" in opdef.attr_specs and "num_args" not in attrs:
            attrs["num_args"] = len(sym_args) + len(sym_kwargs)
        parsed = parse_attrs(opdef, attrs)
        slots = opdef.input_names(parsed) + opdef.aux_names(parsed)
        hint = opdef.name.lower().lstrip("_") or opdef.name.lower()
        name = NameManager.current().get(name, hint)
        if len(sym_args) > len(slots):
            raise MXNetError(
                "%s: too many positional inputs (%d given, expects %s)"
                % (op_name, len(sym_args), slots)
            )
        filled: Dict[str, Symbol] = {}
        for slot, s in zip(slots, sym_args):
            filled[slot] = s
        for k, v in sym_kwargs.items():
            if k not in slots:
                raise MXNetError("%s: unknown tensor input %r (expects %s)" % (op_name, k, slots))
            if k in filled:
                raise MXNetError("%s: input %r given twice" % (op_name, k))
            filled[k] = v
        input_syms = []
        for slot in slots:
            if slot in filled:
                input_syms.append(filled[slot])
            else:
                # auto-create the parameter variable (reference behavior:
                # omitted named inputs become new variables "<name>_<slot>")
                input_syms.append(Variable("%s_%s" % (name, slot)))
        node_attrs = dict(attrs)
        scope_attrs = AttrScope.current().get(attr)
        if scope_attrs:
            node_attrs.update(scope_attrs)
        inputs = []
        for s in input_syms:
            if len(s._outputs) != 1:
                raise MXNetError("op %s: input symbols must have a single output" % op_name)
            inputs.append(s._outputs[0])
        node = _Node(opdef.name, name, node_attrs, inputs)
        return Symbol([(node, i) for i in range(opdef.num_outputs(parsed))])

    creator.__name__ = op_name
    creator.__doc__ = opdef.doc
    return creator


def pow(base, exp):
    if isinstance(base, Symbol) and isinstance(exp, Symbol):
        return _create("_power", [base, exp], {})
    if isinstance(base, Symbol):
        return base.__pow__(exp)
    if isinstance(exp, Symbol):
        return exp.__rpow__(base) if hasattr(exp, "__rpow__") else _create("_rpower_scalar", [exp], {"scalar": float(base)})
    raise TypeError("pow: need at least one Symbol")


def maximum(left, right):
    if isinstance(left, Symbol) and isinstance(right, Symbol):
        return _create("_maximum", [left, right], {})
    if isinstance(left, Symbol):
        return _create("_maximum_scalar", [left], {"scalar": float(right)})
    return _create("_maximum_scalar", [right], {"scalar": float(left)})


def minimum(left, right):
    if isinstance(left, Symbol) and isinstance(right, Symbol):
        return _create("_minimum", [left, right], {})
    if isinstance(left, Symbol):
        return _create("_minimum_scalar", [left], {"scalar": float(right)})
    return _create("_minimum_scalar", [right], {"scalar": float(left)})


# -------------------------------------------------------------------- JSON load
def load_json(json_str: str) -> Symbol:
    """Rebuild a Symbol from nnvm graph JSON (reference format,
    src/nnvm/legacy_json_util.cc handles the same keys)."""
    graph = json.loads(json_str)
    nodes_json = graph["nodes"]
    built: List[_Node] = []
    for nj in nodes_json:
        op = nj["op"]
        attrs = nj.get("attr") or nj.get("attrs") or nj.get("param") or {}
        inputs = [(built[e[0]], e[1]) for e in nj.get("inputs", [])]
        built.append(_Node(None if op == "null" else get_op(op).name, nj["name"], attrs, inputs))
    heads = graph.get("heads")
    if not heads:
        heads = [[len(built) - 1, 0, 0]]
    return Symbol([(built[h[0]], h[1]) for h in heads])


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


def fromjson(json_str: str) -> Symbol:
    return load_json(json_str)


def _init_symbol_module():
    mod = sys.modules[__name__]
    for name in list(_registry._REGISTRY.keys()):
        if not hasattr(mod, name):
            setattr(mod, name, _make_symbol_function(name))


_init_symbol_module()
