"""Deployment predictor: minimal inference API over a saved checkpoint.

Counterpart of the reference's C predict API (include/mxnet/c_predict_api.h,
src/c_api/c_predict_api.cc: MXPredCreate / MXPredSetInput / MXPredForward /
MXPredGetOutput / MXPredReshape) — the surface its amalgamation/mobile builds
ship. TPU-native: executors come from the serving subsystem's
``PersistentExecutableCache`` (docs/SERVING.md) — ONE compiled executable
per input-shape set, created on first use and kept hot, so repeated
``forward()`` at an identical shape is a guaranteed zero-recompile replay
and ``reshape()`` back to a previously-seen shape reuses its executable
instead of re-binding (the pre-serving behavior re-bound and re-traced on
every reshape).

    pred = Predictor(open("m-symbol.json").read(), open("m-0010.params","rb").read(),
                     {"data": (1, 3, 224, 224)})
    pred.forward(data=batch)
    probs = pred.get_output(0)
"""
from __future__ import annotations

from typing import Dict, Sequence

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from . import symbol as sym

__all__ = ["Predictor", "load_ndarray_file"]


def load_ndarray_file(binary: bytes):
    """Parse a .params blob into {name: NDArray} (reference:
    MXNDListCreate, c_predict_api.cc)."""
    import io as _io

    return nd._load_stream(_io.BytesIO(binary)) if hasattr(nd, "_load_stream") \
        else _load_params_bytes(binary)


def _load_params_bytes(binary: bytes):
    import os
    import tempfile

    fd, path = tempfile.mkstemp(suffix=".params")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(binary)
        return nd.load(path)
    finally:
        os.unlink(path)


class Predictor:
    """(reference: c_predict_api.h MXPredCreate → PredictorHandle)"""

    def __init__(self, symbol_json: str, param_bytes: bytes,
                 input_shapes: Dict[str, Sequence[int]], ctx=None,
                 output_names=None):
        net = sym.load_json(symbol_json)
        if output_names:  # MXPredCreatePartialOut semantics
            outputs = net.list_outputs()
            chosen = []
            for name in output_names:
                if name not in outputs:
                    raise MXNetError("output %r not in %s" % (name, outputs))
                chosen.append(net[outputs.index(name)])
            net = sym.Group(chosen)
        self._sym = net
        params = load_ndarray_file(param_bytes) if param_bytes else {}
        # the saved dict uses the reference's "arg:name"/"aux:name" prefixes
        self._arg_params, self._aux_params = {}, {}
        for k, v in params.items():
            if k.startswith("arg:"):
                self._arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                self._aux_params[k[4:]] = v
            else:
                self._arg_params[k] = v
        from .context import current_context

        self._ctx = ctx or current_context()
        self._input_shapes = {k: tuple(v) for k, v in input_shapes.items()}
        from .serving import PersistentExecutableCache

        # unsealed: the predict API allows new shapes at any time, each
        # compiled once; reshape() back to a seen shape is a cache hit.
        # MXNET_SERVE_MAX_EXECUTABLES (default 8, 0=unbounded) LRU-bounds
        # the retained executors so a reshape-heavy workload over many
        # distinct shapes cannot grow device memory without limit.
        from .serving.engine import _env_int

        cap = _env_int("MXNET_SERVE_MAX_EXECUTABLES", 8)
        self._cache = PersistentExecutableCache(
            self._sym, self._arg_params, self._aux_params, ctx=self._ctx,
            max_executables=cap)
        self._bind()

    def _bind(self):
        self._exe = self._cache.executable(dict(self._input_shapes))
        # sync the CURRENT params (reshape may have harvested updates) into
        # the possibly-reused executor
        for k, v in self._arg_params.items():
            if k in self._exe.arg_dict:
                self._exe.arg_dict[k][:] = v
        for k, v in self._aux_params.items():
            if k in self._exe.aux_dict:
                self._exe.aux_dict[k][:] = v
        self._dirty = False

    def set_input(self, key, data):
        """(reference: MXPredSetInput)"""
        if key not in self._input_shapes:
            raise MXNetError("unknown input %r" % key)
        self._exe.arg_dict[key][:] = np.asarray(data, np.float32)

    def forward(self, **inputs):
        """(reference: MXPredForward; kwargs are a convenience over
        set_input + forward)"""
        for k, v in inputs.items():
            self.set_input(k, v)
        self._exe.forward(is_train=False)

    def reshape(self, new_input_shapes):
        """(reference: MXPredReshape) — switch to the executable for the
        new shapes. A shape set seen before reuses its cached executable
        with ZERO recompilation; a new one compiles once."""
        self._input_shapes.update({k: tuple(v) for k, v in new_input_shapes.items()})
        # preserve current (possibly updated) params
        for k in self._arg_params:
            if k in self._exe.arg_dict:
                self._arg_params[k] = self._exe.arg_dict[k].copy()
        self._bind()

    def get_output(self, index) -> np.ndarray:
        """(reference: MXPredGetOutput — copies out to host)"""
        return self._exe.outputs[index].asnumpy()

    @property
    def num_outputs(self):
        return len(self._exe.outputs)

    @property
    def input_shapes(self):
        """Bound input spec (consumed by the C ABI's MXPredSetInput size
        check, src/predict_api.cc)."""
        return dict(self._input_shapes)
