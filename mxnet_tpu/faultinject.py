"""Deterministic, seeded fault injection (docs/RESILIENCE.md).

Every recovery path this repo has grown — serving dispatch retry, the
checkpoint writer's transient-I/O retry, elastic re-form, the prefetcher
wedge latch — used to be exercisable only by hand-rolled chaos scripts
(sleeps, kills, monkeypatched file systems). This module makes faults a
first-class, *reproducible* input: named injection sites sit at the
existing seams, each site evaluates a seeded plan, and the same seed
replays the same injected-event sequence — the determinism argument the
PyGraph / cross-replica-sharding line of work (PAPERS.md) makes for the
happy path applies to the failure path too.

Configuration — ``MXNET_FAULTINJECT`` is a comma-separated list of plans::

    MXNET_FAULTINJECT="serving.dispatch:raise:0.1:42,io.prefetch:delay_ms:0.5:7:20"

each ``site:kind:prob:seed[:arg]`` meaning: at ``site``, with probability
``prob`` per evaluation (drawn from a dedicated ``random.Random(seed)``
stream, so the fire/skip sequence is a pure function of the seed and the
call order), perform ``kind``:

================  ============================================================
``raise``         raise ``FaultInjected`` (an ``MXNetError``); ``arg`` may name
                  an errno (``EIO``/``ENOSPC``/``EAGAIN``/...) to raise a real
                  ``OSError`` instead — exercises OS-error recovery paths
``delay_ms``      sleep ``arg`` milliseconds (default 10) — latency faults
``hang``          sleep ``arg`` *seconds* (default 60) — a wedged dependency
``torn_write``    at byte-writing sites only: the write persists just a prefix
                  (fraction ``arg`` of the bytes, default 0.5) and raises
                  ``OSError(EIO)`` — a crash/ENOSPC mid-write
================  ============================================================

Tests use the scoped context-manager API instead of the env::

    with faultinject.inject("serving.dispatch", "raise", prob=1.0, seed=3,
                            times=1):
        ...   # exactly one dispatch fails, deterministically

Zero overhead when unset (the telemetry ``NULL_SPAN`` discipline):
``fire()``'s fast path is one env-membership check plus one empty-dict
check — no plan objects, no RNG, no allocation. The env is re-read every
check so subprocesses and tests can flip it live; parsing is cached on the
raw string.

Telemetry: every fired event counts into an internal table (``stats()``,
available even with telemetry off) and, when telemetry is enabled, into
``faultinject.fired`` plus a ``faultinject.<site>.<kind>`` counter per
site/kind (docs/OBSERVABILITY.md).

Sites are just strings; the ones wired today are listed in ``SITES`` (and
docs/RESILIENCE.md). Firing an unknown site is legal — new seams only need
a ``faultinject.fire("my.site")`` call.
"""
from __future__ import annotations

import errno as _errno
import logging
import os
import random
import threading
import time

from .base import MXNetError
from . import telemetry as _tm

__all__ = ["FaultInjected", "fire", "torn_fraction", "inject", "refresh",
           "stats", "reset_stats", "SITES", "KINDS", "ENV_FAULTINJECT"]

log = logging.getLogger("mxnet_tpu.faultinject")

ENV_FAULTINJECT = "MXNET_FAULTINJECT"

KINDS = ("raise", "delay_ms", "hang", "torn_write")

#: the seams wired today (site -> where it fires); informational — see
#: docs/RESILIENCE.md for the per-site failure semantics
SITES = {
    "serving.submit": "InferenceEngine.submit entry (request admission)",
    "serving.dispatch": "InferenceEngine._dispatch, before the executable "
                        "runs (the retry-covered window)",
    "serving.batcher": "top of the batcher loop, outside the per-batch "
                       "recovery (a fire here latches the engine)",
    "checkpoint.write": "checkpoint.atomic_write_bytes (torn_write "
                        "supported; covered by the writer retry)",
    "dist.heartbeat": "the heartbeat thread's beat (a raise skips one "
                      "beat; delay/hang make the file go stale)",
    "dist.collective": "_Collective.make_global_rows — every kvstore "
                       "allreduce/reduce-scatter passes through it",
    "io.prefetch": "PrefetchingIter._pump, before child.next() (a raise "
                   "surfaces to the consumer as the epoch's error)",
    "fleet.dispatch": "Router dispatch worker, before the replica RPC "
                      "(the re-dispatch-covered window; a raise exercises "
                      "redispatch-to-another-replica)",
    "fleet.health": "Router health poll, before the replica's health RPC "
                    "(a raise/hang makes that replica's snapshot go stale "
                    "— the router must stop dispatching on it)",
    "fleet.replica_spawn": "ReplicaSupervisor._spawn, before the process "
                           "launch (a raise fails the spawn; the capped "
                           "restart backoff retries it)",
}


class FaultInjected(MXNetError):
    """An injected fault (kind=``raise``). Carries ``site`` and ``kind`` so
    recovery code and tests can tell injected faults from organic ones."""

    def __init__(self, site, kind="raise"):
        super().__init__(
            "faultinject: injected %s at site %r (%s=...)"
            % (kind, site, ENV_FAULTINJECT))
        self.site = site
        self.kind = kind


class _Plan:
    """One site's seeded decision stream. ``roll()`` draws exactly one
    uniform per evaluation (until the optional ``times`` cap is reached),
    so the fire/skip sequence is deterministic in (seed, call order)."""

    __slots__ = ("site", "kind", "prob", "seed", "arg", "times",
                 "fired", "calls", "_rng", "_lock")

    def __init__(self, site, kind, prob, seed, arg=None, times=None):
        if kind not in KINDS:
            raise MXNetError("faultinject: unknown kind %r (one of %s)"
                             % (kind, "/".join(KINDS)))
        self.site = site
        self.kind = kind
        self.prob = float(prob)
        self.seed = int(seed)
        self.arg = arg
        self.times = times
        self.fired = 0
        self.calls = 0
        self._rng = random.Random(int(seed))
        self._lock = threading.Lock()

    def roll(self):
        with self._lock:
            if self.times is not None and self.fired >= self.times:
                return False
            self.calls += 1
            if self._rng.random() >= self.prob:
                return False
            self.fired += 1
            return True


def _parse(raw):
    """Parse the env value into {site: [plan, ...]}. Malformed entries are
    skipped with one warning — a bad knob must degrade, not kill import."""
    plans = {}
    if not raw:
        return plans
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        try:
            if len(parts) < 4 or len(parts) > 5:
                raise ValueError("need site:kind:prob:seed[:arg]")
            site, kind, prob, seed = parts[0], parts[1], float(parts[2]), \
                int(parts[3])
            if not 0.0 <= prob <= 1.0:
                raise ValueError("prob %r outside [0, 1]" % prob)
            arg = parts[4] if len(parts) == 5 else None
            plans.setdefault(site, []).append(
                _Plan(site, kind, prob, seed, arg=arg))
        except (ValueError, MXNetError) as exc:
            log.warning("%s entry %r ignored (%s)", ENV_FAULTINJECT,
                        entry, exc)
    return plans


_lock = threading.Lock()
_env_cache = (None, {})   # (raw env string, parsed {site: [plans]})
_ctx_plans = {}           # inject() overlays; replaced wholesale (COW)
_counts = {}              # "site:kind" -> fired count; survives refresh()


def _env_plans(site):
    global _env_cache
    raw = os.environ.get(ENV_FAULTINJECT) or None
    cached_raw, cached = _env_cache
    if raw != cached_raw:
        with _lock:
            # re-check under the lock; first thread in parses
            if raw != _env_cache[0]:
                _env_cache = (raw, _parse(raw))
            cached = _env_cache[1]
    return cached.get(site)


def _active():
    """The no-op fast path's whole cost: one env membership test + one
    truthiness test. No parsing, no allocation."""
    return _ctx_plans or ENV_FAULTINJECT in os.environ


def _record(site, kind):
    key = "%s:%s" % (site, kind)
    with _lock:
        _counts[key] = _counts.get(key, 0) + 1
    if _tm.enabled():
        _tm.counter("faultinject.fired").inc()
        _tm.counter("faultinject.%s.%s" % (site, kind)).inc()


def _all_plans(site):
    env = _env_plans(site)
    ctx = _ctx_plans.get(site)
    if env and ctx:
        return ctx + env  # scoped overlays evaluate first
    return ctx or env


def fire(site):
    """Evaluate the ``raise``/``delay_ms``/``hang`` plans for ``site``:
    may sleep, may raise ``FaultInjected`` (or an ``OSError`` when the
    plan's arg names an errno). No-op (and allocation-free) when no
    injection is configured. ``torn_write`` plans are evaluated by
    byte-writing sites via ``torn_fraction`` instead."""
    if not _active():
        return
    plans = _all_plans(site)
    if not plans:
        return
    for plan in plans:
        if plan.kind == "torn_write" or not plan.roll():
            continue
        _record(site, plan.kind)
        if plan.kind == "delay_ms":
            time.sleep(float(plan.arg if plan.arg is not None else 10.0)
                       / 1000.0)
        elif plan.kind == "hang":
            time.sleep(float(plan.arg if plan.arg is not None else 60.0))
        else:  # raise
            eno = getattr(_errno, str(plan.arg), None) \
                if plan.arg is not None else None
            if eno is not None:
                raise OSError(eno, "faultinject: injected %s at site %r"
                              % (plan.arg, site))
            raise FaultInjected(site)


def torn_fraction(site):
    """For byte-writing sites: the fraction of the payload to KEEP if a
    ``torn_write`` plan fires (then the site must persist only that prefix
    and raise ``OSError(EIO)``), else None. See
    ``checkpoint.atomic_write_bytes`` for the canonical consumer."""
    if not _active():
        return None
    plans = _all_plans(site)
    if not plans:
        return None
    for plan in plans:
        if plan.kind == "torn_write" and plan.roll():
            _record(site, plan.kind)
            frac = float(plan.arg) if plan.arg is not None else 0.5
            return min(max(frac, 0.0), 1.0)
    return None


class inject:
    """Scoped injection for tests::

        with faultinject.inject("serving.dispatch", "raise",
                                prob=1.0, seed=3, times=1) as plan:
            ...
        assert plan.fired == 1

    Overlays the env configuration for the ``with`` body (evaluated before
    env plans at the same site); nestable; thread-safe via copy-on-write of
    the overlay table, so readers never take a lock."""

    def __init__(self, site, kind, prob=1.0, seed=0, arg=None, times=None):
        self.plan = _Plan(site, kind, prob, seed, arg=arg, times=times)

    def __enter__(self):
        global _ctx_plans
        with _lock:
            table = {k: list(v) for k, v in _ctx_plans.items()}
            table.setdefault(self.plan.site, []).append(self.plan)
            _ctx_plans = table
        return self.plan

    def __exit__(self, *exc):
        global _ctx_plans
        with _lock:
            table = {k: [p for p in v if p is not self.plan]
                     for k, v in _ctx_plans.items()}
            _ctx_plans = {k: v for k, v in table.items() if v}
        return False


def refresh():
    """Drop the parsed-env cache so the NEXT evaluation re-parses
    ``MXNET_FAULTINJECT`` with fresh RNG streams — the same env value then
    replays the same injected-event sequence from the start (tests pin the
    determinism contract on this)."""
    global _env_cache
    with _lock:
        _env_cache = (None, {})


def stats():
    """Fired-event counts ``{"site:kind": n}`` — live regardless of the
    telemetry gate (the chaos harness asserts injections actually ran)."""
    with _lock:
        return dict(_counts)


def reset_stats():
    with _lock:
        _counts.clear()
