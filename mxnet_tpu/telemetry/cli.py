"""mxtrace — inspect/validate a telemetry chrome-trace dump.

    python tools/mxtrace profile.json              # per-step table + top spans
    python tools/mxtrace profile.json --top 40
    python tools/mxtrace profile.json --check      # schema gate (CI), exit 0/1
    python tools/mxtrace profile.json --json       # machine-readable summary

The dump is what ``profiler.dump_profile()`` (or
``telemetry.export_chrome_trace``) wrote: chrome-trace ``traceEvents`` plus
an ``otherData`` block with the counter snapshot and per-step rows
(docs/OBSERVABILITY.md). ``--check`` validates the schema every consumer
of the dump relies on — the CI smoke gate after a telemetry-on fit.
"""
from __future__ import annotations

import argparse
import json
import sys

from .trace import SCHEMA_VERSION, gap_summary, span_summary

# per-step table columns: (header, counter name in the step row)
_STEP_COLS = [
    ("compile", "executor.compile"),
    ("hit", "executor.cache_hit"),
    ("retrace", "executor.retrace"),
    ("fused", "fusion.fwd_engaged"),
    ("fallbk", "fusion.fwd_fallback"),
    ("kv_B", "kvstore.push_bytes"),
    ("io", "io.batches"),
    ("push", "engine.push"),
]


def load(path):
    with open(path) as f:
        return json.load(f)


def check(trace):
    """Validate the dump schema. Returns a list of problems (empty = ok)."""
    bad = []
    if not isinstance(trace, dict):
        return ["top level is %s, expected object" % type(trace).__name__]
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    other = trace.get("otherData")
    if not isinstance(other, dict):
        bad.append("otherData missing or not an object")
        other = {}
    ver = other.get("mxnet_telemetry")
    if ver != SCHEMA_VERSION:
        bad.append("otherData.mxnet_telemetry is %r, expected %d"
                   % (ver, SCHEMA_VERSION))
    if not isinstance(other.get("counters", {}), dict):
        bad.append("otherData.counters is not an object")
    steps = other.get("steps", [])
    if not isinstance(steps, list):
        bad.append("otherData.steps is not a list")
        steps = []
    for i, row in enumerate(steps):
        if not (isinstance(row, dict) and "step" in row
                and isinstance(row.get("counters", None), dict)):
            bad.append("steps[%d] malformed (need step + counters)" % i)
            break
    saw_process_meta = False
    for i, ev in enumerate(events):
        if not isinstance(ev, dict) or "ph" not in ev:
            bad.append("traceEvents[%d] has no ph" % i)
            break
        if ev["ph"] == "M" and ev.get("name") == "process_name":
            saw_process_meta = True
        if ev["ph"] == "X":
            if not isinstance(ev.get("name"), str):
                bad.append("traceEvents[%d]: X event without a name" % i)
                break
            if not isinstance(ev.get("ts"), (int, float)) \
                    or not isinstance(ev.get("dur"), (int, float)):
                bad.append("traceEvents[%d] (%s): non-numeric ts/dur"
                           % (i, ev["name"]))
                break
            if "pid" not in ev or "tid" not in ev:
                bad.append("traceEvents[%d] (%s): missing pid/tid"
                           % (i, ev["name"]))
                break
    if events and not saw_process_meta:
        bad.append("no process_name metadata event")
    return bad


def _fmt_table(headers, rows):
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    out = ["  ".join(h.rjust(w) for h, w in zip(headers, widths))]
    for r in rows:
        out.append("  ".join(c.rjust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


def step_table(trace):
    steps = (trace.get("otherData") or {}).get("steps") or []
    if not steps:
        return "(no per-step rows — no step marks ran during the capture)"
    headers = ["step", "wall_ms"] + [h for h, _ in _STEP_COLS]
    rows = []
    for row in steps:
        c = row.get("counters", {})
        wall = row.get("wall_ms")
        rows.append([str(row.get("step", "?")),
                     "-" if wall is None else "%.1f" % wall]
                    + [str(c.get(key, 0)) for _, key in _STEP_COLS])
    return _fmt_table(headers, rows)


def spans_table(trace, top):
    rows = span_summary(trace=trace, top=top)
    if not rows:
        return "(no spans recorded — was MXNET_TELEMETRY=trace set?)"
    return _fmt_table(
        ["span", "ms", "count", "ms/call"],
        [[r["name"], "%.3f" % r["ms"], str(r["count"]),
          "%.3f" % (r["ms"] / r["count"])] for r in rows])


def gaps_table(trace, top):
    """Host-gap attribution: per span name, the host time between one
    span's end and the next one's start on the same thread (negative
    overlaps from threaded interleaving clamp to zero; the ``clamp``
    column counts them). ``gap%%`` is gap/busy — the GL705 ratio.
    Megastep dispatches (K tokens / N batches per launch) are tagged
    ``[megastep]`` so their per-interval gap is read as amortized over
    K, not compared 1:1 against single-step rows."""

    def _label(name):
        return name + " [megastep]" if "megastep" in name else name

    rows = [r for r in gap_summary(trace=trace, top=top)
            if r["intervals"] > 0]
    if not rows:
        return "(no repeated spans — gap attribution needs >= 2 spans " \
               "of a name on one thread)"
    return _fmt_table(
        ["span", "gap_ms", "busy_ms", "gap%", "gap/iv", "max_gap",
         "ivs", "clamp"],
        [[_label(r["name"]), "%.3f" % r["gap_ms"], "%.3f" % r["busy_ms"],
          ("%.0f%%" % (100.0 * r["gap_ms"] / r["busy_ms"])
           if r["busy_ms"] > 0 else "-"),
          "%.3f" % (r["gap_ms"] / r["intervals"]),
          "%.3f" % r["max_gap_ms"], str(r["intervals"]),
          str(r["clamped"])] for r in rows])


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mxtrace", description="inspect/validate a mxnet_tpu telemetry "
        "chrome-trace dump (docs/OBSERVABILITY.md)")
    ap.add_argument("dump", help="chrome-trace JSON from "
                    "profiler.dump_profile()")
    ap.add_argument("--top", type=int, default=25,
                    help="span summary length (default 25)")
    ap.add_argument("--check", action="store_true",
                    help="validate the dump schema; exit 0 iff valid")
    ap.add_argument("--json", action="store_true",
                    help="print a machine-readable summary")
    args = ap.parse_args(argv)

    try:
        trace = load(args.dump)
    except (OSError, ValueError) as exc:
        print("mxtrace: cannot load %s: %s" % (args.dump, exc),
              file=sys.stderr)
        return 1

    if args.check:
        problems = check(trace)
        if problems:
            for p in problems:
                print("mxtrace: SCHEMA: %s" % p, file=sys.stderr)
            return 1
        n_x = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
        cats = sorted({e.get("cat") for e in trace["traceEvents"]
                       if e.get("ph") == "X" and e.get("cat")})
        print("mxtrace: OK — %d span(s), categories: %s, %d step row(s)"
              % (n_x, ",".join(cats) or "(none)",
                 len((trace.get("otherData") or {}).get("steps") or [])))
        return 0

    other = trace.get("otherData") or {}
    if args.json:
        print(json.dumps({
            "counters": other.get("counters", {}),
            "num_steps": len(other.get("steps") or []),
            "spans": span_summary(trace=trace, top=args.top),
            "gaps": gap_summary(trace=trace, top=args.top),
            "xla_trace_dir": other.get("xla_trace_dir"),
        }))
        return 0

    print("== per-step table ==")
    print(step_table(trace))
    print()
    print("== top %d spans ==" % args.top)
    print(spans_table(trace, args.top))
    print()
    print("== host-gap attribution (span end -> next same-name start) ==")
    print(gaps_table(trace, args.top))
    counters = other.get("counters") or {}
    if counters:
        print()
        print("== final counters ==")
        for name, v in sorted(counters.items()):
            print("  %-40s %s" % (name, v))
    if other.get("xla_trace_dir"):
        print()
        print("XLA trace dir: %s (TensorBoard/Perfetto)"
              % other["xla_trace_dir"])
    return 0
