"""mxtrace — inspect/validate a telemetry chrome-trace dump.

    python tools/mxtrace profile.json              # per-step table + top spans
    python tools/mxtrace profile.json --top 40
    python tools/mxtrace profile.json --check      # schema gate (CI), exit 0/1
    python tools/mxtrace profile.json --json       # machine-readable summary
    python tools/mxtrace router.json r0.json r1.json --out fleet.json
    python tools/mxtrace fleet.json --fleet        # fleet rollups + SLO
    python tools/mxtrace fleet.json --fleet-trace  # per-request span chains

The dump is what ``profiler.dump_profile()`` (or
``telemetry.export_chrome_trace``) wrote: chrome-trace ``traceEvents`` plus
an ``otherData`` block with the counter snapshot and per-step rows
(docs/OBSERVABILITY.md). ``--check`` validates the schema every consumer
of the dump relies on — the CI smoke gate after a telemetry-on fit.

Fleet plane: multiple dump arguments are clock-aligned and merged into
ONE timeline (``telemetry.merge_traces``; per-dump
``otherData.clock_offset_s`` stamps — the router's RPC midpoint
handshake — are honored). ``--fleet`` renders the router's ``fleet.*``
rollups and SLO status; ``--fleet-trace`` reconstructs each request's
cross-process span chain by shared ``trace_id``.
"""
from __future__ import annotations

import argparse
import json
import sys

from .trace import SCHEMA_VERSION, gap_summary, merge_traces, span_summary

# per-step table columns: (header, counter name in the step row)
_STEP_COLS = [
    ("compile", "executor.compile"),
    ("hit", "executor.cache_hit"),
    ("retrace", "executor.retrace"),
    ("fused", "fusion.fwd_engaged"),
    ("fallbk", "fusion.fwd_fallback"),
    ("kv_B", "kvstore.push_bytes"),
    ("io", "io.batches"),
    ("push", "engine.push"),
]


def load(path):
    with open(path) as f:
        return json.load(f)


def check(trace):
    """Validate the dump schema. Returns a list of problems (empty = ok)."""
    bad = []
    if not isinstance(trace, dict):
        return ["top level is %s, expected object" % type(trace).__name__]
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    other = trace.get("otherData")
    if not isinstance(other, dict):
        bad.append("otherData missing or not an object")
        other = {}
    ver = other.get("mxnet_telemetry")
    if ver != SCHEMA_VERSION:
        bad.append("otherData.mxnet_telemetry is %r, expected %d"
                   % (ver, SCHEMA_VERSION))
    if not isinstance(other.get("counters", {}), dict):
        bad.append("otherData.counters is not an object")
    steps = other.get("steps", [])
    if not isinstance(steps, list):
        bad.append("otherData.steps is not a list")
        steps = []
    for i, row in enumerate(steps):
        if not (isinstance(row, dict) and "step" in row
                and isinstance(row.get("counters", None), dict)):
            bad.append("steps[%d] malformed (need step + counters)" % i)
            break
    saw_process_meta = False
    for i, ev in enumerate(events):
        if not isinstance(ev, dict) or "ph" not in ev:
            bad.append("traceEvents[%d] has no ph" % i)
            break
        if ev["ph"] == "M" and ev.get("name") == "process_name":
            saw_process_meta = True
        if ev["ph"] == "X":
            if not isinstance(ev.get("name"), str):
                bad.append("traceEvents[%d]: X event without a name" % i)
                break
            if not isinstance(ev.get("ts"), (int, float)) \
                    or not isinstance(ev.get("dur"), (int, float)):
                bad.append("traceEvents[%d] (%s): non-numeric ts/dur"
                           % (i, ev["name"]))
                break
            if "pid" not in ev or "tid" not in ev:
                bad.append("traceEvents[%d] (%s): missing pid/tid"
                           % (i, ev["name"]))
                break
    if events and not saw_process_meta:
        bad.append("no process_name metadata event")
    return bad


def _fmt_table(headers, rows):
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    out = ["  ".join(h.rjust(w) for h, w in zip(headers, widths))]
    for r in rows:
        out.append("  ".join(c.rjust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


def step_table(trace):
    steps = (trace.get("otherData") or {}).get("steps") or []
    if not steps:
        return "(no per-step rows — no step marks ran during the capture)"
    headers = ["step", "wall_ms"] + [h for h, _ in _STEP_COLS]
    rows = []
    for row in steps:
        c = row.get("counters", {})
        wall = row.get("wall_ms")
        rows.append([str(row.get("step", "?")),
                     "-" if wall is None else "%.1f" % wall]
                    + [str(c.get(key, 0)) for _, key in _STEP_COLS])
    return _fmt_table(headers, rows)


def spans_table(trace, top):
    rows = span_summary(trace=trace, top=top)
    if not rows:
        return "(no spans recorded — was MXNET_TELEMETRY=trace set?)"
    return _fmt_table(
        ["span", "ms", "count", "p50", "p95", "p99"],
        [[r["name"], "%.3f" % r["ms"], str(r["count"]),
          "%.3f" % r.get("p50_ms", 0.0), "%.3f" % r.get("p95_ms", 0.0),
          "%.3f" % r.get("p99_ms", 0.0)] for r in rows])


def gaps_table(trace, top):
    """Host-gap attribution: per span name, the host time between one
    span's end and the next one's start on the same thread (negative
    overlaps from threaded interleaving clamp to zero; the ``clamp``
    column counts them). ``gap%%`` is gap/busy — the GL705 ratio.
    Megastep dispatches (K tokens / N batches per launch) are tagged
    ``[megastep]`` so their per-interval gap is read as amortized over
    K, not compared 1:1 against single-step rows."""

    def _label(name):
        return name + " [megastep]" if "megastep" in name else name

    rows = [r for r in gap_summary(trace=trace, top=top)
            if r["intervals"] > 0]
    if not rows:
        return "(no repeated spans — gap attribution needs >= 2 spans " \
               "of a name on one thread)"
    return _fmt_table(
        ["span", "gap_ms", "busy_ms", "gap%", "gap/iv", "max_gap",
         "ivs", "clamp"],
        [[_label(r["name"]), "%.3f" % r["gap_ms"], "%.3f" % r["busy_ms"],
          ("%.0f%%" % (100.0 * r["gap_ms"] / r["busy_ms"])
           if r["busy_ms"] > 0 else "-"),
          "%.3f" % (r["gap_ms"] / r["intervals"]),
          "%.3f" % r["max_gap_ms"], str(r["intervals"]),
          str(r["clamped"])] for r in rows])


def locks_table(trace, top=25):
    """Lock-contention attribution from a ``MXNET_CONCLINT=witness`` run
    (``otherData.lock_witness``, telemetry/lockwitness.py): top locks by
    total hold time, with contention counts, waiter time, the >threshold
    hold count, and the per-thread acquisition split. Witnessed hazards
    (the GL805 feed) print below the table."""
    w = (trace.get("otherData") or {}).get("lock_witness")
    if not w:
        return "(no lock_witness block — capture with MXNET_CONCLINT=" \
               "witness to record lock orders and hold times)"
    rows = sorted(w.get("locks") or [], key=lambda r: -r.get("hold_ms", 0))
    out = []
    if rows:
        out.append(_fmt_table(
            ["lock", "acqs", "cont", "wait_ms", "hold_ms", "max_hold",
             "long", "threads"],
            [[r["name"], str(r["acquisitions"]), str(r["contentions"]),
              "%.3f" % r["wait_ms"], "%.3f" % r["hold_ms"],
              "%.3f" % r["max_hold_ms"], str(r["long_holds"]),
              ",".join("%s:%d" % kv
                       for kv in sorted((r.get("threads") or {}).items()))]
             for r in rows[:top]]))
    else:
        out.append("(witness enabled but no named lock was acquired)")
    events = w.get("events") or []
    inv = [e for e in events if e.get("kind") == "inversion"]
    holds = [e for e in events if e.get("kind") == "long_hold"]
    if inv or holds:
        out.append("")
        for e in inv:
            out.append("  INVERSION %s -> %s on %s (reverse order seen "
                       "%dx) [GL805]" % (e.get("first"), e.get("then"),
                                         e.get("thread"),
                                         e.get("prior_count", 1)))
        for e in holds:
            out.append("  LONG HOLD %s %.1fms on %s%s%s"
                       % (e.get("lock"), e.get("hold_ms", 0.0),
                          e.get("thread"),
                          " across a dispatch seam"
                          if e.get("dispatch_seam") else "",
                          " [GL805]" if e.get("dispatch_seam") else ""))
    if w.get("events_dropped"):
        out.append("  (%d witness event(s) dropped — ring full)"
                   % w["events_dropped"])
    return "\n".join(out)


def _event_trace_ids(ev):
    """trace id(s) stamped on one X event (single or batch form)."""
    args_ = ev.get("args") or {}
    tid = args_.get("trace_id")
    out = [tid] if tid is not None else []
    ids = args_.get("trace_ids")
    if isinstance(ids, list):
        out.extend(ids)
    return out


def request_chains(trace, top=10):
    """Per-request cross-process span chains, keyed by ``trace_id``:
    ``{trace_id: [{"pid", "name", "ts", "dur_ms"}, ...]}`` sorted by
    start time. The --fleet-trace view (router-queue → rpc →
    replica-queue → dispatch → decode per request)."""
    chains = {}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        for tid in _event_trace_ids(ev):
            chains.setdefault(tid, []).append(
                {"pid": ev.get("pid"), "name": ev.get("name"),
                 "ts": ev.get("ts", 0),
                 "dur_ms": round(ev.get("dur", 0) / 1000.0, 3)})
    for spans_ in chains.values():
        spans_.sort(key=lambda s: s["ts"])
    ranked = sorted(chains.items(), key=lambda kv: -len(kv[1]))
    return dict(ranked[:top]) if top else dict(ranked)


def _proc_labels(trace):
    labels = {}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            labels[ev.get("pid")] = (ev.get("args") or {}).get("name",
                                                               "?")
    return labels


def fleet_trace_table(trace, top=10):
    chains = request_chains(trace, top=top)
    if not chains:
        return "(no trace_id-stamped spans — fleet tracing needs " \
               "MXNET_TELEMETRY=trace on router AND replicas)"
    labels = _proc_labels(trace)
    out = []
    for tid, spans_ in chains.items():
        pids = sorted({s["pid"] for s in spans_})
        t0 = spans_[0]["ts"]
        out.append("request %s — %d span(s) across %d process(es)"
                   % (tid, len(spans_), len(pids)))
        out.append(_fmt_table(
            ["t+ms", "dur_ms", "process", "span"],
            [["%.3f" % ((s["ts"] - t0) / 1000.0), "%.3f" % s["dur_ms"],
              str(labels.get(s["pid"], s["pid"])), s["name"]]
             for s in spans_]))
        out.append("")
    return "\n".join(out).rstrip()


def fleet_table(trace):
    """Render otherData.fleet (Router.metrics() rollups stamped by
    serve_bench / profiler) + merged per-process block + SLO status."""
    other = trace.get("otherData") or {}
    fleet = other.get("fleet")
    out = []
    if not fleet:
        return "(no otherData.fleet block — write the dump from a " \
               "fleet run: serve_bench --fleet --trace-out, or stamp " \
               "Router.metrics() via export_chrome_trace(extra=...))"
    top = [("qps", "%.1f"), ("requests", "%d"), ("errors", "%d"),
           ("shed", "%d"), ("redispatches", "%d"),
           ("tokens_per_dispatch", "%.1f"), ("replicas_fresh", "%d")]
    line = []
    for key, fmt in top:
        if fleet.get(key) is not None:
            line.append(("%s=" + fmt) % (key, fleet[key]))
    out.append("fleet: " + "  ".join(line))
    hists = fleet.get("latency_ms") or {}
    if hists:
        out.append("")
        out.append(_fmt_table(
            ["timer", "count", "p50", "p95", "p99"],
            [[name, str(row.get("count", 0)),
              "%.3f" % row.get("p50", 0.0), "%.3f" % row.get("p95", 0.0),
              "%.3f" % row.get("p99", 0.0)]
             for name, row in sorted(hists.items())]))
    per = fleet.get("replicas") or {}
    if per:
        out.append("")
        out.append(_fmt_table(
            ["replica", "state", "qps", "requests", "clock_off_ms"],
            [[str(rid), str(row.get("state", "?")),
              "%.1f" % row.get("qps", 0.0), str(row.get("requests", 0)),
              "%.3f" % row.get("clock_offset_ms", 0.0)]
             for rid, row in sorted(per.items())]))
    slo = fleet.get("slo")
    if slo:
        out.append("")
        out.append("slo: ok=%s burn_rate=%.3f (threshold %.2f, windows "
                   "%.0fs/%.0fs)" % (slo.get("ok"),
                                     slo.get("burn_rate", 0.0),
                                     slo.get("burn_threshold", 1.0),
                                     slo.get("short_window_s", 0),
                                     slo.get("window_s", 0)))
        for key, row in sorted((slo.get("objectives") or {}).items()):
            out.append("  %-10s threshold=%-8g burn=%-8.3f value=%s%s"
                       % (key, row.get("threshold"),
                          row.get("burn_rate", 0.0), row.get("value"),
                          "  FIRING" if row.get("firing") else ""))
        viol = fleet.get("violations") or []
        if viol:
            out.append("  %d violation event(s): %s" % (
                len(viol), ", ".join(
                    "%s:%s" % (v.get("kind"), v.get("objective"))
                    for v in viol[-8:])))
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mxtrace", description="inspect/validate a mxnet_tpu telemetry "
        "chrome-trace dump (docs/OBSERVABILITY.md)")
    ap.add_argument("dump", nargs="+",
                    help="chrome-trace JSON from profiler.dump_profile(); "
                    "several dumps merge into one fleet timeline")
    ap.add_argument("--top", type=int, default=25,
                    help="span summary length (default 25)")
    ap.add_argument("--check", action="store_true",
                    help="validate the dump schema; exit 0 iff valid")
    ap.add_argument("--json", action="store_true",
                    help="print a machine-readable summary")
    ap.add_argument("--fleet", action="store_true",
                    help="render fleet.* rollups + SLO status "
                    "(otherData.fleet)")
    ap.add_argument("--fleet-trace", action="store_true",
                    help="per-request cross-process span chains by "
                    "trace_id")
    ap.add_argument("--out", help="write the (merged) dump JSON here")
    args = ap.parse_args(argv)

    dumps = []
    for path in args.dump:
        try:
            dumps.append(load(path))
        except (OSError, ValueError) as exc:
            print("mxtrace: cannot load %s: %s" % (path, exc),
                  file=sys.stderr)
            return 1
    if len(dumps) == 1:
        trace = dumps[0]
    else:
        offsets, labels = {}, {}
        for d in dumps:
            other = d.get("otherData") or {}
            pid = other.get("pid")
            if pid is not None:
                if other.get("clock_offset_s") is not None:
                    offsets[pid] = other["clock_offset_s"]
                if other.get("label"):
                    labels[pid] = other["label"]
        trace = merge_traces(dumps, offsets_s=offsets, labels=labels)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(trace, f)

    other = trace.get("otherData") or {}
    dropped = other.get("dropped") or 0

    if args.check:
        problems = check(trace)
        if problems:
            for p in problems:
                print("mxtrace: SCHEMA: %s" % p, file=sys.stderr)
            return 1
        n_x = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
        cats = sorted({e.get("cat") for e in trace["traceEvents"]
                       if e.get("ph") == "X" and e.get("cat")})
        print("mxtrace: OK — %d span(s), categories: %s, %d step row(s)"
              % (n_x, ",".join(cats) or "(none)",
                 len((trace.get("otherData") or {}).get("steps") or [])))
        if dropped:
            print("mxtrace: WARNING — %d span(s) dropped (ring-buffer "
                  "overflow; the trace is TRUNCATED — raise "
                  "MXNET_TELEMETRY_MAX_EVENTS)" % dropped)
        return 0

    if args.fleet or args.fleet_trace:
        if args.fleet:
            print("== fleet rollups ==")
            print(fleet_table(trace))
        if args.fleet_trace:
            if args.fleet:
                print()
            print("== per-request fleet chains (top %d by span count) =="
                  % min(args.top, 10))
            print(fleet_trace_table(trace, top=min(args.top, 10)))
        if dropped:
            print()
            print("WARNING: %d dropped span(s) — truncated trace"
                  % dropped)
        return 0

    if args.json:
        print(json.dumps({
            "counters": other.get("counters", {}),
            "num_steps": len(other.get("steps") or []),
            "spans": span_summary(trace=trace, top=args.top),
            "gaps": gap_summary(trace=trace, top=args.top),
            "dropped": dropped,
            "fleet": other.get("fleet"),
            "locks": other.get("lock_witness"),
            "xla_trace_dir": other.get("xla_trace_dir"),
        }))
        return 0

    print("== per-step table ==")
    print(step_table(trace))
    print()
    print("== top %d spans ==" % args.top)
    print(spans_table(trace, args.top))
    print()
    print("== host-gap attribution (span end -> next same-name start) ==")
    print(gaps_table(trace, args.top))
    if other.get("lock_witness"):
        print()
        print("== lock witness (MXNET_CONCLINT=witness) ==")
        print(locks_table(trace, args.top))
    counters = other.get("counters") or {}
    if counters:
        print()
        print("== final counters ==")
        for name, v in sorted(counters.items()):
            print("  %-40s %s" % (name, v))
    if dropped:
        print()
        print("WARNING: %d span(s) dropped (ring-buffer overflow) — "
              "this trace is TRUNCATED" % dropped)
    if other.get("xla_trace_dir"):
        print()
        print("XLA trace dir: %s (TensorBoard/Perfetto)"
              % other["xla_trace_dir"])
    return 0
