"""Declarative SLOs with multi-window burn-rate evaluation
(docs/OBSERVABILITY.md §Fleet).

An SLO spec is a small set of objectives over the serving request
stream::

    MXNET_SLO="p99_ms:250,err_pct:1,avail_pct:99"

or a JSON object / path to a JSON file with the same keys
(``{"p99_ms": 250, "err_pct": 1, "avail_pct": 99}``).  Objectives:

* ``p50_ms`` / ``p95_ms`` / ``p99_ms`` — latency ceiling at that
  quantile.  The error budget is the quantile's complement (a p99
  objective tolerates 1% of requests over the ceiling).
* ``err_pct`` — maximum failed-request percentage.
* ``avail_pct`` — minimum fraction of evaluation ticks with at least one
  eligible replica.

``SloMonitor`` consumes per-tick DELTAS (requests completed, errors,
sparse latency-histogram buckets from :mod:`telemetry.histogram`, an
availability sample) and evaluates each objective over TWO sliding
windows — short (default 5 s, ``MXNET_SLO_SHORT_WINDOW_S``) and long
(default 60 s, ``MXNET_SLO_WINDOW_S``).  The burn rate of an objective
is budget consumption speed: observed bad fraction / allowed bad
fraction (1.0 = exactly exhausting the budget).  The reported
``slo.burn_rate`` gauge is the worst objective's ``min(short, long)`` —
the multi-window AND that ignores both ancient history (long-only) and
one-tick blips (short-only).  Crossing ``MXNET_SLO_BURN_THRESHOLD``
(default 1.0) fires a structured violation event
(``telemetry.event("slo.violation")`` + the ``violations()`` list); the
matching ``slo.clear`` event is emitted when the burn drops back under.

Stdlib-only: this module rides the standalone telemetry import
(tools/mxtrace) and the replica subprocess.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time

from . import histogram as _histmod
from . import registry, spans

__all__ = ["SloSpec", "SloMonitor", "DEFAULT_WINDOW_S",
           "DEFAULT_SHORT_WINDOW_S", "DEFAULT_BURN_THRESHOLD"]

DEFAULT_WINDOW_S = 60.0
DEFAULT_SHORT_WINDOW_S = 5.0
DEFAULT_BURN_THRESHOLD = 1.0

_LATENCY_KEYS = {"p50_ms": 0.50, "p95_ms": 0.95, "p99_ms": 0.99}
_KEYS = set(_LATENCY_KEYS) | {"err_pct", "avail_pct"}


def _env_float(name, default):
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        v = float(raw)
        if v <= 0:
            raise ValueError
        return v
    except ValueError:
        import logging

        logging.getLogger("mxnet_tpu").warning(
            "%s=%r is not a positive number; using the default %s",
            name, raw, default)
        return default


class SloSpec:
    """Parsed objectives: ``{key: threshold}`` over ``_KEYS``."""

    __slots__ = ("objectives",)

    def __init__(self, objectives):
        bad = set(objectives) - _KEYS
        if bad:
            raise ValueError("unknown SLO objective(s): %s (known: %s)"
                             % (sorted(bad), sorted(_KEYS)))
        self.objectives = {k: float(v) for k, v in objectives.items()}
        for k, v in self.objectives.items():
            if v <= 0 or (k.endswith("_pct") and v > 100):
                raise ValueError("SLO %s:%r out of range" % (k, v))

    @classmethod
    def parse(cls, text):
        """``"p99_ms:250,err_pct:1"``, an inline JSON object, or a path
        to a JSON file holding one."""
        text = (text or "").strip()
        if not text:
            raise ValueError("empty SLO spec")
        if text.startswith("{"):
            return cls(json.loads(text))
        if os.path.exists(text):
            with open(text) as f:
                return cls(json.load(f))
        obj = {}
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            if ":" not in part:
                raise ValueError(
                    "malformed SLO entry %r (want key:value)" % part)
            k, v = part.split(":", 1)
            obj[k.strip()] = float(v)
        return cls(obj)

    @classmethod
    def from_env(cls):
        """MXNET_SLO, or None when unset/empty. A malformed value warns
        and disables (a bad knob must not take down a server)."""
        raw = os.environ.get("MXNET_SLO", "").strip()
        if not raw:
            return None
        try:
            return cls.parse(raw)
        except (ValueError, OSError) as exc:
            import logging

            logging.getLogger("mxnet_tpu").warning(
                "MXNET_SLO=%r is unparseable (%s); SLO gating disabled",
                raw, exc)
            return None

    def __repr__(self):
        return "SloSpec(%s)" % ",".join(
            "%s:%g" % kv for kv in sorted(self.objectives.items()))


class SloMonitor:
    """Sliding-window burn-rate evaluator over per-tick deltas."""

    def __init__(self, spec, window_s=None, short_window_s=None,
                 burn_threshold=None, clock=time.monotonic):
        self.spec = spec
        self.window_s = window_s if window_s is not None else \
            _env_float("MXNET_SLO_WINDOW_S", DEFAULT_WINDOW_S)
        self.short_window_s = short_window_s if short_window_s is not None \
            else _env_float("MXNET_SLO_SHORT_WINDOW_S",
                            DEFAULT_SHORT_WINDOW_S)
        self.short_window_s = min(self.short_window_s, self.window_s)
        self.burn_threshold = burn_threshold if burn_threshold is not None \
            else _env_float("MXNET_SLO_BURN_THRESHOLD",
                            DEFAULT_BURN_THRESHOLD)
        self._clock = clock
        self._lock = threading.Lock()
        self._samples = collections.deque()   # (t, total, errors, buckets, avail)
        self._violations = []                 # structured fire/clear events
        self._active = set()                  # objectives currently firing

    # ------------------------------------------------------------ feed
    def observe(self, total=0, errors=0, latency_buckets=None,
                available=None, t=None):
        """One tick of DELTAS: ``total`` requests finished, ``errors`` of
        them failed, their latency as sparse histogram buckets, and an
        availability sample (bool or 0..1 fraction; None = no opinion)."""
        t = self._clock() if t is None else t
        av = None if available is None else float(available)
        with self._lock:
            self._samples.append((t, int(total), int(errors),
                                  dict(latency_buckets or {}), av))
            self._prune(t)

    def _prune(self, now):
        horizon = now - self.window_s
        while self._samples and self._samples[0][0] < horizon:
            self._samples.popleft()

    # ------------------------------------------------------- evaluate
    def _window_stats(self, now, width):
        total = errors = 0
        buckets = {}
        avail_sum, avail_n = 0.0, 0
        for t, n, e, b, av in self._samples:
            if t < now - width:
                continue
            total += n
            errors += e
            for k, v in b.items():
                buckets[k] = buckets.get(k, 0) + v
            if av is not None:
                avail_sum += av
                avail_n += 1
        return total, errors, buckets, (avail_sum / avail_n
                                        if avail_n else None)

    @staticmethod
    def _bad_latency(buckets, threshold_ms):
        """How many bucketed samples exceed the ceiling (bucket geometric
        midpoint vs threshold — within the histogram's ~10% error)."""
        bad = 0
        thr_s = threshold_ms / 1000.0
        for k, n in buckets.items():
            if _histmod._bucket_mid(int(k)) > thr_s:
                bad += n
        return bad

    def _objective_burn(self, key, threshold, stats):
        """(burn_rate, observed_value) for one objective in one window.
        burn_rate = observed bad fraction / allowed bad fraction; None
        when the window holds no relevant signal."""
        total, errors, buckets, avail = stats
        if key in _LATENCY_KEYS:
            n = sum(buckets.values())
            if n == 0:
                return None, None
            bad = self._bad_latency(buckets, threshold)
            allowed = 1.0 - _LATENCY_KEYS[key]
            q = _histmod.quantiles_from_buckets(
                buckets, ps=(_LATENCY_KEYS[key],))
            observed = q.get("p%g" % (100.0 * _LATENCY_KEYS[key]))
            return (bad / float(n)) / allowed, observed
        if key == "err_pct":
            if total == 0:
                return None, None
            allowed = threshold / 100.0
            return (errors / float(total)) / allowed, \
                100.0 * errors / float(total)
        if key == "avail_pct":
            if avail is None:
                return None, None
            allowed = 1.0 - threshold / 100.0
            if allowed <= 0:
                allowed = 1e-9      # avail_pct:100 — any downtime burns
            return (1.0 - avail) / allowed, 100.0 * avail
        return None, None

    def evaluate(self, t=None):
        """Evaluate every objective over both windows; update the
        ``slo.*`` gauges; fire/clear structured violation events.

        Returns ``{"ok", "burn_rate", "objectives": {key: {burn_rate,
        short, long, value, threshold, firing}}, ...}``."""
        now = self._clock() if t is None else t
        with self._lock:
            self._prune(now)
            long_stats = self._window_stats(now, self.window_s)
            short_stats = self._window_stats(now, self.short_window_s)
            objectives = {}
            worst = 0.0
            fired, cleared = [], []
            for key, thr in sorted(self.spec.objectives.items()):
                b_long, v_long = self._objective_burn(key, thr, long_stats)
                b_short, v_short = self._objective_burn(key, thr,
                                                        short_stats)
                # multi-window AND: both must burn — the long window
                # screens out blips, the short screens out stale history
                burn = min(b_long, b_short) \
                    if b_long is not None and b_short is not None \
                    else (b_long if b_short is None else b_short)
                burn = 0.0 if burn is None else burn
                firing = burn >= self.burn_threshold
                was = key in self._active
                if firing and not was:
                    self._active.add(key)
                    fired.append((key, thr, burn, v_long))
                elif was and not firing:
                    self._active.discard(key)
                    cleared.append((key, thr, burn, v_long))
                worst = max(worst, burn)
                objectives[key] = {
                    "threshold": thr, "burn_rate": round(burn, 4),
                    "short": None if b_short is None else round(b_short, 4),
                    "long": None if b_long is None else round(b_long, 4),
                    "value": None if v_long is None else round(v_long, 3),
                    "firing": firing}
            result = {"ok": not self._active, "burn_rate": round(worst, 4),
                      "objectives": objectives,
                      "window_s": self.window_s,
                      "short_window_s": self.short_window_s,
                      "burn_threshold": self.burn_threshold}
        if spans.enabled():
            registry.gauge("slo.burn_rate").set(result["burn_rate"])
        for key, thr, burn, val in fired:
            ev = {"kind": "slo.violation", "objective": key,
                  "threshold": thr, "burn_rate": round(burn, 4),
                  "value": None if val is None else round(val, 3),
                  "t": now}
            with self._lock:
                self._violations.append(ev)
            if spans.enabled():
                registry.counter("slo.violations").inc()
            spans.event("slo.violation", objective=key, threshold=thr,
                        burn_rate=round(burn, 4))
        for key, thr, burn, val in cleared:
            ev = {"kind": "slo.clear", "objective": key, "threshold": thr,
                  "burn_rate": round(burn, 4), "t": now}
            with self._lock:
                self._violations.append(ev)
            spans.event("slo.clear", objective=key,
                        burn_rate=round(burn, 4))
        return result

    # ---------------------------------------------------------- reads
    def violations(self):
        """The structured fire/clear event log, oldest first."""
        with self._lock:
            return list(self._violations)

    def firing(self):
        """Objectives currently in violation."""
        with self._lock:
            return sorted(self._active)
