"""mxnet_tpu.telemetry: low-overhead runtime observability.

The framework-level counterpart of the reference engine profiler
(src/engine/profiler.cc hand-stamped per-op start/end times and dumped
chrome-trace JSON): a process-wide registry of named counters/gauges/timers
with per-step snapshots, structured spans at the hot seams (engine push,
executor compile-vs-cache-hit, fusion engage/fallback, kvstore push/pull,
io batch fetch), and a chrome-trace exporter that merges with the XLA
capture directory. Gated by ``MXNET_TELEMETRY=0|counters|trace``
(docs/ENV_VARS.md); off is the default and costs one mode check per
instrumented seam. Taxonomy and usage: docs/OBSERVABILITY.md.

    MXNET_TELEMETRY=trace python train.py
    python tools/mxtrace profile.json          # per-step table + top spans
"""
from __future__ import annotations

from .registry import (Counter, Gauge, StepStats, Timer, counter, counters,
                       gauge, mark_step, reset, snapshot, step_rows, timer)
from .spans import (MODE_COUNTERS, MODE_OFF, MODE_TRACE, NULL_SPAN,
                    clear_events, current_override, drain_events, enabled,
                    event, mode, set_mode, span, tracing)
from .trace import (SCHEMA_VERSION, build_trace, export_chrome_trace,
                    gap_summary, span_summary, summarize)

__all__ = [
    # registry
    "Counter", "Gauge", "Timer", "StepStats",
    "counter", "gauge", "timer", "counters", "snapshot",
    "mark_step", "step_rows", "reset",
    # spans / gating
    "MODE_OFF", "MODE_COUNTERS", "MODE_TRACE", "NULL_SPAN",
    "mode", "enabled", "tracing", "set_mode", "current_override",
    "span", "event", "drain_events", "clear_events",
    # export
    "SCHEMA_VERSION", "build_trace", "export_chrome_trace",
    "gap_summary", "span_summary", "summarize",
]
