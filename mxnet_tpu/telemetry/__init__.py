"""mxnet_tpu.telemetry: low-overhead runtime observability.

The framework-level counterpart of the reference engine profiler
(src/engine/profiler.cc hand-stamped per-op start/end times and dumped
chrome-trace JSON): a process-wide registry of named counters/gauges/timers
with per-step snapshots, structured spans at the hot seams (engine push,
executor compile-vs-cache-hit, fusion engage/fallback, kvstore push/pull,
io batch fetch), and a chrome-trace exporter that merges with the XLA
capture directory. Gated by ``MXNET_TELEMETRY=0|counters|trace``
(docs/ENV_VARS.md); off is the default and costs one mode check per
instrumented seam. Taxonomy and usage: docs/OBSERVABILITY.md.

Fleet plane (docs/OBSERVABILITY.md §Fleet): every Timer streams into a
log-bucketed mergeable :mod:`histogram` (p50/p95/p99 with fixed memory),
spans inherit a per-request trace context that the fleet RPC layer
propagates across processes, ``merge_traces`` aligns per-pid chrome
dumps into one clock-corrected timeline, and :mod:`slo` evaluates
declarative SLOs (``MXNET_SLO``) with multi-window burn rates.

    MXNET_TELEMETRY=trace python train.py
    python tools/mxtrace profile.json          # per-step table + top spans
"""
from __future__ import annotations

from . import histogram, lockwitness, slo
from .histogram import Histogram
from .lockwitness import (named_condition, named_lock, named_rlock,
                          note_dispatch, reset_witness, witness_report,
                          witnessing)
from .registry import (Counter, Gauge, StepStats, Timer, counter, counters,
                       gauge, hist_buckets, mark_step, reset, snapshot,
                       step_rows, timer)
from .slo import SloMonitor, SloSpec
from .spans import (MODE_COUNTERS, MODE_OFF, MODE_TRACE, NULL_SPAN,
                    clear_events, current_override, drain_events,
                    dropped_events, enabled, event, mode, record_span,
                    set_mode, set_trace_context, span, trace_context,
                    trace_scope, tracing)
from .trace import (SCHEMA_VERSION, build_trace, export_chrome_trace,
                    gap_summary, merge_traces, span_summary, summarize)

__all__ = [
    # registry
    "Counter", "Gauge", "Timer", "StepStats",
    "counter", "gauge", "timer", "counters", "snapshot", "hist_buckets",
    "mark_step", "step_rows", "reset",
    # histograms / SLO
    "Histogram", "histogram", "slo", "SloSpec", "SloMonitor",
    # spans / gating
    "MODE_OFF", "MODE_COUNTERS", "MODE_TRACE", "NULL_SPAN",
    "mode", "enabled", "tracing", "set_mode", "current_override",
    "span", "event", "record_span", "drain_events", "clear_events",
    "dropped_events",
    # trace context (fleet request tracing)
    "set_trace_context", "trace_context", "trace_scope",
    # lock witness (MXNET_CONCLINT=witness; analysis/concurrency_lint GL805)
    "lockwitness", "named_lock", "named_rlock", "named_condition",
    "note_dispatch", "witnessing", "witness_report", "reset_witness",
    # export
    "SCHEMA_VERSION", "build_trace", "export_chrome_trace",
    "gap_summary", "span_summary", "summarize", "merge_traces",
]
