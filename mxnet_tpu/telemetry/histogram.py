"""Log-bucketed streaming latency histogram (docs/OBSERVABILITY.md §Fleet).

Fixed memory, mergeable, bounded relative error — the representation every
hot-seam timer keeps so p50/p95/p99 exist without per-sample storage:

* ``record(seconds)`` is one bucket increment (an integer ``+= 1`` under a
  lock — CPython's ``list[i] += 1`` is not atomic, and the serving seams
  record from many threads).
* Buckets are geometric: ``BUCKETS_PER_DECADE`` (12) per factor-of-10 over
  ``LO``..``HI`` (1 µs .. 100 s), 96 buckets + 2 overflow sentinels.  The
  growth factor is ``10**(1/12)`` ≈ 1.2115, so any quantile read from a
  bucket's geometric midpoint is within ``10**(1/24) - 1`` ≈ 10.1% of the
  true sample value — the documented error bound.
* ``merge()`` is element-wise addition: associative, commutative, lossless
  with respect to the bucketed representation.  That is what lets the
  router fold per-replica snapshots into fleet rollups in any order.
* ``to_dict()`` is sparse (only non-zero buckets) and pure-JSON, so it
  rides health() snapshots and chrome-dump ``otherData`` unchanged.

Stdlib-only on purpose: ``tools/mxtrace`` imports the telemetry package
standalone (no jax, no numpy), and this module is on that path.
"""
from __future__ import annotations

import math
import threading

BUCKETS_PER_DECADE = 12
LO = 1e-6                     # 1 µs — bucket 0 upper edge region
HI = 100.0                    # 100 s — everything above lands in overflow
DECADES = 8                   # log10(HI / LO)
NUM_BUCKETS = BUCKETS_PER_DECADE * DECADES          # 96 finite buckets
# bucket index for value v (LO <= v < HI):
#   floor(log10(v / LO) * BUCKETS_PER_DECADE)
# under-/overflow get dedicated sentinel buckets so counts are never lost.
UNDER = NUM_BUCKETS            # v < LO (incl. zero/negative clamps)
OVER = NUM_BUCKETS + 1         # v >= HI
TOTAL_BUCKETS = NUM_BUCKETS + 2

_LOG10_LO = math.log10(LO)
# Relative half-width of one bucket read at its geometric midpoint.
REL_ERROR = 10.0 ** (1.0 / (2 * BUCKETS_PER_DECADE)) - 1.0   # ~10.1%


def bucket_index(seconds):
    """Bucket index for a duration in seconds (sentinels included)."""
    if seconds < LO:
        return UNDER
    if seconds >= HI:
        return OVER
    i = int((math.log10(seconds) - _LOG10_LO) * BUCKETS_PER_DECADE)
    # float edge: log10 can land exactly on NUM_BUCKETS for v ~= HI
    return i if i < NUM_BUCKETS else OVER


def bucket_bounds(i):
    """(lo, hi) seconds covered by finite bucket ``i``."""
    lo = 10.0 ** (_LOG10_LO + i / BUCKETS_PER_DECADE)
    hi = 10.0 ** (_LOG10_LO + (i + 1) / BUCKETS_PER_DECADE)
    return lo, hi


def _bucket_mid(i):
    if i == UNDER:
        return LO
    if i == OVER:
        return HI
    return 10.0 ** (_LOG10_LO + (i + 0.5) / BUCKETS_PER_DECADE)


class Histogram:
    """Fixed-size log-bucketed histogram of durations (seconds)."""

    __slots__ = ("_counts", "_lock")

    def __init__(self):
        self._counts = [0] * TOTAL_BUCKETS
        self._lock = threading.Lock()

    # ------------------------------------------------------------ write
    def record(self, seconds):
        i = bucket_index(seconds)
        with self._lock:
            self._counts[i] += 1

    # ------------------------------------------------------------- read
    @property
    def count(self):
        with self._lock:
            return sum(self._counts)

    def quantile(self, p):
        """Value (seconds) at quantile ``p`` in [0, 1]; None when empty.

        Reads the geometric midpoint of the bucket holding the p-th
        sample — within ``REL_ERROR`` (~10%) of the true sample for
        values inside [LO, HI); sentinel buckets answer their edge."""
        if not 0.0 <= p <= 1.0:
            raise ValueError("quantile p must be in [0, 1], got %r" % (p,))
        with self._lock:
            counts = list(self._counts)
        total = sum(counts)
        if total == 0:
            return None
        # rank of the target sample, 1-based, ceil(p * total) clamped
        rank = max(1, min(total, int(math.ceil(p * total))))
        seen = 0
        # scan order puts UNDER first (smallest values), then finite
        # buckets ascending, then OVER — rank order over values.
        for i in [UNDER] + list(range(NUM_BUCKETS)) + [OVER]:
            seen += counts[i]
            if seen >= rank:
                return _bucket_mid(i)
        return _bucket_mid(OVER)      # unreachable

    def quantiles_ms(self, ps=(0.5, 0.95, 0.99)):
        """{"p50": ms, ...} for the given quantiles; {} when empty."""
        out = {}
        for p in ps:
            q = self.quantile(p)
            if q is None:
                return {}
            out["p%g" % (100.0 * p)] = q * 1000.0
        return out

    # ------------------------------------------------------- merge/wire
    def merge(self, other):
        """Fold ``other`` (Histogram or to_dict() output) into self."""
        if isinstance(other, Histogram):
            with other._lock:
                add = list(other._counts)
            with self._lock:
                for i, n in enumerate(add):
                    self._counts[i] += n
            return self
        # dict form: sparse {index: count}
        buckets = other.get("buckets", other) if isinstance(other, dict) \
            else other
        with self._lock:
            for k, n in buckets.items():
                i = int(k)
                if 0 <= i < TOTAL_BUCKETS and n > 0:
                    self._counts[i] += int(n)
        return self

    def to_dict(self):
        """Sparse JSON-safe snapshot: {"v": 1, "buckets": {"i": count}}."""
        with self._lock:
            buckets = {str(i): n for i, n in enumerate(self._counts) if n}
        return {"v": 1, "buckets": buckets}

    @classmethod
    def from_dict(cls, d):
        h = cls()
        h.merge(d)
        return h

    def delta_since(self, prev_buckets):
        """Sparse bucket delta vs a previous dense/sparse snapshot.

        ``prev_buckets`` is the {index: count} map a prior ``to_dict()``
        carried (or None).  Returns only buckets that grew — the compact
        increment a replica ships in each health() snapshot."""
        with self._lock:
            cur = list(self._counts)
        prev = prev_buckets or {}
        out = {}
        for i, n in enumerate(cur):
            d = n - int(prev.get(str(i), 0))
            if d > 0:
                out[str(i)] = d
        return out

    def clear(self):
        with self._lock:
            self._counts = [0] * TOTAL_BUCKETS

    def __repr__(self):
        q = self.quantiles_ms()
        return "Histogram(n=%d%s)" % (
            self.count,
            "".join(", %s=%.3fms" % kv for kv in sorted(q.items())))


def merge_bucket_maps(*maps):
    """Merge sparse {index: count} maps (associative, commutative)."""
    out = {}
    for m in maps:
        if not m:
            continue
        for k, n in m.items():
            out[k] = out.get(k, 0) + int(n)
    return out


def quantiles_from_buckets(buckets, ps=(0.5, 0.95, 0.99)):
    """{"p50": ms, ...} straight from a sparse bucket map (router path)."""
    if not buckets:
        return {}
    return Histogram.from_dict({"buckets": buckets}).quantiles_ms(ps)
