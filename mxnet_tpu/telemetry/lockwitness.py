"""Lock witness — the measured side of the GL8xx concurrency analyzer.

The static lint (``analysis/concurrency_lint.py``) proves what it can
about lock discipline from the AST; this module witnesses what actually
happens. Under ``MXNET_CONCLINT=witness`` the repo's named-lock
construction sites (``named_lock``/``named_rlock``/``named_condition`` in
the serving engine, the fleet router/supervisor/replica, the executable
cache and the checkpoint writer) return instrumented wrappers that
record, per thread, the order locks are acquired in and how long they are
held:

  * a real lock-order inversion — some thread acquires X then Y after any
    thread acquired Y then X — is recorded as an ``inversion`` event the
    moment the reversed edge appears in the global acquisition graph;
  * a hold longer than ``MXNET_CONCLINT_HOLD_MS`` (default 50) is a
    ``long_hold`` event, flagged ``dispatch_seam`` when ``note_dispatch``
    ticked while the lock was held — the lock sat across device-dispatch
    work, the exact shape that serializes the batcher behind a collective
    or a compile;
  * every lock keeps acquisition/contention/wait/hold statistics for the
    mxtrace contention table (``otherData.lock_witness`` in chrome dumps,
    rendered by ``tools/mxtrace``).

``analysis.concurrency_lint.lint_lock_witness`` turns the event list into
GL805 diagnostics; the bind-time pass suite and ``graphlint --concurrency
--witness dump.json`` both consume ``witness_report()``.

Off (the default) the factories return PLAIN ``threading`` primitives, so
an unarmed run pays one env read per lock *construction* and nothing per
acquire. ``set_mode()`` overrides the env for tests. The wrappers define
the private ``Condition`` hooks (``_is_owned``/``_release_save``/
``_acquire_restore``) so ``threading.Condition(witness_lock)`` releases
end the hold measurement exactly like a plain release.
"""
from __future__ import annotations

import os
import threading
import time

__all__ = ["named_lock", "named_rlock", "named_condition", "note_dispatch",
           "witnessing", "set_mode", "current_override", "witness_report",
           "reset_witness", "hold_threshold_ms"]

MODE_OFF, MODE_WITNESS = 0, 1
_MODE_NAMES = {"": MODE_OFF, "0": MODE_OFF, "off": MODE_OFF,
               "false": MODE_OFF,
               "witness": MODE_WITNESS, "1": MODE_WITNESS,
               "on": MODE_WITNESS, "true": MODE_WITNESS}

_override = None
_warned = set()

# all witness bookkeeping below is guarded by this one registry lock —
# deliberately a bare threading.Lock, never a witness wrapper (the witness
# must not witness itself)
_reg_lock = threading.Lock()
_stats: dict = {}                 # lock name -> stats dict
_edges: dict = {}                 # (first, then) -> {"count", "threads"}
_events: list = []                # bounded inversion/long_hold events
_events_dropped = [0]
_MAX_EVENTS = 512
_dispatch_epoch = [0]
_inversions_seen: set = set()     # frozenset({a, b}) pairs already evented
_tls = threading.local()


def _env_mode() -> int:
    raw = os.environ.get("MXNET_CONCLINT", "").strip().lower()
    m = _MODE_NAMES.get(raw)
    if m is None:
        if raw not in _warned:
            _warned.add(raw)
            import logging

            logging.getLogger("mxnet_tpu").warning(
                "MXNET_CONCLINT=%r is not a recognized mode (0|witness); "
                "the lock witness stays OFF", raw)
        return MODE_OFF
    return m


def mode() -> int:
    """The active mode. Reads the env on every call (like
    telemetry.spans.mode) so tests and subprocesses can flip it live."""
    return _override if _override is not None else _env_mode()


def witnessing() -> bool:
    return mode() >= MODE_WITNESS


def set_mode(m):
    """Override the env gate: ``"0"``/``"witness"`` (or the int
    constants), ``None`` to fall back to MXNET_CONCLINT."""
    global _override
    if m is None:
        _override = None
        return
    if isinstance(m, str):
        if m.strip().lower() not in _MODE_NAMES:
            raise ValueError("unknown conclint mode %r" % m)
        m = _MODE_NAMES[m.strip().lower()]
    if m not in (MODE_OFF, MODE_WITNESS):
        raise ValueError("unknown conclint mode %r" % m)
    _override = m


def current_override():
    return _override


def hold_threshold_ms(default: float = 50.0) -> float:
    """GL805 long-hold threshold (``MXNET_CONCLINT_HOLD_MS``, default 50):
    a hold longer than this across a dispatch seam is witness-reported."""
    raw = os.environ.get("MXNET_CONCLINT_HOLD_MS", "").strip()
    if not raw:
        return default
    try:
        val = float(raw)
        if val <= 0:
            raise ValueError
        return val
    except ValueError:
        if raw not in _warned:
            _warned.add(raw)
            import logging

            logging.getLogger("mxnet_tpu").warning(
                "MXNET_CONCLINT_HOLD_MS=%r is not a positive number; "
                "using %.0f", raw, default)
        return default


def note_dispatch():
    """Tick the dispatch-seam epoch. The serving engine calls this once
    per executable dispatch; a lock whose hold spans a tick was held
    across device work. Unconditional integer bump — cheaper than the
    mode check it would otherwise hide behind."""
    _dispatch_epoch[0] += 1


def _held() -> list:
    """This thread's stack of held witness locks:
    ``[lock, name, t_acquired, epoch_at_acquire, reentrant]`` entries."""
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _stat(name: str) -> dict:
    """Per-lock stats row; caller holds ``_reg_lock``."""
    st = _stats.get(name)
    if st is None:
        st = _stats[name] = {"acquisitions": 0, "contentions": 0,
                             "wait_s": 0.0, "hold_s": 0.0, "max_hold_s": 0.0,
                             "long_holds": 0, "threads": {}}
    return st


def _append_event(ev: dict):
    """Bounded event append; caller holds ``_reg_lock``."""
    if len(_events) >= _MAX_EVENTS:
        _events_dropped[0] += 1
        return
    _events.append(ev)


class _WitnessLock:
    """``threading.Lock`` wrapper recording order edges, waits and holds."""

    _reentrant = False

    def __init__(self, name: str):
        self.name = name
        self._lock = self._make()
        with _reg_lock:
            _stat(name)

    def _make(self):
        return threading.Lock()

    # ------------------------------------------------------------ acquire
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held()
        if self._reentrant and any(e[0] is self for e in held):
            # recursion level: no edges, no contention — the outer
            # acquisition owns the hold window
            got = self._lock.acquire(blocking, timeout)
            if got:
                held.append([self, self.name, time.perf_counter(),
                             _dispatch_epoch[0], True])
            return got
        t0 = time.perf_counter()
        got = self._lock.acquire(False)
        contended = not got
        if not got:
            if not blocking:
                return False
            got = self._lock.acquire(True, timeout)
            if not got:
                # timed out: the contention (and the fruitless wait) still
                # happened — the table must show it
                with _reg_lock:
                    st = _stat(self.name)
                    st["contentions"] += 1
                    st["wait_s"] += time.perf_counter() - t0
                return False
        t1 = time.perf_counter()
        self._note_acquired(held, t1, t1 - t0 if contended else 0.0,
                            contended)
        return True

    def _note_acquired(self, held, t_now, wait_s, contended):
        tname = threading.current_thread().name
        with _reg_lock:
            st = _stat(self.name)
            st["acquisitions"] += 1
            if contended:
                st["contentions"] += 1
                st["wait_s"] += wait_s
            st["threads"][tname] = st["threads"].get(tname, 0) + 1
            for entry in held:
                if entry[4] or entry[1] == self.name:
                    continue
                edge = (entry[1], self.name)
                row = _edges.get(edge)
                if row is None:
                    row = _edges[edge] = {"count": 0, "threads": set()}
                row["count"] += 1
                if len(row["threads"]) < 4:
                    row["threads"].add(tname)
                rev = (self.name, entry[1])
                if rev in _edges:
                    pair = frozenset(edge)
                    if pair not in _inversions_seen:
                        _inversions_seen.add(pair)
                        _append_event({
                            "kind": "inversion",
                            "first": entry[1], "then": self.name,
                            "thread": tname,
                            "prior_order": "%s -> %s" % rev,
                            "prior_count": _edges[rev]["count"]})
        held.append([self, self.name, t_now, _dispatch_epoch[0], False])

    # ------------------------------------------------------------ release
    def release(self):
        held = _held()
        entry = None
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                entry = held.pop(i)
                break
        if entry is not None and not entry[4]:
            hold = time.perf_counter() - entry[2]
            seam = _dispatch_epoch[0] != entry[3]
            thr = hold_threshold_ms() / 1e3
            with _reg_lock:
                st = _stat(self.name)
                st["hold_s"] += hold
                if hold > st["max_hold_s"]:
                    st["max_hold_s"] = hold
                if hold > thr:
                    st["long_holds"] += 1
                    _append_event({
                        "kind": "long_hold", "lock": self.name,
                        "hold_ms": hold * 1e3,
                        "threshold_ms": thr * 1e3,
                        "thread": threading.current_thread().name,
                        "dispatch_seam": seam})
        self._lock.release()

    # ------------------------------------------------- context / Condition
    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        probe = getattr(self._lock, "locked", None)
        return probe() if probe is not None else self._is_owned()

    def _is_owned(self) -> bool:
        # Condition's ownership probe: answer from the thread-local stack
        # instead of the default try-acquire probe (which would show up as
        # a phantom acquisition in the stats)
        return any(e[0] is self for e in _held())

    def _release_save(self):
        self.release()
        return 1

    def _acquire_restore(self, state):
        self.acquire()

    def __repr__(self):
        return "<%s %r>" % (type(self).__name__, self.name)


class _WitnessRLock(_WitnessLock):
    """``threading.RLock`` wrapper: recursion levels piggyback on the
    outer acquisition's hold window."""

    _reentrant = True

    def _make(self):
        return threading.RLock()

    def _release_save(self):
        n = 0
        while any(e[0] is self for e in _held()):
            self.release()
            n += 1
        return n

    def _acquire_restore(self, state):
        for _ in range(max(1, state)):
            self.acquire()


# ------------------------------------------------------------- factories

def named_lock(name: str):
    """A named mutex: plain ``threading.Lock`` unless witnessing."""
    if not witnessing():
        return threading.Lock()
    return _WitnessLock(name)


def named_rlock(name: str):
    """A named reentrant mutex: plain ``threading.RLock`` unless
    witnessing."""
    if not witnessing():
        return threading.RLock()
    return _WitnessRLock(name)


def named_condition(name: str, lock=None):
    """A named condition variable. ``lock=None`` gets its own (witnessed)
    lock; passing an existing lock aliases the condition to it — same
    semantics as ``threading.Condition(lock)``."""
    if not witnessing():
        return threading.Condition(lock)
    if lock is None:
        lock = _WitnessLock(name)
    return threading.Condition(lock)


# --------------------------------------------------------------- reports

def witness_report() -> dict:
    """Everything the witness recorded: per-lock stats rows, the
    acquisition-order edge list, and the inversion/long-hold events.
    ``analysis.concurrency_lint.lint_lock_witness`` maps it to GL805;
    ``telemetry.trace.build_trace`` embeds it in chrome dumps for
    mxtrace's contention table."""
    with _reg_lock:
        locks = []
        for name in sorted(_stats):
            st = _stats[name]
            locks.append({
                "name": name,
                "acquisitions": st["acquisitions"],
                "contentions": st["contentions"],
                "wait_ms": round(st["wait_s"] * 1e3, 3),
                "hold_ms": round(st["hold_s"] * 1e3, 3),
                "max_hold_ms": round(st["max_hold_s"] * 1e3, 3),
                "long_holds": st["long_holds"],
                "threads": dict(st["threads"])})
        edges = [{"first": a, "then": b, "count": row["count"],
                  "threads": sorted(row["threads"])}
                 for (a, b), row in sorted(_edges.items())]
        events = [dict(ev) for ev in _events]
        dropped = _events_dropped[0]
    return {"enabled": witnessing(),
            "threshold_ms": hold_threshold_ms(),
            "dispatch_epochs": _dispatch_epoch[0],
            "locks": locks, "edges": edges, "events": events,
            "events_dropped": dropped}


def reset_witness():
    """Drop all recorded stats/edges/events (tests, capture windows).
    Locks currently held keep working: release() re-creates stats rows on
    demand."""
    with _reg_lock:
        _stats.clear()
        _edges.clear()
        del _events[:]
        _events_dropped[0] = 0
        _inversions_seen.clear()
