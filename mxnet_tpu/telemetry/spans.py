"""Structured spans + the MXNET_TELEMETRY mode gate.

Modes (``MXNET_TELEMETRY``):
  * ``0`` (default) — off. The one contract that matters on the hot path:
    ``span()`` returns a process-wide singleton no-op context manager, so a
    disabled run allocates NO span objects and pays one env read per
    instrumented seam (measured ~2-3us; seams fire at batch frequency, so
    well under 1% of any training step). The env is deliberately re-read
    every check so subprocesses and tests can flip the gate live.
  * ``counters`` — the registry (counters/gauges/timers + StepStats) is
    live, span events are NOT buffered.
  * ``trace`` — counters plus span events into a bounded ring buffer, for
    chrome-trace export (trace.py).

``set_mode()`` overrides the env for the process (tests, profiler capture
windows); ``None`` reverts to the env value. Span timestamps are
``time.perf_counter`` anchored to a process epoch recorded next to
``time.time`` so the exporter can place spans on the wall clock (the
chrome-trace ``ts`` contract, microseconds).
"""
from __future__ import annotations

import collections
import os
import threading
import time

__all__ = ["mode", "enabled", "tracing", "set_mode", "current_override",
           "span", "event", "record_span", "drain_events", "clear_events",
           "epoch", "dropped_events", "set_trace_context", "trace_context",
           "trace_scope"]

MODE_OFF, MODE_COUNTERS, MODE_TRACE = 0, 1, 2
_MODE_NAMES = {"0": MODE_OFF, "": MODE_OFF, "off": MODE_OFF,
               "false": MODE_OFF,
               "counters": MODE_COUNTERS, "1": MODE_COUNTERS,
               "true": MODE_COUNTERS, "on": MODE_COUNTERS,
               "trace": MODE_TRACE}

_override = None  # set_mode() value, wins over the env
_warned_modes = set()
_lock = threading.Lock()

# perf_counter/wall-clock epoch pair: spans are stamped with perf_counter
# (monotonic, ns resolution) and exported as wall-clock microseconds
_EPOCH_PERF = time.perf_counter()
_EPOCH_WALL = time.time()

def _max_events():
    """MXNET_TELEMETRY_MAX_EVENTS, defaulting on malformed values — a bad
    knob must log, not kill `import mxnet_tpu` (engine.py imports this
    module unconditionally)."""
    raw = os.environ.get("MXNET_TELEMETRY_MAX_EVENTS", "200000")
    try:
        return max(1, int(raw))
    except ValueError:
        import logging

        logging.getLogger("mxnet_tpu").warning(
            "MXNET_TELEMETRY_MAX_EVENTS=%r is not an integer; using the "
            "default 200000", raw)
        return 200000


_events = collections.deque(maxlen=_max_events())
_dropped = [0]                # ring-buffer overflow count (satellite:
_dropped_lock = threading.Lock()   # a truncated trace must say so)


def _append_event(tup):
    """Ring-buffer append that ACCOUNTS for truncation: once the deque is
    full, every append evicts the oldest span — tick
    ``telemetry.dropped_events`` so a truncated dump cannot masquerade as
    a complete one (trace.py stamps the count into otherData, mxtrace
    --check reports it)."""
    if len(_events) == _events.maxlen:
        with _dropped_lock:
            _dropped[0] += 1
        from . import registry

        registry.counter("telemetry.dropped_events").inc()
    _events.append(tup)


def dropped_events():
    """Spans evicted from the ring buffer since the last clear."""
    return _dropped[0]


# --------------------------------------------------------- trace context
# The distributed-tracing propagation point: the fleet router mints a
# trace_id per request, rpc.py ships it in the call frame, and RpcServer
# installs it here (thread-local) around the handler — so every span the
# handler's thread records inherits the id without any call-site plumbing.
_trace_ctx = threading.local()


def set_trace_context(trace_id):
    """Install (or clear, with None) the current thread's trace id."""
    _trace_ctx.tid = trace_id


def trace_context():
    """The current thread's trace id, or None."""
    return getattr(_trace_ctx, "tid", None)


class trace_scope:
    """Context manager: install a trace id for the block, restoring the
    previous one on exit (RpcServer handler wrap, engine dispatch)."""

    __slots__ = ("_tid", "_prev")

    def __init__(self, trace_id):
        self._tid = trace_id

    def __enter__(self):
        self._prev = trace_context()
        set_trace_context(self._tid)
        return self

    def __exit__(self, *exc):
        set_trace_context(self._prev)
        return False


def _env_mode():
    raw = os.environ.get("MXNET_TELEMETRY", "0").strip().lower()
    m = _MODE_NAMES.get(raw)
    if m is None:
        if raw not in _warned_modes:
            _warned_modes.add(raw)
            import logging

            logging.getLogger("mxnet_tpu").warning(
                "MXNET_TELEMETRY=%r is not a recognized mode "
                "(0|counters|trace); telemetry stays OFF", raw)
        return MODE_OFF
    return m


def mode() -> int:
    """The active mode (MODE_OFF/MODE_COUNTERS/MODE_TRACE). Reads the env
    on every call so tests and subprocesses can flip it; call sites on hot
    paths guard with ``enabled()``/``tracing()`` once per operation, not
    per element."""
    return _override if _override is not None else _env_mode()


def enabled() -> bool:
    return mode() >= MODE_COUNTERS


def tracing() -> bool:
    return mode() >= MODE_TRACE


def set_mode(m):
    """Override the env gate: ``"0"``/``"counters"``/``"trace"`` (or the
    int constants), ``None`` to fall back to MXNET_TELEMETRY."""
    global _override
    if m is None:
        _override = None
        return
    if isinstance(m, str):
        if m.strip().lower() not in _MODE_NAMES:
            raise ValueError("unknown telemetry mode %r" % m)
        m = _MODE_NAMES[m.strip().lower()]
    if m not in (MODE_OFF, MODE_COUNTERS, MODE_TRACE):
        raise ValueError("unknown telemetry mode %r" % m)
    _override = m


def current_override():
    """The active ``set_mode`` override (int mode or None) — callers that
    force a mode for a window (profiler capture) save and restore this."""
    return _override


def epoch():
    """(perf_counter_epoch, wall_epoch) — the exporter's timebase."""
    return _EPOCH_PERF, _EPOCH_WALL


class _NullSpan:
    """The disabled-path span: a single shared instance, every method a
    no-op. ``span() is span()`` when telemetry is off (test-pinned)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        pass


NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "attrs", "_t0")

    def __init__(self, name, attrs):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def set(self, **attrs):
        """Attach attributes discovered mid-span (e.g. compile vs hit)."""
        self.attrs.update(attrs)

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        tid = trace_context()
        if tid is not None and "trace_id" not in self.attrs:
            self.attrs["trace_id"] = tid
        _append_event((self.name, self._t0, t1 - self._t0,
                       threading.get_ident(), self.attrs))
        return False


def span(name, **attrs):
    """A context manager timing one named operation. Off → the shared
    no-op singleton (zero allocation beyond the kwargs dict — hot seams
    that cannot afford even that guard with ``tracing()`` first)."""
    if mode() < MODE_TRACE:
        return NULL_SPAN
    return _Span(name, attrs)


def event(name, **attrs):
    """An instant (zero-duration) event."""
    if mode() < MODE_TRACE:
        return
    tid = trace_context()
    if tid is not None and "trace_id" not in attrs:
        attrs["trace_id"] = tid
    _append_event((name, time.perf_counter(), 0.0,
                   threading.get_ident(), attrs))


def record_span(name, t0_perf, dur_s, **attrs):
    """Append a span whose interval was measured OUT of band — e.g. the
    per-request replica queue-wait, whose start (enqueue) and end
    (dispatch pull) are observed on different threads. No-op unless
    tracing."""
    if mode() < MODE_TRACE:
        return
    tid = trace_context()
    if tid is not None and "trace_id" not in attrs:
        attrs["trace_id"] = tid
    _append_event((name, t0_perf, dur_s, threading.get_ident(), attrs))


def drain_events():
    """Snapshot-and-keep the recorded span tuples
    ``(name, t0_perf, dur_s, thread_ident, attrs)`` oldest-first."""
    return list(_events)


def clear_events():
    _events.clear()
    with _dropped_lock:
        _dropped[0] = 0
