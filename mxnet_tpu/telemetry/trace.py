"""Chrome-trace export + live summary for the telemetry subsystem.

The exporter honors the reference ``MXDumpProfile`` contract
(src/engine/profiler.cc wrote ``traceEvents`` JSON the chrome://tracing
viewer loads directly): complete ``"ph": "X"`` events with microsecond
``ts``/``dur``, process/thread metadata events, plus an ``otherData``
block carrying the counter snapshot and per-step rows — the part the
reference never had and ``tools/mxtrace`` tables are built from. When a
JAX/XLA capture ran alongside (profiler.py), the dump records the XLA
trace directory so viewers and ``profiler.trace_files()`` can merge both.
"""
from __future__ import annotations

import json
import os

from . import histogram, lockwitness, registry, spans

__all__ = ["export_chrome_trace", "summarize", "span_summary",
           "gap_summary", "merge_traces", "SCHEMA_VERSION"]

SCHEMA_VERSION = 1

_PID = 1  # single framework process lane (merge_traces re-pids by os pid)


def _category(name):
    """Span taxonomy: the dotted prefix is the category lane
    (``engine.push`` → ``engine``; docs/OBSERVABILITY.md)."""
    return name.split(".", 1)[0] if "." in name else name


def build_trace(xla_trace_dir=None, extra=None):
    """The chrome-trace dict for the events recorded so far."""
    perf0, wall0 = spans.epoch()
    raw = spans.drain_events()
    tids = {}
    events = [{"ph": "M", "pid": _PID, "name": "process_name",
               "args": {"name": "mxnet_tpu framework"}}]
    for name, t0, dur, ident, attrs in raw:
        tid = tids.get(ident)
        if tid is None:
            tid = tids[ident] = len(tids) + 1
            events.append({"ph": "M", "pid": _PID, "tid": tid,
                           "name": "thread_name",
                           "args": {"name": "py-thread-%d" % tid}})
        ev = {"ph": "X", "pid": _PID, "tid": tid,
              "cat": _category(name), "name": name,
              "ts": round((wall0 + (t0 - perf0)) * 1e6, 1),
              "dur": round(dur * 1e6, 1)}
        if attrs:
            ev["args"] = {k: _jsonable(v) for k, v in attrs.items()}
        events.append(ev)
    other = {"mxnet_telemetry": SCHEMA_VERSION,
             "counters": registry.snapshot(),
             "steps": registry.step_rows(),
             "pid": os.getpid(),
             "dropped": spans.dropped_events()}
    if xla_trace_dir:
        other["xla_trace_dir"] = os.path.abspath(xla_trace_dir)
    if lockwitness.witnessing():
        # MXNET_CONCLINT=witness: ship the lock-contention/inversion record
        # with the trace so mxtrace renders the table and
        # `graphlint --concurrency --witness dump.json` can judge it (GL805)
        other["lock_witness"] = lockwitness.witness_report()
    if extra:
        other.update(extra)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": other}


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (tuple, list)):
        return [_jsonable(x) for x in v]
    return str(v)


def export_chrome_trace(path, xla_trace_dir=None, extra=None):
    """Write the chrome-trace JSON to ``path``; returns the trace dict."""
    trace = build_trace(xla_trace_dir=xla_trace_dir, extra=extra)
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)
    return trace


def span_summary(trace=None, top=25):
    """Aggregate span wall time by name, heaviest first — the per-op stat
    table of the reference engine profiler, over framework spans. Accepts a
    loaded trace dict (mxtrace) or None for the live buffer.

    Each row carries p50/p95/p99 milliseconds from a log-bucketed
    histogram of the span's durations (bounded ~10% relative error) —
    ``total/count`` means hide tail behavior."""
    acc = {}          # name -> [ms, count, Histogram]
    def _add(name, dur_s):
        row = acc.get(name)
        if row is None:
            row = acc[name] = [0.0, 0, histogram.Histogram()]
        row[0] += dur_s * 1000.0
        row[1] += 1
        row[2].record(dur_s)

    if trace is None:
        for name, _t0, dur, _ident, _attrs in spans.drain_events():
            _add(name, dur)
    else:
        for ev in trace.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            _add(ev.get("name", "?"), ev.get("dur", 0) / 1e6)
    rows = []
    for n, (ms, cnt, h) in acc.items():
        q = h.quantiles_ms()
        rows.append({"name": n, "ms": round(ms, 3), "count": cnt,
                     "p50_ms": round(q.get("p50", 0.0), 3),
                     "p95_ms": round(q.get("p95", 0.0), 3),
                     "p99_ms": round(q.get("p99", 0.0), 3)})
    rows.sort(key=lambda r: -r["ms"])
    return rows[:top]


def gap_summary(trace=None, prefix=None, top=25):
    """Inter-span host-gap attribution per span name: the time between one
    span's END and the NEXT same-name span's START on the same thread —
    for dispatch-shaped spans (``serving.decode_step``,
    ``serving.dispatch``) that is exactly the host time between an
    executable's return and the next enqueue, the seam the GL7xx
    dispatch lint prices (docs/static_analysis.md).

    Threaded spans interleave non-monotonically: a batcher's span can
    overlap the step span that contains it, so a successor may START
    before its predecessor ENDED and the raw gap goes negative. Negative
    gaps CLAMP TO ZERO per interval — they must not cancel real gaps
    elsewhere in the chain (the mxtrace gap-math fix).

    Accepts a loaded chrome-trace dict (mxtrace) or None for the live
    buffer (drains it, like ``span_summary``). ``prefix`` filters span
    names (``prefix="serving."``). Rows: ``{"name", "count", "intervals",
    "busy_ms", "gap_ms", "max_gap_ms", "clamped"}``, largest gap first.
    """
    per_site = {}  # (name, tid) -> list[(start_ms, dur_ms)]
    if trace is None:
        for name, t0, dur, ident, _attrs in spans.drain_events():
            if prefix and not name.startswith(prefix):
                continue
            per_site.setdefault((name, ident), []).append(
                (t0 * 1000.0, dur * 1000.0))
    else:
        for ev in trace.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            name = ev.get("name", "?")
            if prefix and not name.startswith(prefix):
                continue
            per_site.setdefault((name, ev.get("tid", 0)), []).append(
                (ev.get("ts", 0) / 1000.0, ev.get("dur", 0) / 1000.0))
    acc = {}  # name -> [count, intervals, busy, gap, max_gap, clamped]
    for (name, _tid), evs in per_site.items():
        evs.sort(key=lambda e: e[0])
        row = acc.setdefault(name, [0, 0, 0.0, 0.0, 0.0, 0])
        prev_end = None
        for start, dur in evs:
            row[0] += 1
            row[2] += dur
            if prev_end is not None:
                raw = start - prev_end
                row[1] += 1
                if raw < 0.0:
                    row[5] += 1  # clamped interval, not a negative credit
                else:
                    row[3] += raw
                    row[4] = max(row[4], raw)
            prev_end = max(prev_end, start + dur) if prev_end is not None \
                else start + dur
    rows = [{"name": n, "count": c, "intervals": it,
             "busy_ms": round(busy, 3), "gap_ms": round(gap, 3),
             "max_gap_ms": round(mx, 3), "clamped": cl}
            for n, (c, it, busy, gap, mx, cl) in acc.items()]
    rows.sort(key=lambda r: -r["gap_ms"])
    return rows[:top]


def _fold_counters(dst, src):
    """Fold one process's counter snapshot into a fleet rollup: counters
    and gauges add, timer rows add total_ms/count (quantile fields are
    per-process — rebuilt fleet-wide from merged buckets, not summed)."""
    for k, v in (src or {}).items():
        if isinstance(v, dict):
            d = dst.setdefault(k, {"total_ms": 0.0, "count": 0})
            d["total_ms"] = round(d.get("total_ms", 0.0)
                                  + (v.get("total_ms") or 0.0), 3)
            d["count"] = d.get("count", 0) + (v.get("count") or 0)
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            dst[k] = (dst.get(k) or 0) + v
    return dst


def merge_traces(dumps, offsets_s=None, labels=None):
    """Align per-process chrome dumps into ONE fleet timeline.

    ``dumps`` are ``build_trace()`` dicts (live or JSON-loaded), each
    self-identified by ``otherData.pid``. ``offsets_s`` maps pid → clock
    correction in SECONDS, ADDED to that process's timestamps — the
    router's per-connection midpoint handshake (rpc.py) measures these,
    so replica spans land on the router's wall clock and a request's
    router→rpc→replica→dispatch chain reads monotonically. ``labels``
    maps pid → display name (``router``, ``replica-0``).

    The merged dump keeps the single-process schema (mxtrace --check
    passes on it) plus ``otherData.merged`` and a per-process block:
    ``processes[pid] = {label, counters, dropped, clock_offset_ms}``.
    Top-level counters/dropped are fleet-folded; steps come from the
    first dump (the router's lane)."""
    offsets_s = offsets_s or {}
    labels = labels or {}
    events, processes, counters = [], {}, {}
    dropped_total, steps, used_pids = 0, None, set()
    fleet = None
    for i, dump in enumerate(dumps):
        if not isinstance(dump, dict):
            continue
        other = dump.get("otherData") or {}
        pid = other.get("pid")
        if not isinstance(pid, int) or pid in used_pids:
            pid = 100000 + i
            while pid in used_pids:
                pid += 1
        used_pids.add(pid)
        off = offsets_s.get(pid, offsets_s.get(str(pid), 0.0)) or 0.0
        label = labels.get(pid, labels.get(str(pid))) \
            or "pid-%d" % pid
        events.append({"ph": "M", "pid": pid, "name": "process_name",
                       "args": {"name": label}})
        for ev in dump.get("traceEvents", []):
            if not isinstance(ev, dict):
                continue
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                continue      # replaced by the labeled one above
            ev = dict(ev)
            ev["pid"] = pid
            if off and isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = round(ev["ts"] + off * 1e6, 1)
            events.append(ev)
        dropped = other.get("dropped") or 0
        dropped_total += dropped
        _fold_counters(counters, other.get("counters"))
        processes[str(pid)] = {
            "label": label, "dropped": dropped,
            "clock_offset_ms": round(off * 1000.0, 3),
            "counters": other.get("counters") or {}}
        if steps is None:
            steps = other.get("steps") or []
        if fleet is None and other.get("fleet"):
            fleet = other["fleet"]   # router's metrics() rollup survives
    merged_other = {"mxnet_telemetry": SCHEMA_VERSION,
                    "merged": True, "counters": counters,
                    "steps": steps or [], "dropped": dropped_total,
                    "processes": processes}
    if fleet is not None:
        merged_other["fleet"] = fleet
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": merged_other}


# counters the scoreboard cares about, reported per step when steps exist
_KEY_COUNTERS = ("executor.retrace", "executor.compile", "executor.cache_hit",
                 "fusion.fwd_engaged", "fusion.fwd_fallback",
                 "fusion.bwd_engaged",
                 "kvstore.push_bytes", "kvstore.pull_bytes",
                 "engine.push")


def summarize():
    """Live summary for bench.py: the full counter snapshot, per-step rates
    of the scoreboard counters, and the heaviest spans (trace mode only).

    ``{"mode", "counters", "num_steps", "per_step", "spans"}`` — all
    JSON-safe, cheap to build (no device work)."""
    snap = registry.snapshot()
    rows = registry.step_rows()
    out = {"mode": {0: "off", 1: "counters", 2: "trace"}[spans.mode()],
           "counters": snap, "num_steps": len(rows)}
    if rows:
        per_step = {}
        for key in _KEY_COUNTERS:
            total = sum(r["counters"].get(key, 0) for r in rows)
            if total:
                per_step[key] = round(total / float(len(rows)), 3)
        timed = [r["wall_ms"] for r in rows if r["wall_ms"] is not None]
        if timed:
            per_step["wall_ms"] = round(sum(timed) / len(timed), 3)
        out["per_step"] = per_step
    if spans.tracing():
        out["spans"] = span_summary(top=10)
    return out
