"""Process-wide instrument registry: counters, gauges, timers, StepStats.

The reference engine's profiler kept per-op stat tables inside the engine
(src/engine/profiler.cc); here the registry is the framework-wide single
source of truth every layer reports into — executor compiles/cache hits,
fusion engage decisions, kvstore bytes, io fetch latency — and every
consumer reads out of (Speedometer, Monitor.toc, bench.py, mxtrace).

Thread-safety: one process-wide lock guards instrument *creation*; each
instrument carries its own lock for mutation, so concurrent engine workers
incrementing different counters never contend on a global. All instruments
are monotonically named — ``counter("engine.push")`` get-or-creates — and
live for the process unless ``reset()`` is called (tests).
"""
from __future__ import annotations

import threading
import time

__all__ = ["Counter", "Gauge", "Timer", "StepStats",
           "counter", "gauge", "timer", "counters", "snapshot",
           "mark_step", "step_rows", "reset"]


class Counter:
    """Monotonic integer counter (exact under threads)."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name):
        self.name = name
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self._v += n

    @property
    def value(self):
        return self._v


class Gauge:
    """Last-written value (e.g. heartbeat age, dead-node count)."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name):
        self.name = name
        self._v = None
        self._lock = threading.Lock()

    def set(self, v):
        with self._lock:
            self._v = v

    @property
    def value(self):
        return self._v


class Timer:
    """Accumulated duration + call count. ``add`` takes SECONDS (what
    ``time.perf_counter`` deltas produce); readers get milliseconds."""

    __slots__ = ("name", "_total", "_count", "_lock")

    def __init__(self, name):
        self.name = name
        self._total = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def add(self, seconds):
        with self._lock:
            self._total += seconds
            self._count += 1

    @property
    def total_ms(self):
        return self._total * 1000.0

    @property
    def count(self):
        return self._count


_lock = threading.Lock()
_instruments = {}  # name -> instrument


def _get(name, cls):
    inst = _instruments.get(name)
    if inst is None:
        with _lock:
            inst = _instruments.get(name)
            if inst is None:
                inst = cls(name)
                _instruments[name] = inst
    if not isinstance(inst, cls):
        raise TypeError("instrument %r already exists as %s"
                        % (name, type(inst).__name__))
    return inst


def counter(name) -> Counter:
    return _get(name, Counter)


def gauge(name) -> Gauge:
    return _get(name, Gauge)


def timer(name) -> Timer:
    return _get(name, Timer)


def _items():
    """Stable view for iteration: another thread creating its first
    instrument mid-iteration (a pump thread's lazy ``timer()``) must not
    blow up a reader with 'dict changed size during iteration'."""
    with _lock:
        return sorted(_instruments.items())


def counters():
    """Flat name->value view of every counter (bench/tests convenience)."""
    return {n: i.value for n, i in _items() if isinstance(i, Counter)}


def snapshot():
    """Point-in-time view of EVERY instrument, JSON-safe."""
    out = {}
    for name, inst in _items():
        if isinstance(inst, Counter):
            out[name] = inst.value
        elif isinstance(inst, Gauge):
            out[name] = inst.value
        else:
            out[name] = {"total_ms": round(inst.total_ms, 3),
                         "count": inst.count}
    return out


class StepStats:
    """Per-step counter/timer deltas, ring-buffered.

    ``mark()`` closes the current step: it diffs every counter/timer against
    the previous mark and appends one row ``{"step", "wall_ms",
    "counters": {name: delta}, "timers": {name: {ms, count}}}``. Rows
    are bounded (``maxlen``) so a long fit cannot grow host memory without
    bound. The registry-global instance backs ``mark_step``/``step_rows``.
    """

    def __init__(self, maxlen=4096):
        self._lock = threading.Lock()
        self._maxlen = maxlen
        self._rows = []
        self._step = 0
        self._last_t = None
        self._last_counters = {}
        self._last_timers = {}

    def mark(self, wall_ms=None):
        now = time.perf_counter()
        with self._lock:
            cur_c, cur_t = {}, {}
            for name, inst in _items():
                if isinstance(inst, Counter):
                    cur_c[name] = inst.value
                elif isinstance(inst, Timer):
                    cur_t[name] = (inst.total_ms, inst.count)
            if wall_ms is None:
                wall_ms = ((now - self._last_t) * 1000.0
                           if self._last_t is not None else None)
            dc = {n: v - self._last_counters.get(n, 0)
                  for n, v in cur_c.items()
                  if v - self._last_counters.get(n, 0)}
            dt = {}
            for n, (ms, cnt) in cur_t.items():
                pms, pcnt = self._last_timers.get(n, (0.0, 0))
                if cnt - pcnt:
                    dt[n] = {"ms": round(ms - pms, 3), "count": cnt - pcnt}
            row = {"step": self._step,
                   "wall_ms": None if wall_ms is None else round(wall_ms, 3),
                   "counters": dc, "timers": dt}
            self._rows.append(row)
            if len(self._rows) > self._maxlen:
                del self._rows[: len(self._rows) - self._maxlen]
            self._step += 1
            self._last_t = now
            self._last_counters = cur_c
            self._last_timers = cur_t
            return row

    def rows(self, last=None):
        with self._lock:
            rows = list(self._rows)
        return rows if last is None else rows[-last:]

    def clear(self):
        with self._lock:
            self._rows = []
            self._step = 0
            self._last_t = None
            self._last_counters = {}
            self._last_timers = {}


_steps = StepStats()


def mark_step(wall_ms=None):
    """Close the current training step (Module.fit / SPMDTrainer call this
    once per batch when telemetry is enabled)."""
    return _steps.mark(wall_ms=wall_ms)


def step_rows(last=None):
    """The recorded per-step rows, oldest first (``last`` = only the most
    recent N)."""
    return _steps.rows(last=last)


def reset():
    """Drop every instrument and step row (tests / capture restart)."""
    global _instruments
    with _lock:
        _instruments = {}
    _steps.clear()
