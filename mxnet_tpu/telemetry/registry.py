"""Process-wide instrument registry: counters, gauges, timers, StepStats.

The reference engine's profiler kept per-op stat tables inside the engine
(src/engine/profiler.cc); here the registry is the framework-wide single
source of truth every layer reports into — executor compiles/cache hits,
fusion engage decisions, kvstore bytes, io fetch latency — and every
consumer reads out of (Speedometer, Monitor.toc, bench.py, mxtrace).

Thread-safety: one process-wide lock guards instrument *creation*; each
instrument carries its own lock for mutation, so concurrent engine workers
incrementing different counters never contend on a global. All instruments
are monotonically named — ``counter("engine.push")`` get-or-creates — and
live for the process unless ``reset()`` is called (tests).
"""
from __future__ import annotations

import os
import threading
import time

from . import histogram as _histmod

__all__ = ["Counter", "Gauge", "Timer", "StepStats",
           "counter", "gauge", "timer", "counters", "snapshot",
           "hist_buckets", "mark_step", "step_rows", "reset"]


def _hist_enabled():
    """MXNET_TELEMETRY_HIST gate (default ON): each Timer carries a
    fixed-memory log-bucketed histogram so hot-seam timers report
    p50/p95/p99 (docs/OBSERVABILITY.md §Fleet). Read at instrument
    creation — ``reset()`` (tests) picks up a flipped env."""
    raw = os.environ.get("MXNET_TELEMETRY_HIST", "1").strip().lower()
    return raw not in ("0", "off", "false")


class Counter:
    """Monotonic integer counter (exact under threads)."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name):
        self.name = name
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self._v += n

    @property
    def value(self):
        return self._v


class Gauge:
    """Last-written value (e.g. heartbeat age, dead-node count)."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name):
        self.name = name
        self._v = None
        self._lock = threading.Lock()

    def set(self, v):
        with self._lock:
            self._v = v

    @property
    def value(self):
        return self._v


class Timer:
    """Accumulated duration + call count. ``add`` takes SECONDS (what
    ``time.perf_counter`` deltas produce); readers get milliseconds.

    Unless ``MXNET_TELEMETRY_HIST=0``, every Timer also streams samples
    into a log-bucketed :class:`telemetry.histogram.Histogram` — one
    bucket increment per ``add``, fixed memory — so quantile readers
    (``quantiles_ms``, ``snapshot``, StepStats, mxtrace, fleet rollups)
    see tail latency, not just the mean."""

    __slots__ = ("name", "_total", "_count", "_lock", "hist")

    def __init__(self, name):
        self.name = name
        self._total = 0.0
        self._count = 0
        self._lock = threading.Lock()
        self.hist = _histmod.Histogram() if _hist_enabled() else None

    def add(self, seconds):
        with self._lock:
            self._total += seconds
            self._count += 1
        if self.hist is not None:
            self.hist.record(seconds)

    @property
    def total_ms(self):
        return self._total * 1000.0

    @property
    def count(self):
        return self._count

    def quantiles_ms(self, ps=(0.5, 0.95, 0.99)):
        """{"p50": ms, "p95": ms, "p99": ms} (bounded ~10% relative
        error); {} when the histogram is disabled or empty."""
        if self.hist is None:
            return {}
        return self.hist.quantiles_ms(ps)


_lock = threading.Lock()
_instruments = {}  # name -> instrument


def _get(name, cls):
    inst = _instruments.get(name)
    if inst is None:
        with _lock:
            inst = _instruments.get(name)
            if inst is None:
                inst = cls(name)
                _instruments[name] = inst
    if not isinstance(inst, cls):
        raise TypeError("instrument %r already exists as %s"
                        % (name, type(inst).__name__))
    return inst


def counter(name) -> Counter:
    return _get(name, Counter)


def gauge(name) -> Gauge:
    return _get(name, Gauge)


def timer(name) -> Timer:
    return _get(name, Timer)


def _items():
    """Stable view for iteration: another thread creating its first
    instrument mid-iteration (a pump thread's lazy ``timer()``) must not
    blow up a reader with 'dict changed size during iteration'."""
    with _lock:
        return sorted(_instruments.items())


def counters():
    """Flat name->value view of every counter (bench/tests convenience)."""
    return {n: i.value for n, i in _items() if isinstance(i, Counter)}


def snapshot():
    """Point-in-time view of EVERY instrument, JSON-safe. Timers with a
    live histogram additionally carry p50/p95/p99 milliseconds."""
    out = {}
    for name, inst in _items():
        if isinstance(inst, Counter):
            out[name] = inst.value
        elif isinstance(inst, Gauge):
            out[name] = inst.value
        else:
            row = {"total_ms": round(inst.total_ms, 3),
                   "count": inst.count}
            q = inst.quantiles_ms()
            if q:
                row.update({"p50_ms": round(q["p50"], 3),
                            "p95_ms": round(q["p95"], 3),
                            "p99_ms": round(q["p99"], 3)})
            out[name] = row
    return out


def hist_buckets():
    """Sparse histogram buckets per timer: {timer_name: {bucket: count}}.
    The wire form replica health() snapshots delta-encode and the router
    merges into fleet rollups (merge is element-wise add — associative)."""
    out = {}
    for name, inst in _items():
        if isinstance(inst, Timer) and inst.hist is not None:
            b = inst.hist.to_dict()["buckets"]
            if b:
                out[name] = b
    return out


class StepStats:
    """Per-step counter/timer deltas, ring-buffered.

    ``mark()`` closes the current step: it diffs every counter/timer against
    the previous mark and appends one row ``{"step", "wall_ms",
    "counters": {name: delta}, "timers": {name: {ms, count}}}``. Rows
    are bounded (``maxlen``) so a long fit cannot grow host memory without
    bound. The registry-global instance backs ``mark_step``/``step_rows``.
    """

    def __init__(self, maxlen=4096):
        self._lock = threading.Lock()
        self._maxlen = maxlen
        self._rows = []
        self._step = 0
        self._last_t = None
        self._last_counters = {}
        self._last_timers = {}
        self._last_hists = {}

    def mark(self, wall_ms=None):
        now = time.perf_counter()
        with self._lock:
            cur_c, cur_t, cur_h = {}, {}, {}
            for name, inst in _items():
                if isinstance(inst, Counter):
                    cur_c[name] = inst.value
                elif isinstance(inst, Timer):
                    cur_t[name] = (inst.total_ms, inst.count)
                    if inst.hist is not None:
                        cur_h[name] = inst.hist.to_dict()["buckets"]
            if wall_ms is None:
                wall_ms = ((now - self._last_t) * 1000.0
                           if self._last_t is not None else None)
            dc = {n: v - self._last_counters.get(n, 0)
                  for n, v in cur_c.items()
                  if v - self._last_counters.get(n, 0)}
            dt = {}
            for n, (ms, cnt) in cur_t.items():
                pms, pcnt = self._last_timers.get(n, (0.0, 0))
                if cnt - pcnt:
                    dt[n] = {"ms": round(ms - pms, 3), "count": cnt - pcnt}
                    # this step's OWN latency distribution, not the
                    # run-cumulative one: diff the buckets, read quantiles
                    prev_b = self._last_hists.get(n, {})
                    db = {k: v - prev_b.get(k, 0)
                          for k, v in cur_h.get(n, {}).items()
                          if v - prev_b.get(k, 0) > 0}
                    if db:
                        q = _histmod.quantiles_from_buckets(db)
                        dt[n].update(
                            {"p50_ms": round(q["p50"], 3),
                             "p95_ms": round(q["p95"], 3),
                             "p99_ms": round(q["p99"], 3)})
            row = {"step": self._step,
                   "wall_ms": None if wall_ms is None else round(wall_ms, 3),
                   "counters": dc, "timers": dt}
            self._rows.append(row)
            if len(self._rows) > self._maxlen:
                del self._rows[: len(self._rows) - self._maxlen]
            self._step += 1
            self._last_t = now
            self._last_counters = cur_c
            self._last_timers = cur_t
            self._last_hists = cur_h
            return row

    def rows(self, last=None):
        with self._lock:
            rows = list(self._rows)
        return rows if last is None else rows[-last:]

    def clear(self):
        with self._lock:
            self._rows = []
            self._step = 0
            self._last_t = None
            self._last_counters = {}
            self._last_timers = {}
            self._last_hists = {}


_steps = StepStats()


def mark_step(wall_ms=None):
    """Close the current training step (Module.fit / SPMDTrainer call this
    once per batch when telemetry is enabled)."""
    return _steps.mark(wall_ms=wall_ms)


def step_rows(last=None):
    """The recorded per-step rows, oldest first (``last`` = only the most
    recent N)."""
    return _steps.rows(last=last)


def reset():
    """Drop every instrument and step row (tests / capture restart)."""
    global _instruments
    with _lock:
        _instruments = {}
    _steps.clear()
