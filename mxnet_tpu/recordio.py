"""RecordIO: the reference's packed binary record format.

Counterpart of python/mxnet/recordio.py over dmlc-core's recordio framing:
each record is [magic u32][lrecord u32][payload][pad to 4B] with
magic 0xced7230a and lrecord = (cflag << 29) | length
(dmlc recordio convention the reference's MXRecordIO C API wraps).
``IRHeader``/``pack``/``unpack`` reproduce the image-record header layout
(flag u32, label f32, id u64, id2 u64) used by im2rec datasets.

A native C++ reader with threaded prefetch lives in src/ (io_native.py binds
it); this module is the portable pure-python implementation and the format
oracle for its tests.
"""
from __future__ import annotations

import os
import struct
from collections import namedtuple

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xCED7230A
_LREC_MASK = (1 << 29) - 1


class MXRecordIO:
    """Sequential record reader/writer (reference: recordio.py MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.is_open = False
        self.open()

    def open(self):
        if self.flag == "w":
            self.handle = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError("Invalid flag %s" % self.flag)
        self.is_open = True

    def close(self):
        if self.is_open:
            self.handle.close()
            self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        return self.handle.tell()

    def write(self, buf):
        assert self.writable
        if isinstance(buf, str):
            buf = buf.encode("utf-8")
        n = len(buf)
        lrecord = n & _LREC_MASK  # cflag=0: complete record
        self.handle.write(struct.pack("<II", _MAGIC, lrecord))
        self.handle.write(buf)
        pad = (4 - n % 4) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        header = self.handle.read(8)
        if len(header) < 8:
            return None
        magic, lrecord = struct.unpack("<II", header)
        if magic != _MAGIC:
            raise MXNetError("invalid record magic 0x%x" % magic)
        n = lrecord & _LREC_MASK
        data = self.handle.read(n)
        pad = (4 - n % 4) % 4
        if pad:
            self.handle.read(pad)
        return data


class MXIndexedRecordIO(MXRecordIO):
    """Random-access records via a key→offset .idx file (reference:
    recordio.py MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if flag == "r" and os.path.isfile(idx_path):
            with open(idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) != 2:
                        continue
                    key = key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        if self.is_open and self.writable:
            with open(self.idx_path, "w") as fout:
                for key in self.keys:
                    fout.write("%s\t%d\n" % (key, self.idx[key]))
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.handle.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack a header + payload into one record blob (reference: recordio.py
    pack). ``flag`` > 0 means the label is an array of ``flag`` floats."""
    header = IRHeader(*header)
    if isinstance(header.label, (np.ndarray, list, tuple)):
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0.0)
        payload = label.tobytes() + (s if isinstance(s, bytes) else s.encode())
    else:
        payload = s if isinstance(s, bytes) else s.encode()
    return struct.pack(_IR_FORMAT, int(header.flag), float(header.label),
                       int(header.id), int(header.id2)) + payload


def unpack(s):
    """(reference: recordio.py unpack)"""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    payload = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(payload[: header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        payload = payload[header.flag * 4 :]
    return header, payload


def _encode_img(img, quality, img_fmt):
    """Encode an HWC uint8 array to jpeg/png bytes: cv2 when present, else
    PIL (this image ships PIL, not opencv)."""
    try:
        import cv2

        params = [cv2.IMWRITE_JPEG_QUALITY, quality] if img_fmt in (".jpg", ".jpeg") else None
        ret, buf = cv2.imencode(img_fmt, img, params)
        if not ret:
            raise MXNetError("failed to encode image")
        return buf.tobytes()
    except ImportError:
        import io as _io

        from PIL import Image

        arr = np.asarray(img, dtype=np.uint8)
        if arr.ndim == 3 and arr.shape[2] == 3:
            arr = arr[:, :, ::-1]  # keep the cv2 BGR disk convention
        pil = Image.fromarray(arr)
        fmt = "JPEG" if img_fmt in (".jpg", ".jpeg") else img_fmt.lstrip(".").upper()
        bio = _io.BytesIO()
        pil.save(bio, format=fmt, quality=quality)
        return bio.getvalue()


def _decode_img(payload, iscolor):
    try:
        import cv2

        return cv2.imdecode(np.frombuffer(payload, dtype=np.uint8), iscolor)
    except ImportError:
        import io as _io

        from PIL import Image

        pil = Image.open(_io.BytesIO(payload))
        if iscolor == 0:
            return np.asarray(pil.convert("L"))
        if iscolor < 0 and pil.mode == "L":
            # IMREAD_UNCHANGED semantics: grayscale stays (H, W)
            return np.asarray(pil)
        arr = np.asarray(pil.convert("RGB"))
        return arr[:, :, ::-1]  # BGR, matching the cv2 convention


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """JPEG/PNG-encode an image array (HWC, BGR like cv2) and pack it
    (reference: recordio.py pack_img)."""
    return pack(header, _encode_img(img, quality, img_fmt))


def unpack_img(s, iscolor=-1):
    """(reference: recordio.py unpack_img) — returns (header, HWC BGR array)."""
    header, payload = unpack(s)
    return header, _decode_img(payload, iscolor)
