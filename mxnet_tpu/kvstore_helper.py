"""KVStore/updater plumbing for the Module layer.

Counterpart of the reference's python/mxnet/model.py:40-116 (_create_kvstore,
_initialize_kvstore, _update_params_on_kvstore, _update_params) — the glue
deciding where the optimizer runs and moving gradients through the store.
"""
from __future__ import annotations

from . import kvstore as kvs

__all__ = [
    "create_kvstore",
    "initialize_kvstore",
    "update_params_on_kvstore",
    "update_params",
]


def create_kvstore(kvstore, num_device, arg_params):
    """Decide kvstore + update_on_kvstore (reference: model.py:40 _create_kvstore)."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            # one device: updater runs directly on the bound arrays
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                # same heuristic as the reference: big arrays → update on store
                max_size = max(np_prod(param.shape) for param in arg_params.values())
                if max_size < 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def np_prod(shape):
    p = 1
    for s in shape:
        p *= int(s)
    return p


def initialize_kvstore(kvstore, param_arrays, arg_params, param_names, update_on_kvstore):
    """(reference: model.py _initialize_kvstore)"""
    for idx, param_on_devs in enumerate(param_arrays):
        kvstore.init(idx, arg_params[param_names[idx]])
        if update_on_kvstore:
            kvstore.pull(idx, param_on_devs, priority=-idx)


def update_params_on_kvstore(param_arrays, grad_arrays, kvstore,
                             priorities=None, sparse_indices=()):
    """(reference: model.py:88 _update_params_on_kvstore) — push grads (store
    reduces + runs the optimizer), pull fresh weights back to every device.

    On bucketed dist stores (see ``_bucketed``), pushes go PER KEY in
    reverse-topo order (deepest layer first — the order backward produced
    the gradients) with
    ``priority=-index``, the reference's hand-ordered per-key transfer
    schedule: each push lands in its static bucket
    (kvstore_bucket.BucketPlan) and a filled bucket's collective dispatches
    asynchronously while the host is still issuing the shallower layers'
    pushes. The pull then walks keys in FORWARD order, so layer 0's weights
    — the ones the next forward needs first — finalize while the deep
    buckets' collectives are still in flight (docs/PERF.md §11). Non-dist
    stores keep the single batched round: with no inter-process collective
    there is nothing to overlap.

    ``sparse_indices`` names the param indices whose producer declared a
    row-sparse gradient (``SparseEmbedding`` / ``Embedding(sparse_grad=
    True)``, resolved by ``Module`` via ``sparse.sparse_param_names``):
    their dense grad buffers convert at this boundary (``from_dense``
    nonzero-row detection — the executor layer does not thread the batch's
    ids here) and ride the KVStore sparse round + lazy update
    (docs/SPARSE.md) instead of the bucket plan."""
    keys, grads, args = [], [], []
    sparse_set = set(sparse_indices or ())
    if sparse_set:
        from .sparse import from_dense
    for index, (arg_list, grad_list) in enumerate(zip(param_arrays, grad_arrays)):
        if grad_list[0] is None:
            continue
        keys.append(index)
        if index in sparse_set:
            grad_list = [from_dense(g) for g in grad_list]
        grads.append(grad_list)
        args.append(arg_list)
    if not keys:
        return
    if _bucketed(kvstore):
        prio = dict(priorities or {})
        for k, g in zip(reversed(keys), reversed(grads)):
            kvstore.push(k, g, priority=prio.get(k, -k))
        for k, a in zip(keys, args):
            kvstore.pull(k, a, priority=prio.get(k, -k))
        return
    kvstore.push(keys, grads)
    kvstore.pull(keys, args)


def _bucketed(kvstore) -> bool:
    """True when the store's bucket engine will absorb per-key pushes
    (multi-process dist, MXNET_KVSTORE_BUCKET not disabled). Otherwise the
    single batched round is strictly better — per-key pushes on the
    unbucketed dist path would launch one collective per key, and on local
    stores there is no collective to overlap at all."""
    try:
        return "dist" in kvstore.type and kvstore._engine() is not None
    except Exception:
        return False


def update_params(param_arrays, grad_arrays, updater, num_device, kvstore=None,
                  priorities=None):
    """(reference: model.py:99 _update_params) — optionally reduce via kvstore,
    then run the updater per device copy. Dist stores get the same per-key
    reverse-topo priority schedule as ``update_params_on_kvstore`` (here the
    pulled value is the reduced gradient; the updater runs locally)."""
    live = [(i, a, g) for i, (a, g) in enumerate(zip(param_arrays, grad_arrays))
            if g[0] is not None]
    if kvstore and live:
        keys = [i for i, _, _ in live]
        if _bucketed(kvstore):
            prio = dict(priorities or {})
            for i, _, g in reversed(live):
                kvstore.push(i, g, priority=prio.get(i, -i))
            for i, _, g in live:
                kvstore.pull(i, g, priority=prio.get(i, -i))
        else:
            # one batched reduce round for every key (no collective to overlap)
            kvstore.push(keys, [g for _, _, g in live])
            kvstore.pull(keys, [g for _, _, g in live])
    for index, arg_list, grad_list in live:
        for k, p, g in zip(range(len(arg_list)), arg_list, grad_list):
            # use a unique integer key per (param, device) for updater state
            updater(index * num_device + k, g, p)
