"""KVStore/updater plumbing for the Module layer.

Counterpart of the reference's python/mxnet/model.py:40-116 (_create_kvstore,
_initialize_kvstore, _update_params_on_kvstore, _update_params) — the glue
deciding where the optimizer runs and moving gradients through the store.
"""
from __future__ import annotations

from . import kvstore as kvs
from .base import MXNetError

__all__ = [
    "create_kvstore",
    "initialize_kvstore",
    "update_params_on_kvstore",
    "update_params",
]


def create_kvstore(kvstore, num_device, arg_params):
    """Decide kvstore + update_on_kvstore (reference: model.py:40 _create_kvstore)."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            # one device: updater runs directly on the bound arrays
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                # same heuristic as the reference: big arrays → update on store
                max_size = max(np_prod(param.shape) for param in arg_params.values())
                if max_size < 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def np_prod(shape):
    p = 1
    for s in shape:
        p *= int(s)
    return p


def initialize_kvstore(kvstore, param_arrays, arg_params, param_names, update_on_kvstore):
    """(reference: model.py _initialize_kvstore)"""
    for idx, param_on_devs in enumerate(param_arrays):
        kvstore.init(idx, arg_params[param_names[idx]])
        if update_on_kvstore:
            kvstore.pull(idx, param_on_devs, priority=-idx)


def update_params_on_kvstore(param_arrays, grad_arrays, kvstore):
    """(reference: model.py:88 _update_params_on_kvstore) — push grads (store
    reduces + runs the optimizer), pull fresh weights back to every device.

    All keys go in ONE push and ONE pull: in dist mode the store batches the
    whole round into a single compiled all-reduce (the reference instead
    hand-ordered per-key transfers with priority=-index; the batched
    collective makes that scheduling XLA's problem)."""
    keys, grads, args = [], [], []
    for index, (arg_list, grad_list) in enumerate(zip(param_arrays, grad_arrays)):
        if grad_list[0] is None:
            continue
        keys.append(index)
        grads.append(grad_list)
        args.append(arg_list)
    if not keys:
        return
    kvstore.push(keys, grads)
    kvstore.pull(keys, args)


def update_params(param_arrays, grad_arrays, updater, num_device, kvstore=None):
    """(reference: model.py:99 _update_params) — optionally reduce via kvstore,
    then run the updater per device copy."""
    live = [(i, a, g) for i, (a, g) in enumerate(zip(param_arrays, grad_arrays))
            if g[0] is not None]
    if kvstore and live:
        # one batched reduce round for every key (dist: one collective)
        keys = [i for i, _, _ in live]
        kvstore.push(keys, [g for _, _, g in live])
        kvstore.pull(keys, [g for _, _, g in live])
    for index, arg_list, grad_list in live:
        for k, p, g in zip(range(len(arg_list)), arg_list, grad_list):
            # use a unique integer key per (param, device) for updater state
            updater(index * num_device + k, g, p)
