"""Data iterators.

Counterpart of the reference's python/mxnet/io.py (DataBatch/DataDesc :19-103,
NDArrayIter :453, ResizeIter :216, PrefetchingIter :281) and the C++ iterators
in src/io (MNISTIter iter_mnist.cc:241, CSVIter iter_csv.cc:132). The
prefetcher is a real background thread double-buffering host batches so the
accelerator never waits on host-side slicing — the reference's
PrefetcherIter (src/io/iter_prefetcher.h:28) re-designed for the JAX async
dispatch model.
"""
from __future__ import annotations

import queue
import struct
import threading
from collections import namedtuple

import numpy as np

from .base import MXNetError
from . import telemetry as _tm
from .ndarray import NDArray, array

__all__ = [
    "DataDesc",
    "DataBatch",
    "DataIter",
    "NDArrayIter",
    "ResizeIter",
    "PrefetchingIter",
    "DevicePrefetchIter",
    "device_prefetch_enabled",
    "CSVIter",
    "MNISTIter",
]


def device_prefetch_enabled():
    """Whether ``Module.fit`` auto-wraps the training iterator in a
    ``DevicePrefetchIter`` (``MXNET_IO_DEVICE_PREFETCH=1``,
    docs/ENV_VARS.md). Off by default: the wrap changes nothing numerically
    (device transfers are bit-preserving) but adds a pump thread."""
    import os

    return os.environ.get("MXNET_IO_DEVICE_PREFETCH", "0").strip().lower() \
        in ("1", "true", "on")


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Name+shape(+dtype/layout) of one input stream (reference: io.py:19)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, tuple(shape))
        ret.dtype = dtype
        ret.layout = layout
        return ret

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One batch: data/label NDArray lists + pad/index bookkeeping."""

    def __init__(self, data, label=None, pad=None, index=None, bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Iterator base (reference: io.py DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(), pad=self.getpad(), index=self.getindex()
            )
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError()

    def getdata(self):
        raise NotImplementedError()

    def getlabel(self):
        raise NotImplementedError()

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError()


def _init_data(data, allow_empty, default_name):
    """Normalize data/label input to a list of (name, numpy) pairs
    (reference: io.py _init_data)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of them or dict with them as values")
    out = {}
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out[k] = np.asarray(v)
    return list(sorted(out.items()))


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays with shuffle/pad handling
    (reference: io.py:453)."""

    def __init__(
        self,
        data,
        label=None,
        batch_size=1,
        shuffle=False,
        last_batch_handle="pad",
        data_name="data",
        label_name="softmax_label",
    ):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)

        self.idx = np.arange(self.data[0][1].shape[0])
        if shuffle:
            np.random.shuffle(self.idx)
            self.data = [(k, v[self.idx]) for k, v in self.data]
            self.label = [(k, v[self.idx]) for k, v in self.label]

        if last_batch_handle == "discard":
            new_n = self.data[0][1].shape[0] - self.data[0][1].shape[0] % batch_size
            self.idx = self.idx[:new_n]

        self.data_list = [x[1] for x in self.data] + [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        self.num_data = self.idx.shape[0]
        assert self.num_data >= batch_size, "batch_size needs to be smaller than data size."
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype) for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype) for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _next_batch(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=None)
        raise StopIteration

    def next(self):
        if not _tm.enabled():
            return self._next_batch()
        # batch-fetch latency: host slicing + NDArray materialization — the
        # time the accelerator would wait on input without a prefetcher.
        # The timer serves `counters` mode; the span serves `trace` mode.
        import time as _time

        t0 = _time.perf_counter()
        with _tm.span("io.next", iter=type(self).__name__):
            batch = self._next_batch()
        _tm.counter("io.batches").inc()
        _tm.timer("io.batch_fetch").add(_time.perf_counter() - t0)
        return batch

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [array(x[1][self.cursor : self.cursor + self.batch_size]) for x in data_source]
        # padding: wrap around (reference pads from the head)
        pad = self.batch_size - self.num_data + self.cursor
        return [
            array(np.concatenate((x[1][self.cursor :], x[1][:pad]), axis=0)) for x in data_source
        ]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to ``size`` batches per epoch (reference: io.py:216)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur >= self.size:
            return False
        self.cur += 1
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            # wrap the child's epoch: this iterator's epoch is `size` batches
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


def _pump_loop(fetch, q, stop, end_sentinel):
    """The shared prefetch pump body (PrefetchingIter and
    DevicePrefetchIter): drive ``fetch()`` until epoch end (StopIteration)
    or a child error (surfaced to the consumer as the end token), with a
    bounded ``put`` that stays responsive to shutdown. ALWAYS terminates
    the queue with a sentinel/exception so the consumer can't hang."""
    end_token = end_sentinel
    try:
        while not stop.is_set():
            try:
                batch = fetch()
            except StopIteration:
                break
            except BaseException as exc:  # surface child errors
                end_token = exc
                break
            while not stop.is_set():
                try:
                    q.put(batch, timeout=0.1)
                    break
                except queue.Full:
                    continue
    finally:
        q.put(end_token)


def _get_bounded(q, threads, what, poll_s=1.0):
    """``queue.get`` that cannot hang on a dead pump (GL804 audit,
    docs/static_analysis.md §GL8xx): poll with a timeout and raise once
    every pump thread is gone while the queue stayed empty — the sentinel
    guarantee of ``_pump_loop`` was violated (a hard-killed thread), so
    blocking forever is the only alternative. A slow-but-alive pump just
    keeps the poll going; steady state never times out."""
    while True:
        try:
            return q.get(timeout=poll_s)
        except queue.Empty:
            if not any(t.is_alive() for t in threads):
                raise MXNetError(
                    "%s: prefetch pump thread(s) died without terminating "
                    "their queue — batch stream lost; reset the iterator"
                    % what)


def _drain_and_join(queues, threads, stop, end_sentinel, timeout):
    """The shared bounded teardown: signal stop, drain each queue until
    its sentinel (unblocking a pump stuck on a full queue), then join
    every pump against ONE shared deadline. Returns the still-alive
    (wedged) threads."""
    import time as _time

    stop.set()
    for q in queues:
        while True:
            try:
                if q.get_nowait() is end_sentinel:
                    break
            except queue.Empty:
                break
    deadline = _time.monotonic() + timeout
    stuck = []
    for t in threads:
        t.join(timeout=max(0.0, deadline - _time.monotonic()))
        if t.is_alive():
            stuck.append(t)
    return stuck


class PrefetchingIter(DataIter):
    """Background-thread prefetch over one or more iterators (reference:
    io.py PrefetchingIter, C++ PrefetcherIter iter_prefetcher.h:28).

    Mechanism (original to this port): one pump thread per child iterator
    feeds a bounded queue (``prefetch_depth`` batches ahead, vs. the
    reference's fixed one-ahead event handshake); a sentinel marks epoch
    end. ``reset()`` tears the epoch's pumps down and starts fresh ones, so
    no cross-epoch thread state can leak.
    """

    _END = object()  # epoch-end sentinel

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2, shutdown_timeout=5.0):
        super().__init__()
        self.iters = iters if isinstance(iters, list) else [iters]
        assert self.iters
        self.n_iter = len(self.iters)
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0].shape[0]
        self.current_batch = None
        self._depth = max(1, int(prefetch_depth))
        self._shutdown_timeout = float(shutdown_timeout)
        self._queues = None
        self._threads = []
        self._stop = None
        self._ended = False  # epoch exhausted; queues carry no more batches
        self._wedged = None  # MXNetError once a pump failed to shut down
        self._start_epoch()

    # ------------------------------------------------------------ pump plumbing
    def _pump(self, child, q, stop):
        from . import faultinject as _fi

        def fetch():
            # injection site io.prefetch (docs/RESILIENCE.md): a `raise`
            # rides the error channel and surfaces to the consumer as the
            # epoch's failure; a delay/hang starves the training loop
            # (visible as io.prefetch_wait) and, past shutdown_timeout,
            # trips the wedge latch
            _fi.fire("io.prefetch")
            return child.next()

        _pump_loop(fetch, q, stop, PrefetchingIter._END)

    def _start_epoch(self):
        self._queues = [queue.Queue(maxsize=self._depth)
                        for _ in range(self.n_iter)]
        self._stop = threading.Event()
        self._ended = False
        self._threads = [
            threading.Thread(target=self._pump, args=(it, q, self._stop),
                             daemon=True)
            for it, q in zip(self.iters, self._queues)]
        for t in self._threads:
            t.start()

    def _shutdown(self, strict=True):
        """Stop the epoch's pumps with a BOUNDED join: one shared deadline
        (``shutdown_timeout`` seconds total, not per thread) covers every
        pump. A pump still alive past the deadline means its child iterator
        is wedged in user code — resetting the child underneath it would be
        a two-thread data race on the iterator's cursor, and silently
        carrying the thread into the next epoch leaks it forever. So the
        iterator latches a hard MXNetError: this reset raises it, and every
        later next()/reset() re-raises until the owner rebuilds the
        pipeline."""
        if self._stop is None:
            return
        stuck = _drain_and_join(self._queues, self._threads, self._stop,
                                PrefetchingIter._END,
                                self._shutdown_timeout)
        self._threads = []
        if stuck:
            self._wedged = MXNetError(
                "PrefetchingIter: %d pump thread(s) [%s] still running %gs "
                "after shutdown — a child iterator is blocked in user code; "
                "this prefetcher is wedged and cannot be reused (rebuild the "
                "data pipeline)" % (len(stuck),
                                    ", ".join(t.name for t in stuck),
                                    self._shutdown_timeout))
            if strict:
                raise self._wedged

    def _check_wedged(self):
        if self._wedged is not None:
            raise self._wedged

    def __del__(self):
        try:
            self._shutdown(strict=False)
        except Exception:
            pass

    # ------------------------------------------------------------------ DataIter
    @property
    def provide_data(self):
        return self._renamed(lambda it: it.provide_data, self.rename_data)

    @property
    def provide_label(self):
        return self._renamed(lambda it: it.provide_label, self.rename_label)

    def _renamed(self, get, renames):
        descs = []
        for k, it in enumerate(self.iters):
            for d in get(it):
                d = d if isinstance(d, DataDesc) else DataDesc(*d)
                if renames is not None:
                    d = DataDesc(renames[k][d.name], d.shape, d.dtype)
                descs.append(d)
        return descs

    def reset(self):
        self._check_wedged()
        self._shutdown()
        for it in self.iters:
            it.reset()
        self._start_epoch()

    def iter_next(self):
        self._check_wedged()
        if self._ended:
            return False  # pumps are gone; blocking on the queues would hang
        if _tm.enabled():
            # consumer-side stall: >0 here means the pumps can't keep up and
            # the accelerator is input-bound for this batch
            import time as _time

            t0 = _time.perf_counter()
            with _tm.span("io.prefetch_wait"):
                got = [_get_bounded(q, self._threads, "PrefetchingIter")
                       for q in self._queues]
            _tm.timer("io.prefetch_wait").add(_time.perf_counter() - t0)
        else:
            got = [_get_bounded(q, self._threads, "PrefetchingIter")
                   for q in self._queues]
        for g in got:
            if isinstance(g, BaseException):
                self._ended = True
                raise g  # a pump's child iterator failed mid-epoch
        ended = [g is PrefetchingIter._END for g in got]
        if any(ended):
            assert all(ended), "iterators disagree on epoch length"
            self._ended = True
            return False
        pad = got[0].pad
        assert all(g.pad == pad for g in got), "different pad between iterators"
        data, label = [], []
        for g in got:
            data.extend(g.data)
            label.extend(g.label)
        self.current_batch = DataBatch(data, label, pad, got[0].index)
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class DevicePrefetchIter(DataIter):
    """Double-buffered device-side prefetch (docs/PERF.md §15).

    One pump thread drives the child iterator AHEAD of the training loop:
    while step N runs, batch N+1 is host-sliced, ``jax.device_put`` to the
    target device (the transfer dispatches asynchronously and lands during
    step N's compute), optionally run through a jitted on-device
    ``augment`` hook, and parked in a bounded queue. ``next()`` then
    returns an already-device-resident batch — the ``io.prefetch_wait``
    seam (and ``Module.fit``'s ``io.input_bound_pct`` gauge) stops gating
    the step.

    ``augment`` receives the batch's DATA arrays (jax arrays, device
    resident) positionally and returns the same number of arrays — e.g. a
    random-crop/flip pipeline compiled once with ``jax.jit``. Labels pass
    through untouched. With ``augment=None`` the wrap is numerically a
    no-op: ``device_put`` preserves bits, so training results are
    bit-identical to the unwrapped iterator.

    The pump/teardown discipline (bounded-queue put, epoch-end sentinel,
    bounded shutdown join with the wedge latch) is ``PrefetchingIter``'s.
    """

    _END = object()

    def __init__(self, data_iter, prefetch_depth=2, device=None,
                 augment=None, shutdown_timeout=5.0):
        super().__init__()
        assert not isinstance(data_iter, list), \
            "DevicePrefetchIter wraps ONE iterator; compose PrefetchingIter for multi-stream"
        self.data_iter = data_iter
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size
        self.current_batch = None
        self._depth = max(1, int(prefetch_depth))
        self._shutdown_timeout = float(shutdown_timeout)
        if device is None:
            from .context import current_context

            device = current_context().jax_device
        self._device = device
        self._augment = augment
        self._augment_jit = None
        if augment is not None:
            import jax

            self._augment_jit = jax.jit(lambda *xs: tuple(augment(*xs)))
        self.wait_s = 0.0  # consumer-side stall, accumulated per epoch
        self._queue = None
        self._thread = None
        self._stop = None
        self._ended = False
        self._wedged = None
        # the pump starts LAZILY on the first consume after construction /
        # reset(): the fit loop's unconditional end-of-epoch reset() (and
        # the final one after the last epoch) must not spin up a thread
        # that eagerly transfers batches nobody will read

    # ------------------------------------------------------------- device side
    def _put_array(self, a):
        import jax

        raw = a._jax() if isinstance(a, NDArray) else a
        return jax.device_put(raw, self._device)

    def _to_device(self, batch):
        """Transfer (and augment) one host batch; dispatch is async, so the
        pump returns while the copies are still in flight."""
        data = [self._put_array(a) for a in (batch.data or [])]
        if self._augment_jit is not None and data:
            out = self._augment_jit(*data)
            assert len(out) == len(data), \
                "augment must return one array per data input"
            data = list(out)
        label = [self._put_array(a) for a in (batch.label or [])]
        return DataBatch([NDArray(d) for d in data],
                         [NDArray(lb) for lb in label],
                         batch.pad, batch.index)

    # ------------------------------------------------------------ pump plumbing
    def _pump(self, child, q, stop):
        from . import faultinject as _fi

        def fetch():
            _fi.fire("io.prefetch")
            return self._to_device(child.next())

        _pump_loop(fetch, q, stop, DevicePrefetchIter._END)

    def _ensure_started(self):
        if self._thread is not None:
            return
        self._queue = queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._ended = False
        self.wait_s = 0.0
        self._thread = threading.Thread(
            target=self._pump, args=(self.data_iter, self._queue,
                                     self._stop),
            daemon=True, name="device-prefetch")
        self._thread.start()

    def _shutdown(self, strict=True):
        if self._stop is None or self._thread is None:
            return
        stuck = _drain_and_join([self._queue], [self._thread], self._stop,
                                DevicePrefetchIter._END,
                                self._shutdown_timeout)
        self._thread = None
        if stuck:
            self._wedged = MXNetError(
                "DevicePrefetchIter: pump thread still running %gs after "
                "shutdown — the child iterator is blocked in user code; "
                "rebuild the data pipeline" % self._shutdown_timeout)
            if strict:
                raise self._wedged

    def __del__(self):
        try:
            self._shutdown(strict=False)
        except Exception:
            pass

    # ------------------------------------------------------------------ DataIter
    def reset(self):
        if self._wedged is not None:
            raise self._wedged
        self._shutdown()
        self.data_iter.reset()
        self._ended = False  # next consume lazily starts a fresh pump

    def iter_next(self):
        if self._wedged is not None:
            raise self._wedged
        if self._ended:
            return False
        self._ensure_started()
        import time as _time

        t0 = _time.perf_counter()
        if _tm.enabled():
            with _tm.span("io.prefetch_wait"):
                got = _get_bounded(self._queue, (self._thread,),
                                   "DevicePrefetchIter")
            _tm.timer("io.prefetch_wait").add(_time.perf_counter() - t0)
        else:
            got = _get_bounded(self._queue, (self._thread,),
                               "DevicePrefetchIter")
        self.wait_s += _time.perf_counter() - t0
        if isinstance(got, BaseException):
            self._ended = True
            raise got
        if got is DevicePrefetchIter._END:
            self._ended = True
            return False
        self.current_batch = got
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class CSVIter(NDArrayIter):
    """CSV-file-backed iterator (reference: src/io/iter_csv.cc:132). Parses on
    host with numpy, then batches like NDArrayIter."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,), batch_size=1, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label.shape[-1] == 1:
                label = label.reshape(label.shape[:-1])
        super().__init__(data, label, batch_size=batch_size, **kwargs)


def _read_idx_file(path):
    """Read an MNIST idx-format file (reference: iter_mnist.cc ReadInt/LoadImage)."""
    import gzip

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        dtype_code = (magic >> 8) & 0xFF
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        dtype = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16, 0x0C: np.int32, 0x0D: np.float32}[
            dtype_code
        ]
        data = np.frombuffer(f.read(), dtype=dtype.newbyteorder(">"))
        return data.reshape(dims).astype(dtype)


class MNISTIter(NDArrayIter):
    """MNIST idx-format iterator (reference: src/io/iter_mnist.cc:241)."""

    def __init__(
        self,
        image,
        label,
        batch_size=128,
        shuffle=True,
        flat=False,
        silent=False,
        seed=0,
        input_shape=None,
        **kwargs,
    ):
        images = _read_idx_file(image).astype(np.float32) / 255.0
        labels = _read_idx_file(label).astype(np.float32)
        if flat:
            images = images.reshape(images.shape[0], -1)
        elif input_shape is not None:
            images = images.reshape((-1,) + tuple(input_shape))
        else:
            images = images.reshape(images.shape[0], 1, images.shape[1], images.shape[2])
        super().__init__(
            images, labels, batch_size=batch_size, shuffle=shuffle, last_batch_handle="discard"
        )
