"""Data iterators.

Counterpart of the reference's python/mxnet/io.py (DataBatch/DataDesc :19-103,
NDArrayIter :453, ResizeIter :216, PrefetchingIter :281) and the C++ iterators
in src/io (MNISTIter iter_mnist.cc:241, CSVIter iter_csv.cc:132). The
prefetcher is a real background thread double-buffering host batches so the
accelerator never waits on host-side slicing — the reference's
PrefetcherIter (src/io/iter_prefetcher.h:28) re-designed for the JAX async
dispatch model.
"""
from __future__ import annotations

import struct
import threading
from collections import namedtuple

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray, array

__all__ = [
    "DataDesc",
    "DataBatch",
    "DataIter",
    "NDArrayIter",
    "ResizeIter",
    "PrefetchingIter",
    "CSVIter",
    "MNISTIter",
]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Name+shape(+dtype/layout) of one input stream (reference: io.py:19)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, tuple(shape))
        ret.dtype = dtype
        ret.layout = layout
        return ret

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One batch: data/label NDArray lists + pad/index bookkeeping."""

    def __init__(self, data, label=None, pad=None, index=None, bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Iterator base (reference: io.py DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(), pad=self.getpad(), index=self.getindex()
            )
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError()

    def getdata(self):
        raise NotImplementedError()

    def getlabel(self):
        raise NotImplementedError()

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError()


def _init_data(data, allow_empty, default_name):
    """Normalize data/label input to a list of (name, numpy) pairs
    (reference: io.py _init_data)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of them or dict with them as values")
    out = {}
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out[k] = np.asarray(v)
    return list(sorted(out.items()))


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays with shuffle/pad handling
    (reference: io.py:453)."""

    def __init__(
        self,
        data,
        label=None,
        batch_size=1,
        shuffle=False,
        last_batch_handle="pad",
        data_name="data",
        label_name="softmax_label",
    ):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)

        self.idx = np.arange(self.data[0][1].shape[0])
        if shuffle:
            np.random.shuffle(self.idx)
            self.data = [(k, v[self.idx]) for k, v in self.data]
            self.label = [(k, v[self.idx]) for k, v in self.label]

        if last_batch_handle == "discard":
            new_n = self.data[0][1].shape[0] - self.data[0][1].shape[0] % batch_size
            self.idx = self.idx[:new_n]

        self.data_list = [x[1] for x in self.data] + [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        self.num_data = self.idx.shape[0]
        assert self.num_data >= batch_size, "batch_size needs to be smaller than data size."
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype) for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype) for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(), pad=self.getpad(), index=None
            )
        raise StopIteration

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [array(x[1][self.cursor : self.cursor + self.batch_size]) for x in data_source]
        # padding: wrap around (reference pads from the head)
        pad = self.batch_size - self.num_data + self.cursor
        return [
            array(np.concatenate((x[1][self.cursor :], x[1][:pad]), axis=0)) for x in data_source
        ]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to ``size`` batches per epoch (reference: io.py:216)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread prefetch over one or more iterators (reference:
    io.py:281 PrefetchingIter, C++ PrefetcherIter iter_prefetcher.h:28)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0].shape[0]
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None for _ in range(self.n_iter)]
        self.next_batch = [None for _ in range(self.n_iter)]

        def prefetch_func(self, i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    self.next_batch[i] = self.iters[i].next()
                except StopIteration:
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch_func, args=[self, i]) for i in range(self.n_iter)
        ]
        for thread in self.prefetch_threads:
            thread.setDaemon(True)
            thread.start()

    def __del__(self):
        self.started = False
        for e in self.data_taken:
            e.set()
        for thread in self.prefetch_threads:
            thread.join()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum(
            [
                [DataDesc(r[x.name], x.shape, x.dtype) if isinstance(x, DataDesc) else DataDesc(*x) for x in i.provide_data]
                for r, i in zip(self.rename_data, self.iters)
            ],
            [],
        )

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum(
            [
                [DataDesc(r[x.name], x.shape, x.dtype) if isinstance(x, DataDesc) else DataDesc(*x) for x in i.provide_label]
                for r, i in zip(self.rename_label, self.iters)
            ],
            [],
        )

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        if self.next_batch[0] is None:
            for i in self.next_batch:
                assert i is None, "Number of entry mismatches between iterators"
            return False
        for batch in self.next_batch:
            assert batch.pad == self.next_batch[0].pad, "Different pad between iterators"
        self.current_batch = DataBatch(
            sum([batch.data for batch in self.next_batch], []),
            sum([batch.label for batch in self.next_batch], []),
            self.next_batch[0].pad,
            self.next_batch[0].index,
        )
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class CSVIter(NDArrayIter):
    """CSV-file-backed iterator (reference: src/io/iter_csv.cc:132). Parses on
    host with numpy, then batches like NDArrayIter."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,), batch_size=1, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label.shape[-1] == 1:
                label = label.reshape(label.shape[:-1])
        super().__init__(data, label, batch_size=batch_size, **kwargs)


def _read_idx_file(path):
    """Read an MNIST idx-format file (reference: iter_mnist.cc ReadInt/LoadImage)."""
    import gzip

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        dtype_code = (magic >> 8) & 0xFF
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        dtype = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16, 0x0C: np.int32, 0x0D: np.float32}[
            dtype_code
        ]
        data = np.frombuffer(f.read(), dtype=dtype.newbyteorder(">"))
        return data.reshape(dims).astype(dtype)


class MNISTIter(NDArrayIter):
    """MNIST idx-format iterator (reference: src/io/iter_mnist.cc:241)."""

    def __init__(
        self,
        image,
        label,
        batch_size=128,
        shuffle=True,
        flat=False,
        silent=False,
        seed=0,
        input_shape=None,
        **kwargs,
    ):
        images = _read_idx_file(image).astype(np.float32) / 255.0
        labels = _read_idx_file(label).astype(np.float32)
        if flat:
            images = images.reshape(images.shape[0], -1)
        elif input_shape is not None:
            images = images.reshape((-1,) + tuple(input_shape))
        else:
            images = images.reshape(images.shape[0], 1, images.shape[1], images.shape[2])
        super().__init__(
            images, labels, batch_size=batch_size, shuffle=shuffle, last_batch_handle="discard"
        )
