"""Sparse push/pull rounds for the KVStore (docs/SPARSE.md).

The bucketed engine (kvstore_bucket) owns DENSE gradients: a static plan,
fixed offsets, one compiled collective per bucket. A row-sparse gradient is
the opposite shape of problem — *which* rows move changes every round — so
sparse keys bypass the bucket plan entirely and run through this engine,
the TPU-native translation of the reference's ps-lite sparse push /
PullRowSparse (kvstore_dist.h):

1. **Index union** — every worker computes its local touched-row set (the
   segment-sum backward's unique ids); the round's working set is the
   allgather'd UNION across workers. The allgather ships counts first,
   then sentinel-padded id vectors (host-side, 8 bytes/row — noise next to
   the value rows it saves).
2. **Padded-row collective** — the union's value rows scatter into a
   ``(U_pad, row)`` buffer, ``U_pad`` = next power of two ≥ U: the
   collective executable re-specializes per power-of-two bucket instead of
   per round, bounding retraces at log2(vocab) while wasting < 2× wire on
   padding (counted honestly — ``kvstore.bytes.sparse`` is the PADDED
   wire formula, the same ``2·(W-1)/W·N`` accounting the dense path uses).
3. **Lazy update** — the reduced rows apply through
   ``optimizer.update_row_sparse``: only union rows pass through the flat
   kernel, untouched rows keep bit-identical weight AND optimizer state.
4. **Dense fallback** — when the union covers ≥
   ``MXNET_SPARSE_DENSE_FALLBACK_PCT`` of the table (or
   ``MXNET_KVSTORE_SPARSE=0``), the round ships the plain dense buffer
   through the ordinary allreduce instead — near-dense unions cost more as
   index+rows than as the table, and the fixed shape keeps one executable.
   The update is STILL row-lazy: the dense wire result is re-sparsified
   against the union before the optimizer sees it, so a fallback round can
   never silently decay untouched Adam state (regression-tested).

Telemetry (docs/OBSERVABILITY.md): ``kvstore.sparse_rows_pushed``,
``kvstore.bytes.sparse``, ``kvstore.sparse_dense_fallbacks`` counters and
``kvstore.sparse_push`` spans.
"""
from __future__ import annotations

import logging
from typing import Dict

import numpy as np

from ..base import MXNetError
from .. import telemetry as _tm
from ..ndarray import NDArray
from . import (RowSparseNDArray, dense_fallback_pct, from_dense,
               sparse_enabled)

__all__ = ["SparseEngine"]

log = logging.getLogger("mxnet_tpu.sparse")


def _next_pow2(n: int) -> int:
    return 1 << max(0, int(n - 1).bit_length())


class SparseEngine:
    """Per-KVStore engine for row-sparse keys. Stateless across rounds
    except for telemetry and the per-key registration (shape/dtype checks);
    optimizer state lives in the Updater's per-key ``RowSparseState``."""

    def __init__(self, kv):
        self._kv = kv
        self._keys: Dict = {}  # key -> (shape, dtype str)

    # ------------------------------------------------------------------ util
    def _dist(self) -> bool:
        if "dist" not in self._kv._type:
            return False
        import jax

        return jax.process_count() > 1

    def _coll(self):
        from ..kvstore import _Collective

        return _Collective.get()

    def _register(self, key, rsp: RowSparseNDArray):
        stored = self._kv._store[key]
        if tuple(stored.shape) != tuple(rsp.shape):
            raise MXNetError(
                "sparse push of key %s: gradient dense shape %s does not "
                "match the stored value %s"
                % (key, tuple(rsp.shape), tuple(stored.shape)))
        self._keys[key] = (tuple(rsp.shape), str(stored.dtype))

    # ----------------------------------------------------------------- rounds
    def push(self, key, rsp: RowSparseNDArray, priority=0):
        """One key's locally-reduced row-sparse gradient: union the touched
        rows across workers, reduce the rows, lazily update the store."""
        if key not in self._keys:
            self._register(key, rsp)
        shape, dtype = self._keys[key]
        vocab = shape[0]
        local_idx = rsp.indices.asnumpy().astype(np.int64)
        dist = self._dist()
        if dist:
            union = self._allgather_union(local_idx, vocab)
        else:
            union = local_idx
        pct = 100.0 * union.size / max(1, vocab)
        go_dense = (not sparse_enabled()) or pct >= dense_fallback_pct()
        sp = _tm.NULL_SPAN
        if _tm.enabled():
            sp = _tm.span("kvstore.sparse_push", key=key,
                          rows=int(union.size), vocab=vocab,
                          density_pct=round(pct, 3), dense_wire=go_dense,
                          priority=priority)
        with sp:
            if go_dense:
                reduced = self._dense_wire_round(key, rsp, union, dtype)
            else:
                reduced = self._sparse_wire_round(key, rsp, union, local_idx,
                                                  shape, dtype)
            self._apply(key, reduced)

    def _allgather_union(self, local_idx, vocab):
        """Sorted unique union of every worker's touched rows. Two host
        allgathers: fixed-shape counts, then max-count sentinel-padded id
        vectors — every worker derives the identical union (SPMD)."""
        from jax.experimental.multihost_utils import process_allgather

        counts = np.asarray(process_allgather(
            np.asarray([local_idx.size], np.int64))).reshape(-1)
        cap = int(counts.max())
        if cap == 0:
            return np.zeros((0,), np.int64)
        padded = np.full((cap,), -1, np.int64)
        padded[:local_idx.size] = local_idx
        allv = np.asarray(process_allgather(padded)).reshape(-1)
        union = np.unique(allv[allv >= 0])
        if union.size and (union[0] < 0 or union[-1] >= vocab):
            raise MXNetError("sparse push: row id out of [0, %d)" % vocab)
        return union

    def _sparse_wire_round(self, key, rsp, union, local_idx, shape, dtype):
        """Reduce only the union rows: scatter local rows into the padded
        (U_pad, row) buffer, one allreduce, slice back."""
        import jax.numpy as jnp

        row_shape = shape[1:]
        U = int(union.size)
        U_pad = _next_pow2(U)
        acc_dt = jnp.dtype(dtype)
        buf = jnp.zeros((U_pad,) + tuple(row_shape), acc_dt)
        if local_idx.size:
            pos = np.searchsorted(union, local_idx)
            buf = buf.at[pos].set(rsp.values._jax().astype(acc_dt))
        if self._dist():
            coll = self._coll()
            W = coll.n_workers
            itemsize = np.dtype(dtype).itemsize
            row_elems = int(np.prod(row_shape)) if row_shape else 1
            wire = int(2 * (W - 1) / W * U_pad * row_elems * itemsize)
            out = coll.allreduce_rows(buf.reshape(1, -1), acc_dtype=dtype)
            vals = out.addressable_data(0).reshape(
                (U_pad,) + tuple(row_shape))[:U]
            if _tm.enabled():
                _tm.counter("kvstore.bytes.sparse").inc(wire)
        else:
            vals = buf[:U]
        if _tm.enabled():
            _tm.counter("kvstore.sparse_rows_pushed").inc(U)
        stored = self._kv._store[key]
        return RowSparseNDArray(union, NDArray(vals, ctx=stored.context),
                                shape, ctx=stored.context)

    def _dense_wire_round(self, key, rsp, union, dtype):
        """Near-dense round: ship the plain dense buffer (fixed-shape
        executable, ``kvstore.bytes.allreduce`` accounting), then
        re-sparsify against the union so the UPDATE stays row-lazy."""
        if _tm.enabled():
            _tm.counter("kvstore.sparse_dense_fallbacks").inc()
            _tm.counter("kvstore.sparse_rows_pushed").inc(int(union.size))
        dense = rsp.to_dense()
        if self._dist():
            coll = self._coll()
            W = coll.n_workers
            wire = int(2 * (W - 1) / W * dense.size
                       * np.dtype(dtype).itemsize)
            out = coll.allreduce_concat([dense._jax().reshape(-1)])
            dense = NDArray(out.reshape(dense.shape), ctx=dense.context)
            if _tm.enabled():
                _tm.counter("kvstore.bytes.allreduce").inc(wire)
        return from_dense(dense, rows=union)

    def _apply(self, key, reduced: RowSparseNDArray):
        kv = self._kv
        stored = kv._store[key]
        if kv._updater is not None:
            kv._updater(key, reduced, stored)
            return
        # no updater: sparse push REPLACES the touched rows (the dense
        # path's replace semantics, restricted to the rows that moved)
        rows = reduced.indices.asnumpy().astype(np.int64)
        if rows.size:
            stored._set_jax(
                stored._jax().at[rows].set(
                    reduced.values._jax().astype(stored.dtype)))

    # ------------------------------------------------------------- checkpoint
    def sparse_states(self):
        """``{key: (shape, dtype, RowSparseState)}`` for every registered
        sparse key whose Updater state is row-sparse — the checkpoint
        writer's view (checkpoint.sparse_shard_arrays)."""
        from . import RowSparseState

        upd = self._kv._updater
        out = {}
        if upd is None:
            return out
        for key, (shape, dtype) in self._keys.items():
            st = upd.states.get(key)
            if isinstance(st, RowSparseState):
                out[key] = (shape, dtype, st)
        return out
