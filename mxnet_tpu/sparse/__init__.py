"""Row-sparse storage kind: the recommender subsystem's foundation.

The reference made sparse push/pull a first-class KVStore citizen because
embedding-dominated recommenders are the canonical "millions of users"
training workload (kvstore_dist.h's sparse PushImpl/PullRowSparse over
ps-lite): an Embedding gradient only ever touches the rows the batch looked
up, so shipping — or running the optimizer over — the other 99% of a
(vocab, dim) table is pure waste. This package is that capability for the
TPU-native port (docs/SPARSE.md):

* ``RowSparseNDArray`` — the ``row_sparse`` storage kind: a sorted unique
  ``indices`` vector plus the corresponding value ROWS of a logically-dense
  ``(vocab, ...)`` array. ``to_dense``/``retain``/``from_dense`` convert;
  ``__add__`` merges two row-sparse values (the KVStore local reduce).
* ``embedding_backward`` — the segment-sum backward of the Embedding
  lookup: grad rows accumulate per UNIQUE looked-up id
  (``jax.ops.segment_sum``), emitting a row-sparse gradient directly —
  never materializing the (vocab, dim) dense grad. This is the producer
  the sparse KVStore round (``sparse/kvstore_sparse.py``) consumes.
* ``RowSparseState`` — lazily-grown row-sparse optimizer state: a row that
  was never touched has NO state row at all, which makes the lazy-update
  contract (``optimizer.Optimizer.update_row_sparse``) auditable — an
  untouched row's state is bit-identical to seed *by construction*.

Telemetry: ``embedding.rows_touched`` counts unique rows entering
``embedding_backward``/``from_dense`` (docs/OBSERVABILITY.md).

Env knobs (docs/ENV_VARS.md): ``MXNET_KVSTORE_SPARSE`` gates the sparse
wire path, ``MXNET_SPARSE_DENSE_FALLBACK_PCT`` the density threshold past
which a round ships dense (the update stays row-lazy either way).
"""
from __future__ import annotations

import logging
import os

import numpy as np

from ..base import MXNetError
from ..context import Context, current_context
from .. import telemetry as _tm
from ..ndarray import NDArray

__all__ = ["RowSparseNDArray", "row_sparse_array", "from_dense",
           "embedding_backward", "RowSparseState", "sparse_enabled",
           "dense_fallback_pct", "sparse_param_names", "normalize_row_ids"]

log = logging.getLogger("mxnet_tpu.sparse")

DEFAULT_DENSE_FALLBACK_PCT = 50.0


def sparse_enabled() -> bool:
    """MXNET_KVSTORE_SPARSE (docs/ENV_VARS.md) — `0` disables the sparse
    WIRE path (row-sparse pushes then ship dense buffers); the row-lazy
    update semantics are not affected by this knob."""
    return os.environ.get("MXNET_KVSTORE_SPARSE", "1").lower() not in (
        "0", "off", "false")


def dense_fallback_pct() -> float:
    """MXNET_SPARSE_DENSE_FALLBACK_PCT — when a round's unique-row union
    touches at least this percentage of the table, the round ships the
    DENSE buffer instead (a near-dense union costs more as index+rows than
    as the plain table: indices ride along and the allreduce loses its
    fixed-shape executable). The optimizer update remains row-lazy — the
    fallback changes wire strategy only, never semantics."""
    raw = os.environ.get("MXNET_SPARSE_DENSE_FALLBACK_PCT", "")
    try:
        pct = float(raw) if raw else DEFAULT_DENSE_FALLBACK_PCT
        if not (0.0 < pct <= 100.0):
            raise ValueError(pct)
    except ValueError:
        log.warning("MXNET_SPARSE_DENSE_FALLBACK_PCT=%r is not in (0, 100]; "
                    "using %g", raw, DEFAULT_DENSE_FALLBACK_PCT)
        pct = DEFAULT_DENSE_FALLBACK_PCT
    return pct


def normalize_row_ids(rows) -> np.ndarray:
    """Sorted unique int64 row ids from an NDArray or array-like — the one
    boundary normalization every row-id consumer (``retain``,
    ``from_dense``, ``KVStore.row_sparse_pull``) shares."""
    return np.unique(np.asarray(
        rows.asnumpy() if isinstance(rows, NDArray) else rows
    ).astype(np.int64).reshape(-1))


class RowSparseNDArray:
    """The ``row_sparse`` storage kind (reference: RowSparseNDArray,
    python/mxnet/ndarray/sparse.py / kRowSparseStorage in ndarray.h):
    ``indices`` — sorted UNIQUE int32 row ids, shape (nnz,); ``values`` —
    the corresponding rows, shape ``(nnz,) + shape[1:]``; ``shape`` — the
    logical dense shape. A zero-nnz array is valid (the all-zero
    gradient)."""

    stype = "row_sparse"

    def __init__(self, indices, values, shape, ctx: Context = None):
        ctx = ctx or (values.context if isinstance(values, NDArray)
                      else current_context())
        idx = (indices.asnumpy() if isinstance(indices, NDArray)
               else np.asarray(indices)).astype(np.int64).reshape(-1)
        if idx.size and (np.any(idx[1:] <= idx[:-1])
                         or idx[0] < 0 or idx[-1] >= shape[0]):
            raise MXNetError(
                "row_sparse indices must be sorted, unique and in "
                "[0, %d); got %r..." % (shape[0], idx[:8].tolist()))
        self.shape = tuple(int(s) for s in shape)
        vals = values if isinstance(values, NDArray) else NDArray(values,
                                                                  ctx=ctx)
        if tuple(vals.shape) != (idx.size,) + self.shape[1:]:
            raise MXNetError(
                "row_sparse values shape %s does not match %d indices of "
                "dense shape %s" % (tuple(vals.shape), idx.size, self.shape))
        self.indices = NDArray(idx.astype(np.int32), ctx=ctx)
        self.values = vals
        self._ctx = ctx

    # ------------------------------------------------------------ properties
    @property
    def nnz(self) -> int:
        return int(self.indices.shape[0])

    @property
    def size(self) -> int:
        """Stored element count (nnz rows × row size) — what actually moves,
        which is what the kvstore byte telemetry should count."""
        row = 1
        for s in self.shape[1:]:
            row *= int(s)
        return self.nnz * row

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def context(self) -> Context:
        return self._ctx

    ctx = context

    @property
    def density(self) -> float:
        return self.nnz / max(1, self.shape[0])

    def __repr__(self):
        return "<RowSparseNDArray %s nnz=%d @%s>" % (
            "x".join(str(s) for s in self.shape), self.nnz, self.context)

    # ----------------------------------------------------------- conversions
    def to_dense(self) -> NDArray:
        """Scatter the rows into a dense NDArray of ``self.shape``."""
        import jax.numpy as jnp

        dense = jnp.zeros(self.shape, dtype=self.dtype)
        if self.nnz:
            dense = dense.at[self.indices._jax()].set(self.values._jax())
        return NDArray(dense, ctx=self.context)

    def asnumpy(self) -> np.ndarray:
        return self.to_dense().asnumpy()

    def retain(self, row_ids) -> "RowSparseNDArray":
        """Keep only the rows named in ``row_ids`` (reference:
        sparse_retain) — rows absent from self come back as nothing, not
        zeros, so ``retain`` composes with the lazy-state contract."""
        want = normalize_row_ids(row_ids)
        mine = self.indices.asnumpy().astype(np.int64)
        keep = np.isin(mine, want)
        if keep.all():
            return self
        pos = np.flatnonzero(keep)
        vals = self.values._jax()[pos] if pos.size else \
            np.zeros((0,) + self.shape[1:], self.dtype)
        return RowSparseNDArray(mine[keep], NDArray(vals, ctx=self.context),
                                self.shape, ctx=self.context)

    def copy(self) -> "RowSparseNDArray":
        return RowSparseNDArray(self.indices.asnumpy(), self.values.copy(),
                                self.shape, ctx=self.context)

    # ------------------------------------------------------------ arithmetic
    def __add__(self, other) -> "RowSparseNDArray":
        """Merge two row-sparse arrays (segment-sum on the index union) —
        the KVStore local multi-device reduce for sparse gradients."""
        if not isinstance(other, RowSparseNDArray):
            raise TypeError("row_sparse + %s is not defined" % type(other))
        if other.shape != self.shape:
            raise MXNetError("shape mismatch %s vs %s"
                             % (self.shape, other.shape))
        import jax.numpy as jnp

        a_idx = self.indices.asnumpy().astype(np.int64)
        b_idx = other.indices.asnumpy().astype(np.int64)
        union = np.union1d(a_idx, b_idx)
        vals = jnp.zeros((union.size,) + self.shape[1:],
                         dtype=np.promote_types(self.dtype, other.dtype))
        if a_idx.size:
            vals = vals.at[np.searchsorted(union, a_idx)].add(
                self.values._jax())
        if b_idx.size:
            vals = vals.at[np.searchsorted(union, b_idx)].add(
                other.values._jax())
        return RowSparseNDArray(union, NDArray(vals, ctx=self.context),
                                self.shape, ctx=self.context)

    def __mul__(self, scalar) -> "RowSparseNDArray":
        return RowSparseNDArray(self.indices.asnumpy(),
                                self.values * float(scalar), self.shape,
                                ctx=self.context)

    __rmul__ = __mul__


def row_sparse_array(data, shape, ctx=None) -> RowSparseNDArray:
    """Construct from ``(values, indices)`` (reference:
    mx.nd.sparse.row_sparse_array)."""
    values, indices = data
    return RowSparseNDArray(indices, values if isinstance(values, NDArray)
                            else NDArray(np.asarray(values), ctx=ctx),
                            shape, ctx=ctx)


def from_dense(dense: NDArray, rows=None, shape=None) -> RowSparseNDArray:
    """Dense → row_sparse. With ``rows`` (the batch's looked-up ids — what
    the executor boundary knows for free) only those rows are gathered —
    O(nnz), no full-table scan; without it, rows with any non-zero entry
    are detected (O(size), the tolerant path)."""
    shape = tuple(shape or dense.shape)
    d = dense._jax().reshape(shape)
    if rows is not None:
        idx = normalize_row_ids(rows)
    else:
        flat = np.asarray(d.reshape(shape[0], -1))
        idx = np.flatnonzero(np.any(flat != 0, axis=1)).astype(np.int64)
    if _tm.enabled():
        _tm.counter("embedding.rows_touched").inc(int(idx.size))
    vals = d[idx] if idx.size else np.zeros((0,) + shape[1:], dense.dtype)
    return RowSparseNDArray(idx, NDArray(vals, ctx=dense.context), shape,
                            ctx=dense.context)


def embedding_backward(data, ograd, input_dim) -> RowSparseNDArray:
    """Row-sparse gradient of an Embedding lookup via segment-sum
    (reference: the Embedding op's ``sparse_grad=True`` backward,
    src/operator/tensor/indexing_op.cc EmbeddingOpBackward over
    kRowSparseStorage).

    ``data`` — the looked-up ids, any shape; ``ograd`` — the output
    cotangent, shape ``data.shape + (dim,)``. Gradient rows accumulate per
    unique id with ``jax.ops.segment_sum`` over compacted segment ids, so
    the (vocab, dim) dense gradient is never materialized — the whole
    computation is O(batch · dim + nnz · dim)."""
    import jax
    import jax.numpy as jnp

    ids = np.asarray(data.asnumpy() if isinstance(data, NDArray)
                     else data).astype(np.int64).reshape(-1)
    g = (ograd._jax() if isinstance(ograd, NDArray)
         else jnp.asarray(ograd))
    dim = int(g.shape[-1])
    g = g.reshape(-1, dim)
    if g.shape[0] != ids.size:
        raise MXNetError(
            "embedding_backward: %d ids but %d gradient rows"
            % (ids.size, g.shape[0]))
    uniq, seg = np.unique(ids, return_inverse=True)
    if uniq.size and (uniq[0] < 0 or uniq[-1] >= input_dim):
        raise MXNetError("embedding_backward: id out of [0, %d)" % input_dim)
    rows = jax.ops.segment_sum(g, jnp.asarray(seg, jnp.int32),
                               num_segments=max(1, uniq.size))
    if not uniq.size:
        rows = rows[:0]
    if _tm.enabled():
        _tm.counter("embedding.rows_touched").inc(int(uniq.size))
    ctx = ograd.context if isinstance(ograd, NDArray) else None
    return RowSparseNDArray(uniq, NDArray(rows, ctx=ctx),
                            (int(input_dim), dim), ctx=ctx)


class RowSparseState:
    """Lazily-grown row-sparse optimizer state for one parameter
    (docs/SPARSE.md): ``indices`` — sorted unique rows that have EVER been
    updated; ``rows`` — one ``(nnz, ...)`` host-backed value array per
    optimizer state slot (SGD momentum: 1, Adam: 2). A row outside
    ``indices`` has state bit-identical to a fresh Updater's zeros because
    it literally has no storage — the auditable form of the lazy-update
    contract ``optimizer.Optimizer.update_row_sparse`` enforces.

    Pickles (``Updater.get_states``) and checkpoints (index+rows per
    shard, ``checkpoint.sparse_shard_arrays``) as plain numpy."""

    def __init__(self, shape, dtype, n_states):
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.n_states = int(n_states)
        self.indices = np.zeros((0,), np.int64)
        self.rows = [np.zeros((0,) + self.shape[1:], self.dtype)
                     for _ in range(self.n_states)]

    @property
    def nnz(self) -> int:
        return int(self.indices.size)

    def gather(self, rows):
        """Per-slot state rows for ``rows`` (sorted unique int64) — zeros
        for rows never updated (what a fresh Updater would lazily create)."""
        out = [np.zeros((rows.size,) + self.shape[1:], self.dtype)
               for _ in range(self.n_states)]
        if self.indices.size:
            pos = np.searchsorted(self.indices, rows)
            pos = np.clip(pos, 0, self.indices.size - 1)
            hit = self.indices[pos] == rows
            for i in range(self.n_states):
                out[i][hit] = self.rows[i][pos[hit]]
        return out

    def scatter(self, rows, new_rows):
        """Write back updated state rows, growing the touched set."""
        if not rows.size:
            return
        union = np.union1d(self.indices, rows)
        if union.size != self.indices.size:
            grown = [np.zeros((union.size,) + self.shape[1:], self.dtype)
                     for _ in range(self.n_states)]
            if self.indices.size:
                old_pos = np.searchsorted(union, self.indices)
                for i in range(self.n_states):
                    grown[i][old_pos] = self.rows[i]
            self.indices, self.rows = union, grown
        pos = np.searchsorted(self.indices, rows)
        for i in range(self.n_states):
            self.rows[i][pos] = np.asarray(new_rows[i], self.dtype)

    def state_bytes(self) -> int:
        return sum(r.nbytes for r in self.rows) + self.indices.nbytes

    def __getstate__(self):
        return {"shape": self.shape, "dtype": self.dtype.name,
                "n_states": self.n_states, "indices": self.indices,
                "rows": self.rows}

    def __setstate__(self, d):
        self.shape = tuple(d["shape"])
        self.dtype = np.dtype(d["dtype"])
        self.n_states = int(d["n_states"])
        self.indices = np.asarray(d["indices"], np.int64)
        self.rows = [np.asarray(r, self.dtype) for r in d["rows"]]

    def __repr__(self):
        return "<RowSparseState %s nnz=%d x%d slots>" % (
            "x".join(str(s) for s in self.shape), self.nnz, self.n_states)


def sparse_param_names(symbol):
    """Names of parameters consumed as a sparse-grad embedding table: the
    weight input of every ``SparseEmbedding`` node and of every
    ``Embedding`` node carrying ``sparse_grad=True`` — what the Module/
    kvstore glue uses to route those keys through the sparse path."""
    names = []
    for node in symbol._topo():
        if node.is_variable:
            continue
        sparse = node.op == "SparseEmbedding"
        if node.op == "Embedding":
            flag = str(node.attrs.get("sparse_grad", "")).lower()
            sparse = flag in ("1", "true")
        if sparse and len(node.inputs) > 1:
            w = node.inputs[1][0]
            if w.is_variable:
                names.append(w.name)
    return names
