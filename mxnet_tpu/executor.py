"""Executor: bound, compiled symbol graphs.

TPU-native redesign of the reference's GraphExecutor
(src/executor/graph_executor.cc:333 Init, :178 InitFullGraph,
python/mxnet/executor.py). The reference builds an explicit fwd+bwd nnvm
graph, plans memory, and pushes per-node engine ops; here the whole graph is
*traced once* into a single jitted XLA computation — forward via topological
interpretation of the op registry, backward via ``jax.vjp`` over that same
trace (SURVEY.md §3.2 TPU mapping: "InitGraph down collapses into trace →
XLA compile"). Memory planning, fusion, scheduling, and the reference's
inplace/bulk-exec optimizations are XLA's job.

Semantics kept from the reference:
  * ``grad_req`` ∈ {write, add, null} per argument (kWriteTo/kAddTo/kNullOp).
  * aux states (BN moving stats) are threaded functionally through the trace
    and written back after ``forward`` — never by ``backward`` — matching the
    FMutateInputs contract.
  * ``backward`` reuses the forward's PRNG key so stochastic ops (Dropout)
    see identical masks in both passes, like the reference's cached masks.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .base import MXNetError, np_dtype
from .context import Context
from .ndarray import NDArray, _Chunk, zeros
from .ops.registry import get_op
from . import telemetry as _tm

__all__ = ["Executor", "bind", "simple_bind"]


class _GraphProgram:
    """The traced interpretation of a Symbol: pure functions over arg/aux
    tuples, compiled lazily per (is_train, shapes) by jax.jit."""

    def __init__(self, symbol, group2ctx=None, fusion=True):
        self.symbol = symbol
        self.topo = symbol._topo()
        self.group2ctx = dict(group2ctx or {})
        # fusion plan (fusion.py): structural rewrite map covering the
        # conv+BN Pallas stack AND the generic pattern engine (attention,
        # matmul+bias+act, norm+residual, elementwise chains — each gated
        # per shape by the fusion_tune measured verdict); disabled under
        # ctx-group placement (a fused subgraph would straddle a device
        # boundary). plan() itself honors the MXNET_FUSED_CONV_BN /
        # MXNET_FUSED_PATTERNS kill-switches and returns {} when all off.
        self._fusion_plan = {}
        self._infer_fusion = False
        if fusion and not self.group2ctx:
            from . import fusion as _fusion

            # graph-output node ids keep the planner from deferring (or
            # folding) a node whose value must materialize as a program
            # output — a deferred conv's PendingConv marker would otherwise
            # escape interpret() into the jit output pytree (Group symbols)
            self._fusion_plan = _fusion.plan(
                self.topo, output_ids={id(n) for n, _ in symbol._outputs})
            # grad-less/inference executions additionally need the CONV+BN
            # side of the plan declared ACTIVE for is_train=False
            # (fusion.infer_default(): forced env, on-device WINS match, or
            # a quantized variant) — the default keeps CPU eval numerics
            # byte-identical to the unfused op-by-op lowering. Generic
            # pattern directives stay live at inference (their fallback IS
            # the unfused lowering; per-pattern inference gating happens in
            # fusion.gate_pattern_explain).
            self._infer_fusion = bool(self._fusion_plan) \
                and _fusion.infer_default()
        # the plan's per-pattern site inventory, computed ONCE here — the
        # serving cache, health probes and the graphlint --rewrite dump all
        # read this instead of re-walking the directive map per call
        if self._fusion_plan:
            from . import fusion as _fusion

            self.pattern_sites, self.conv_bn_directives = \
                _fusion.plan_sites(self._fusion_plan)
        else:
            self.pattern_sites, self.conv_bn_directives = {}, 0
        # PlaceDevice-pass analogue (reference: graph_executor.cc:242
        # AssignContext → nnvm PlaceDevice inserting _CrossDeviceCopy): map
        # each node carrying a __ctx_group__ attr to its concrete device;
        # interpret() transfers that node's inputs there, so under jit XLA
        # compiles a multi-device program with real transfers at the group
        # boundaries (example: example/model-parallel-lstm in the reference).
        self._node_devices = {}
        if self.group2ctx:
            from .context import Context as _Ctx

            for node in self.topo:
                group = node.attrs.get("__ctx_group__") if node.op else None
                if group and group in self.group2ctx:
                    ctx = self.group2ctx[group]
                    ctx = ctx if isinstance(ctx, _Ctx) else _Ctx(ctx)
                    self._node_devices[id(node)] = ctx.jax_device
        args, auxs = symbol._classified_variables()
        self.arg_names = [n.name for n in args]
        self.aux_names = [n.name for n in auxs]
        self._arg_index = {n: i for i, n in enumerate(self.arg_names)}
        self._aux_index = {n: i for i, n in enumerate(self.aux_names)}
        self.outputs = list(symbol._outputs)
        self.output_names = symbol.list_outputs()
        # one stable int per rng-consuming node for fold_in
        self._rng_ids = {}
        for node in self.topo:
            if node.op is not None and get_op(node.op).needs_rng:
                self._rng_ids[id(node)] = len(self._rng_ids)
        # per-instance jit cache (an lru_cache on the methods would key a
        # class-level cache on self and leak every program + XLA executable)
        self._jit_cache = {}
        # telemetry: abstract-value signatures seen per jit entry, mirroring
        # jax.jit's own cache key so compile/cache-hit/retrace is observable
        # without reaching into jax internals (maintained only when
        # MXNET_TELEMETRY is on)
        self._seen_sigs = {}
        self._retrace_reason = None  # lazy GL201-203 diagnosis, cached

    # -------------------------------------------------------------- telemetry
    def _note_call(self, key, args, aux, extra=()):
        """Classify one compiled-entry call: ``compile`` (first signature
        for this jit key), ``cache_hit`` (signature seen before), or
        ``retrace`` (a NEW signature after the first — jax.jit compiles a
        fresh XLA program). Returns ``(kind, reason)``; ``reason`` is the
        cached GL201-203 retrace-guard diagnosis on retraces."""
        sig = (tuple((tuple(a.shape), str(a.dtype)) for a in args),
               tuple((tuple(a.shape), str(a.dtype)) for a in aux),
               extra)
        seen = self._seen_sigs.setdefault(key, set())
        if sig in seen:
            return "cache_hit", None
        first = not seen
        seen.add(sig)
        if first:
            return "compile", None
        return "retrace", self._retrace_reasons()

    def _retrace_reasons(self):
        """Why this program retraces, per the static retrace guard
        (analysis/retrace_guard.py GL201-203) — run once per program, on
        the first observed retrace, and cached."""
        if self._retrace_reason is None:
            try:
                from .analysis import lint

                rep = lint(self.symbol, passes=["retrace_guard"])
                self._retrace_reason = "; ".join(
                    "%s: %s" % (d.code, d.message) for d in rep) \
                    or "no GL201-203 pattern found (shape/dtype change " \
                       "came from the caller)"
            except Exception as exc:  # diagnosis must never sink a step
                self._retrace_reason = "retrace-guard diagnosis failed: %s" \
                    % exc
        return self._retrace_reason

    # ---------------------------------------------------------------- tracing
    def interpret(self, arg_vals, aux_vals, is_train, rng):
        """Run the graph on jax values. Returns (outputs, new_aux_tuple)."""
        import jax

        fusion_on = bool(self._fusion_plan)
        if fusion_on:
            from . import fusion as _fusion

        vals = {}
        new_aux = list(aux_vals)
        for node in self.topo:
            if node.is_variable:
                if node.name in self._arg_index:
                    vals[(id(node), 0)] = arg_vals[self._arg_index[node.name]]
                else:
                    vals[(id(node), 0)] = aux_vals[self._aux_index[node.name]]
                continue
            opdef = get_op(node.op)
            parsed = node.parsed_attrs()
            n_aux = len(opdef.aux_names(parsed))
            ins = [vals[(id(inp), oi)] for inp, oi in node.inputs]
            directive = self._fusion_plan.get(id(node)) if fusion_on else None
            if (directive is not None and not is_train
                    and not self._infer_fusion
                    and directive["kind"] in _fusion.CONV_BN_KINDS):
                # inference with the conv+BN plan INACTIVE: those nodes run
                # the plain op-by-op lowering (byte-identical eval); generic
                # pattern directives stay live
                directive = None
            if directive is not None:
                outs, aux_out = _fusion.execute(
                    directive, node,
                    ins[: len(ins) - n_aux] if n_aux else ins,
                    ins[len(ins) - n_aux :] if n_aux else [],
                    is_train)
                if not isinstance(outs, tuple):
                    outs = (outs,)
            else:
                if fusion_on:
                    ins = [_fusion.resolve(x) for x in ins]
                dev = self._node_devices.get(id(node))
                if dev is not None:
                    # cross-device copy at a ctx-group boundary
                    ins = [jax.device_put(x, dev) for x in ins]
                node_rng = None
                if opdef.needs_rng:
                    node_rng = jax.random.fold_in(rng, self._rng_ids[id(node)])
                outs, aux_out = opdef.apply(
                    parsed,
                    ins[: len(ins) - n_aux] if n_aux else ins,
                    aux=ins[len(ins) - n_aux :] if n_aux else [],
                    is_train=is_train,
                    rng=node_rng,
                )
            for i, o in enumerate(outs):
                vals[(id(node), i)] = o
            if n_aux:
                for (inp, _), new in zip(node.inputs[len(node.inputs) - n_aux :], aux_out):
                    if not inp.is_variable:
                        raise MXNetError(
                            "aux input of %s must be a variable" % node.name
                        )
                    new_aux[self._aux_index[inp.name]] = new
        outputs = tuple(vals[(id(n), i)] for n, i in self.outputs)
        if fusion_on:
            outputs = tuple(_fusion.resolve(o) for o in outputs)
        return outputs, tuple(new_aux)

    # --------------------------------------------------------------- compiled
    def _fwd(self, is_train):
        key = ("fwd", is_train)
        if key in self._jit_cache:
            return self._jit_cache[key]
        import jax

        def run(args, aux, rng):
            return self.interpret(args, aux, is_train, rng)

        self._jit_cache[key] = jax.jit(run)
        return self._jit_cache[key]

    def _fwd_bwd_cached(self, with_head_grads):
        key = ("fwd_bwd", with_head_grads)
        if key not in self._jit_cache:
            self._jit_cache[key] = self._fwd_bwd(with_head_grads)
        return self._jit_cache[key]

    def _fwd_bwd(self, with_head_grads):
        """One XLA computation: forward + full backward (the reference's
        InitFullGraph fwd+bwd graph, graph_executor.cc:178)."""
        import jax
        import jax.numpy as jnp

        def run(args, aux, head_grads, rng):
            def f(a):
                outs, new_aux = self.interpret(a, aux, True, rng)
                return outs, new_aux

            outs, vjp_fn, new_aux = jax.vjp(f, args, has_aux=True)
            if with_head_grads:
                cot = tuple(h.astype(o.dtype) for h, o in zip(head_grads, outs))
            else:
                # loss-style outputs: custom-vjp loss ops ignore the incoming
                # cotangent, so ones is the identity head gradient
                cot = tuple(jnp.ones_like(o) for o in outs)
            (grads,) = vjp_fn(cot)
            return outs, grads, new_aux

        return jax.jit(run)


class Executor:
    """A bound computation (reference: python/mxnet/executor.py)."""

    def __init__(self, symbol, ctx: Context, arg_arrays, grad_arrays, grad_req, aux_arrays, program=None):
        self._symbol = symbol
        self._ctx = ctx
        self._prog = program or _GraphProgram(symbol)
        self.arg_arrays: List[NDArray] = list(arg_arrays)
        self.grad_arrays: List[Optional[NDArray]] = list(grad_arrays)
        self.aux_arrays: List[NDArray] = list(aux_arrays)
        self._grad_req: List[str] = list(grad_req)
        self.outputs: List[NDArray] = []
        self.arg_dict: Dict[str, NDArray] = dict(zip(self._prog.arg_names, self.arg_arrays))
        self.grad_dict: Dict[str, Optional[NDArray]] = dict(zip(self._prog.arg_names, self.grad_arrays))
        self.aux_dict: Dict[str, NDArray] = dict(zip(self._prog.aux_names, self.aux_arrays))
        self.output_dict: Dict[str, NDArray] = {}
        self._last_rng = None
        self._monitor_callback = None
        self._cached_vjp = None

    # ----------------------------------------------------------------- running
    def _collect(self):
        args = tuple(a._jax() for a in self.arg_arrays)
        aux = tuple(a._jax() for a in self.aux_arrays)
        return args, aux

    def _next_rng(self):
        from . import random as _random

        self._last_rng = _random._next_key()
        return self._last_rng

    def _set_outputs(self, outs):
        self.outputs = [NDArray(chunk=_Chunk(o, self._ctx), shape=o.shape) for o in outs]
        self.output_dict = dict(zip(self._prog.output_names, self.outputs))
        if self._monitor_callback is not None:
            for name, arr in self.output_dict.items():
                self._monitor_callback(name, arr)
        return self.outputs

    def _write_aux(self, new_aux):
        for arr, new in zip(self.aux_arrays, new_aux):
            arr._set_jax(new)

    def _apply_grads(self, grads):
        import jax
        import jax.numpy as jnp

        for garr, g, req in zip(self.grad_arrays, grads, self._grad_req):
            if req == "null" or garr is None:
                continue
            if g.dtype == jax.dtypes.float0:
                # integer-typed argument (e.g. token ids): no tangent space
                continue
            if req == "add":
                garr._set_jax(garr._jax() + g.astype(garr.dtype))
            else:  # write
                garr._set_jax(g.astype(garr.dtype))

    def forward(self, is_train=False, **kwargs):
        """Run forward; optional kwargs copy new values into bound args
        (reference: executor.py forward).

        Cost note: every train-mode forward re-runs the jax.vjp
        linearization (a Python retrace, unlike the cached fused
        forward_backward program) and pins the residual set on device until
        ``backward()`` or the next forward — callers that never backward
        should pass ``is_train=False`` (or use Module's fused path) to skip
        both costs.

        With ``is_train=True`` the forward is run under ``jax.vjp`` and the
        vjp closure (holding the forward-time residuals on device, like the
        reference's retained activations) is cached so a later
        ``backward()`` executes ONLY the backward computation — the manual
        forward/backward idiom costs 1x fwd + 1x bwd, same as
        ``forward_backward``'s single fused program."""
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("unknown argument %r" % k)
            self.arg_dict[k][:] = v
        args, aux = self._collect()
        rng = self._next_rng()
        sp = _tm.NULL_SPAN
        if _tm.enabled():
            sp = _tm.span("executor.forward", train=bool(is_train))
            self._note_telemetry(sp, ("fwd", bool(is_train)), args, aux)
        # release the previous step's residuals BEFORE tracing the new vjp —
        # otherwise two full activation sets coexist on device
        self._cached_vjp = None
        with sp:
            if is_train and any(r != "null" for r in self._grad_req):
                import jax

                fn = self._prog._fwd(True)

                def f(a):
                    return fn(a, aux, rng)

                outs, vjp_fn, new_aux = jax.vjp(f, args, has_aux=True)
                self._cached_vjp = (vjp_fn, tuple(o.dtype for o in outs))
            else:
                outs, new_aux = self._prog._fwd(bool(is_train))(args, aux, rng)
        if is_train:
            self._write_aux(new_aux)
        return self._set_outputs(outs)

    def _note_telemetry(self, sp, key, args, aux, extra=()):
        """Count compile/cache_hit/retrace for this call and attach the
        classification (plus the GL201-203 diagnosis on retraces) to the
        span. Caller guards with ``_tm.enabled()``."""
        kind, reason = self._prog._note_call(key, args, aux, extra)
        _tm.counter("executor." + kind).inc()
        sp.set(cache=kind)
        if reason is not None:
            sp.set(retrace_reason=reason)
            _tm.gauge("executor.last_retrace_reason").set(reason)

    def backward(self, out_grads=None):
        """Run backward, accumulating into grad arrays per grad_req.

        After ``forward(is_train=True)`` this applies the cached vjp —
        gradients come from the forward-time activations (reference
        semantics) with no forward recompute. Without a cached vjp (e.g.
        ``backward()`` cold) it falls back to the fused fwd+bwd program."""
        if out_grads is not None:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            if len(out_grads) != len(self._prog.outputs):
                raise MXNetError(
                    "backward: expected %d head gradients, got %d"
                    % (len(self._prog.outputs), len(out_grads))
                )
        cached = getattr(self, "_cached_vjp", None)
        if cached is not None:
            import jax.numpy as jnp

            vjp_fn, out_dtypes = cached
            if out_grads is None:
                # loss-style outputs: custom-vjp loss ops ignore the incoming
                # cotangent, so ones is the identity head gradient
                cot = tuple(jnp.ones(o.shape, dt)
                            for o, dt in zip(self.outputs, out_dtypes))
            else:
                cot = tuple(g._jax().astype(dt)
                            for g, dt in zip(out_grads, out_dtypes))
            with _tm.span("executor.backward", path="cached_vjp"):
                (grads,) = vjp_fn(cot)
            self._cached_vjp = None  # residuals consumed — free the activations
            self._apply_grads(grads)
            return
        args, aux = self._collect()
        rng = self._last_rng if self._last_rng is not None else self._next_rng()
        with_head = out_grads is not None
        head = tuple(g._jax() for g in out_grads) if with_head else ()
        sp = _tm.NULL_SPAN
        if _tm.enabled():
            sp = _tm.span("executor.backward", path="fused_fwd_bwd")
            self._note_telemetry(
                sp, ("fwd_bwd", with_head), args, aux,
                extra=tuple((tuple(h.shape), str(h.dtype)) for h in head))
        with sp:
            fn = self._prog._fwd_bwd_cached(with_head)
            outs, grads, _ = fn(args, aux, head, rng)
        self._apply_grads(grads)

    def forward_backward(self, out_grads=None, is_train=True):
        """Fused fwd+bwd: ONE compiled XLA computation per training step —
        the TPU-native analogue of the reference's cached-op bulk segments
        (graph_executor.cc:690 InitOpSegs)."""
        args, aux = self._collect()
        rng = self._next_rng()
        self._cached_vjp = None  # this step supersedes any cached forward
        with_head = out_grads is not None
        head = tuple(g._jax() for g in out_grads) if with_head else ()
        sp = _tm.NULL_SPAN
        if _tm.enabled():
            sp = _tm.span("executor.forward_backward", train=bool(is_train))
            self._note_telemetry(
                sp, ("fwd_bwd", with_head), args, aux,
                extra=tuple((tuple(h.shape), str(h.dtype)) for h in head))
        with sp:
            fn = self._prog._fwd_bwd_cached(with_head)
            outs, grads, new_aux = fn(args, aux, head, rng)
        self._write_aux(new_aux)
        self._apply_grads(grads)
        return self._set_outputs(outs)

    # ------------------------------------------------------------------ misc
    def copy_params_from(self, arg_params, aux_params=None, allow_extra_params=False):
        for name, arr in (arg_params or {}).items():
            if name in self.arg_dict:
                self.arg_dict[name][:] = arr
            elif not allow_extra_params:
                raise MXNetError("Found name %r not in executor arguments" % name)
        for name, arr in (aux_params or {}).items():
            if name in self.aux_dict:
                self.aux_dict[name][:] = arr
            elif not allow_extra_params:
                raise MXNetError("Found name %r not in executor aux states" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor bound to new shapes (reference:
        executor.py reshape). XLA recompiles per shape — same economics as the
        reference's executor-per-bucket. ``partial_shaping`` keeps old shapes
        for arguments the new hints leave undetermined; without
        ``allow_up_sizing`` an argument may not grow."""
        if partial_shaping:
            arg_shapes, _, aux_shapes = self._symbol.infer_shape_partial(**kwargs)
        else:
            arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
            if arg_shapes is None:
                raise MXNetError(
                    "reshape: insufficient shape info (pass partial_shaping=True "
                    "to keep old shapes for undetermined arguments)"
                )

        def _renew(arr, shape, name):
            if shape is None:
                if not partial_shaping:
                    raise MXNetError("reshape: shape of %r undetermined" % name)
                return arr, False
            if tuple(arr.shape) == tuple(shape):
                return arr, False
            new_size = int(np.prod(shape))
            if new_size > arr.size and not allow_up_sizing:
                raise MXNetError(
                    "reshape: new shape %s of %r is larger than original %s; pass "
                    "allow_up_sizing=True to permit reallocation" % (shape, name, arr.shape)
                )
            return zeros(shape, ctx=self._ctx, dtype=arr.dtype), True

        new_args, new_grads, new_aux = [], [], []
        for name, arr, garr, shape in zip(
            self._prog.arg_names, self.arg_arrays, self.grad_arrays, arg_shapes
        ):
            na, changed = _renew(arr, shape, name)
            new_args.append(na)
            if garr is None:
                new_grads.append(None)
            else:
                new_grads.append(zeros(na.shape, ctx=self._ctx, dtype=garr.dtype) if changed else garr)
        for name, arr, shape in zip(self._prog.aux_names, self.aux_arrays, aux_shapes):
            new_aux.append(_renew(arr, shape, name)[0])
        exe = Executor(self._symbol, self._ctx, new_args, new_grads, self._grad_req, new_aux, program=self._prog)
        # keep the pre-rewrite symbol identity: a reshaped executor handed
        # to bind(shared_exec=...) must still match the user's symbol
        exe._orig_symbol = getattr(self, "_orig_symbol", self._symbol)
        return exe

    def set_monitor_callback(self, callback):
        self._monitor_callback = callback

    def debug_str(self):
        return self._symbol.debug_str()


# -------------------------------------------------------------------- binding
def _normalize_grad_req(grad_req, arg_names):
    if isinstance(grad_req, str):
        return [grad_req] * len(arg_names)
    if isinstance(grad_req, (list, tuple)):
        if len(grad_req) != len(arg_names):
            raise MXNetError("grad_req list length mismatch")
        return list(grad_req)
    if isinstance(grad_req, dict):
        return [grad_req.get(n, "null") for n in arg_names]
    raise TypeError("grad_req must be str/list/dict")


def _lint_at_bind(symbol, arg_arrays, arg_names, aux_arrays, aux_names,
                  train=True):
    """MXNET_GRAPHLINT=warn|error hook: run the static passes with the
    concrete bind shapes/dtypes (analysis/: the nnvm-attribute-pass
    analogue). ``warn`` logs findings; ``error`` raises MXNetError with the
    structured report instead of letting a broken graph reach jit tracing.
    ``train`` steers the GL5xx memory planner: a grad-less bind plans
    forward-only liveness, a training bind adds grads + optimizer state."""
    from .analysis import graphlint_mode, lint_bind

    mode = graphlint_mode()
    if mode is None:
        return
    shapes = {n: tuple(a.shape) for n, a in zip(arg_names, arg_arrays)
              if a is not None}
    types = {n: np.dtype(a.dtype) for n, a in zip(arg_names, arg_arrays)
             if a is not None}
    shapes.update({n: tuple(a.shape) for n, a in zip(aux_names, aux_arrays)})
    types.update({n: np.dtype(a.dtype) for n, a in zip(aux_names, aux_arrays)})
    lint_bind(symbol, shapes, types, mode, target="bind", train=train)


def _rewrite_at_bind(symbol, args, grad_req, aux_states):
    """MXNET_GRAPHREWRITE=on|verify hook: run the Symbol→Symbol rewrite
    pipeline (analysis/rewrite.py — const fold, CSE, canonicalize, DCE,
    optional bf16 legalization) with the concrete bind shapes/dtypes and
    bind the REWRITTEN graph. Under ``verify`` the GL6xx provenance
    verifier gates the result (GL601/602/604 raise). Any failure falls
    back to the original symbol — a rewrite must never sink a bind."""
    from .analysis.rewrite import graphrewrite_mode, rewrite_for_bind

    if graphrewrite_mode() is None:
        return symbol
    shapes, types = {}, {}
    named = (dict(args) if isinstance(args, dict)
             else dict(zip(symbol.list_arguments(), args or [])))
    if isinstance(aux_states, dict):
        named.update(aux_states)
    elif aux_states:
        named.update(zip(symbol.list_auxiliary_states(), aux_states))
    for n, a in named.items():
        if a is not None:
            shapes[n] = tuple(a.shape)
            types[n] = np.dtype(a.dtype)
    return rewrite_for_bind(symbol, shapes, types, grad_req=grad_req,
                            target="bind")[0]


def bind(symbol, ctx, args, args_grad=None, grad_req="write", aux_states=None, shared_exec=None, group2ctx=None):
    """Bind NDArrays to a symbol's arguments (reference: symbol.py:917 bind →
    Executor::Bind, graph_executor.cc:936)."""
    if _tm.enabled():
        _tm.counter("executor.bind").inc()
    orig_symbol = symbol
    if shared_exec is not None and (
            shared_exec._symbol is symbol
            or getattr(shared_exec, "_orig_symbol", None) is symbol):
        # reuse the shared program's (possibly rewritten) symbol so the
        # jit cache and fusion plan carry over (reshape/bucketing path)
        symbol = shared_exec._symbol
    else:
        symbol = _rewrite_at_bind(symbol, args, grad_req, aux_states)
    with _tm.span("executor.bind", symbol=symbol.name,
                  shared=shared_exec is not None):
        if shared_exec is not None and shared_exec._symbol is symbol \
                and shared_exec._prog.group2ctx == dict(group2ctx or {}):
            prog = shared_exec._prog
        else:
            prog = _GraphProgram(symbol, group2ctx=group2ctx)
    arg_names = prog.arg_names
    aux_names = prog.aux_names
    ctx = Context(ctx) if not isinstance(ctx, Context) else ctx

    if isinstance(args, dict):
        missing = [n for n in arg_names if n not in args]
        if missing:
            raise MXNetError("bind: missing arguments %s" % missing)
        arg_arrays = [args[n] for n in arg_names]
    else:
        if len(args) != len(arg_names):
            raise MXNetError("bind: expected %d args, got %d" % (len(arg_names), len(args)))
        arg_arrays = list(args)

    reqs = _normalize_grad_req(grad_req, arg_names)
    if args_grad is None:
        grad_arrays = [None] * len(arg_names)
        reqs = ["null"] * len(arg_names)
    elif isinstance(args_grad, dict):
        grad_arrays = [args_grad.get(n) for n in arg_names]
        reqs = [r if g is not None else "null" for r, g in zip(reqs, grad_arrays)]
    else:
        grad_arrays = list(args_grad)

    if aux_states is None:
        aux_arrays = []
        for n in aux_names:
            raise MXNetError("bind: missing aux state %r" % n)
    elif isinstance(aux_states, dict):
        missing = [n for n in aux_names if n not in aux_states]
        if missing:
            raise MXNetError("bind: missing aux states %s" % missing)
        aux_arrays = [aux_states[n] for n in aux_names]
    else:
        aux_arrays = list(aux_states)
        if len(aux_arrays) != len(aux_names):
            raise MXNetError("bind: expected %d aux states, got %d" % (len(aux_names), len(aux_arrays)))

    _lint_at_bind(symbol, arg_arrays, arg_names, aux_arrays, aux_names,
                  train=any(r != "null" and g is not None
                            for r, g in zip(reqs, grad_arrays)))
    exe = Executor(symbol, ctx, arg_arrays, grad_arrays, reqs, aux_arrays, program=prog)
    # the caller's symbol, pre-rewrite: reshape()/shared_exec identity
    # checks and debugging compare against what the user actually built
    exe._orig_symbol = orig_symbol
    return exe


def simple_bind(symbol, ctx, grad_req="write", type_dict=None, group2ctx=None, shared_exec=None, **kwargs):
    """Infer shapes/types from kwarg shapes, allocate all arrays, bind
    (reference: symbol.py:836 simple_bind)."""
    shape_hints = {k: tuple(v) for k, v in kwargs.items() if v is not None}
    type_hints = {k: np_dtype(v) for k, v in (type_dict or {}).items()}
    try:
        res = symbol._infer_impl(shape_hints, type_hints, partial=False)
    except Exception as e:
        from .analysis import graphlint_mode

        if graphlint_mode() is not None:
            # diagnose the failure with the full pass suite: structured
            # per-node findings with provenance instead of a jit traceback
            from .analysis import lint

            report = lint(symbol, shapes=shape_hints, types=type_hints,
                          strict_shapes=True, target="simple_bind")
            if report.errors:
                raise MXNetError(
                    "simple_bind failed: %s\ngraphlint diagnosis:\n%s"
                    % (e, report.format(min_severity="warning")))
        if isinstance(e, MXNetError):
            raise MXNetError("simple_bind failed: %s" % e)
        raise
    arg_shapes, out_shapes, aux_shapes, arg_types, out_types, aux_types = res
    ctx = Context(ctx) if not isinstance(ctx, Context) else ctx

    arg_names = symbol.list_arguments()
    reqs = _normalize_grad_req(grad_req, arg_names)
    arg_arrays = [zeros(s, ctx=ctx, dtype=t) for s, t in zip(arg_shapes, arg_types)]
    grad_arrays = [
        zeros(s, ctx=ctx, dtype=t) if r != "null" else None
        for s, t, r in zip(arg_shapes, arg_types, reqs)
    ]
    aux_arrays = [zeros(s, ctx=ctx, dtype=t) for s, t in zip(aux_shapes, aux_types)]
    return bind(
        symbol,
        ctx,
        arg_arrays,
        args_grad=grad_arrays,
        grad_req=reqs,
        aux_states=aux_arrays,
        shared_exec=shared_exec,
        group2ctx=group2ctx,
    )
