"""Network visualization (reference: python/mxnet/visualization.py).

``print_summary`` walks the Symbol graph printing a per-layer table with
output shapes and parameter counts; ``plot_network`` renders via graphviz
when available."""
from __future__ import annotations


from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=(0.44, 0.64, 0.74, 1.0)):
    """Print a layer-by-layer summary (reference: visualization.py
    print_summary)."""
    shape_dict = {}
    data_names = set(shape or ())
    if shape is not None:
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shape)
        shape_dict = dict(zip(symbol.list_arguments(), arg_shapes))
        shape_dict.update(zip(symbol.list_auxiliary_states(), aux_shapes))

    topo = symbol._topo()
    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(f, pos):
        line = ""
        for i, field in enumerate(f):
            line += str(field)
            line = line[: pos[i]]
            line += " " * (pos[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(fields, positions)
    print("=" * line_length)
    total_params = 0
    for node in topo:
        if node.is_variable:
            continue
        params = 0
        for inp, _ in node.inputs:
            if inp.is_variable and inp.name in shape_dict and inp.name not in data_names:
                import numpy as np

                params += int(np.prod(shape_dict[inp.name]))
        total_params += params
        prevs = ",".join(i.name for i, _ in node.inputs if not i.is_variable)
        out_shape = ""
        print_row(["%s (%s)" % (node.name, node.op), out_shape, params, prevs], positions)
    print("=" * line_length)
    print("Total params: %d" % total_params)
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", shape=None, node_attrs=None):
    """Render the graph with graphviz (reference: visualization.py
    plot_network). Raises if graphviz is unavailable."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise MXNetError("plot_network requires the graphviz package") from e
    node_attrs = dict(node_attrs or {})
    attrs = {"shape": "box", "fixedsize": "false"}
    attrs.update(node_attrs)
    dot = Digraph(name=title)
    topo = symbol._topo()
    for node in topo:
        if node.is_variable:
            dot.node(name=node.name, label=node.name, shape="oval")
        else:
            dot.node(name=node.name, label="%s\n%s" % (node.name, node.op), **attrs)
    for node in topo:
        for inp, _ in node.inputs:
            dot.edge(tail_name=inp.name, head_name=node.name)
    return dot
