"""Monitor: per-batch output statistics (reference: python/mxnet/monitor.py:16).

Installs an executor monitor callback; each ``tic``/``toc`` window collects
(name, stat) pairs for outputs matching the pattern — the observability layer
Module.fit wires when ``monitor`` is passed (base_module.py fit)."""
from __future__ import annotations

import logging
import re

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                """|x|/size(x), the reference's default stat"""
                arr = x.asnumpy()
                return abs(arr).sum() / arr.size

            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def stat_helper(self, name, arr):
        if not self.activated or not self.re_prog.match(name):
            return
        try:
            stat = self.stat_func(arr)
        except Exception as exc:
            # a non-numeric/odd-dtype output (int tokens, bool masks, a
            # custom stat_func choking on bf16) must not abort fit mid-epoch
            # — record the failure as the stat instead of raising
            stat = "<stat failed: %s: %s>" % (type(exc).__name__, exc)
        self.queue.append((self.step, name, stat))

    def install(self, exe):
        """(reference: monitor.py install — executor.set_monitor_callback)"""
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v in self.queue:
            res.append((n, k, str(v)))
        self.queue = []
        res.extend(self._telemetry_stats())
        return res

    def _telemetry_stats(self):
        """Per-batch framework stats from the telemetry registry (single
        source of truth with the trace/Speedometer): the latest step row's
        counter/timer deltas, rendered like output stats. Empty when
        telemetry is off or no step has been marked yet."""
        from . import telemetry

        if not telemetry.enabled():
            return []
        rows = telemetry.step_rows(last=1)
        if not rows:
            return []
        row = rows[-1]
        # label with THIS monitor's batch counter, not the registry's
        # process-global step id — a prior fit/bench in the process would
        # otherwise make the two row families disagree in the Batch column
        n = self.step - 1
        out = []
        if row["wall_ms"] is not None:
            out.append((n, "telemetry.step_wall_ms", str(row["wall_ms"])))
        for name, delta in sorted(row["counters"].items()):
            out.append((n, "telemetry." + name, str(delta)))
        for name, t in sorted(row["timers"].items()):
            out.append((n, "telemetry.%s_ms" % name, str(t["ms"])))
        return out

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)
        return res
