"""Monitor: per-batch output statistics (reference: python/mxnet/monitor.py:16).

Installs an executor monitor callback; each ``tic``/``toc`` window collects
(name, stat) pairs for outputs matching the pattern — the observability layer
Module.fit wires when ``monitor`` is passed (base_module.py fit)."""
from __future__ import annotations

import logging
import re
from math import sqrt

from .ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                """|x|/size(x), the reference's default stat"""
                arr = x.asnumpy()
                return abs(arr).sum() / arr.size

            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def stat_helper(self, name, arr):
        if not self.activated or not self.re_prog.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(arr)))

    def install(self, exe):
        """(reference: monitor.py install — executor.set_monitor_callback)"""
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v in self.queue:
            res.append((n, k, str(v)))
        self.queue = []
        return res

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)
        return res
