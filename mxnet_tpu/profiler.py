"""Profiler (reference: python/mxnet/profiler.py + src/engine/profiler.cc).

The reference hand-stamped per-op start/end times in the engine and emitted
Chrome trace-event JSON (SURVEY.md §5.1). Here profiling delegates to the JAX
profiler: ``profiler_set_state('run')`` starts an XLA trace capture (viewable
in TensorBoard/Perfetto, a superset of the chrome-trace contract) and
``dump_profile`` finalizes it. The ``mode`` knob maps to the same API names.
"""
from __future__ import annotations

import os

from .base import MXNetError

__all__ = ["profiler_set_config", "profiler_set_state", "dump_profile",
           "trace_files", "summarize", "State"]

_config = {"mode": "symbolic", "filename": "profile.json"}
_state = "stop"
_trace_dir = None


class State:
    stop = "stop"
    run = "run"


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """(reference: profiler.py profiler_set_config; modes kOnlySymbolic/
    kAllOperator — with one fused XLA program the distinction collapses)."""
    if mode not in ("symbolic", "all"):
        raise MXNetError("profiler mode must be 'symbolic' or 'all'")
    _config["mode"] = mode
    _config["filename"] = filename


def profiler_set_state(state="stop"):
    """(reference: profiler.py profiler_set_state)"""
    global _state, _trace_dir
    if state not in ("stop", "run"):
        raise MXNetError("profiler state must be 'stop' or 'run'")
    import jax

    if state == "run" and _state == "stop":
        _trace_dir = os.path.join(
            os.path.dirname(os.path.abspath(_config["filename"])) or ".",
            "jax_trace")
        jax.profiler.start_trace(_trace_dir)
        _state = "run"
    elif state == "stop" and _state == "run":
        jax.profiler.stop_trace()
        _state = "stop"


def dump_profile():
    """Finalize the capture (reference: MXDumpProfile)."""
    if _state == "run":
        profiler_set_state("stop")
    return _trace_dir


def trace_files(trace_dir=None):
    """The trace artifacts a capture produced (perfetto/xplane files under
    <dir>/plugins/profile/<ts>/). Empty list = the capture failed."""
    import glob

    d = trace_dir or _trace_dir
    if not d:
        return []
    return sorted(glob.glob(os.path.join(d, "plugins", "profile", "*", "*")))


def summarize(trace_dir=None, top=25, device_only=True):
    """Aggregate per-kernel wall time from a captured trace — the per-op
    stat table of the reference's engine profiler (src/engine/profiler.cc
    chrome-trace events), recovered from the XLA trace.

    Returns a list of {"name", "ms", "count", "process"} dicts, heaviest
    first. ``device_only=False`` includes host-side python/runtime spans.
    """
    import collections
    import glob
    import gzip
    import json
    import re

    d = trace_dir or _trace_dir
    files = sorted(glob.glob(
        os.path.join(d or ".", "plugins", "profile", "*", "*.trace.json.gz")))
    if not files:
        return []
    raw = json.loads(gzip.open(files[-1]).read().decode())
    events = raw.get("traceEvents", [])
    pids = {e["pid"]: e["args"].get("name", "")
            for e in events if e.get("ph") == "M"
            and e.get("name") == "process_name"}
    acc = collections.Counter()
    cnt = collections.Counter()
    for e in events:
        if e.get("ph") != "X":
            continue
        proc = pids.get(e["pid"], str(e["pid"]))
        if device_only and "TPU" not in proc and "GPU" not in proc \
                and "device" not in proc.lower():
            continue
        name = e.get("name", "?")
        # drop the whole-program umbrella spans and bare step-number marks
        if name.startswith("jit_") or re.fullmatch(r"\d+", name):
            continue
        key = (proc, name)
        acc[key] += e.get("dur", 0)
        cnt[key] += 1
    out = [{"process": proc, "name": name, "ms": round(us / 1000.0, 3),
            "count": cnt[(proc, name)]}
           for (proc, name), us in acc.most_common(top)]
    return out
