"""Profiler (reference: python/mxnet/profiler.py + src/engine/profiler.cc).

The reference hand-stamped per-op start/end times in the engine and emitted
Chrome trace-event JSON (SURVEY.md §5.1). Here profiling delegates to the JAX
profiler: ``profiler_set_state('run')`` starts an XLA trace capture (viewable
in TensorBoard/Perfetto, a superset of the chrome-trace contract) and
``dump_profile`` finalizes it. The ``mode`` knob maps to the same API names.
"""
from __future__ import annotations

import os

from .base import MXNetError

__all__ = ["profiler_set_config", "profiler_set_state", "dump_profile", "State"]

_config = {"mode": "symbolic", "filename": "profile.json"}
_state = "stop"
_trace_dir = None


class State:
    stop = "stop"
    run = "run"


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """(reference: profiler.py profiler_set_config; modes kOnlySymbolic/
    kAllOperator — with one fused XLA program the distinction collapses)."""
    if mode not in ("symbolic", "all"):
        raise MXNetError("profiler mode must be 'symbolic' or 'all'")
    _config["mode"] = mode
    _config["filename"] = filename


def profiler_set_state(state="stop"):
    """(reference: profiler.py profiler_set_state)"""
    global _state, _trace_dir
    if state not in ("stop", "run"):
        raise MXNetError("profiler state must be 'stop' or 'run'")
    import jax

    if state == "run" and _state == "stop":
        _trace_dir = os.path.join(
            os.path.dirname(os.path.abspath(_config["filename"])) or ".",
            "jax_trace")
        jax.profiler.start_trace(_trace_dir)
        _state = "run"
    elif state == "stop" and _state == "run":
        jax.profiler.stop_trace()
        _state = "stop"


def dump_profile():
    """Finalize the capture (reference: MXDumpProfile)."""
    if _state == "run":
        profiler_set_state("stop")
    return _trace_dir
