"""Profiler (reference: python/mxnet/profiler.py + src/engine/profiler.cc).

The reference hand-stamped per-op start/end times in the engine and emitted
Chrome trace-event JSON (SURVEY.md §5.1). Here a capture is TWO coordinated
recorders:

  * the XLA trace — ``jax.profiler`` capture into ``<filename dir>/jax_trace``
    (viewable in TensorBoard/Perfetto, a superset of the chrome-trace
    contract), and
  * the framework telemetry spans (mxnet_tpu.telemetry) — engine/executor/
    fusion/kvstore/io seams, forced to ``trace`` mode for the window even
    when ``MXNET_TELEMETRY`` is off.

``dump_profile()`` finalizes both and honors the reference ``MXDumpProfile``
contract: it writes the framework spans as chrome-trace JSON to the
configured ``filename`` (with the XLA trace directory recorded in
``otherData.xla_trace_dir`` so viewers can merge), and returns that path.
State transitions are idempotent: ``profiler_set_state('run')`` while
running, ``'stop'`` while stopped, and ``dump_profile()`` with no capture
are all clean no-ops that never leave ``_state``/``_trace_dir`` torn.
"""
from __future__ import annotations

import logging
import os

from .base import MXNetError
from . import telemetry as _tm

__all__ = ["profiler_set_config", "profiler_set_state", "dump_profile",
           "trace_files", "summarize", "State"]

_LOG = logging.getLogger("mxnet_tpu")

_config = {"mode": "symbolic", "filename": "profile.json"}
_state = "stop"
_trace_dir = None     # XLA capture dir of the current/last capture
_dump_path = None     # framework chrome-trace written by the last dump
_xla_active = False   # jax.profiler capture actually started
_captured = False     # at least one capture window ran (dump has content)
_saved_override = None  # telemetry mode override to restore at stop


class State:
    stop = "stop"
    run = "run"


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """(reference: profiler.py profiler_set_config; modes kOnlySymbolic/
    kAllOperator — with one fused XLA program the distinction collapses)."""
    if mode not in ("symbolic", "all"):
        raise MXNetError("profiler mode must be 'symbolic' or 'all'")
    _config["mode"] = mode
    _config["filename"] = filename


def profiler_set_state(state="stop"):
    """(reference: profiler.py profiler_set_state). Idempotent in both
    directions: re-entering the current state is a no-op."""
    global _state, _trace_dir, _xla_active, _captured, _saved_override
    if state not in ("stop", "run"):
        raise MXNetError("profiler state must be 'stop' or 'run'")
    if state == _state:
        return  # already there — never tear _trace_dir/telemetry mode

    if state == "run":
        # frame the capture window: force span recording on, remember what
        # to restore (an explicit set_mode override, or the env default)
        _saved_override = _tm.current_override()
        _tm.set_mode("trace")
        _tm.clear_events()
        _trace_dir = os.path.join(
            os.path.dirname(os.path.abspath(_config["filename"])) or ".",
            "jax_trace")
        _xla_active = False
        try:
            import jax

            jax.profiler.start_trace(_trace_dir)
            _xla_active = True
        except Exception as exc:
            # framework spans still record; the dump just has no XLA half
            _LOG.warning("profiler: XLA trace capture failed to start (%s); "
                         "capturing framework spans only", exc)
        _state = "run"
        _captured = True
        return

    # state == "stop"
    if _xla_active:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as exc:
            _LOG.warning("profiler: XLA trace capture failed to stop: %s",
                         exc)
        _xla_active = False
    _tm.set_mode(_saved_override)
    _state = "stop"


def dump_profile():
    """Finalize the capture and write the framework chrome-trace JSON to the
    configured ``filename`` (reference: MXDumpProfile). Returns the written
    path — or ``None``, cleanly, when no capture ever ran."""
    global _dump_path
    if _state == "run":
        profiler_set_state("stop")
    if not _captured:
        return None  # nothing recorded; stay consistent instead of raising
    _dump_path = os.path.abspath(_config["filename"])
    _tm.export_chrome_trace(
        _dump_path, xla_trace_dir=_trace_dir,
        extra={"profiler_mode": _config["mode"]})
    return _dump_path


def trace_files(trace_dir=None):
    """Every artifact the capture produced, framework AND XLA: the
    chrome-trace JSON ``dump_profile`` wrote (if any) plus the
    perfetto/xplane files under ``<dir>/plugins/profile/<ts>/``. Empty
    list = no capture (or the capture failed)."""
    import glob

    d = trace_dir or _trace_dir
    out = []
    if (trace_dir is None or trace_dir == _trace_dir) \
            and _dump_path and os.path.exists(_dump_path):
        out.append(_dump_path)
    if d:
        out.extend(sorted(glob.glob(
            os.path.join(d, "plugins", "profile", "*", "*"))))
    return out


def _framework_rows(trace_dir):
    """Aggregate framework spans for the CURRENT capture: from the dumped
    chrome-trace when one exists, else the live telemetry buffer. An
    explicit ``trace_dir`` naming a DIFFERENT capture gets no framework
    rows — this process's buffer/dump says nothing about an archived
    trace, and attributing it there would misreport where that capture's
    time went."""
    if trace_dir is not None and trace_dir != _trace_dir:
        return []
    trace = None
    if _dump_path and os.path.exists(_dump_path):
        import json

        try:
            with open(_dump_path) as f:
                trace = json.load(f)
        except (OSError, ValueError):
            trace = None
    rows = _tm.span_summary(trace=trace, top=None if trace else 10**6)
    return [{"process": "mxnet_tpu framework", "name": r["name"],
             "ms": r["ms"], "count": r["count"]} for r in rows]


def summarize(trace_dir=None, top=25, device_only=True):
    """Aggregate per-kernel wall time from a captured trace — the per-op
    stat table of the reference's engine profiler (src/engine/profiler.cc
    chrome-trace events), recovered from the XLA trace and MERGED with the
    framework telemetry spans.

    Returns a list of {"name", "ms", "count", "process"} dicts, heaviest
    first. ``device_only=False`` includes host-side python/runtime spans
    and the framework spans (framework seams are host work by definition).
    """
    import collections
    import glob
    import gzip
    import json
    import re

    d = trace_dir or _trace_dir
    out = []
    files = sorted(glob.glob(
        os.path.join(d or ".", "plugins", "profile", "*",
                     "*.trace.json.gz")))
    if files:
        raw = json.loads(gzip.open(files[-1]).read().decode())
        events = raw.get("traceEvents", [])
        pids = {e["pid"]: e["args"].get("name", "")
                for e in events if e.get("ph") == "M"
                and e.get("name") == "process_name"}
        acc = collections.Counter()
        cnt = collections.Counter()
        for e in events:
            if e.get("ph") != "X":
                continue
            proc = pids.get(e["pid"], str(e["pid"]))
            if device_only and "TPU" not in proc and "GPU" not in proc \
                    and "device" not in proc.lower():
                continue
            name = e.get("name", "?")
            # drop the whole-program umbrella spans and bare step-number
            # marks
            if name.startswith("jit_") or re.fullmatch(r"\d+", name):
                continue
            key = (proc, name)
            acc[key] += e.get("dur", 0)
            cnt[key] += 1
        out = [{"process": proc, "name": name,
                "ms": round(us / 1000.0, 3), "count": cnt[(proc, name)]}
               for (proc, name), us in acc.items()]
    if not device_only:
        out.extend(_framework_rows(trace_dir))
    out.sort(key=lambda r: -r["ms"])
    return out[:top]
