"""Attention operator (the Transformer building block).

The reference (2017 MXNet 0.9.5) predates Transformers; its README's stretch
config (BASELINE.md Transformer-base MT) needs one. Registered as a single
fused op rather than a symbol-level composition of batch_dot/softmax so XLA
sees the whole softmax(QKᵀ)V contraction at once — the same reasoning that
made the reference wrap cuDNN kernels as one op. The sequence-parallel
(ring) execution of this op lives in parallel/ring_attention.py.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .registry import AttrSpec, register

# trace-time dispatch counters (observability for tests and the multichip
# dryrun: proves the seq-parallel path actually engaged)
DISPATCH_COUNTS = {"ring": 0, "pallas": 0, "xla": 0}


@register(
    "_contrib_MultiHeadAttention",
    attrs={
        "causal": AttrSpec("bool", default=False),
        "scale": AttrSpec("float", default=-1.0),
    },
    input_names=("query", "key", "value"),
    aliases=("MultiHeadAttention",),
)
def _multi_head_attention(attrs, query, key, value):
    """softmax(QKᵀ·scale + mask)V over (B, H, T, D) tensors. Computation in
    fp32 for a stable softmax regardless of the IO dtype (bf16 fast path).
    ``MXNET_USE_PALLAS_ATTENTION=1`` routes to the hand-tiled flash kernel
    (ops/pallas_attention.py) on TPU when the shapes tile cleanly.

    Sequence parallelism: when traced inside an SPMD step whose mesh has a
    ``seq`` axis (parallel.make_mesh({"data": dp, "seq": sp})), self-attention
    dispatches to ring attention (parallel/ring_attention.py) — q stays put,
    k/v blocks rotate over ICI via ppermute, softmax accumulates online.
    Disable with MXNET_RING_ATTENTION=0."""
    import os

    mesh = None
    if os.environ.get("MXNET_RING_ATTENTION", "1") == "1":
        from ..parallel.mesh import current_trace_mesh

        mesh = current_trace_mesh()
    if (mesh is not None and "seq" in mesh.axis_names
            and mesh.shape["seq"] > 1):
        T = query.shape[2]
        batch_ok = ("data" not in mesh.axis_names
                    or query.shape[0] % mesh.shape["data"] == 0)
        if key.shape[2] == T and T % mesh.shape["seq"] == 0 and batch_ok:
            # self-attention with divisible shards only; else dense fallback
            from ..parallel.ring_attention import ring_attention

            DISPATCH_COUNTS["ring"] += 1
            out = ring_attention(
                query.transpose(0, 2, 1, 3), key.transpose(0, 2, 1, 3),
                value.transpose(0, 2, 1, 3), mesh, seq_axis="seq",
                causal=attrs["causal"],
                scale=attrs["scale"] if attrs["scale"] > 0 else None,
                batch_axis="data" if "data" in mesh.axis_names else None)
            return out.transpose(0, 2, 1, 3)

    if os.environ.get("MXNET_USE_PALLAS_ATTENTION", "0") == "1":
        from . import pallas_attention as pa

        if pa.supported(query.shape, key.shape, causal=attrs["causal"]):
            on_tpu = jax.default_backend() == "tpu"
            return pa.flash_attention(
                query, key, value, causal=attrs["causal"],
                scale=max(attrs["scale"], 0.0), interpret=not on_tpu)
    d = query.shape[-1]
    scale = attrs["scale"] if attrs["scale"] > 0 else 1.0 / np.sqrt(d)
    q = query.astype("float32")
    k = key.astype("float32")
    v = value.astype("float32")
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if attrs["causal"]:
        # bottom-right aligned so a rectangular (decode) call — T queries over
        # S >= T keys — lets each query see all S-T+q past keys
        T, S = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((T, S), bool), k=S - T)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return out.astype(query.dtype)
