"""Attention operator (the Transformer building block).

The reference (2017 MXNet 0.9.5) predates Transformers; its README's stretch
config (BASELINE.md Transformer-base MT) needs one. Registered as a single
fused op rather than a symbol-level composition of batch_dot/softmax so XLA
sees the whole softmax(QKᵀ)V contraction at once — the same reasoning that
made the reference wrap cuDNN kernels as one op. The sequence-parallel
(ring) execution of this op lives in parallel/ring_attention.py.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .registry import AttrSpec, register


@register(
    "_contrib_MultiHeadAttention",
    attrs={
        "causal": AttrSpec("bool", default=False),
        "scale": AttrSpec("float", default=-1.0),
    },
    input_names=("query", "key", "value"),
    aliases=("MultiHeadAttention",),
)
def _multi_head_attention(attrs, query, key, value):
    """softmax(QKᵀ·scale + mask)V over (B, H, T, D) tensors. Computation in
    fp32 for a stable softmax regardless of the IO dtype (bf16 fast path).
    ``MXNET_USE_PALLAS_ATTENTION=1`` routes to the hand-tiled flash kernel
    (ops/pallas_attention.py) on TPU when the shapes tile cleanly."""
    import os

    if os.environ.get("MXNET_USE_PALLAS_ATTENTION", "0") == "1":
        from . import pallas_attention as pa

        if pa.supported(query.shape, key.shape, causal=attrs["causal"]):
            on_tpu = jax.default_backend() == "tpu"
            return pa.flash_attention(
                query, key, value, causal=attrs["causal"],
                scale=max(attrs["scale"], 0.0), interpret=not on_tpu)
    d = query.shape[-1]
    scale = attrs["scale"] if attrs["scale"] > 0 else 1.0 / np.sqrt(d)
    q = query.astype("float32")
    k = key.astype("float32")
    v = value.astype("float32")
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if attrs["causal"]:
        # bottom-right aligned so a rectangular (decode) call — T queries over
        # S >= T keys — lets each query see all S-T+q past keys
        T, S = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((T, S), bool), k=S - T)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return out.astype(query.dtype)
