"""Sequence ops for padded RNN batches.

Covers the reference's src/operator/sequence_{last,mask,reverse}.{cc,cu}.
Data layout (max_seq_len, batch, ...) with optional per-sample
sequence_length vector, matching the reference's SequenceXxxParam.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import AttrSpec, register


def _seq_names(attrs):
    return ["data", "sequence_length"] if attrs.get("use_sequence_length") else ["data"]


_SEQ_ATTRS = lambda: {"use_sequence_length": AttrSpec("bool", default=False)}


@register("SequenceLast", attrs=_SEQ_ATTRS(), input_names=_seq_names)
def _sequence_last(attrs, data, sequence_length=None):
    if not attrs["use_sequence_length"] or sequence_length is None:
        return data[-1]
    idx = (sequence_length.astype(jnp.int32) - 1).clip(0, data.shape[0] - 1)
    return jnp.take_along_axis(
        data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0
    )[0]


@register(
    "SequenceMask",
    attrs={
        "use_sequence_length": AttrSpec("bool", default=False),
        "value": AttrSpec("float", default=0.0),
    },
    input_names=_seq_names,
)
def _sequence_mask(attrs, data, sequence_length=None):
    if not attrs["use_sequence_length"] or sequence_length is None:
        return data
    steps = jnp.arange(data.shape[0])
    mask = steps[:, None] < sequence_length.astype(jnp.int32)[None, :]
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, attrs["value"]).astype(data.dtype)


@register("SequenceReverse", attrs=_SEQ_ATTRS(), input_names=_seq_names)
def _sequence_reverse(attrs, data, sequence_length=None):
    if not attrs["use_sequence_length"] or sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    lengths = sequence_length.astype(jnp.int32)
    steps = jnp.arange(T)[:, None]
    src = jnp.where(steps < lengths[None, :], lengths[None, :] - 1 - steps, steps)
    src = src.reshape((T,) + lengths.shape + (1,) * (data.ndim - 2))
    return jnp.take_along_axis(data, jnp.broadcast_to(src, data.shape), axis=0)
