"""Broadcasting binary ops and reductions.

Covers the reference's src/operator/tensor/elemwise_binary_broadcast_op*.cc and
broadcast_reduce_op_{value,index}.{cc,cu} (registration macros at
broadcast_reduce_op.h:615-643). Reductions map to jnp reductions which XLA
lowers to tiled tree-reductions on the VPU — the hand-written
broadcast_reduce-inl.cuh kernels have no TPU analogue to write.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import AttrSpec, register

_B2 = ("lhs", "rhs")

_BCAST = {
    "broadcast_add": jnp.add,
    "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply,
    "broadcast_div": jnp.divide,
    "broadcast_mod": jnp.mod,
    "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
    "broadcast_equal": lambda a, b: (a == b).astype(a.dtype),
    "broadcast_not_equal": lambda a, b: (a != b).astype(a.dtype),
    "broadcast_greater": lambda a, b: (a > b).astype(a.dtype),
    "broadcast_greater_equal": lambda a, b: (a >= b).astype(a.dtype),
    "broadcast_lesser": lambda a, b: (a < b).astype(a.dtype),
    "broadcast_lesser_equal": lambda a, b: (a <= b).astype(a.dtype),
}
_BCAST_ALIASES = {
    "broadcast_add": ("broadcast_plus",),
    "broadcast_sub": ("broadcast_minus",),
}
for _name, _f in _BCAST.items():

    def _fn(attrs, lhs, rhs, _f=_f):
        return _f(lhs, rhs)

    register(_name, input_names=_B2, aliases=_BCAST_ALIASES.get(_name, ()))(_fn)


@register("broadcast_to", attrs={"shape": AttrSpec("shape", default=())})
def _broadcast_to(attrs, data):
    """Broadcast to target shape; 0 in shape keeps the input dim (reference:
    broadcast_reduce_op.h BroadcastTo)."""
    tgt = tuple(
        int(s) if int(s) != 0 else int(d) for s, d in zip(attrs["shape"], data.shape)
    )
    return jnp.broadcast_to(data, tgt)


@register(
    "broadcast_axis",
    attrs={"axis": AttrSpec("shape", default=()), "size": AttrSpec("shape", default=())},
    aliases=("broadcast_axes",),
)
def _broadcast_axis(attrs, data):
    tgt = list(data.shape)
    for ax, sz in zip(attrs["axis"], attrs["size"]):
        tgt[ax] = sz
    return jnp.broadcast_to(data, tuple(tgt))


def _norm_axis(axis, ndim):
    if axis is None or axis == ():
        return None
    if isinstance(axis, int):
        axis = (axis,)
    return tuple(a % ndim for a in axis)


_RED_ATTRS = lambda: {
    "axis": AttrSpec("shape", default=()),
    "keepdims": AttrSpec("bool", default=False),
    "exclude": AttrSpec("bool", default=False),
}


def _resolve_axis(attrs, ndim):
    ax = _norm_axis(attrs.get("axis", ()), ndim)
    if attrs.get("exclude"):
        ax = tuple(i for i in range(ndim) if ax is None or i not in ax)
    return ax


def _reg_reduce(name, f, aliases=()):
    def fn(attrs, data, _f=f):
        return _f(data, axis=_resolve_axis(attrs, data.ndim), keepdims=bool(attrs.get("keepdims", False)))

    fn.__doc__ = "Reduce-%s over the given axes (reference: broadcast_reduce_op_value.cc)." % name
    register(name, attrs=_RED_ATTRS(), aliases=aliases)(fn)


_reg_reduce("sum", jnp.sum, aliases=("sum_axis",))
_reg_reduce("mean", jnp.mean)
_reg_reduce("prod", jnp.prod)
_reg_reduce("nansum", jnp.nansum)
_reg_reduce("nanprod", jnp.nanprod)
_reg_reduce("max", jnp.max, aliases=("max_axis",))
_reg_reduce("min", jnp.min, aliases=("min_axis",))


@register("norm")
def _norm(attrs, data):
    """L2 norm of the whole array (reference: broadcast_reduce_op_value.cc norm)."""
    return jnp.sqrt(jnp.sum(jnp.square(data.astype(jnp.float32)))).astype(data.dtype)


def _argminmax(attrs, data, f):
    ax = attrs.get("axis", None)
    keepdims = bool(attrs.get("keepdims", False))
    if ax is None or ax == ():
        out = f(data.reshape(-1), axis=0)
        return out.astype(jnp.float32)
    ax = int(ax) if not isinstance(ax, tuple) else int(ax[0])
    out = f(data, axis=ax)
    if keepdims:
        out = jnp.expand_dims(out, ax)
    return out.astype(jnp.float32)


_ARG_ATTRS = lambda: {
    "axis": AttrSpec("any", default=None),
    "keepdims": AttrSpec("bool", default=False),
}


@register("argmax", attrs=_ARG_ATTRS())
def _argmax(attrs, data):
    return _argminmax(attrs, data, jnp.argmax)


@register("argmin", attrs=_ARG_ATTRS())
def _argmin(attrs, data):
    return _argminmax(attrs, data, jnp.argmin)


@register("argmax_channel")
def _argmax_channel(attrs, data):
    """argmax over axis 1 (reference: broadcast_reduce_op_index.cc)."""
    return jnp.argmax(data, axis=1).astype(jnp.float32)
