"""Elementwise, scalar, logic ops.

Covers the reference's src/operator/tensor/elemwise_binary_op_basic.cc (+_extended,
_logic), elemwise_unary_op.{h,cc}, elemwise_binary_scalar_op_*. Each op is a thin
pure-JAX function; XLA fuses chains of these into single kernels, which replaces
the reference's mshadow expression templates (elemwise_binary_op.h:18-33).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import AttrSpec, register

_B2 = ("lhs", "rhs")


def _reg_binary(name, f, aliases=()):
    def fn(attrs, lhs, rhs, _f=f):
        return _f(lhs, rhs)

    fn.__doc__ = "Elementwise %s (same-shape; see broadcast_%s for broadcasting)." % (name, name)
    register(name, input_names=_B2, aliases=aliases)(fn)


def _reg_unary(name, f, aliases=()):
    def fn(attrs, data, _f=f):
        return _f(data)

    fn.__doc__ = "Elementwise %s." % name
    register(name, aliases=aliases)(fn)


def _reg_scalar(name, f, aliases=()):
    specs = {"scalar": AttrSpec("float", required=True)}

    def fn(attrs, data, _f=f):
        return _f(data, jnp.asarray(attrs["scalar"], dtype=data.dtype))

    register(name, attrs=specs, aliases=aliases)(fn)


# --- binary (reference: elemwise_binary_op_basic.cc:11-78, _extended, _logic) ---
_gelu = None
_BINARY = {
    "elemwise_add": jnp.add,
    "elemwise_sub": jnp.subtract,
    "elemwise_mul": jnp.multiply,
    "elemwise_div": jnp.divide,
    "_grad_add": jnp.add,  # gradient accumulation add (reference :18)
    "_power": jnp.power,
    "_maximum": jnp.maximum,
    "_minimum": jnp.minimum,
    "_hypot": jnp.hypot,
    "_equal": lambda a, b: (a == b).astype(a.dtype),
    "_not_equal": lambda a, b: (a != b).astype(a.dtype),
    "_greater": lambda a, b: (a > b).astype(a.dtype),
    "_greater_equal": lambda a, b: (a >= b).astype(a.dtype),
    "_lesser": lambda a, b: (a < b).astype(a.dtype),
    "_lesser_equal": lambda a, b: (a <= b).astype(a.dtype),
    "_mod": jnp.mod,
}
_BINARY_ALIASES = {
    "elemwise_add": ("_add", "_plus", "_Plus"),
    "elemwise_sub": ("_sub", "_minus", "_Minus"),
    "elemwise_mul": ("_mul", "_Mul"),
    "elemwise_div": ("_div", "_Div"),
    "_power": ("_Power", "_pow"),
    "_maximum": ("_Maximum",),
    "_minimum": ("_Minimum",),
    "_hypot": ("_Hypot",),
    "_equal": ("_Equal", "_eq"),
    "_not_equal": ("_Not_Equal", "_ne"),
    "_greater": ("_Greater", "_gt"),
    "_greater_equal": ("_Greater_Equal", "_ge"),
    "_lesser": ("_Lesser", "_lt"),
    "_lesser_equal": ("_Lesser_Equal", "_le"),
    "_mod": ("_Mod",),
}
for _n, _f in _BINARY.items():
    _reg_binary(_n, _f, aliases=_BINARY_ALIASES.get(_n, ()))


# --- unary (reference: elemwise_unary_op.cc, ~39 ops) -------------------------
def _softrelu(x):
    return jnp.logaddexp(x, 0.0)


_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "round": jnp.round,
    "rint": jnp.rint,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "fix": jnp.trunc,
    "trunc": jnp.trunc,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: jax.lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "negative": jnp.negative,
    "reciprocal": lambda x: 1.0 / x,
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "softsign": lambda x: x / (1 + jnp.abs(x)),
    "gamma": lambda x: jnp.exp(jax.lax.lgamma(x)),
    "gammaln": lambda x: jax.lax.lgamma(x),
    "erf": jax.lax.erf,
    "logical_not": lambda x: (x == 0).astype(x.dtype),
}
for _n, _f in _UNARY.items():
    _reg_unary(_n, _f)

register("_copy", aliases=("identity", "_identity_with_attr_like_rhs"))(
    lambda attrs, data, *rest: data
)
register("BlockGrad", aliases=("stop_gradient", "make_no_grad"))(
    lambda attrs, data: jax.lax.stop_gradient(data)
)
@register("_CrossDeviceCopy", aliases=("_copyto",), attrs={"__target_ctx__": AttrSpec("str", default="")})
def _cross_device_copy(attrs, data):
    """Move data to another device (reference: src/operator/cross_device_copy.cc,
    executed as CopyFromTo by the executor). Inside a traced graph this lowers
    to an XLA transfer annotation when the executor stamps ``__target_ctx__``
    (the PlaceDevice pass analogue); with no target it is the identity copy,
    matching ``_copyto`` on one device."""
    target = attrs.get("__target_ctx__") or ""
    if target:
        import jax

        from ..context import Context

        name, _, idx = target.partition(":")
        dev = Context(name, int(idx or 0)).jax_device
        return jax.device_put(data, dev)
    return data


@register("Cast", attrs={"dtype": AttrSpec("dtype", required=True)}, aliases=("cast",))
def _cast(attrs, data):
    """Cast to a new dtype (reference: elemwise_unary_op.cc Cast)."""
    return data.astype(attrs["dtype"])


@register(
    "clip",
    attrs={"a_min": AttrSpec("float", required=True), "a_max": AttrSpec("float", required=True)},
)
def _clip(attrs, data):
    return jnp.clip(data, attrs["a_min"], attrs["a_max"])


# --- scalar ops (reference: elemwise_binary_scalar_op_*.cc) -------------------
_SCALAR = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_power_scalar": lambda x, s: jnp.power(x, s),
    "_rpower_scalar": lambda x, s: jnp.power(s, x),
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
    "_hypot_scalar": lambda x, s: jnp.hypot(x, s),
    "_mod_scalar": lambda x, s: jnp.mod(x, s),
    "_rmod_scalar": lambda x, s: jnp.mod(s, x),
    "_equal_scalar": lambda x, s: (x == s).astype(x.dtype),
    "_not_equal_scalar": lambda x, s: (x != s).astype(x.dtype),
    "_greater_scalar": lambda x, s: (x > s).astype(x.dtype),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(x.dtype),
    "_lesser_scalar": lambda x, s: (x < s).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(x.dtype),
}
_SCALAR_ALIASES = {
    "_plus_scalar": ("_PlusScalar",),
    "_minus_scalar": ("_MinusScalar",),
    "_rminus_scalar": ("_RMinusScalar",),
    "_mul_scalar": ("_MulScalar",),
    "_div_scalar": ("_DivScalar",),
    "_rdiv_scalar": ("_RDivScalar",),
    "_power_scalar": ("_PowerScalar",),
    "_rpower_scalar": ("_RPowerScalar",),
    "_maximum_scalar": ("_MaximumScalar",),
    "_minimum_scalar": ("_MinimumScalar",),
    "_hypot_scalar": ("_HypotScalar",),
    "_equal_scalar": ("_EqualScalar",),
    "_not_equal_scalar": ("_NotEqualScalar",),
    "_greater_scalar": ("_GreaterScalar",),
    "_greater_equal_scalar": ("_GreaterEqualScalar",),
    "_lesser_scalar": ("_LesserScalar",),
    "_lesser_equal_scalar": ("_LesserEqualScalar",),
}
for _n, _f in _SCALAR.items():
    _reg_scalar(_n, _f, aliases=_SCALAR_ALIASES.get(_n, ()))


@register(
    "smooth_l1",
    attrs={"scalar": AttrSpec("float", default=1.0)},
)
def _smooth_l1(attrs, data):
    """Smooth L1 (reference: elemwise_binary_scalar_op_extended.cc smooth_l1)."""
    s2 = attrs["scalar"] ** 2
    a = jnp.abs(data)
    return jnp.where(a < 1.0 / s2, 0.5 * s2 * jnp.square(data), a - 0.5 / s2)


def _n_args_names(attrs):
    n = int(attrs.get("num_args", 1))
    return ["arg%d" % i for i in range(n)]


@register(
    "add_n",
    attrs={"num_args": AttrSpec("int", required=True)},
    input_names=_n_args_names,
    aliases=("ElementWiseSum", "_sum"),
)
def _add_n(attrs, *args):
    """Sum of N arrays (reference: ElementwiseSum, src/ndarray/ndarray.cc:302)."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out
