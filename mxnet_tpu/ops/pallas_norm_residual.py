"""LayerNorm + affine as a differentiable Pallas TPU kernel.

The ``norm_residual`` fusion pattern's kernel lowering: the transformer
zoo's 9-op LayerNorm composition (mean → center → var → rsqrt → scale →
shift) reads its input from HBM three times and writes two normalized
intermediates under XLA; this kernel does the whole normalization on one
resident (block_rows, D) tile in VMEM — one read of x, one write of y.
The per-row moments (mean, rstd) are emitted as tiny (R, 1) side outputs
so the backward re-derives x̂ without re-reducing.

Backward is a second Pallas kernel over the same row tiling: rows are
independent, so every grid step computes its block's dx in VMEM and emits
per-block partial dgamma/dbeta rows ((n_blocks, D), summed by XLA — a
cheap (n_blocks, D) reduction instead of a serialized accumulator, keeping
the grid fully parallel).

Layout: x flattened to (R, D) rows; gamma/beta (D,). ``supported`` gates
on the TPU tiling constraints (D lane-aligned, row blocks sublane-aligned);
``block_candidates`` enumerates the bounded schedule space the autotuner
measures (docs/PERF.md §15). Runs anywhere under Pallas interpret mode,
which is how the CPU tests exercise it.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["layer_norm_affine", "supported", "choose_block_rows",
           "block_candidates"]

_ROW_BLOCKS = (256, 128, 64, 32, 16, 8)
_VMEM_BUDGET = 12 * 1024 * 1024


def _rows_of(shape):
    r = 1
    for d in shape[:-1]:
        r *= int(d)
    return r


def choose_block_rows(shape, itemsize=4):
    """The planner-default row-block height: the largest sublane-aligned
    divisor of R whose (br, D) working set (x, y, f32 temps) fits VMEM.
    None when nothing tiles (callers fall back to XLA)."""
    cands = block_candidates(shape, itemsize)
    return cands[0] if cands else None


def block_candidates(shape, itemsize=4):
    """Every valid row-block height for this shape, largest first — the
    bounded schedule space ``fusion_tune`` measures (the head of the list
    is the default candidate)."""
    if len(shape) < 2:
        return []
    R, D = _rows_of(shape), int(shape[-1])
    if D % 128 or R < 8:
        return []
    out = []
    for br in _ROW_BLOCKS:
        if R % br:
            continue
        # x tile + y tile (io dtype, double-buffered) + f32 working copy
        est = 2 * 2 * br * D * itemsize + br * D * 4 + 2 * D * 4
        if est <= _VMEM_BUDGET:
            out.append(br)
    return out


def supported(shape, itemsize=4):
    """Whether this input tiles onto the kernel grid at all."""
    return bool(block_candidates(shape, itemsize))


# --------------------------------------------------------------------- forward
def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)                # (br, D)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    cent = x - mean
    var = jnp.mean(cent * cent, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = cent * rstd
    g = g_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)
    y_ref[...] = (xhat * g + b).astype(y_ref.dtype)
    mean_ref[...] = mean
    rstd_ref[...] = rstd


def _bwd_kernel(x_ref, g_ref, mean_ref, rstd_ref, dy_ref, dx_ref,
                dg_ref, db_ref):
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    mean, rstd = mean_ref[...], rstd_ref[...]
    xhat = (x - mean) * rstd
    g = g_ref[...].astype(jnp.float32)
    # per-block partial parameter grads: one (1, D) row per grid step
    dg_ref[...] = jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[...] = jnp.sum(dy, axis=0, keepdims=True)
    dxhat = dy * g
    m1 = jnp.mean(dxhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (dxhat - m1 - xhat * m2)).astype(dx_ref.dtype)


def _compiler_params(interpret):
    if interpret:
        return None
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.CompilerParams(
        dimension_semantics=(pltpu.GridDimensionSemantics.PARALLEL,))


def _fwd_call(x2, gamma, beta, eps, br, interpret):
    from jax.experimental import pallas as pl

    R, D = x2.shape
    return pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(R // br,),
        in_specs=[
            pl.BlockSpec((br, D), lambda i: (i, 0)),
            pl.BlockSpec((1, D), lambda i: (0, 0)),
            pl.BlockSpec((1, D), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, D), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, D), x2.dtype),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(x2, gamma.reshape(1, D), beta.reshape(1, D))


def _bwd_call(x2, gamma, mean, rstd, dy2, br, interpret):
    from jax.experimental import pallas as pl

    R, D = x2.shape
    nb = R // br
    dx, dg_part, db_part = pl.pallas_call(
        _bwd_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((br, D), lambda i: (i, 0)),
            pl.BlockSpec((1, D), lambda i: (0, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, D), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, D), lambda i: (i, 0)),
            pl.BlockSpec((1, D), lambda i: (i, 0)),
            pl.BlockSpec((1, D), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, D), x2.dtype),
            jax.ShapeDtypeStruct((nb, D), jnp.float32),
            jax.ShapeDtypeStruct((nb, D), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(x2, gamma.reshape(1, D), mean, rstd, dy2)
    return dx, jnp.sum(dg_part, axis=0), jnp.sum(db_part, axis=0)


# ------------------------------------------------------------------ custom vjp
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ln(x2, gamma, beta, eps, br, interpret):
    return _fwd_call(x2, gamma, beta, eps, br, interpret)[0]


def _ln_fwd(x2, gamma, beta, eps, br, interpret):
    y, mean, rstd = _fwd_call(x2, gamma, beta, eps, br, interpret)
    return y, (x2, gamma, mean, rstd)


def _ln_bwd(eps, br, interpret, res, dy):
    x2, gamma, mean, rstd = res
    dx, dg, db = _bwd_call(x2, gamma, mean, rstd, dy, br, interpret)
    return dx, dg.astype(gamma.dtype), db.astype(gamma.dtype)


_ln.defvjp(_ln_fwd, _ln_bwd)


def _interpret_mode():
    return jax.default_backend() != "tpu"


def layer_norm_affine(x, gamma, beta, eps=1e-5, block_rows=None,
                      interpret=None):
    """``(x − E[x]) · rsqrt(Var[x] + eps) · gamma + beta`` over the last
    axis, one VMEM-resident tile per row block. Differentiable
    (custom_vjp Pallas backward). Callers gate with ``supported()``;
    ``block_rows`` overrides the planner default (the autotuner's schedule
    axis)."""
    shape = x.shape
    D = int(shape[-1])
    if interpret is None:
        interpret = _interpret_mode()
    br = block_rows if block_rows is not None else choose_block_rows(
        shape, jnp.dtype(x.dtype).itemsize)
    if br is None or br not in block_candidates(
            shape, jnp.dtype(x.dtype).itemsize):
        raise ValueError("layer_norm_affine: shape %s does not tile at "
                         "block_rows=%r (gate with supported())"
                         % (shape, block_rows))
    y = _ln(x.reshape(-1, D), gamma, beta, float(eps), int(br),
            bool(interpret))
    return y.reshape(shape)
