"""CTC loss (reference: plugin/warpctc/warpctc-inl.h — the one plugin with
real model coverage: speech/OCR).

API parity with the reference's ``WarpCTC`` operator:

- ``data``: ``(input_length * batch, alphabet)`` time-major activations
  (row ``t*B + b``), exactly the FC output the OCR examples feed it.
- ``label``: ``(batch, label_length)`` ints, padded with the blank (0 — the
  warp-ctc convention, warpctc-inl.h labelLengths/removeBlank strip 0s).
- forward output is ``softmax(data)`` (warpctc-inl.h Forward), and backward
  IGNORES the incoming head gradient and emits the CTC gradient — the
  loss-layer contract shared with SoftmaxOutput.

The TPU-native formulation: instead of an external C library, the forward
log-likelihood is a log-space alpha recursion over ``lax.scan`` (one fused
step per frame, all batch rows in parallel), and the backward pass IS
``jax.grad`` of that recursion — which mathematically equals the classic
softmax-minus-occupancy CTC gradient, with no hand-maintained beta pass.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .registry import AttrSpec, register

_NEG = -1e30  # -inf stand-in that keeps logsumexp autodiff NaN-free


def _compact_labels(label, blank):
    """Left-align non-blank entries per row (reference removeBlank):
    [3,0,2,0] → [3,2,0,0], plus per-row true lengths."""
    is_pad = (label == blank)
    # stable argsort of the pad mask moves non-blanks to the front while
    # preserving their order
    order = jnp.argsort(is_pad.astype(jnp.int32), axis=1, stable=True)
    compact = jnp.take_along_axis(label, order, axis=1)
    lengths = jnp.sum(~is_pad, axis=1)
    return compact, lengths


def ctc_nll(log_probs, label, label_lengths, blank=0):
    """Per-sample negative log-likelihood.

    log_probs: (T, B, C) log-softmax scores; label: (B, L) compacted
    (non-blank first); label_lengths: (B,) true lengths.
    """
    T, B, C = log_probs.shape
    L = label.shape[1]
    S = 2 * L + 1

    s_idx = jnp.arange(S)
    # extended sequence: blank at even s, label[(s-1)//2] at odd s
    lab_at = jnp.where(s_idx % 2 == 1,
                       label[:, jnp.minimum((s_idx - 1) // 2, L - 1)],
                       blank)  # (B, S)
    # a skip s-2 → s is legal when ext[s] is a non-blank differing from ext[s-2]
    prev2 = jnp.concatenate([jnp.full((B, 2), -1, lab_at.dtype),
                             lab_at[:, :-2]], axis=1)
    can_skip = (lab_at != blank) & (lab_at != prev2)  # (B, S)
    # states beyond 2*len+1 are unreachable
    valid = s_idx[None, :] < (2 * label_lengths[:, None] + 1)

    alpha0 = jnp.full((B, S), _NEG)
    alpha0 = alpha0.at[:, 0].set(log_probs[0, :, blank])
    has1 = label_lengths > 0
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(has1, jnp.take_along_axis(
            log_probs[0], label[:, :1], axis=1)[:, 0], _NEG))

    def step(alpha, lp_t):
        em = jnp.take_along_axis(lp_t, lab_at, axis=1)
        stay = alpha
        diag = jnp.concatenate([jnp.full((B, 1), _NEG), alpha[:, :-1]], axis=1)
        skip = jnp.concatenate([jnp.full((B, 2), _NEG), alpha[:, :-2]], axis=1)
        skip = jnp.where(can_skip, skip, _NEG)
        stacked = jnp.stack([stay, diag, skip], axis=0)
        merged = jax.scipy.special.logsumexp(stacked, axis=0)
        new = jnp.where(valid, merged + em, _NEG)
        return new, None

    alpha_T, _ = jax.lax.scan(step, alpha0, log_probs[1:])
    # accept states: 2*len (final blank) and 2*len-1 (last symbol)
    endb = jnp.take_along_axis(alpha_T, (2 * label_lengths)[:, None], axis=1)[:, 0]
    ends = jnp.take_along_axis(
        alpha_T, jnp.maximum(2 * label_lengths - 1, 0)[:, None], axis=1)[:, 0]
    ends = jnp.where(label_lengths > 0, ends, _NEG)
    ll = jnp.logaddexp(endb, ends)
    return -ll


@functools.lru_cache(maxsize=None)
def _warpctc_core(input_length, blank):
    """custom_vjp closure: fwd = softmax scores, bwd = CTC gradient (head
    gradient ignored, per the reference loss-layer contract)."""

    def total_nll(data2d, label):
        B = data2d.shape[0] // input_length
        logits = data2d.reshape(input_length, B, data2d.shape[1])
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        lab = label.astype(jnp.int32).reshape(B, -1)
        compact, lengths = _compact_labels(lab, blank)
        nll = ctc_nll(lp, compact, lengths, blank)
        # infeasible samples (label needs more frames than input_length —
        # adjacent repeats require a mandatory blank between them) contribute
        # zero loss AND zero gradient, matching warp-ctc's behavior; without
        # this the all-_NEG accept states would backprop garbage occupancies
        repeats = jnp.sum(
            (compact[:, 1:] == compact[:, :-1])
            & (jnp.arange(1, compact.shape[1])[None, :] < lengths[:, None]),
            axis=1)
        feasible = (lengths + repeats) <= input_length
        return jnp.sum(jnp.where(feasible, nll, 0.0))

    @jax.custom_vjp
    def warpctc(data2d, label):
        return jax.nn.softmax(data2d, axis=-1)

    def fwd(data2d, label):
        return warpctc(data2d, label), (data2d, label)

    def bwd(res, _head_grad):
        data2d, label = res
        g = jax.grad(total_nll)(data2d, label)
        return g.astype(data2d.dtype), jnp.zeros_like(label)

    warpctc.defvjp(fwd, bwd)
    return warpctc


@register(
    "WarpCTC",
    attrs={
        "label_length": AttrSpec("int", default=0),
        "input_length": AttrSpec("int", default=0),
    },
    input_names=("data", "label"),
)
def _warpctc(attrs, data, label):
    T = int(attrs["input_length"])
    if T <= 0:
        raise ValueError("WarpCTC requires input_length > 0")
    if data.ndim != 2:
        data = data.reshape(-1, data.shape[-1])
    return _warpctc_core(T, 0)(data, label)
