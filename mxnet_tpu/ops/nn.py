"""Neural-network layer ops.

Covers the reference's legacy OperatorProperty layers (src/operator/
{fully_connected,convolution,deconvolution,batch_norm,pooling,activation,
dropout,softmax_output,leaky_relu,lrn,instance_norm,l2_normalization,
upsampling,make_loss,regression_output,svm_output}.*). There are no cuDNN
wrappers to reproduce (src/operator/cudnn_*): conv/pool/BN lower to
lax.conv_general_dilated / lax.reduce_window and XLA fuses the rest — the
TPU-native answer to vendor kernels (SURVEY.md §7 translation table).

Loss layers reproduce the reference's backward contract — they IGNORE the
incoming head gradient and emit their own (softmax_output-inl.h Backward) —
via jax.custom_vjp.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

import functools

from ..base import MXNetError
from .registry import AttrSpec, register


# --- FullyConnected (reference: fully_connected.cc:60, -inl.h) ----------------
def _fc_names(attrs):
    return ["data", "weight"] if attrs.get("no_bias") else ["data", "weight", "bias"]


@register(
    "FullyConnected",
    attrs={
        "num_hidden": AttrSpec("int", required=True),
        "no_bias": AttrSpec("bool", default=False),
        "flatten": AttrSpec("bool", default=True),
    },
    input_names=_fc_names,
)
def _fully_connected(attrs, data, weight, bias=None):
    """y = x · Wᵀ + b. Batched 2D matmul → single MXU op. With flatten=False
    the matmul applies over the last axis, keeping leading axes (the later
    reference semantics the attr advertises)."""
    if attrs.get("flatten", True):
        x = data.reshape((data.shape[0], -1)) if data.ndim != 2 else data
        y = jnp.dot(x, weight.T)
    else:
        y = jnp.einsum("...i,oi->...o", data, weight)
    if bias is not None:
        y = y + bias
    return y


# --- Convolution (reference: convolution.cc:81, -inl.h) -----------------------
_CONV_ATTRS = lambda: {
    "kernel": AttrSpec("shape", required=True),
    "stride": AttrSpec("shape", default=()),
    "dilate": AttrSpec("shape", default=()),
    "pad": AttrSpec("shape", default=()),
    "num_filter": AttrSpec("int", required=True),
    "num_group": AttrSpec("int", default=1),
    "workspace": AttrSpec("int", default=1024),
    "no_bias": AttrSpec("bool", default=False),
    "cudnn_tune": AttrSpec("str", default=None),
    "cudnn_off": AttrSpec("bool", default=False),
    "layout": AttrSpec("str", default=None),
    "target_shape": AttrSpec("shape", default=()),
    "adj": AttrSpec("shape", default=()),
}


def _conv_dnums(nd):
    # NC + spatial, OI + spatial — the reference's NCHW/NCDHW layouts.
    sp = "DHW"[3 - nd :]
    return ("NC" + sp, "OI" + sp, "NC" + sp)


def _spatial(attrs, key, nd, fill):
    v = attrs.get(key) or ()
    return tuple(v) if len(v) == nd else (fill,) * nd


@register("Convolution", attrs=_CONV_ATTRS(), input_names=_fc_names, aliases=("Convolution_v1",))
def _convolution(attrs, data, weight, bias=None):
    nd = len(attrs["kernel"])
    stride = _spatial(attrs, "stride", nd, 1)
    dilate = _spatial(attrs, "dilate", nd, 1)
    pad = _spatial(attrs, "pad", nd, 0)
    out = jax.lax.conv_general_dilated(
        data,
        weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=_conv_dnums(nd),
        feature_group_count=attrs["num_group"],
    )
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


@register("Deconvolution", attrs=_CONV_ATTRS(), input_names=_fc_names)
def _deconvolution(attrs, data, weight, bias=None):
    """Transposed convolution = conv with lhs dilation (reference:
    deconvolution-inl.h). Weight layout (C_in, num_filter/g, *kernel)."""
    nd = len(attrs["kernel"])
    stride = _spatial(attrs, "stride", nd, 1)
    pad = _spatial(attrs, "pad", nd, 0)
    adj = _spatial(attrs, "adj", nd, 0)
    kernel = attrs["kernel"]
    # flip spatial dims and swap I/O to express deconv as a dilated conv
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    g = attrs["num_group"]
    if g > 1:
        cin = w.shape[0]
        w = w.reshape((g, cin // g) + w.shape[1:])
        w = jnp.swapaxes(w, 1, 2).reshape((w.shape[2] * g, cin // g) + w.shape[3:])
    else:
        w = jnp.swapaxes(w, 0, 1)
    pads = [
        (kernel[i] - 1 - pad[i], kernel[i] - 1 - pad[i] + adj[i]) for i in range(nd)
    ]
    out = jax.lax.conv_general_dilated(
        data,
        w,
        window_strides=(1,) * nd,
        padding=pads,
        lhs_dilation=stride,
        dimension_numbers=_conv_dnums(nd),
        feature_group_count=g,
    )
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


# --- Pooling (reference: pooling.cc, pool.h) ----------------------------------
@register(
    "Pooling",
    attrs={
        "kernel": AttrSpec("shape", required=True),
        "pool_type": AttrSpec("str", default="max"),
        "global_pool": AttrSpec("bool", default=False),
        "stride": AttrSpec("shape", default=()),
        "pad": AttrSpec("shape", default=()),
        "pooling_convention": AttrSpec("str", default="valid"),
        "cudnn_off": AttrSpec("bool", default=False),
    },
    aliases=("Pooling_v1",),
)
def _pooling(attrs, data):
    nd = data.ndim - 2
    if attrs["global_pool"]:
        kernel = data.shape[2:]
        stride = (1,) * nd
        pad = (0,) * nd
    else:
        kernel = tuple(attrs["kernel"])
        stride = _spatial(attrs, "stride", nd, 1)
        pad = _spatial(attrs, "pad", nd, 0)
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    if attrs["pooling_convention"] == "full":
        # ceil-mode output: pad high edge enough to cover the last window
        pads = [(0, 0), (0, 0)]
        for i in range(nd):
            in_sz = data.shape[2 + i] + 2 * pad[i]
            out_sz = -(-(in_sz - kernel[i]) // stride[i]) + 1
            needed = (out_sz - 1) * stride[i] + kernel[i] - in_sz
            pads.append((pad[i], pad[i] + max(needed, 0)))
    else:
        pads = [(0, 0), (0, 0)] + [(p, p) for p in pad]
    pt = attrs["pool_type"]
    if pt == "max":
        init = -jnp.inf
        out = jax.lax.reduce_window(data, init, jax.lax.max, window, strides, pads)
    elif pt in ("avg", "sum"):
        out = jax.lax.reduce_window(data, 0.0, jax.lax.add, window, strides, pads)
        if pt == "avg":
            out = out / np.prod(kernel)  # count-include-pad, as mshadow pool does
    else:
        raise MXNetError("unknown pool_type %r" % pt)
    return out


# --- Activations --------------------------------------------------------------
@register("Activation", attrs={"act_type": AttrSpec("str", required=True)})
def _activation(attrs, data):
    """(reference: activation.cc) act_type ∈ relu|sigmoid|tanh|softrelu."""
    t = attrs["act_type"]
    if t == "relu":
        return jnp.maximum(data, 0)
    if t == "sigmoid":
        return jax.nn.sigmoid(data)
    if t == "tanh":
        return jnp.tanh(data)
    if t == "softrelu":
        return jnp.logaddexp(data, 0.0)
    raise MXNetError("unknown act_type %r" % t)


def _lrelu_names(attrs):
    return ["data", "gamma"] if attrs.get("act_type") == "prelu" else ["data"]


@register(
    "LeakyReLU",
    attrs={
        "act_type": AttrSpec("str", default="leaky"),
        "slope": AttrSpec("float", default=0.25),
        "lower_bound": AttrSpec("float", default=0.125),
        "upper_bound": AttrSpec("float", default=0.334),
    },
    input_names=_lrelu_names,
    needs_rng=True,
    needs_train_flag=True,
)
def _leaky_relu(attrs, data, gamma=None, is_train=False, rng=None):
    """(reference: leaky_relu.cc) leaky|prelu|elu|rrelu."""
    t = attrs["act_type"]
    if t == "leaky":
        return jnp.where(data >= 0, data, attrs["slope"] * data)
    if t == "elu":
        return jnp.where(data >= 0, data, attrs["slope"] * jnp.expm1(data))
    if t == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) if data.ndim > 2 else gamma
        return jnp.where(data >= 0, data, g * data)
    if t == "rrelu":
        if is_train and rng is not None:
            slope = jax.random.uniform(
                rng, data.shape, minval=attrs["lower_bound"], maxval=attrs["upper_bound"], dtype=data.dtype
            )
        else:
            slope = (attrs["lower_bound"] + attrs["upper_bound"]) / 2.0
        return jnp.where(data >= 0, data, slope * data)
    raise MXNetError("unknown act_type %r" % t)


@register(
    "Dropout",
    attrs={"p": AttrSpec("float", default=0.5)},
    needs_rng=True,
    needs_train_flag=True,
)
def _dropout(attrs, data, is_train=False, rng=None):
    """Inverted dropout (reference: dropout-inl.h); identity at inference."""
    p = attrs["p"]
    if not is_train or p <= 0.0 or rng is None:
        return data
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, data.shape)
    return jnp.where(mask, data / keep, 0.0).astype(data.dtype)


@register(
    "softmax",
    attrs={"axis": AttrSpec("int", default=-1), "temperature": AttrSpec("any", default=None)},
)
def _softmax(attrs, data):
    t = attrs.get("temperature")
    if t not in (None, "None"):
        data = data / float(t)
    return jax.nn.softmax(data, axis=attrs["axis"])


@register("log_softmax", attrs={"axis": AttrSpec("int", default=-1)})
def _log_softmax(attrs, data):
    return jax.nn.log_softmax(data, axis=attrs["axis"])


@register(
    "SoftmaxActivation",
    attrs={"mode": AttrSpec("str", default="instance")},
)
def _softmax_activation(attrs, data):
    """(reference: softmax_activation.cc) instance → over trailing dims of each
    sample; channel → over axis 1."""
    if attrs["mode"] == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


# --- BatchNorm (reference: batch_norm.cc:38, -inl.h) --------------------------
def _bn_outputs(attrs):
    return 3 if attrs.get("output_mean_var") else 1


@functools.lru_cache(maxsize=None)
def _bn_train_core(ndim, eps, fix_gamma):
    """Hand-derived BN fwd/bwd as a custom_vjp.

    Why not plain autodiff: differentiating through the fp32 stats view of the
    activation makes XLA materialise fp32 cotangents of every BN input in the
    backward pass — on a ResNet-50/224 b256 step that was ~28% of device time
    in `multiply_reduce`/`add_any` fusions (see docs/PERF.md, round-4 profile).
    Here every elementwise pass stays in the activation dtype (bf16 on the MXU
    fast path) and fp32 appears only inside reduction accumulators — the
    canonical memory-bound-TPU formulation. Math matches the reference's
    batch_norm-inl.h Forward/Backward (biased batch variance, dgamma=0 under
    fix_gamma).
    """
    axes = (0,) + tuple(range(2, ndim))

    def stats(x):
        # one fused pass: sum(x) and sum(x^2) with fp32 accumulators
        cnt = 1
        for a in axes:
            cnt *= x.shape[a]
        x32 = x.astype(jnp.float32)
        mean = jnp.sum(x32, axis=axes) / cnt
        var = jnp.sum(jnp.square(x32), axis=axes) / cnt - jnp.square(mean)
        return mean, var

    def fwd_impl(x, gamma, beta):
        bshape = (1, -1) + (1,) * (ndim - 2)
        mean, var = stats(x)
        invstd = jax.lax.rsqrt(var + eps)
        m = mean.astype(x.dtype)
        istd = invstd.astype(x.dtype)
        xhat = (x - m.reshape(bshape)) * istd.reshape(bshape)
        if fix_gamma:
            out = xhat + beta.reshape(bshape)
        else:
            out = xhat * gamma.reshape(bshape) + beta.reshape(bshape)
        return out, mean, var, m, istd

    @jax.custom_vjp
    def bn(x, gamma, beta):
        out, mean, var, _, _ = fwd_impl(x, gamma, beta)
        return out, mean, var

    def bn_fwd(x, gamma, beta):
        out, mean, var, m, istd = fwd_impl(x, gamma, beta)
        return (out, mean, var), (x, gamma, m, istd)

    def bn_bwd(res, cts):
        dy, ct_mean, ct_var = cts
        x, gamma, m, istd = res
        bshape = (1, -1) + (1,) * (ndim - 2)
        cnt = 1
        for a in axes:
            cnt *= x.shape[a]
        xhat = (x - m.reshape(bshape)) * istd.reshape(bshape)
        # both reductions in one fused pass, fp32 accumulators
        dbeta32 = jnp.sum(dy.astype(jnp.float32), axis=axes)
        dgamma32 = jnp.sum((dy * xhat).astype(jnp.float32), axis=axes)
        g_istd = (istd if fix_gamma else gamma * istd).astype(x.dtype)
        c1 = (dbeta32 / cnt).astype(x.dtype)
        c2 = (dgamma32 / cnt).astype(x.dtype)
        dx = g_istd.reshape(bshape) * (dy - c1.reshape(bshape) - xhat * c2.reshape(bshape))
        # graphs may differentiate through the mean/var heads too
        # (output_mean_var=True): mean = Σx/n, var = Σx²/n − mean². The terms
        # are per-channel scalars broadcast into the dx pass — they fuse, so
        # the usual zero-cotangent case costs nothing extra in HBM traffic.
        dx = dx + (ct_mean / cnt).astype(x.dtype).reshape(bshape)
        cv = (2.0 * ct_var / cnt).astype(x.dtype).reshape(bshape)
        dx = dx + cv * (x - m.reshape(bshape))
        dgamma = (jnp.zeros_like(dgamma32) if fix_gamma else dgamma32).astype(gamma.dtype)
        return dx, dgamma, dbeta32.astype(gamma.dtype)

    bn.defvjp(bn_fwd, bn_bwd)
    return bn


@register(
    "BatchNorm",
    attrs={
        "eps": AttrSpec("float", default=1e-3),
        "momentum": AttrSpec("float", default=0.9),
        "fix_gamma": AttrSpec("bool", default=True),
        "use_global_stats": AttrSpec("bool", default=False),
        "output_mean_var": AttrSpec("bool", default=False),
    },
    input_names=("data", "gamma", "beta"),
    aux_names=("moving_mean", "moving_var"),
    num_outputs=_bn_outputs,
    output_names=lambda a: ["output", "mean", "var"][: _bn_outputs(a)],
    needs_train_flag=True,
)
def _batch_norm(attrs, inputs, aux, is_train=False):
    """Channel-axis-1 batch norm with moving-stat aux state. The reference
    mutates aux in-place via FMutateInputs; here new aux values are returned
    as functional carries and threaded by the executor (SURVEY.md §7 hard
    parts: "Mutable aux states")."""
    data, gamma, beta = inputs
    moving_mean, moving_var = aux
    eps, momentum = attrs["eps"], attrs["momentum"]
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    if is_train and not attrs["use_global_stats"]:
        bn = _bn_train_core(data.ndim, float(eps), bool(attrs["fix_gamma"]))
        out, mean, var = bn(data, gamma, beta)
        new_mean = moving_mean * momentum + jax.lax.stop_gradient(mean) * (1 - momentum)
        new_var = moving_var * momentum + jax.lax.stop_gradient(var) * (1 - momentum)
        m, v = mean.astype(data.dtype), var.astype(data.dtype)
        outs = (out, m, v) if attrs["output_mean_var"] else (out,)
        return outs, (new_mean, new_var)
    if attrs["fix_gamma"]:
        gamma = jax.lax.stop_gradient(jnp.ones_like(gamma))
    m, v = moving_mean, moving_var
    out = (data - m.reshape(bshape)) * jax.lax.rsqrt(v.reshape(bshape) + eps)
    out = out * gamma.reshape(bshape) + beta.reshape(bshape)
    outs = (out, m, v) if attrs["output_mean_var"] else (out,)
    return outs, (moving_mean, moving_var)


# --- Loss/output layers (custom-vjp: ignore head gradient) --------------------
_SM_ATTRS = lambda: {
    "grad_scale": AttrSpec("float", default=1.0),
    "ignore_label": AttrSpec("float", default=-1.0),
    "multi_output": AttrSpec("bool", default=False),
    "use_ignore": AttrSpec("bool", default=False),
    "preserve_shape": AttrSpec("bool", default=False),
    "normalization": AttrSpec("str", default="null"),
    "out_grad": AttrSpec("bool", default=False),
}


def _softmax_output_grad(prob, label, attrs):
    """(p - onehot(y)) · scale, with 'null'|'batch'|'valid' normalization
    (reference: softmax_output-inl.h Backward)."""
    if prob.ndim > 2 and attrs["multi_output"]:
        # (N, C, ...) with label (N, ...)
        nclass = prob.shape[1]
        onehot = jax.nn.one_hot(label.astype(jnp.int32), nclass, axis=1, dtype=prob.dtype)
    else:
        nclass = prob.shape[-1]
        onehot = jax.nn.one_hot(label.astype(jnp.int32), nclass, dtype=prob.dtype)
    grad = prob - onehot
    valid = jnp.ones(label.shape, dtype=prob.dtype)
    if attrs["use_ignore"]:
        keep = (label != attrs["ignore_label"]).astype(prob.dtype)
        if attrs["multi_output"] and prob.ndim > 2:
            grad = grad * jnp.expand_dims(keep, 1)
        else:
            grad = grad * keep.reshape(keep.shape + (1,))
        valid = keep
    norm = attrs["normalization"]
    scale = attrs["grad_scale"]
    if norm == "batch":
        grad = grad / label.shape[0]
    elif norm == "valid":
        grad = grad / jnp.maximum(jnp.sum(valid), 1.0)
    return grad * scale


@functools.lru_cache(maxsize=None)
def _softmax_output_core(attrs_key):
    """Build a custom-vjp softmax-output closure for one attr signature.
    Attrs are static (compile-time) config, matching the reference where
    SoftmaxOutputParam is baked into the bound operator."""
    attrs = dict(attrs_key)

    @jax.custom_vjp
    def core(data, label):
        axis = 1 if (attrs["multi_output"] and data.ndim > 2) else -1
        return jax.nn.softmax(data, axis=axis)

    def fwd(data, label):
        out = core(data, label)
        return out, (out, label)

    def bwd(res, g):
        prob, label = res
        dgrad = _softmax_output_grad(prob, label, attrs).astype(prob.dtype)
        return (dgrad, jnp.zeros_like(label))

    core.defvjp(fwd, bwd)
    return core


@register(
    "SoftmaxOutput",
    attrs=_SM_ATTRS(),
    input_names=("data", "label"),
    aliases=("Softmax",),
)
def _softmax_output(attrs, data, label):
    """Softmax forward + cross-entropy gradient on backward, ignoring the head
    gradient exactly like the reference (softmax_output-inl.h)."""
    key = tuple(
        (k, attrs[k])
        for k in ("grad_scale", "ignore_label", "multi_output", "use_ignore", "normalization")
    )
    return _softmax_output_core(key)(data, label)


def _make_output_op(name, fwd, grad):
    """Regression-output family: forward transform + own backward (reference:
    regression_output-inl.h). grad_scale is compile-time config baked into the
    cached closure so the vjp's cotangent pytree matches the primal args
    exactly (custom_vjp rejects None cotangents for array args)."""

    @functools.lru_cache(maxsize=None)
    def core_for(grad_scale):
        @jax.custom_vjp
        def core(data, label):
            return fwd(data)

        def core_fwd(data, label):
            out = fwd(data)
            return out, (out, label)

        def core_bwd(res, g):
            out, label = res
            num_output = max(int(np.prod(out.shape[1:])), 1)
            d = grad(out, label.reshape(out.shape)) * (grad_scale / num_output)
            return (d.astype(out.dtype), jnp.zeros_like(label))

        core.defvjp(core_fwd, core_bwd)
        return core

    @register(name, attrs={"grad_scale": AttrSpec("float", default=1.0)}, input_names=("data", "label"))
    def op(attrs, data, label):
        return core_for(float(attrs["grad_scale"]))(data, label)

    return op


_make_output_op("LinearRegressionOutput", lambda x: x, lambda o, y: o - y)
_make_output_op("LogisticRegressionOutput", jax.nn.sigmoid, lambda o, y: o - y)
_make_output_op("MAERegressionOutput", lambda x: x, lambda o, y: jnp.sign(o - y))


@functools.lru_cache(maxsize=None)
def _make_loss_core(grad_scale, norm_div):
    """grad_scale/norm_div are static config (like the bound MakeLossParam in
    the reference), so the vjp returns exactly one cotangent for `data`."""

    @jax.custom_vjp
    def core(data):
        return data

    def ml_fwd(data):
        return data, None

    def ml_bwd(res, g):
        # output aliases data, so g's shape/dtype are data's
        return (jnp.full(jnp.shape(g), grad_scale / norm_div, dtype=g.dtype),)

    core.defvjp(ml_fwd, ml_bwd)
    return core


@register(
    "MakeLoss",
    attrs={
        "grad_scale": AttrSpec("float", default=1.0),
        "valid_thresh": AttrSpec("float", default=0.0),
        "normalization": AttrSpec("str", default="null"),
    },
)
def _make_loss(attrs, data):
    """Treat data as a loss: backward emits grad_scale (reference: make_loss.cc)."""
    norm_div = float(data.shape[0]) if attrs["normalization"] == "batch" else 1.0
    return _make_loss_core(float(attrs["grad_scale"]), norm_div)(data)


@functools.lru_cache(maxsize=None)
def _svm_core(margin, coef, use_linear):
    @jax.custom_vjp
    def core(data, label):
        return data

    def fwd(data, label):
        return data, (data, label)

    def bwd(res, g):
        data, label = res
        onehot = jax.nn.one_hot(label.astype(jnp.int32), data.shape[-1], dtype=data.dtype)
        ty = 2.0 * onehot - 1.0  # +1 for target class, -1 otherwise
        viol = (margin - ty * data) > 0
        if use_linear:
            d = jnp.where(viol, -ty * coef, 0.0)
        else:
            d = jnp.where(viol, -2.0 * coef * (margin - ty * data) * ty, 0.0)
        return (d.astype(data.dtype), jnp.zeros_like(label))

    core.defvjp(fwd, bwd)
    return core


@register(
    "SVMOutput",
    attrs={
        "margin": AttrSpec("float", default=1.0),
        "regularization_coefficient": AttrSpec("float", default=1.0),
        "use_linear": AttrSpec("bool", default=False),
    },
    input_names=("data", "label"),
)
def _svm_output(attrs, data, label):
    """Hinge-loss output layer (reference: svm_output.cc)."""
    return _svm_core(
        attrs["margin"], attrs["regularization_coefficient"], bool(attrs["use_linear"])
    )(data, label)


@register(
    "IdentityAttachKLSparseReg",
    attrs={
        "sparseness_target": AttrSpec("float", default=0.1),
        "penalty": AttrSpec("float", default=0.001),
        "momentum": AttrSpec("float", default=0.9),
    },
    aux_names=("moving_avg",),
)
def _identity_kl(attrs, inputs, aux):
    """Identity forward with KL sparseness penalty added to the gradient
    (reference: identity_attach_KL_sparse_reg.cc)."""
    (data,) = inputs
    (moving,) = aux
    rho_hat = jnp.mean(jax.nn.sigmoid(data))
    new_moving = moving * attrs["momentum"] + rho_hat * (1 - attrs["momentum"])
    rho = attrs["sparseness_target"]
    penalty = attrs["penalty"] * (-rho / (rho_hat + 1e-8) + (1 - rho) / (1 - rho_hat + 1e-8))
    # forward identity; penalty enters via a zero-valued term with gradient
    out = data + jax.lax.stop_gradient(penalty) * (data - jax.lax.stop_gradient(data))
    return (out,), (new_moving,)


# --- Norm layers --------------------------------------------------------------
@register(
    "LRN",
    attrs={
        "alpha": AttrSpec("float", default=1e-4),
        "beta": AttrSpec("float", default=0.75),
        "knorm": AttrSpec("float", default=2.0),
        "nsize": AttrSpec("int", required=True),
    },
)
def _lrn(attrs, data):
    """Local response norm across channels (reference: lrn.cc)."""
    n = attrs["nsize"]
    sq = jnp.square(data)
    half = n // 2
    pad = [(0, 0), (half, half)] + [(0, 0)] * (data.ndim - 2)
    sq = jnp.pad(sq, pad)
    window = (1, n) + (1,) * (data.ndim - 2)
    ssum = jax.lax.reduce_window(sq, 0.0, jax.lax.add, window, (1,) * data.ndim, [(0, 0)] * data.ndim)
    norm = attrs["knorm"] + (attrs["alpha"] / n) * ssum
    return data * jnp.power(norm, -attrs["beta"])


@register(
    "InstanceNorm",
    attrs={"eps": AttrSpec("float", default=1e-3)},
    input_names=("data", "gamma", "beta"),
)
def _instance_norm(attrs, data, gamma, beta):
    """Per-sample per-channel normalization (reference: instance_norm.cc)."""
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    out = (data - mean) * jax.lax.rsqrt(var + attrs["eps"])
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register(
    "L2Normalization",
    attrs={"eps": AttrSpec("float", default=1e-10), "mode": AttrSpec("str", default="instance")},
)
def _l2_normalization(attrs, data):
    """(reference: l2_normalization.cc) instance|channel|spatial."""
    mode = attrs["mode"]
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, data.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + attrs["eps"])
    return data / norm


@register(
    "UpSampling",
    attrs={
        "scale": AttrSpec("int", required=True),
        "num_filter": AttrSpec("int", default=0),
        "sample_type": AttrSpec("str", default="nearest"),
        "multi_input_mode": AttrSpec("str", default="concat"),
        "num_args": AttrSpec("int", default=1),
        "workspace": AttrSpec("int", default=512),
    },
    input_names=lambda a: ["arg%d" % i for i in range(int(a.get("num_args", 1)))],
)
def _upsampling(attrs, *args):
    """Nearest/bilinear upsampling (reference: upsampling.cc)."""
    s = attrs["scale"]
    outs = []
    for data in args:
        if attrs["sample_type"] == "nearest":
            out = jnp.repeat(jnp.repeat(data, s, axis=2), s, axis=3)
        else:
            n, c, h, w = data.shape
            out = jax.image.resize(data, (n, c, h * s, w * s), method="bilinear")
        outs.append(out)
    if len(outs) == 1:
        return outs[0]
    if attrs["multi_input_mode"] == "sum":
        total = outs[0]
        for o in outs[1:]:
            total = total + o
        return total
    return jnp.concatenate(outs, axis=1)


# --- Correlation-style vision ops are in vision.py (round scope) --------------
