"""Per-op shape/dtype inference metadata.

One registry both the executor-side inference (``symbol._infer_impl`` via
``shape_rules.RULES``) and the static-analysis passes share, so lint rules
are never re-derived per pass. The reference kept the same facts scattered
across per-op ``FInferShape``/``FInferType`` lambdas and dmlc parameter
structs; here they are declarative:

  * ``input_ranks``  — slot name -> required rank (int) or (min, max) range;
                       the lint pass turns violations into ``GL006`` with the
                       provenance chain instead of a ``jax.eval_shape`` crash.
  * ``dtype_policy`` — how the op treats input dtypes:
                       ``"promote"`` numpy-promotes its inputs (mixed input
                       dtypes silently widen — lint warns ``GL004``),
                       ``"forced"`` output dtype comes from a ``dtype`` attr
                       (Cast, creation ops), ``"first"`` follows the first
                       input, ``"bool"`` emits comparison results.
  * ``param_slots``  — input slots holding *learned parameters* (their shapes
                       flow backward via ``shape_rules``); everything else is
                       data-like, which is what the retrace guard (``GL203``)
                       uses to name the inputs that drive compile-cache
                       cardinality.

``backward_shape_rule(op)`` re-exports ``shape_rules.RULES`` so callers need
only this module.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

from .shape_rules import RULES as _BACKWARD_RULES

__all__ = ["OpMeta", "register_meta", "get_meta", "backward_shape_rule",
           "rank_range"]


def rank_range(v) -> Optional[Tuple[int, int]]:
    """Normalize a rank constraint to an inclusive (min, max) pair."""
    if v is None:
        return None
    if isinstance(v, int):
        return (v, v)
    lo, hi = v
    return (lo, 10 ** 9 if hi is None else hi)


class OpMeta:
    __slots__ = ("name", "input_ranks", "dtype_policy", "param_slots")

    def __init__(self, name: str, input_ranks=None, dtype_policy: str = "promote",
                 param_slots: Tuple[str, ...] = ()):
        self.name = name
        self.input_ranks: Dict[str, Tuple[int, int]] = {
            slot: rank_range(r) for slot, r in (input_ranks or {}).items()
        }
        self.dtype_policy = dtype_policy
        self.param_slots = tuple(param_slots)


_META: Dict[str, OpMeta] = {}

_DEFAULT = OpMeta("<default>")


def register_meta(name, input_ranks=None, dtype_policy="promote",
                  param_slots=(), aliases=()):
    meta = OpMeta(name, input_ranks=input_ranks, dtype_policy=dtype_policy,
                  param_slots=param_slots)
    for n in (name,) + tuple(aliases):
        _META[n] = meta
    return meta


def get_meta(op_name: str) -> OpMeta:
    """Metadata for an op; unregistered ops get a permissive default
    (no rank constraints, promote dtype policy, no param slots)."""
    return _META.get(op_name, _DEFAULT)


def backward_shape_rule(op_name: str):
    """The backward-flowing parameter-shape rule for an op, or None —
    the same table ``symbol._infer_impl`` consumes (shape_rules.RULES)."""
    return _BACKWARD_RULES.get(op_name)


# ---------------------------------------------------------------------------
# Seed metadata for the bundled operator set. Rank facts mirror what each
# op's JAX implementation requires (NCHW layouts per SURVEY §2.3); param
# slots mirror shape_rules.py — the two stay adjacent on purpose.
# ---------------------------------------------------------------------------
register_meta("Convolution",
              input_ranks={"data": 4, "weight": 4, "bias": 1},
              param_slots=("weight", "bias"))
register_meta("Deconvolution",
              input_ranks={"data": 4, "weight": 4, "bias": 1},
              param_slots=("weight", "bias"))
register_meta("FullyConnected",
              input_ranks={"data": (1, None), "weight": 2, "bias": 1},
              param_slots=("weight", "bias"))
register_meta("BatchNorm",
              input_ranks={"data": (2, 5), "gamma": 1, "beta": 1,
                           "moving_mean": 1, "moving_var": 1},
              param_slots=("gamma", "beta"))
register_meta("InstanceNorm",
              input_ranks={"data": (3, 5), "gamma": 1, "beta": 1},
              param_slots=("gamma", "beta"))
register_meta("L2Normalization", input_ranks={"data": (2, None)})
register_meta("LRN", input_ranks={"data": 4})
register_meta("Pooling", input_ranks={"data": 4})
register_meta("Activation", dtype_policy="first")
register_meta("LeakyReLU", param_slots=("gamma",))
register_meta("Dropout", dtype_policy="first")
register_meta("Flatten", input_ranks={"data": (1, None)}, dtype_policy="first")
register_meta("Reshape", dtype_policy="first")
register_meta("transpose", dtype_policy="first")
register_meta("SwapAxis", dtype_policy="first")
register_meta("expand_dims", dtype_policy="first")
register_meta("Cast", dtype_policy="forced")
register_meta("Embedding",
              input_ranks={"weight": 2},
              dtype_policy="first",
              param_slots=("weight",))
register_meta("RNN",
              input_ranks={"data": 3, "parameters": 1,
                           "state": 3, "state_cell": 3},
              param_slots=("parameters",))
register_meta("SoftmaxOutput", dtype_policy="first")
register_meta("SoftmaxActivation", dtype_policy="first")
register_meta("LinearRegressionOutput", dtype_policy="first")
register_meta("LogisticRegressionOutput", dtype_policy="first")
register_meta("MAERegressionOutput", dtype_policy="first")
register_meta("SVMOutput", dtype_policy="first")
register_meta("MakeLoss", dtype_policy="first")
register_meta("BlockGrad", dtype_policy="first")
register_meta("Concat", dtype_policy="promote")
register_meta("batch_dot", input_ranks={"lhs": 3, "rhs": 3})
register_meta("dot", input_ranks={"lhs": (1, 2), "rhs": (1, 2)})

for _cmp in ("_equal", "_not_equal", "_greater", "_greater_equal",
             "_lesser", "_lesser_equal"):
    register_meta(_cmp, dtype_policy="bool")
    register_meta(_cmp + "_scalar", dtype_policy="bool")
