"""Per-op shape/dtype inference metadata.

One registry both the executor-side inference (``symbol._infer_impl`` via
``shape_rules.RULES``) and the static-analysis passes share, so lint rules
are never re-derived per pass. The reference kept the same facts scattered
across per-op ``FInferShape``/``FInferType`` lambdas and dmlc parameter
structs; here they are declarative:

  * ``input_ranks``  — slot name -> required rank (int) or (min, max) range;
                       the lint pass turns violations into ``GL006`` with the
                       provenance chain instead of a ``jax.eval_shape`` crash.
  * ``dtype_policy`` — how the op treats input dtypes:
                       ``"promote"`` numpy-promotes its inputs (mixed input
                       dtypes silently widen — lint warns ``GL004``),
                       ``"forced"`` output dtype comes from a ``dtype`` attr
                       (Cast, creation ops), ``"first"`` follows the first
                       input, ``"bool"`` emits comparison results.
  * ``param_slots``  — input slots holding *learned parameters* (their shapes
                       flow backward via ``shape_rules``); everything else is
                       data-like, which is what the retrace guard (``GL203``)
                       uses to name the inputs that drive compile-cache
                       cardinality.
  * ``shard_rule``   — how the op propagates PartitionSpecs, as a category
                       the sharding-plan lint (``analysis/shard_lint.py``)
                       interprets: ``"elementwise"`` (per-dim spec merge,
                       shape-preserving ops), ``"conv"`` (batch dim from
                       data, channel dim from weight dim 0, spatial dims
                       replicated), ``"fc"``/``"dot"`` (contraction: out
                       dims from data dim 0 and weight/rhs out dim),
                       ``"embedding"``/``"row_sparse_embedding"`` (lookup
                       tables; the sparse variant's weight gradient is
                       row-sparse by contract, docs/SPARSE.md),
                       ``"flatten"``, ``"reshape"``,
                       ``"transpose"``, ``"concat"``, ``"reduce"``,
                       ``"softmax"`` (needs its softmax'd dim whole). The
                       default ``"batch0"`` keeps the first input's batch-
                       dim sharding when the output's dim 0 has the same
                       extent and replicates everything else.

``backward_shape_rule(op)`` re-exports ``shape_rules.RULES`` so callers need
only this module.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

from .shape_rules import RULES as _BACKWARD_RULES

__all__ = ["OpMeta", "register_meta", "get_meta", "backward_shape_rule",
           "rank_range"]


def rank_range(v) -> Optional[Tuple[int, int]]:
    """Normalize a rank constraint to an inclusive (min, max) pair."""
    if v is None:
        return None
    if isinstance(v, int):
        return (v, v)
    lo, hi = v
    return (lo, 10 ** 9 if hi is None else hi)


SHARD_RULES = ("batch0", "elementwise", "conv", "fc", "dot", "batch_dot",
               "embedding", "row_sparse_embedding", "flatten", "reshape",
               "transpose", "concat", "reduce", "softmax")

# categories whose slot-1 parameter is an embedding TABLE (vocab, dim): the
# sharding lint prices a vocab-sharded table as output-psum traffic (the
# table itself never moves), and GL405's fix hint names the table-specific
# param_pspec instead of the generic rank-2 advice.
EMBEDDING_RULES = ("embedding", "row_sparse_embedding")


class OpMeta:
    __slots__ = ("name", "input_ranks", "dtype_policy", "param_slots",
                 "shard_rule", "bf16_slots")

    def __init__(self, name: str, input_ranks=None, dtype_policy: str = "promote",
                 param_slots: Tuple[str, ...] = (), shard_rule: str = "batch0",
                 bf16_slots: Tuple[str, ...] = ()):
        self.name = name
        self.input_ranks: Dict[str, Tuple[int, int]] = {
            slot: rank_range(r) for slot, r in (input_ranks or {}).items()
        }
        self.dtype_policy = dtype_policy
        self.param_slots = tuple(param_slots)
        if shard_rule not in SHARD_RULES:
            raise ValueError("unknown shard_rule %r for op %r (have: %s)"
                             % (shard_rule, name, SHARD_RULES))
        self.shard_rule = shard_rule
        # input slots the bf16-legalization rewrite pass may cast to
        # bfloat16 (analysis/rewrite.py): the MXU-bound operands of ops
        # whose f32 accumulate makes reduced-precision inputs safe. Empty =
        # the op is never legalized. Every listed slot is cast together
        # (a bf16 data against an f32 bias would just promote back).
        self.bf16_slots = tuple(bf16_slots)


_META: Dict[str, OpMeta] = {}

_DEFAULT = OpMeta("<default>")


def register_meta(name, input_ranks=None, dtype_policy="promote",
                  param_slots=(), aliases=(), shard_rule="batch0",
                  bf16_slots=()):
    meta = OpMeta(name, input_ranks=input_ranks, dtype_policy=dtype_policy,
                  param_slots=param_slots, shard_rule=shard_rule,
                  bf16_slots=bf16_slots)
    for n in (name,) + tuple(aliases):
        _META[n] = meta
    return meta


def get_meta(op_name: str) -> OpMeta:
    """Metadata for an op; unregistered ops get a permissive default
    (no rank constraints, promote dtype policy, no param slots)."""
    return _META.get(op_name, _DEFAULT)


def backward_shape_rule(op_name: str):
    """The backward-flowing parameter-shape rule for an op, or None —
    the same table ``symbol._infer_impl`` consumes (shape_rules.RULES)."""
    return _BACKWARD_RULES.get(op_name)


# ---------------------------------------------------------------------------
# Seed metadata for the bundled operator set. Rank facts mirror what each
# op's JAX implementation requires (NCHW layouts per SURVEY §2.3); param
# slots mirror shape_rules.py — the two stay adjacent on purpose.
# ---------------------------------------------------------------------------
register_meta("Convolution",
              input_ranks={"data": 4, "weight": 4, "bias": 1},
              param_slots=("weight", "bias"), shard_rule="conv",
              bf16_slots=("data", "weight", "bias"))
register_meta("Deconvolution",
              input_ranks={"data": 4, "weight": 4, "bias": 1},
              param_slots=("weight", "bias"), shard_rule="conv",
              bf16_slots=("data", "weight", "bias"))
register_meta("FullyConnected",
              input_ranks={"data": (1, None), "weight": 2, "bias": 1},
              param_slots=("weight", "bias"), shard_rule="fc",
              bf16_slots=("data", "weight", "bias"))
register_meta("BatchNorm",
              input_ranks={"data": (2, 5), "gamma": 1, "beta": 1,
                           "moving_mean": 1, "moving_var": 1},
              param_slots=("gamma", "beta"), shard_rule="elementwise")
register_meta("InstanceNorm",
              input_ranks={"data": (3, 5), "gamma": 1, "beta": 1},
              param_slots=("gamma", "beta"), shard_rule="elementwise")
register_meta("L2Normalization", input_ranks={"data": (2, None)},
              shard_rule="elementwise")
register_meta("LRN", input_ranks={"data": 4}, shard_rule="elementwise")
register_meta("Pooling", input_ranks={"data": 4}, shard_rule="conv")
register_meta("Activation", dtype_policy="first", shard_rule="elementwise")
register_meta("LeakyReLU", param_slots=("gamma",), shard_rule="elementwise")
register_meta("Dropout", dtype_policy="first", shard_rule="elementwise")
register_meta("Flatten", input_ranks={"data": (1, None)}, dtype_policy="first",
              shard_rule="flatten")
register_meta("Reshape", dtype_policy="first", shard_rule="reshape")
register_meta("transpose", dtype_policy="first", shard_rule="transpose")
register_meta("SwapAxis", dtype_policy="first")
register_meta("expand_dims", dtype_policy="first")
register_meta("Cast", dtype_policy="forced", shard_rule="elementwise")
register_meta("Embedding",
              input_ranks={"weight": 2},
              dtype_policy="first",
              param_slots=("weight",), shard_rule="embedding")
# the sparse-grad variant (docs/SPARSE.md): same lookup semantics, but the
# weight's gradient is row-sparse by contract — its own shard-rule category
# so the plan lint/autoplan can price a vocab-sharded table (the lookup
# psums only the OUTPUT; the backward scatters only touched rows)
register_meta("SparseEmbedding",
              input_ranks={"weight": 2},
              dtype_policy="first",
              param_slots=("weight",), shard_rule="row_sparse_embedding",
              aliases=("row_sparse_embedding",))
register_meta("RNN",
              input_ranks={"data": 3, "parameters": 1,
                           "state": 3, "state_cell": 3},
              param_slots=("parameters",))
register_meta("SoftmaxOutput", dtype_policy="first", shard_rule="softmax")
register_meta("SoftmaxActivation", dtype_policy="first", shard_rule="softmax")
register_meta("softmax", dtype_policy="first", shard_rule="softmax",
              aliases=("log_softmax",))
register_meta("LinearRegressionOutput", dtype_policy="first",
              shard_rule="elementwise")
register_meta("LogisticRegressionOutput", dtype_policy="first",
              shard_rule="elementwise")
register_meta("MAERegressionOutput", dtype_policy="first",
              shard_rule="elementwise")
register_meta("SVMOutput", dtype_policy="first")
register_meta("MakeLoss", dtype_policy="first", shard_rule="elementwise")
register_meta("BlockGrad", dtype_policy="first", shard_rule="elementwise")
register_meta("Concat", dtype_policy="promote", shard_rule="concat")
register_meta("batch_dot", input_ranks={"lhs": 3, "rhs": 3},
              shard_rule="batch_dot", bf16_slots=("lhs", "rhs"))
register_meta("dot", input_ranks={"lhs": (1, 2), "rhs": (1, 2)},
              shard_rule="dot", bf16_slots=("lhs", "rhs"))

# elementwise binaries/unaries preserve every input dim, so they preserve
# the full PartitionSpec, not just the batch dim (the "batch0" default);
# the broadcast_* family rides the same rule — its propagation aligns
# trailing dims and lets broadcast (extent-1) dims contribute nothing
for _ew in ("elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
            "_grad_add", "_power", "_maximum", "_minimum", "_hypot", "_mod",
            "relu", "sigmoid", "tanh", "exp", "log", "sqrt", "square",
            "abs", "negative", "_copy", "clip", "add_n",
            "broadcast_add", "broadcast_sub", "broadcast_mul",
            "broadcast_div", "broadcast_mod", "broadcast_power",
            "broadcast_maximum", "broadcast_minimum", "broadcast_hypot",
            "broadcast_equal", "broadcast_not_equal", "broadcast_greater",
            "broadcast_greater_equal", "broadcast_lesser",
            "broadcast_lesser_equal", "broadcast_to", "broadcast_axis"):
    register_meta(_ew, shard_rule="elementwise")
# the executor resolves aliases to canonical names only at apply time; the
# lint sees whatever name the Symbol recorded, so register the common ones
for _alias in ("_add", "_plus", "_Plus", "_sub", "_minus", "_Minus",
               "_mul", "_Mul", "_div", "_Div", "ElementWiseSum", "_sum"):
    register_meta(_alias, shard_rule="elementwise")
for _sc in ("_plus_scalar", "_minus_scalar", "_rminus_scalar", "_mul_scalar",
            "_div_scalar", "_rdiv_scalar", "_power_scalar", "_rpower_scalar",
            "_maximum_scalar", "_minimum_scalar", "smooth_l1"):
    register_meta(_sc, dtype_policy="first", shard_rule="elementwise")

# whole-or-axis reductions: output dims follow the surviving input dims
for _red in ("sum", "sum_axis", "mean", "prod", "nansum", "nanprod",
             "max", "max_axis", "min", "min_axis", "norm"):
    register_meta(_red, shard_rule="reduce")

for _cmp in ("_equal", "_not_equal", "_greater", "_greater_equal",
             "_lesser", "_lesser_equal"):
    register_meta(_cmp, dtype_policy="bool", shard_rule="elementwise")
    register_meta(_cmp + "_scalar", dtype_policy="bool",
                  shard_rule="elementwise")
