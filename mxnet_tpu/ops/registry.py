"""Operator registry.

TPU-native replacement for the reference's NNVM op registry + dmlc::Parameter
system (reference: include/mxnet/op_attr_types.h:59-63, nnvm registration at
src/operator/tensor/elemwise_binary_op_basic.cc:11-14, legacy OperatorProperty
bridge src/nnvm/legacy_op_util.cc).

Design (idiomatic JAX): every operator is a *pure, differentiable JAX function*
``fn(attrs, *inputs)``. There is no per-op gradient registration — backward
comes from ``jax.vjp`` over the composed graph, the way XLA wants it. Shape and
dtype inference (the reference's ``FInferShape``/``FInferType`` passes) come
for free from ``jax.eval_shape`` over the same function, so op implementations
are the single source of truth.

Loss/output ops that in the reference define custom backward semantics
(SoftmaxOutput etc., which ignore the incoming head gradient) use
``jax.custom_vjp`` in their implementation — the semantics live in the op fn,
not in the registry.

Stateful extras are declared, not hard-coded:
  * ``aux``        — ops with auxiliary (mutated-in-forward) state, e.g.
                     BatchNorm moving stats (reference FMutateInputs).
                     Signature: fn(attrs, inputs, aux, is_train, rng) ->
                     (outputs, new_aux).
  * ``needs_rng``  — ops consuming randomness (Dropout, samplers) take a JAX
                     PRNG key (reference ResourceRequest::kRandom,
                     include/mxnet/resource.h:20-25).
  * ``needs_train_flag`` — ops that behave differently under training
                     (Dropout, BatchNorm); fn receives is_train.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..base import MXNetError

__all__ = ["OpDef", "register", "get_op", "list_ops", "parse_attrs", "AttrSpec"]


class AttrSpec:
    """Declarative parameter field (reference: dmlc::Parameter / DMLC_DECLARE_FIELD,
    e.g. src/operator/fully_connected.cc:58)."""

    def __init__(self, typ, default=None, required=False, doc=""):
        self.typ = typ  # 'int'|'float'|'bool'|'str'|'shape'|'dtype'|'any'
        self.default = default
        self.required = required
        self.doc = doc

    def parse(self, value):
        if value is None:
            return None
        t = self.typ
        if t == "int":
            return int(value)
        if t == "float":
            return float(value)
        if t == "bool":
            if isinstance(value, str):
                v = value.strip().lower()
                return v in ("true", "1")
            return bool(value)
        if t == "str":
            return str(value)
        if t == "shape":
            if isinstance(value, str):
                s = value.strip().lstrip("([").rstrip(")]")
                if not s:
                    return ()
                return tuple(int(float(x)) for x in s.replace("L", "").split(",") if x.strip())
            if isinstance(value, (int, np.integer)):
                return (int(value),)
            return tuple(int(v) for v in value)
        if t == "ftuple":
            if isinstance(value, str):
                s = value.strip().lstrip("([").rstrip(")]")
                if not s:
                    return ()
                return tuple(float(x) for x in s.split(",") if x.strip())
            if isinstance(value, (int, float, np.floating, np.integer)):
                return (float(value),)
            return tuple(float(v) for v in value)
        if t == "dtype":
            from ..base import np_dtype

            return np_dtype(value)
        return value


class OpDef:
    def __init__(
        self,
        name: str,
        fn: Callable,
        attrs: Optional[Dict[str, AttrSpec]] = None,
        input_names=("data",),
        aux_names=(),
        num_outputs=1,
        output_names=None,
        needs_rng: bool = False,
        needs_train_flag: bool = False,
        aliases: Sequence[str] = (),
        doc: str = "",
    ):
        self.name = name
        self.fn = fn
        self.attr_specs = attrs or {}
        # input_names/aux_names/num_outputs may be callables of parsed attrs
        self._input_names = input_names
        self._aux_names = aux_names
        self._num_outputs = num_outputs
        self._output_names = output_names
        self.needs_rng = needs_rng
        self.needs_train_flag = needs_train_flag
        self.aliases = tuple(aliases)
        self.doc = doc or (fn.__doc__ or "")

    # --- attr-dependent metadata -----------------------------------------
    def input_names(self, attrs) -> List[str]:
        n = self._input_names
        return list(n(attrs) if callable(n) else n)

    def aux_names(self, attrs) -> List[str]:
        n = self._aux_names
        return list(n(attrs) if callable(n) else n)

    def num_outputs(self, attrs) -> int:
        n = self._num_outputs
        return int(n(attrs) if callable(n) else n)

    def output_names(self, attrs) -> List[str]:
        if self._output_names is None:
            k = self.num_outputs(attrs)
            return ["output"] if k == 1 else ["output%d" % i for i in range(k)]
        n = self._output_names
        return list(n(attrs) if callable(n) else n)

    @property
    def has_aux(self) -> bool:
        if callable(self._aux_names):
            return True
        return len(self._aux_names) > 0

    # --- invocation -------------------------------------------------------
    def apply(self, attrs, inputs, aux=None, is_train=False, rng=None):
        """Run the op on raw jax arrays. Returns (outputs_list, new_aux_list)."""
        kwargs = {}
        if self.needs_train_flag:
            kwargs["is_train"] = is_train
        if self.needs_rng:
            kwargs["rng"] = rng
        if self.has_aux:
            out, new_aux = self.fn(attrs, list(inputs), list(aux or []), **kwargs)
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            return outs, list(new_aux)
        out = self.fn(attrs, *inputs, **kwargs)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        return outs, []


_REGISTRY: Dict[str, OpDef] = {}
_CANONICAL: Dict[str, OpDef] = {}


def register(
    name,
    attrs=None,
    input_names=("data",),
    aux_names=(),
    num_outputs=1,
    output_names=None,
    needs_rng=False,
    needs_train_flag=False,
    aliases=(),
):
    """Decorator registering a JAX function as a framework operator."""

    def _reg(fn):
        op = OpDef(
            name,
            fn,
            attrs=attrs,
            input_names=input_names,
            aux_names=aux_names,
            num_outputs=num_outputs,
            output_names=output_names,
            needs_rng=needs_rng,
            needs_train_flag=needs_train_flag,
            aliases=aliases,
        )
        if name in _REGISTRY:
            raise MXNetError(
                "duplicate operator registration %r (already %s)"
                % (name, "canonical" if name in _CANONICAL else "an alias")
            )
        for a in aliases:
            if a in _REGISTRY:
                raise MXNetError("operator alias %r collides with existing op" % a)
        _CANONICAL[name] = op
        _REGISTRY[name] = op
        for a in aliases:
            _REGISTRY[a] = op
        return fn

    return _reg


def get_op(name: str) -> OpDef:
    if name not in _REGISTRY:
        raise MXNetError("operator %r is not registered" % name)
    return _REGISTRY[name]


def has_op(name: str) -> bool:
    return name in _REGISTRY


def list_ops() -> List[str]:
    return sorted(_CANONICAL.keys())


def parse_attrs(op: OpDef, raw: dict) -> dict:
    """Parse raw kwargs/JSON-string attrs into typed python values using the
    op's AttrSpec table (the reference's dmlc::Parameter::Init)."""
    out = {}
    specs = op.attr_specs
    for k, v in (raw or {}).items():
        if k in ("name", "__proto__"):
            continue
        if k in specs:
            out[k] = specs[k].parse(v)
        else:
            # keep unknown attrs verbatim (reference keeps __xxx__ attrs)
            out[k] = v
    for k, spec in specs.items():
        if k not in out:
            if spec.required:
                raise MXNetError(
                    "operator %s: required attribute %r missing" % (op.name, k)
                )
            out[k] = spec.default
    return out
